"""Behavioral parity against the ACTUAL reference code.

Every other test in this suite checks our implementations against *specified*
behavior (SURVEY.md's analysis of the reference).  This module goes one step
further: it imports the reference's own modules from ``/root/reference``
(read-only), satisfies the dependency modules the reference author never
committed (``dataloaders.helpers``, ``dataloaders.nellipse``,
``dataloaders.skewed_axes_weight_map``, ``mypath`` — SURVEY.md §2.4) with
THIS framework's implementations, and asserts our transforms/dataset produce
the same arrays the reference code produces on the same inputs.

Deterministic paths only: the reference draws from the global numpy RNG
(``import numpy.random as random``), ours from explicit per-sample
generators, so random *draw sequences* are not comparable.  Every case below
is configured so no random draw affects the output: val-mode guidance
(``extreme_points_fixed``), single-element rot/scale lists (the reference's
list variant indexes with ``randint(0, 1) == 0``), ``pert=0``.

``train_pascal.py`` is not importable — the reference's abandoned
``train_epoch`` refactor left it syntactically invalid (SURVEY.md §0) — so
driver-level parity stays covered by the survey-specified tests elsewhere.

Skipped entirely when ``/root/reference`` is not mounted.
"""

from __future__ import annotations

import importlib.util
import os
import sys
import types
from copy import deepcopy

import cv2
import numpy as np
import pytest

REF_DIR = "/root/reference"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF_DIR), reason="reference repo not mounted"
)


# ---------------------------------------------------------------------------
# dependency stubs: the modules the reference imports but never committed,
# filled with this framework's implementations (the §2.4 contract table)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module", autouse=True)
def _scoped_global_patches():
    """Contain this module's process-global mutations.

    The stubs shadow real package names (``dataloaders``, ``mypath``) in
    ``sys.modules`` and re-add numpy<2 aliases (``np.int``/``np.bool``) the
    reference's era assumed; left installed they could shadow genuine
    packages or mask numpy-2.x misuse in unrelated test modules.  Everything
    is restored on module teardown; reference-code execution itself stays
    confined to this opt-in module (skipped when the mount is absent).
    """
    stub_names = ("dataloaders", "dataloaders.helpers",
                  "dataloaders.nellipse",
                  "dataloaders.skewed_axes_weight_map", "mypath",
                  "_ref_custom_transforms", "_ref_pascal")
    saved_modules = {n: sys.modules.get(n) for n in stub_names}
    saved_np = {n: getattr(np, n, None) for n in ("int", "bool")}
    yield
    for n, mod in saved_modules.items():
        if mod is None:
            sys.modules.pop(n, None)
        else:
            sys.modules[n] = mod
    for n, val in saved_np.items():
        if val is None:
            if hasattr(np, n):
                delattr(np, n)
        else:
            setattr(np, n, val)


def _install_stubs() -> None:
    if "dataloaders" in sys.modules:
        return
    from distributedpytorch_tpu.data import guidance as G
    from distributedpytorch_tpu.utils import helpers as H

    dataloaders = types.ModuleType("dataloaders")
    dataloaders.__path__ = []  # mark as package

    helpers = types.ModuleType("dataloaders.helpers")
    for name in (
        "get_bbox", "crop_from_mask", "fixed_resize", "make_gt",
        "crop2fullmask", "tens2image", "overlay_mask",
    ):
        setattr(helpers, name, getattr(H, name))

    nellipse = types.ModuleType("dataloaders.nellipse")
    nellipse.extreme_points = G.extreme_points
    nellipse.extreme_points_fixed = G.extreme_points_fixed
    nellipse.compute_nellipse = G.compute_nellipse
    # the reference's "fast" name for the (ellipse, gaussian-heatmap) pair
    nellipse.compute_nellipse_gaussianHM_fast = G.compute_nellipse_gaussian_hm

    skewed = types.ModuleType("dataloaders.skewed_axes_weight_map")
    skewed.generate_mvL1L2_image_skewed_axes = G.generate_mv_l1l2_image_skewed_axes
    skewed.generate_mvgauss_image = G.generate_mvgauss_image
    skewed.normalize_wtMap = G.normalize_wt_map

    mypath = types.ModuleType("mypath")

    class Path:  # noqa: D401 - the reference's machine-local path registry
        @staticmethod
        def db_root_dir(db: str) -> str:
            return os.path.join("/tmp", "ref_db_unused", db)

    mypath.Path = Path

    sys.modules.update({
        "dataloaders": dataloaders,
        "dataloaders.helpers": helpers,
        "dataloaders.nellipse": nellipse,
        "dataloaders.skewed_axes_weight_map": skewed,
        "mypath": mypath,
    })


def _load_ref_module(name: str, filename: str):
    _install_stubs()
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REF_DIR, filename))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def ref_ct():
    """The reference's transform library, executing its real code."""
    # numpy<1.20 aliases the reference's era assumed (np.int/np.bool were
    # removed in numpy 2.x; the reference uses both)
    if not hasattr(np, "int"):
        np.int = int  # noqa: NPY001
    if not hasattr(np, "bool"):
        np.bool = bool  # noqa: NPY001
    return _load_ref_module("_ref_custom_transforms", "custom_transforms.py")


@pytest.fixture(scope="module")
def ref_pascal():
    if not hasattr(np, "int"):
        np.int = int  # noqa: NPY001
    return _load_ref_module("_ref_pascal", "pascal.py")


# ---------------------------------------------------------------------------
# shared inputs
# ---------------------------------------------------------------------------

def _make_sample(h: int = 80, w: int = 96, seed: int = 3) -> dict:
    """An image + one-object mask + void ring, reference sample schema."""
    rng = np.random.default_rng(seed)
    img = rng.integers(0, 256, (h, w, 3)).astype(np.uint8)
    img = cv2.GaussianBlur(img, (5, 5), 0).astype(np.float32)
    gt = np.zeros((h, w), np.uint8)
    cv2.ellipse(gt, (52, 38), (25, 16), 30.0, 0, 360, 1, -1)
    void = (cv2.dilate(gt, np.ones((3, 3), np.uint8)) - gt).astype(np.float32)
    return {"image": img, "gt": gt.astype(np.float32), "void_pixels": void}


def _clone(sample: dict) -> dict:
    return {k: deepcopy(v) for k, v in sample.items()}


def _assert_samples_equal(ours: dict, ref: dict, atol: float = 0.0) -> None:
    assert set(ours.keys()) == set(ref.keys())
    for key in ref:
        if key == "meta":
            continue
        a, b = ours[key], ref[key]
        if isinstance(b, list):
            assert isinstance(a, list) and len(a) == len(b)
            for x, y in zip(a, b):
                np.testing.assert_allclose(x, y, atol=atol, err_msg=key)
        else:
            np.testing.assert_allclose(
                np.asarray(a, np.float64), np.asarray(b, np.float64),
                atol=atol, err_msg=key)


# ---------------------------------------------------------------------------
# transform parity
# ---------------------------------------------------------------------------

class TestTransformParity:
    def test_scale_n_rotate_fixed_choice(self, ref_ct):
        """Single-element rot/scale lists make the reference's list variant
        deterministic (randint(0,1)==0); the warp itself is the reference's
        own cv2 path — a fully independent check of our warp semantics
        (uint8 cast, per-key interpolation).

        ``void_pixels`` is compared separately: the reference's meta-key
        exemption is a substring test (``'id' in elem``,
        custom_transforms.py:108) and ``'id' in 'vo_id_pixels'`` — so the
        reference never warps the void mask at all, leaving it misaligned
        with the warped gt.  We deliberately do not reproduce that (exact
        key match in ``transforms._is_meta``): our void mask must track gt
        for the void-aware loss to mean anything."""
        from distributedpytorch_tpu.data import transforms as T

        sample = _make_sample()
        ref_out = ref_ct.ScaleNRotate(rots=[17], scales=[0.9])(_clone(sample))
        ours_out = T.ScaleNRotate(rots=[17], scales=[0.9])(
            _clone(sample), np.random.default_rng(0))
        for key in ("image", "gt"):
            np.testing.assert_array_equal(
                np.asarray(ours_out[key]), np.asarray(ref_out[key]),
                err_msg=key)
        # documented divergence: reference void is untouched (the 'id'
        # substring quirk); ours is warped in lockstep with gt
        np.testing.assert_array_equal(ref_out["void_pixels"],
                                      sample["void_pixels"])
        assert not np.array_equal(ours_out["void_pixels"],
                                  sample["void_pixels"])
        import cv2 as _cv2
        h, w = sample["void_pixels"].shape
        m = _cv2.getRotationMatrix2D((w / 2, h / 2), 17, 0.9)
        expected_void = _cv2.warpAffine(
            sample["void_pixels"].astype(np.uint8), m, (w, h),
            flags=_cv2.INTER_NEAREST)
        np.testing.assert_array_equal(ours_out["void_pixels"], expected_void)

    def test_scale_n_rotate_bb_mask_border(self, ref_ct):
        """bb_mask warps with borderValue=255 in the reference — the border
        must stay 'outside the box' under rotation."""
        from distributedpytorch_tpu.data import transforms as T

        sample = _make_sample()
        del sample["void_pixels"]  # the 'id'-substring quirk, tested above
        bb = np.ones_like(sample["gt"]) * 255.0
        bb[10:60, 20:80] = 0.0
        sample["bb_mask"] = bb
        ref_out = ref_ct.ScaleNRotate(rots=[25], scales=[1.1])(_clone(sample))
        ours_out = T.ScaleNRotate(rots=[25], scales=[1.1])(
            _clone(sample), np.random.default_rng(0))
        _assert_samples_equal(ours_out, ref_out)

    def test_crop_from_mask_static(self, ref_ct):
        from distributedpytorch_tpu.data import transforms as T

        sample = _make_sample()
        kw = dict(crop_elems=("image", "gt", "void_pixels"), mask_elem="gt",
                  relax=50, zero_pad=True)
        ref_out = ref_ct.CropFromMaskStatic(**kw)(_clone(sample))
        ours_out = T.CropFromMaskStatic(**kw)(
            _clone(sample), np.random.default_rng(0))
        for key in ("crop_image", "crop_gt", "crop_void_pixels"):
            np.testing.assert_array_equal(
                np.asarray(ours_out[key]), np.asarray(ref_out[key]), err_msg=key)

    def test_crop_from_mask_static_empty_mask(self, ref_ct):
        from distributedpytorch_tpu.data import transforms as T

        sample = _make_sample()
        sample["gt"] = np.zeros_like(sample["gt"])
        kw = dict(crop_elems=("image", "gt"), mask_elem="gt", relax=50,
                  zero_pad=True)
        ref_out = ref_ct.CropFromMaskStatic(**kw)(_clone(sample))
        ours_out = T.CropFromMaskStatic(**kw)(
            _clone(sample), np.random.default_rng(0))
        for key in ("crop_image", "crop_gt"):
            np.testing.assert_array_equal(
                np.asarray(ours_out[key]), np.asarray(ref_out[key]), err_msg=key)

    def test_fixed_resize_quirks(self, ref_ct):
        """None = passthrough; unlisted keys deleted — the two load-bearing
        quirks (SURVEY.md §2.3) — plus the plain resize path, against the
        reference's own loop."""
        from distributedpytorch_tpu.data import transforms as T

        sample = _make_sample()
        sample["extra_debug"] = np.ones((7, 7), np.float32)  # must be pruned
        res = {"image": (64, 64), "gt": (64, 64), "void_pixels": None}
        ref_out = ref_ct.FixedResize(resolutions=dict(res))(_clone(sample))
        ours_out = T.FixedResize(resolutions=dict(res))(
            _clone(sample), np.random.default_rng(0))
        _assert_samples_equal(ours_out, ref_out)
        assert "extra_debug" not in ours_out

    def test_fixed_resize_list_stacking(self, ref_ct):
        """List-valued entries resize elementwise and stack on a trailing
        axis (reference custom_transforms.py:177-188)."""
        from distributedpytorch_tpu.data import transforms as T

        sample = {"crops": [np.float32(np.eye(20) * 200),
                            np.float32(np.ones((20, 20)) * 55)]}
        res = {"crops": (32, 32)}
        ref_out = ref_ct.FixedResize(resolutions=dict(res))(_clone(sample))
        ours_out = T.FixedResize(resolutions=dict(res))(
            _clone(sample), np.random.default_rng(0))
        np.testing.assert_allclose(ours_out["crops"], ref_out["crops"])

    def _square_crop_gt(self) -> dict:
        """The n-ellipse transforms run strictly AFTER the 512x512
        FixedResize in both reference pipelines (train_pascal.py:127-131,
        138-142), so square crops are the only shapes the reference ever
        feeds them.  On non-square inputs the never-committed
        ``compute_nellipse``'s (x_range, y_range) orientation is unknowable;
        on square inputs both orientations agree, so parity is well-defined
        exactly on the reference's live domain."""
        gt = np.asarray(
            _make_sample(h=72, w=72, seed=5)["gt"], np.float32)
        assert gt.max() > 0
        return {"crop_gt": gt}

    def test_nellipse_val(self, ref_ct):
        from distributedpytorch_tpu.data import transforms as T

        sample = self._square_crop_gt()
        ref_out = ref_ct.NEllipse(is_val=True)(_clone(sample))
        ours_out = T.NEllipse(is_val=True)(_clone(sample))
        np.testing.assert_allclose(
            ours_out["nellipse"], ref_out["nellipse"], atol=1e-3)

    def test_nellipse_with_gaussians_val(self, ref_ct):
        """The live guidance channel: the z1 + alpha*z2 combination and the
        rescale-to-255 are the reference's own arithmetic here."""
        from distributedpytorch_tpu.data import transforms as T

        sample = self._square_crop_gt()
        ref_out = ref_ct.NEllipseWithGaussians(alpha=0.6, is_val=True)(
            _clone(sample))
        ours_out = T.NEllipseWithGaussians(alpha=0.6, is_val=True)(
            _clone(sample))
        np.testing.assert_allclose(
            ours_out["nellipseWithGaussians"],
            ref_out["nellipseWithGaussians"], atol=1e-3)

    def test_nellipse_empty_mask(self, ref_ct):
        from distributedpytorch_tpu.data import transforms as T

        sample = {"crop_gt": np.zeros((40, 50), np.float32)}
        ref_out = ref_ct.NEllipseWithGaussians(is_val=True)(_clone(sample))
        ours_out = T.NEllipseWithGaussians(is_val=True)(_clone(sample))
        np.testing.assert_array_equal(
            ours_out["nellipseWithGaussians"],
            ref_out["nellipseWithGaussians"])

    def test_extreme_points_heatmap(self, ref_ct):
        from distributedpytorch_tpu.data import transforms as T

        sample = {"gt": _make_sample()["gt"]}
        ref_out = ref_ct.ExtremePoints(sigma=10, pert=0, elem="gt",
                                       is_val=True)(_clone(sample))
        ours_out = T.ExtremePoints(sigma=10, pert=0, elem="gt", is_val=True)(
            _clone(sample))
        np.testing.assert_allclose(
            ours_out["extreme_points"], ref_out["extreme_points"], atol=1e-5)

    def test_create_bb_mask(self, ref_ct):
        """The reference zeroes ``[bbox[1]:bbox[3], bbox[0]:bbox[2]]`` —
        exclusive upper bounds over whatever convention its never-committed
        ``get_bbox`` used.  Ours is inclusive (+1) over our inclusive
        ``get_bbox`` (the DEXTR-lineage convention every other call site
        here shares).  Parity: the masks agree everywhere except possibly
        the one-pixel inclusive boundary band (the max row / max col)."""
        from distributedpytorch_tpu.data import transforms as T
        from distributedpytorch_tpu.utils.helpers import get_bbox

        sample = _make_sample()
        ref_out = ref_ct.CreateBBMask()(_clone(sample))
        ours_out = T.CreateBBMask()(_clone(sample))
        ours = np.asarray(ours_out["bb_mask"])
        ref = np.asarray(ref_out["bb_mask"])
        diff_rows, diff_cols = np.nonzero(ours != ref)
        x_min, y_min, x_max, y_max = get_bbox(sample["gt"])
        assert diff_rows.size > 0  # the band exists for a non-empty mask
        assert np.all((diff_rows == y_max) | (diff_cols == x_max))
        # inside the band-free interior the masks are identical
        np.testing.assert_array_equal(ours[:y_max, :x_max], ref[:y_max, :x_max])

    def test_concat_inputs(self, ref_ct):
        """Independent parity: the reference's concat is raw numpy."""
        from distributedpytorch_tpu.data import transforms as T

        sample = _make_sample()
        sample["heat"] = np.linspace(
            0, 255, sample["gt"].size, dtype=np.float32
        ).reshape(sample["gt"].shape)
        ref_out = ref_ct.ConcatInputs(elems=("image", "heat"))(_clone(sample))
        ours_out = T.ConcatInputs(elems=("image", "heat"))(_clone(sample))
        np.testing.assert_array_equal(ours_out["concat"], ref_out["concat"])
        assert ours_out["concat"].shape[-1] == 4

    def test_to_image_normalization(self, ref_ct):
        from distributedpytorch_tpu.data import transforms as T

        sample = {"image": np.float32([[1.0, 3.0], [5.0, 9.0]])}
        ref_out = ref_ct.ToImage(norm_elem="image", custom_max=255.0)(
            _clone(sample))
        ours_out = T.ToImage(norm_elem="image", custom_max=255.0)(
            _clone(sample))
        np.testing.assert_allclose(ours_out["image"], ref_out["image"],
                                   rtol=1e-6)

    def test_to_tensor_layout_equivalence(self, ref_ct):
        """The reference emits CHW torch tensors; we emit HWC float32 arrays
        (the TPU layout).  Content must match modulo the transpose."""
        from distributedpytorch_tpu.data import transforms as T

        sample = _make_sample()
        ref_out = ref_ct.ToTensor()(_clone(sample))
        ours_out = T.ToArray()(_clone(sample), np.random.default_rng(0))
        for key in ("image", "gt"):
            ref_np = ref_out[key].numpy()  # (C, H, W)
            np.testing.assert_allclose(
                ours_out[key], np.transpose(ref_np, (1, 2, 0)), err_msg=key)
            assert ours_out[key].dtype == np.float32
        # the 'id'-substring quirk again: the reference's ToTensor skips
        # 'vo_id_pixels' entirely (it reaches collate as a raw numpy array);
        # ours converts it like every other array key
        assert isinstance(ref_out["void_pixels"], np.ndarray)
        np.testing.assert_allclose(
            ours_out["void_pixels"][..., 0], ref_out["void_pixels"])
        assert ours_out["void_pixels"].dtype == np.float32


# ---------------------------------------------------------------------------
# full-pipeline parity: the reference driver's exact val composition
# ---------------------------------------------------------------------------

class TestValPipelineParity:
    def test_val_pipeline_end_to_end(self, ref_ct):
        """The reference's val transform chain (train_pascal.py:135-145),
        deterministic end to end, reference code vs ours — including the
        FixedResize key-pruning that shapes the final sample."""
        from distributedpytorch_tpu.data import transforms as T

        sample = _make_sample(h=100, w=120, seed=11)
        res = {
            "void_pixels": None, "gt": None,
            "crop_image": (64, 64), "crop_gt": (64, 64),
        }

        ref_chain = [
            ref_ct.CropFromMaskStatic(
                crop_elems=("image", "gt"), mask_elem="gt", relax=50,
                zero_pad=True),
            ref_ct.FixedResize(resolutions=dict(res)),
            ref_ct.NEllipseWithGaussians(alpha=0.6, is_val=True),
            ref_ct.ConcatInputs(elems=("crop_image", "nellipseWithGaussians")),
        ]
        ref_out = _clone(sample)
        for t in ref_chain:
            ref_out = t(ref_out)

        ours_chain = [
            T.CropFromMaskStatic(
                crop_elems=("image", "gt"), mask_elem="gt", relax=50,
                zero_pad=True),
            T.FixedResize(resolutions=dict(res)),
            T.NEllipseWithGaussians(alpha=0.6, is_val=True),
            T.ConcatInputs(elems=("crop_image", "nellipseWithGaussians")),
        ]
        ours_out = _clone(sample)
        rng = np.random.default_rng(0)
        for t in ours_chain:
            ours_out = t(ours_out, rng)

        # documented addition: our CropFromMaskStatic records the crop bbox
        # (the evaluator pastes back from it; the reference recomputed the
        # bbox from the full-res gt at eval time, train_pascal.py:287 — its
        # `relaxes[jj]` latent-bug zone).  Not part of the reference sample.
        ours_out.pop("bbox")
        _assert_samples_equal(ours_out, ref_out, atol=1e-3)
        assert ours_out["concat"].shape == (64, 64, 4)


# ---------------------------------------------------------------------------
# dataset parity: the reference's VOCSegmentation, run on the fake fixture
# ---------------------------------------------------------------------------

def _ref_dataset(ref_pascal, root: str, **kw):
    """Instantiate the reference dataset on a local tree: integrity is the
    official 2 GB tar's MD5 (pascal.py:142-152), patched out for the
    fixture."""
    cls = ref_pascal.VOCSegmentation
    orig = cls._check_integrity
    cls._check_integrity = lambda self: True
    try:
        return cls(root=root, **kw)
    finally:
        cls._check_integrity = orig


class TestDatasetParity:
    @pytest.fixture(scope="class")
    def voc_tree(self, tmp_path_factory):
        from distributedpytorch_tpu.data.fake import make_fake_voc
        root = str(tmp_path_factory.mktemp("ref_parity_voc"))
        make_fake_voc(root, n_images=6, size=(72, 88), max_objects=3, n_val=2)
        return root

    def test_samples_match_and_cache_interop_ref_first(
            self, ref_pascal, voc_tree):
        """Reference preprocesses first (writes its JSON cache); our dataset
        must validate + load that cache (same filename, same key-set rule)
        and then produce identical samples."""
        from distributedpytorch_tpu.data.voc import VOCInstanceSegmentation

        ref_ds = _ref_dataset(ref_pascal, voc_tree, split="train",
                              area_thres=50, retname=True)
        ours_ds = VOCInstanceSegmentation(root=voc_tree, split="train",
                                          area_thres=50, retname=True)
        assert len(ours_ds) == len(ref_ds)
        assert ours_ds.obj_dict == {
            k: list(v) for k, v in ref_ds.obj_dict.items()}
        for idx in range(len(ref_ds)):
            ref_s = ref_ds[idx]
            our_s = ours_ds[idx]
            for key in ("image", "gt", "void_pixels"):
                np.testing.assert_array_equal(
                    np.asarray(our_s[key]), np.asarray(ref_s[key]),
                    err_msg=f"{key}[{idx}]")
            assert our_s["meta"]["image"] == ref_s["meta"]["image"]
            assert str(our_s["meta"]["object"]) == str(ref_s["meta"]["object"])
            assert int(our_s["meta"]["category"]) == int(
                ref_s["meta"]["category"])

    def test_cache_interop_ours_first(self, ref_pascal, tmp_path):
        """Our preprocess cache, read back by the reference's
        ``_check_preprocess`` (json.load + key-set comparison)."""
        from distributedpytorch_tpu.data.fake import make_fake_voc
        from distributedpytorch_tpu.data.voc import VOCInstanceSegmentation

        root = str(tmp_path / "voc")
        make_fake_voc(root, n_images=5, size=(64, 80), max_objects=2, n_val=1)
        ours_ds = VOCInstanceSegmentation(root=root, split="train",
                                          area_thres=50, retname=True)
        ref_ds = _ref_dataset(ref_pascal, root, split="train", area_thres=50,
                              retname=True)
        assert {k: list(v) for k, v in ref_ds.obj_dict.items()} \
            == ours_ds.obj_dict
        assert len(ref_ds) == len(ours_ds)

    def test_str_matches(self, ref_pascal, voc_tree):
        from distributedpytorch_tpu.data.voc import VOCInstanceSegmentation

        ref_ds = _ref_dataset(ref_pascal, voc_tree, split="train",
                              area_thres=50)
        ours_ds = VOCInstanceSegmentation(root=voc_tree, split="train",
                                          area_thres=50)
        assert str(ours_ds) == str(ref_ds)
