"""Profiling utilities: StepTimer semantics, annotate/trace no-crash."""

import os

import pytest

import jax.numpy as jnp

from distributedpytorch_tpu.utils import StepTimer, annotate, trace


class TestStepTimer:
    def test_warmup_skipped(self):
        t = StepTimer(warmup=2)
        for _ in range(5):
            t.tick(jnp.zeros(()))
        # 5 ticks = 4 intervals; first 2 are warmup
        assert t.summary()["steps"] == 2

    def test_items_per_sec(self):
        t = StepTimer(warmup=0)
        for _ in range(3):
            t.tick()
        s = t.summary(items_per_step=10)
        assert s["steps"] == 2
        assert s["items_per_sec"] > 0
        assert s["min_s"] <= s["p50_s"] <= s["max_s"]

    def test_empty_summary(self):
        assert StepTimer().summary() == {"steps": 0}

    def test_summary_percentiles(self):
        t = StepTimer(warmup=0)
        for _ in range(6):
            t.tick()
        s = t.summary()
        assert s["p50_s"] <= s["p99_s"] <= s["max_s"]

    def test_default_sync_is_block_until_ready(self, monkeypatch):
        # the default path must be UNCHANGED: block_until_ready, never a
        # value materialization
        import distributedpytorch_tpu.utils.profiling as prof
        calls = []
        monkeypatch.setattr(prof.jax, "block_until_ready",
                            lambda o: calls.append(("block", o)))
        monkeypatch.setattr(prof.jax, "device_get",
                            lambda o: calls.append(("get", o)))
        t = StepTimer(warmup=0)
        t.tick(jnp.zeros(()))
        assert [kind for kind, _ in calls] == ["block"]

    def test_device_get_sync_mode(self, monkeypatch):
        # opt-in mode for remote-tunneled backends where block_until_ready
        # can be a no-op (throughput()'s documented hazard): tick must
        # materialize the outputs instead
        import distributedpytorch_tpu.utils.profiling as prof
        calls = []
        monkeypatch.setattr(prof.jax, "block_until_ready",
                            lambda o: calls.append(("block", o)))
        monkeypatch.setattr(prof.jax, "device_get",
                            lambda o: calls.append(("get", o)))
        t = StepTimer(warmup=0, sync="device_get")
        t.tick(jnp.zeros(()))
        t.tick(jnp.zeros(()))
        assert [kind for kind, _ in calls] == ["get", "get"]
        assert t.summary()["steps"] == 1

    def test_unknown_sync_mode_rejected(self):
        with pytest.raises(ValueError, match="block.*device_get"):
            StepTimer(sync="nope")


class TestPercentile:
    """Nearest-rank percentile — shared by StepTimer and serve/metrics."""

    def test_nearest_rank_is_an_observed_sample(self):
        from distributedpytorch_tpu.utils.profiling import percentile
        values = [5.0, 1.0, 3.0, 2.0, 4.0]
        assert percentile(values, 0.0) == 1.0
        assert percentile(values, 50.0) == 3.0
        assert percentile(values, 99.0) == 5.0
        assert percentile(values, 100.0) == 5.0
        # every answer is a member, never an interpolation
        for q in (0.0, 10.0, 37.5, 50.0, 90.0, 99.0, 100.0):
            assert percentile(values, q) in values

    def test_errors(self):
        from distributedpytorch_tpu.utils.profiling import percentile
        with pytest.raises(ValueError, match="no samples"):
            percentile([], 50.0)
        with pytest.raises(ValueError, match="0, 100"):
            percentile([1.0], 101.0)


class TestTrace:
    def test_annotate_context(self):
        with annotate("region"):
            x = jnp.ones((4,)) * 2
        assert float(x.sum()) == 8.0

    @pytest.mark.slow  # tier-1 budget (PR 18): a real XPlane capture
    # start/stop costs ~30s on the CPU mesh; the annotate path keeps its
    # fast gate (test_annotate_context) and the captured-trace contents
    # stay covered by test_telemetry's slow XPlane lowering test
    def test_trace_writes_files(self, tmp_path):
        d = str(tmp_path / "prof")
        with trace(d):
            jnp.ones((8, 8)).sum().block_until_ready()
        assert os.path.isdir(d) and len(os.listdir(d)) > 0


class TestThroughput:
    def test_counts_and_rate(self):
        from distributedpytorch_tpu.utils.profiling import throughput
        calls = []

        def step():
            calls.append(1)
            return jnp.ones((2, 2)).sum()

        s = throughput(step, steps=3, warmup=2, items_per_step=4)
        assert len(calls) == 5  # warmup excluded from timing, included in calls
        assert s["steps"] == 3 and s["total_s"] > 0
        assert s["items_per_sec"] == pytest.approx(12 / s["total_s"])


def test_device_memory_stats_shape():
    from distributedpytorch_tpu.utils.profiling import device_memory_stats

    stats = device_memory_stats()
    assert set(stats) == {"bytes_in_use", "peak_bytes_in_use", "bytes_limit"}
    assert all(isinstance(v, int) and v >= 0 for v in stats.values())
