"""Metric writers (train/logging.py) — the reference's observability
surface (SURVEY §5.5) as a uniform writer family, including the Comet
backend the reference actually used (train_pascal.py:41,276), key from env.
"""

import json
import os
import sys
import types

import pytest

from distributedpytorch_tpu.train.logging import (
    CometWriter,
    ConsoleWriter,
    JsonlWriter,
    MultiWriter,
    TensorBoardWriter,
    make_writer,
)


class FakeExperiment:
    """Captures the comet_ml.Experiment calls CometWriter makes."""

    instances: list = []

    def __init__(self, **kw):
        self.kw = kw
        self.metrics = []
        self.figures = []
        self.params = None
        self.name = None
        self.ended = False
        FakeExperiment.instances.append(self)

    def set_name(self, name):
        self.name = name

    def log_metrics(self, d, step=None):
        self.metrics.append((dict(d), step))

    def log_figure(self, figure_name=None, figure=None, step=None):
        self.figures.append((figure_name, step))

    def log_parameters(self, d):
        self.params = dict(d)

    def end(self):
        self.ended = True


@pytest.fixture
def fake_comet(monkeypatch):
    mod = types.ModuleType("comet_ml")
    mod.Experiment = FakeExperiment
    monkeypatch.setitem(sys.modules, "comet_ml", mod)
    monkeypatch.setenv("COMET_API_KEY", "test-key")
    FakeExperiment.instances = []
    return mod


class TestCometWriter:
    def test_logs_scalars_figures_hparams(self, fake_comet):
        w = CometWriter(project="proj", workspace="ws",
                        experiment_name="run-1")
        w.scalars({"loss": 1.5, "note": "skipme"}, step=3)
        w.figure("panels", object(), step=3)
        w.hparams({"lr": 5e-8})
        w.close()
        exp = FakeExperiment.instances[0]
        assert exp.kw["project_name"] == "proj"
        assert exp.kw["workspace"] == "ws"
        assert exp.name == "run-1"
        # non-numeric scalars are filtered; the rest land with the step
        assert exp.metrics == [({"loss": 1.5}, 3)]
        assert exp.figures == [("panels", 3)]
        assert exp.params == {"lr": "5e-08"}
        assert exp.ended

    def test_no_key_degrades_to_noop(self, fake_comet, monkeypatch, capsys):
        monkeypatch.delenv("COMET_API_KEY")
        w = CometWriter()
        assert "CometWriter disabled" in capsys.readouterr().out
        w.scalars({"loss": 1.0}, 1)  # must not raise
        w.close()
        assert FakeExperiment.instances == []

    def test_no_sdk_degrades_to_noop(self, monkeypatch, capsys):
        monkeypatch.setitem(sys.modules, "comet_ml", None)  # import fails
        w = CometWriter()
        assert "CometWriter disabled" in capsys.readouterr().out
        w.figure("x", object(), 0)  # must not raise


class TestMakeWriter:
    def test_selects_each_backend(self, tmp_path, fake_comet):
        assert isinstance(make_writer("console", str(tmp_path)),
                          ConsoleWriter)
        assert isinstance(make_writer("jsonl", str(tmp_path)), JsonlWriter)
        assert isinstance(make_writer("tensorboard", str(tmp_path)),
                          TensorBoardWriter)
        assert isinstance(make_writer("comet", str(tmp_path)), CometWriter)

    def test_unknown_writer_raises(self, tmp_path):
        with pytest.raises(ValueError, match="unknown writer"):
            make_writer("wandb", str(tmp_path))


class TestJsonlWriter:
    def test_round_trip(self, tmp_path):
        w = JsonlWriter(str(tmp_path))
        w.scalars({"loss": 2.0}, step=1)
        w.hparams({"lr": 1e-3})
        w.flush()
        w.close()
        lines = [json.loads(l) for l in
                 open(os.path.join(str(tmp_path), "metrics.jsonl"))]
        assert any(l.get("loss") == 2.0 for l in lines)

    def test_nonfinite_serializes_as_null(self, tmp_path):
        # regression: json.dumps writes bare NaN/Infinity (a Python
        # extension no strict parser accepts) — a diverging run is exactly
        # when the log must stay machine-readable
        import numpy as np
        w = JsonlWriter(str(tmp_path))
        w.scalars({"loss": float("nan"), "lr": float("inf"),
                   "g": float("-inf"), "ok": 1.5,
                   "np_nan": np.float32("nan")}, step=7)
        w.close()
        def no_constants(s):
            raise AssertionError(f"bare {s} in metrics.jsonl")
        [rec] = [json.loads(l, parse_constant=no_constants) for l in
                 open(os.path.join(str(tmp_path), "metrics.jsonl"))]
        assert rec["loss"] is None and rec["lr"] is None
        assert rec["g"] is None and rec["ok"] == 1.5
        assert rec["np_nan"] is None

    def test_line_buffered_tail_survives_without_close(self, tmp_path):
        # a crashed run never reaches flush()/close(); the tail is the
        # diagnosis and must already be on disk
        w = JsonlWriter(str(tmp_path))
        w.scalars({"loss": 3.0}, step=1)
        with open(os.path.join(str(tmp_path), "metrics.jsonl")) as f:
            lines = f.readlines()
        assert lines and json.loads(lines[-1])["loss"] == 3.0
        w.close()


class TestTrainerWiring:
    @pytest.mark.slow  # tier-1 budget (PR 10): full trainer build just
    # to check writer wiring (~7s); writer selection keeps its fast
    # gate (TestMakeWriter.test_selects_each_backend)
    def test_log_writers_knob_builds_comet(self, tmp_path, fake_comet):
        import dataclasses

        from distributedpytorch_tpu.train import Config, Trainer, \
            apply_overrides

        cfg = apply_overrides(Config(), [
            "data.fake=true", "data.train_batch=8", "data.val_batch=2",
            "data.crop_size=[48,48]", "data.area_thres=0",
            "data.num_workers=0", "model.backbone=resnet18",
            "model.output_stride=8", "checkpoint.async_save=false",
            "epochs=1", "eval_every=1",
            "log_writers=[\"console\",\"jsonl\",\"comet\"]",
            "comet_project=Attention", "experiment_name=parity-run",
        ])
        cfg = dataclasses.replace(cfg, work_dir=str(tmp_path / "runs"))
        tr = Trainer(cfg)
        hist = tr.fit()
        tr.close()
        assert len(hist["train_loss"]) == 1
        exp = FakeExperiment.instances[0]
        assert exp.kw["project_name"] == "Attention"
        assert exp.name == "parity-run"
        assert any("train/epoch_loss" in m for m, _ in exp.metrics)
        assert any("val/jaccard" in m for m, _ in exp.metrics)
        assert exp.params and "optim.lr" in exp.params
        assert exp.figures, "val panels should reach Comet (the " \
            "reference's exp.log_figure flow)"
        assert exp.ended


class TestCometTransientErrors:
    def test_fails_counter_initialized_in_init(self, fake_comet):
        # _fails is part of the writer's state contract, not a lazy
        # getattr accident of the first error
        assert CometWriter()._fails == 0

    def test_nonconsecutive_failures_never_disable(self, fake_comet):
        # one success resets the consecutive-failure count: 2x(MAX-1)
        # failures with a success between must keep the writer alive
        w = CometWriter()
        exp = FakeExperiment.instances[0]
        boxed = {"dead": True}

        def flaky(d, step=None):
            if boxed["dead"]:
                raise ConnectionError("down")

        exp.log_metrics = flaky
        for i in range(CometWriter._MAX_FAILS - 1):
            w.scalars({"a": float(i)}, i)
        assert w._fails == CometWriter._MAX_FAILS - 1
        boxed["dead"] = False
        w.scalars({"a": 0.0}, 99)          # success resets the count
        assert w._fails == 0
        boxed["dead"] = True
        for i in range(CometWriter._MAX_FAILS - 1):
            w.scalars({"a": float(i)}, i)
        assert w._exp is not None, \
            "non-consecutive failures must not disable the writer"

    def test_transient_error_retries_then_recovers(self, fake_comet, capsys):
        w = CometWriter()
        exp = FakeExperiment.instances[0]
        calls = {"n": 0}

        def flaky(d, step=None):
            calls["n"] += 1
            if calls["n"] <= 2:
                raise ConnectionError("blip")
            exp.metrics.append((dict(d), step))

        exp.log_metrics = flaky
        w.scalars({"a": 1.0}, 1)   # fails
        w.scalars({"a": 2.0}, 2)   # fails
        w.scalars({"a": 3.0}, 3)   # recovers
        assert w._exp is not None, "two blips must not disable the writer"
        assert exp.metrics == [({"a": 3.0}, 3)]
        assert "will retry" in capsys.readouterr().out

    def test_persistent_errors_disable_after_threshold(self, fake_comet,
                                                       capsys):
        w = CometWriter()
        exp = FakeExperiment.instances[0]

        def dead(d, step=None):
            raise ConnectionError("down")

        exp.log_metrics = dead
        for i in range(CometWriter._MAX_FAILS):
            w.scalars({"a": float(i)}, i)
        assert w._exp is None
        assert "disabled after" in capsys.readouterr().out
