"""Val fast path (data.val_prepared, VERDICT r3 item 3): prepared eval
caches, the uint8 val wire, device-guidance eval preprocessing, and metric
parity against the plain (uncached) validation protocol.

The eval protocol is deterministic end to end (reference
train_pascal.py:135-145, 233-308), so the entire per-epoch val front —
decode, crop, resize, guidance, plus the full-res metric masks — is
cacheable.  What these tests pin down:

* the cached eval sample carries the evaluator's exact contract (wire keys
  + host-side ``gt``/``void_pixels``/``bbox``), with the full-res masks
  BIT-EXACT vs the plain pipeline (they feed the metric; rounding there
  would change reported Jaccards);
* the uint8 wire serves uint8 (the measured 25 MB f32 semantic val batch
  was the 1 img/s bound, BASELINE.md ‡);
* the end-to-end metric matches the plain path.
"""

import dataclasses

import numpy as np
import pytest

from distributedpytorch_tpu.data import (
    DataLoader,
    PreparedInstanceDataset,
    VOCInstanceSegmentation,
    build_eval_transform,
)
from distributedpytorch_tpu.data.pipeline import (
    build_prepared_eval_post_transform,
    build_prepared_semantic_eval_post_transform,
    build_semantic_eval_transform,
)
from distributedpytorch_tpu.data.prepared import PreparedSemanticDataset
from distributedpytorch_tpu.data.voc import VOCSemanticSegmentation


def make_base(root):
    return VOCInstanceSegmentation(root, split="val", transform=None,
                                   preprocess=True, area_thres=0)


@pytest.fixture()
def base(fake_voc_root):
    return make_base(fake_voc_root)


@pytest.fixture()
def plain(fake_voc_root):
    return VOCInstanceSegmentation(
        fake_voc_root, split="val", preprocess=True, area_thres=0,
        transform=build_eval_transform(crop_size=(64, 64), relax=10))


def make_eval_cache(base, tmp_path, uint8=False, guidance="nellipse_gaussians"):
    return PreparedInstanceDataset(
        base, str(tmp_path / "prep"), crop_size=(64, 64), relax=10,
        uint8_arrays=uint8, eval_protocol=True, max_im_size=(256, 256),
        post_transform=build_prepared_eval_post_transform(
            guidance=guidance, uint8_wire=uint8))


class TestInstanceEvalCache:
    def test_contract_vs_plain_pipeline(self, base, plain, tmp_path):
        ds = make_eval_cache(base, tmp_path)
        assert len(ds) == len(plain)
        for i in (0, 1, len(ds) - 1):
            got = ds[i]
            want = plain[i]
            # full-res metric masks: BIT-exact (they feed the Jaccard)
            np.testing.assert_array_equal(
                np.asarray(got["gt"], bool),
                np.asarray(want["gt"], bool).reshape(got["gt"].shape))
            np.testing.assert_array_equal(
                np.asarray(got["void_pixels"], bool),
                np.asarray(want["void_pixels"],
                           bool).reshape(got["void_pixels"].shape))
            np.testing.assert_array_equal(got["bbox"], want["bbox"])
            # crop_gt binary + exact; image within uint8 rounding
            np.testing.assert_array_equal(
                got["crop_gt"], np.asarray(want["crop_gt"], np.float32))
            assert got["concat"].shape == want["concat"].shape
            assert np.abs(got["concat"][..., :3]
                          - want["concat"][..., :3]).max() <= 0.5
            # guidance channel: same crop_gt in, same deterministic points
            # out — differences can only come from the rounded image (none)
            np.testing.assert_allclose(got["concat"][..., 3],
                                       want["concat"][..., 3],
                                       atol=1e-3)

    def test_second_access_never_touches_source(self, base, tmp_path):
        ds = make_eval_cache(base, tmp_path)
        ds.prebuild()
        first = ds[0]

        def boom(i):
            raise AssertionError("source dataset touched after prebuild")

        ds.dataset.__getitem__ = boom
        again = ds[0]
        np.testing.assert_array_equal(first["concat"], again["concat"])
        np.testing.assert_array_equal(first["gt"], again["gt"])

    def test_uint8_wire_dtypes(self, base, tmp_path):
        ds = make_eval_cache(base, tmp_path, uint8=True, guidance="none")
        s = ds[0]
        assert s["concat"].dtype == np.uint8 and s["concat"].shape[-1] == 3
        assert s["crop_gt"].dtype == np.uint8
        assert set(np.unique(s["crop_gt"])) <= {0, 1}

    def test_eval_cache_dir_distinct_from_train(self, base, tmp_path):
        train_ds = PreparedInstanceDataset(base, str(tmp_path / "prep"),
                                           crop_size=(64, 64), relax=10)
        eval_ds = make_eval_cache(base, tmp_path)
        assert train_ds.cache_dir != eval_ds.cache_dir

    def test_oversize_image_raises_with_guidance(self, base, tmp_path):
        ds = PreparedInstanceDataset(
            base, str(tmp_path / "prep"), crop_size=(64, 64), relax=10,
            eval_protocol=True, max_im_size=(8, 8),
            post_transform=build_prepared_eval_post_transform())
        with pytest.raises(ValueError, match="max_im_size"):
            ds[0]


class TestSemanticEvalCache:
    def test_contract_vs_plain_pipeline(self, fake_voc_root, tmp_path):
        base = VOCSemanticSegmentation(fake_voc_root, split="val",
                                       transform=None)
        plain = VOCSemanticSegmentation(
            fake_voc_root, split="val",
            transform=build_semantic_eval_transform(crop_size=(65, 65)))
        ds = PreparedSemanticDataset(
            base, str(tmp_path / "prep"), crop_size=(65, 65),
            post_transform=build_prepared_semantic_eval_post_transform())
        assert len(ds) == len(plain)
        for i in range(len(ds)):
            got, want = ds[i], plain[i]
            # class ids resized nearest: integer-exact
            np.testing.assert_array_equal(
                got["crop_gt"], np.asarray(want["crop_gt"], np.float32))
            assert np.abs(got["concat"] - want["concat"]).max() <= 0.5

    def test_uint8_wire_dtypes(self, fake_voc_root, tmp_path):
        base = VOCSemanticSegmentation(fake_voc_root, split="val",
                                       transform=None)
        ds = PreparedSemanticDataset(
            base, str(tmp_path / "prep"), crop_size=(65, 65),
            uint8_arrays=True,
            post_transform=build_prepared_semantic_eval_post_transform(
                uint8_wire=True))
        s = ds[0]
        assert s["concat"].dtype == np.uint8
        assert s["crop_gt"].dtype == np.uint8

    def test_fullres_gt_cached_exactly(self, fake_voc_root, tmp_path):
        """eval_full_res protocol: the native-resolution class-id mask is
        cached in padded uint8 rows and must come back BIT-exact (it is
        the metric's ground truth) alongside the resized wire keys."""
        base = VOCSemanticSegmentation(fake_voc_root, split="val",
                                       transform=None)
        plain = VOCSemanticSegmentation(
            fake_voc_root, split="val",
            transform=build_semantic_eval_transform(crop_size=(65, 65),
                                                    keep_fullres=True))
        ds = PreparedSemanticDataset(
            base, str(tmp_path / "prep"), crop_size=(65, 65),
            keep_fullres=True, max_im_size=(256, 256),
            post_transform=build_prepared_semantic_eval_post_transform())
        for i in range(len(ds)):
            got, want = ds[i], plain[i]
            np.testing.assert_array_equal(
                got["gt_full"],
                np.asarray(want["gt_full"],
                           np.uint8).reshape(got["gt_full"].shape))
        # distinct cache dir from the crop-res eval cache
        crop_only = PreparedSemanticDataset(
            base, str(tmp_path / "prep"), crop_size=(65, 65),
            post_transform=build_prepared_semantic_eval_post_transform())
        assert crop_only.cache_dir != ds.cache_dir

    def test_fullres_oversize_raises(self, fake_voc_root, tmp_path):
        base = VOCSemanticSegmentation(fake_voc_root, split="val",
                                       transform=None)
        ds = PreparedSemanticDataset(
            base, str(tmp_path / "prep"), crop_size=(65, 65),
            keep_fullres=True, max_im_size=(8, 8),
            post_transform=build_prepared_semantic_eval_post_transform())
        with pytest.raises(ValueError, match="val_max_im_size"):
            ds[0]


class TestTrainerIntegration:
    def _cfg(self, root, tmp_path, **over):
        from distributedpytorch_tpu.train import Config, apply_overrides
        cfg = apply_overrides(Config(), [
            f"data.root={root}", "data.train_batch=8", "data.val_batch=2",
            "data.crop_size=[64,64]", "data.relax=10", "data.area_thres=0",
            "model.backbone=resnet18", "model.output_stride=8",
            "optim.lr=1e-4", "checkpoint.async_save=false", "epochs=1",
            *[f"{k}={v}" for k, v in over.items()]])
        return dataclasses.replace(cfg, work_dir=str(tmp_path / "runs"))

    def test_val_metric_parity_plain_vs_prepared(self, fake_voc_root,
                                                 tmp_path):
        """Same state, same protocol: the prepared+uint8+device-guidance
        val path must reproduce the plain path's Jaccard to within the
        uint8 image rounding (<0.5/255 input perturbation)."""
        from distributedpytorch_tpu.train import Trainer

        tr_plain = Trainer(self._cfg(fake_voc_root, tmp_path / "a"))
        m_plain = tr_plain.validate(epoch=0)
        tr_fast = Trainer(self._cfg(
            fake_voc_root, tmp_path / "b",
            **{"data.prepared_cache": str(tmp_path / "cache"),
               "data.uint8_transfer": "true",
               "data.device_guidance": "true"}))
        # identical params: copy the plain trainer's state
        tr_fast.state = tr_plain.state
        m_fast = tr_fast.validate(epoch=0)
        assert m_fast["n_samples"] == m_plain["n_samples"]
        assert abs(m_fast["jaccard"] - m_plain["jaccard"]) < 2e-2
        for th in ("0.3", "0.5", "0.8"):
            assert abs(m_fast["jaccard_per_threshold"][th]
                       - m_plain["jaccard_per_threshold"][th]) < 2e-2
        tr_plain.close()
        tr_fast.close()

    @pytest.mark.slow  # tier-1 budget (PR 7): packbits val parity
    # (~9s); the packbits wire keeps its fast train-side gate in
    # test_prepared
    def test_val_parity_with_packed_mask_wire(self, fake_voc_root,
                                              tmp_path):
        """data.packbits_masks now rides the VAL wire too (1-bit crop_gt,
        unpacked inside the eval step): metrics must match the plain
        protocol like the unpacked fast path does."""
        from distributedpytorch_tpu.train import Trainer

        tr_plain = Trainer(self._cfg(fake_voc_root, tmp_path / "a"))
        m_plain = tr_plain.validate(epoch=0)
        tr_fast = Trainer(self._cfg(
            fake_voc_root, tmp_path / "b",
            **{"data.prepared_cache": str(tmp_path / "cache"),
               "data.uint8_transfer": "true",
               "data.device_guidance": "true",
               "data.packbits_masks": "true",
               "debug_asserts": "true"}))
        sample = tr_fast.val_set[0]
        h, w = tr_fast.cfg.data.crop_size
        assert sample["crop_gt"].shape == ((h * w + 7) // 8,)
        tr_fast.state = tr_plain.state
        m_fast = tr_fast.validate(epoch=0)
        assert abs(m_fast["jaccard"] - m_plain["jaccard"]) < 2e-2
        # the panels contract: the vis record must carry the UNPACKED
        # mask (the 1-bit wire row would crash make_val_panels silently)
        from distributedpytorch_tpu.train.evaluate import evaluate
        from distributedpytorch_tpu.train.logging import make_val_panels
        m = evaluate(tr_fast.eval_step, tr_fast.state, tr_fast.val_loader,
                     mesh=tr_fast.mesh, packed_masks=True)
        fb = m["_first_batch"]
        assert np.asarray(fb["batch"]["crop_gt"]).shape[1:] == (h, w)
        fig = make_val_panels(fb)
        assert fig is not None
        tr_plain.close()
        tr_fast.close()

    @pytest.mark.slow  # tier-1 budget (PR 18): two semantic fits
    # (~15s); the semantic eval cache keeps its fast contract gate
    # (TestSemanticEvalCache.test_contract_vs_plain_pipeline) and the
    # instance-task parity e2e stays in tier-1 above
    def test_semantic_val_parity(self, tmp_path):
        from distributedpytorch_tpu.data import make_fake_voc
        from distributedpytorch_tpu.train import Trainer

        fake_voc_root = make_fake_voc(str(tmp_path / "voc"), n_images=12,
                                      size=(96, 128), n_val=3, seed=3)
        sem = {"task": "semantic", "model.name": "deeplabv3",
               "model.nclass": 21, "model.in_channels": 3,
               "data.crop_size": "[65,65]"}
        tr_plain = Trainer(self._cfg(fake_voc_root, tmp_path / "a", **sem))
        m_plain = tr_plain.validate(epoch=0)
        tr_fast = Trainer(self._cfg(
            fake_voc_root, tmp_path / "b", **sem,
            **{"data.prepared_cache": str(tmp_path / "cache"),
               "data.uint8_transfer": "true"}))
        tr_fast.state = tr_plain.state
        m_fast = tr_fast.validate(epoch=0)
        assert abs(m_fast["miou"] - m_plain["miou"]) < 2e-2
        tr_plain.close()
        tr_fast.close()

    @pytest.mark.slow  # tier-1 budget (PR 7): TTA x prepared-val
    # composition (~12s); each half keeps its own fast gate
    def test_semantic_tta_composes_with_prepared_val(self, tmp_path):
        """Multi-scale + flip TTA reads the val batch host-side and
        re-forwards resized copies — it must compose with the uint8
        prepared val wire and match the plain path's TTA mIoU."""
        from distributedpytorch_tpu.data import make_fake_voc
        from distributedpytorch_tpu.train import Trainer

        fake_voc_root = make_fake_voc(str(tmp_path / "voc"), n_images=12,
                                      size=(96, 128), n_val=3, seed=9)
        sem = {"task": "semantic", "model.name": "deeplabv3",
               "model.nclass": 21, "model.in_channels": 3,
               "data.crop_size": "[65,65]",
               "eval_tta_scales": "[0.75,1.0]", "eval_tta_flip": "true"}
        tr_plain = Trainer(self._cfg(fake_voc_root, tmp_path / "a", **sem))
        m_plain = tr_plain.validate(epoch=0)
        tr_fast = Trainer(self._cfg(
            fake_voc_root, tmp_path / "b", **sem,
            **{"data.prepared_cache": str(tmp_path / "cache"),
               "data.uint8_transfer": "true"}))
        tr_fast.state = tr_plain.state
        m_fast = tr_fast.validate(epoch=0)
        assert abs(m_fast["miou"] - m_plain["miou"]) < 2e-2
        tr_plain.close()
        tr_fast.close()

    @pytest.mark.slow  # tier-1 budget (PR 10): fullres x prepared-val
    # composition (~7s); the fullres cache contract keeps its unit gate
    # (TestSemanticEvalCache.test_fullres_gt_cached_exactly) and the
    # crop-res prepared-val parity stays (test_semantic_val_parity)
    def test_semantic_fullres_val_parity(self, tmp_path):
        from distributedpytorch_tpu.data import make_fake_voc
        from distributedpytorch_tpu.train import Trainer

        fake_voc_root = make_fake_voc(str(tmp_path / "voc"), n_images=12,
                                      size=(96, 128), n_val=3, seed=5)
        sem = {"task": "semantic", "model.name": "deeplabv3",
               "model.nclass": 21, "model.in_channels": 3,
               "data.crop_size": "[65,65]", "eval_full_res": "true",
               "data.val_max_im_size": "[256,256]"}
        tr_plain = Trainer(self._cfg(fake_voc_root, tmp_path / "a", **sem))
        m_plain = tr_plain.validate(epoch=0)
        tr_fast = Trainer(self._cfg(
            fake_voc_root, tmp_path / "b", **sem,
            **{"data.prepared_cache": str(tmp_path / "cache"),
               "data.uint8_transfer": "true"}))
        tr_fast.state = tr_plain.state
        m_fast = tr_fast.validate(epoch=0)
        assert abs(m_fast["miou"] - m_plain["miou"]) < 2e-2
        tr_plain.close()
        tr_fast.close()

    @pytest.mark.slow  # tier-1 budget (PR 18): two full-res fits
    # (~15s); the device-warp wire keeps its fast gates
    # (TestSemanticEvalCache full-res contracts) and fullres parity
    # stays slow-gated (test_semantic_fullres_val_parity)
    def test_semantic_fullres_device_vs_host_path(self, tmp_path):
        """eval_device_fullres=true (device warp + uint8 class-map wire)
        must reproduce the host resize path's full-res mIoU through the
        real Trainer."""
        from distributedpytorch_tpu.data import make_fake_voc
        from distributedpytorch_tpu.train import Trainer

        fake_voc_root = make_fake_voc(str(tmp_path / "voc"), n_images=12,
                                      size=(96, 128), n_val=3, seed=13)
        sem = {"task": "semantic", "model.name": "deeplabv3",
               "model.nclass": 21, "model.in_channels": 3,
               "data.crop_size": "[65,65]", "eval_full_res": "true",
               "data.val_max_im_size": "[256,256]"}
        tr_host = Trainer(self._cfg(fake_voc_root, tmp_path / "a", **sem,
                                    eval_device_fullres="false"))
        m_host = tr_host.validate(epoch=0)
        tr_dev = Trainer(self._cfg(fake_voc_root, tmp_path / "b", **sem,
                                   eval_device_fullres="true"))
        tr_dev.state = tr_host.state
        m_dev = tr_dev.validate(epoch=0)
        # same protocol arithmetic on device; only f32-association /
        # argmax-tie noise may move individual boundary pixels
        assert abs(m_dev["miou"] - m_host["miou"]) < 1e-3
        assert m_dev["n_samples"] == m_host["n_samples"]
        tr_host.close()
        tr_dev.close()

    def test_instance_bf16_readback_parity(self, fake_voc_root, tmp_path):
        """eval_bf16_probs now also halves the instance val logit D2H:
        bf16 logit rounding may flip boundary pixels at the thresholds but
        must not move the Jaccard beyond noise."""
        from distributedpytorch_tpu.train import Trainer

        tr = Trainer(self._cfg(fake_voc_root, tmp_path / "a"))
        m_bf16 = tr.validate(epoch=0)          # default: bf16 readback
        tr.cfg = dataclasses.replace(tr.cfg, eval_bf16_probs=False)
        m_f32 = tr.validate(epoch=0)
        assert abs(m_bf16["jaccard"] - m_f32["jaccard"]) < 1e-2
        tr.close()

    @pytest.mark.slow  # tier-1 budget (PR 20): overlap is opt-in and its
    # fit smoke is ~24s; fast gate:
    # test_val_prepared_off_keeps_plain_path (default path stays tier-1)
    def test_val_overlap_smoke(self, fake_voc_root, tmp_path):
        """Thin tier-1 smoke: one overlapped fit completes with a val
        entry per epoch and a best checkpoint.  The serial-vs-overlap
        curve-parity A/B (two 3-epoch fits, ~25s) is the `slow` variant
        below."""
        import glob

        from distributedpytorch_tpu.train import Trainer

        tr = Trainer(self._cfg(fake_voc_root, tmp_path / "ov",
                               **{"epochs": 2, "val_overlap": "true"}))
        hist = tr.fit()
        tr.close()
        assert len(hist["val"]) == 2
        assert all(np.isfinite(v["jaccard"]) for v in hist["val"])
        assert glob.glob(str(tmp_path / "ov" / "**" / "best*"),
                         recursive=True), "no best checkpoint"

    @pytest.mark.slow
    def test_val_overlap_matches_serial_fit(self, fake_voc_root, tmp_path):
        """val_overlap runs each validation concurrently with the next
        train epoch.  The evaluated states are identical to the serial
        schedule (training never waits on val), so the val curves must
        match; best-checkpoint gating must also land."""
        import glob

        from distributedpytorch_tpu.train import Trainer

        hists = {}
        for mode, flag in (("serial", "false"), ("overlap", "true")):
            tr = Trainer(self._cfg(fake_voc_root, tmp_path / mode,
                                   **{"epochs": 3,
                                      "val_overlap": flag}))
            hists[mode] = tr.fit()
            tr.close()
            assert glob.glob(str(tmp_path / mode / "**" / "best*"),
                             recursive=True), f"{mode}: no best checkpoint"
        assert len(hists["overlap"]["val"]) == \
            len(hists["serial"]["val"]) == 3
        for a, b in zip(hists["serial"]["val"], hists["overlap"]["val"]):
            assert abs(a["jaccard"] - b["jaccard"]) < 1e-5
        assert hists["serial"]["train_loss"] == pytest.approx(
            hists["overlap"]["train_loss"], abs=1e-6)

    def test_val_prepared_off_keeps_plain_path(self, fake_voc_root,
                                               tmp_path):
        from distributedpytorch_tpu.train import Trainer

        tr = Trainer(self._cfg(
            fake_voc_root, tmp_path,
            **{"data.prepared_cache": str(tmp_path / "cache"),
               "data.val_prepared": "false",
               "data.uint8_transfer": "true",
               "data.device_guidance": "true"}))
        assert not isinstance(tr.val_set, PreparedInstanceDataset)
        tr.close()
