"""Train subsystem: config, optimizer, checkpointing, and an end-to-end
Trainer.fit() on the fake-VOC fixture over the 8-device CPU mesh."""

import dataclasses
import os

import jax
import numpy as np
import optax
import pytest

from distributedpytorch_tpu.train import (
    CheckpointManager,
    Config,
    Trainer,
    apply_overrides,
    flatten,
    from_json,
    make_optimizer,
    make_schedule,
    next_run_dir,
    to_json,
)
from distributedpytorch_tpu.train.config import OptimConfig


class TestConfig:
    def test_defaults_match_reference_point(self):
        cfg = Config()
        assert cfg.optim.lr == 5e-8 and cfg.optim.momentum == 0.9
        assert cfg.optim.weight_decay == 5e-4
        assert cfg.data.train_batch == 16 and cfg.data.val_batch == 1
        assert cfg.data.crop_size == (512, 512)
        assert cfg.model.in_channels == 4 and cfg.model.nclass == 1
        assert cfg.eval_thresholds == (0.3, 0.5, 0.8)
        assert cfg.epochs == 100 and cfg.eval_every == 1

    def test_json_roundtrip(self, tmp_path):
        cfg = Config()
        path = str(tmp_path / "c.json")
        to_json(cfg, path)
        cfg2 = from_json(path)
        assert cfg2 == cfg

    def test_overrides(self):
        cfg = Config()
        cfg2 = apply_overrides(cfg, ["optim.lr=0.001", "epochs=3",
                                     "model.backbone=resnet18",
                                     "data.crop_size=[64, 64]"])
        assert cfg2.optim.lr == 0.001 and cfg2.epochs == 3
        assert cfg2.model.backbone == "resnet18"
        assert cfg2.data.crop_size == (64, 64)
        assert cfg.optim.lr == 5e-8  # original untouched

    def test_unknown_override_raises(self):
        with pytest.raises(KeyError):
            apply_overrides(Config(), ["optim.nope=1"])

    def test_flatten(self):
        flat = flatten(Config())
        assert flat["optim.lr"] == 5e-8
        assert flat["data.train_batch"] == 16


class TestOptim:
    def test_constant_schedule(self):
        s = make_schedule(OptimConfig(lr=0.1, schedule="constant"), 100)
        assert float(s(0)) == float(s(99)) == pytest.approx(0.1)

    def test_poly_schedule_decays_to_zero(self):
        s = make_schedule(OptimConfig(lr=0.1, schedule="poly"), 100)
        assert float(s(0)) == pytest.approx(0.1)
        assert 0 < float(s(50)) < 0.1
        assert float(s(100)) == pytest.approx(0.0, abs=1e-9)

    def test_warmup(self):
        s = make_schedule(
            OptimConfig(lr=0.1, schedule="poly", warmup_steps=10), 100)
        assert float(s(0)) == pytest.approx(0.0)
        assert float(s(10)) == pytest.approx(0.1)

    def test_cosine_schedule(self):
        s = make_schedule(OptimConfig(lr=0.1, schedule="cosine"), 100)
        assert float(s(0)) == pytest.approx(0.1)
        assert float(s(50)) == pytest.approx(0.05, rel=1e-3)  # half-cosine
        assert float(s(100)) == pytest.approx(0.0, abs=1e-9)
        warm = make_schedule(
            OptimConfig(lr=0.1, schedule="cosine", warmup_steps=10), 100)
        assert float(warm(0)) == pytest.approx(0.0)
        assert float(warm(10)) == pytest.approx(0.1)

    def test_unknown_schedule_raises(self):
        with pytest.raises(ValueError, match="cosine"):
            make_schedule(OptimConfig(schedule="nope"), 10)

    def test_adamw_first_step_matches_closed_form(self):
        # Adam step 1 from zero moments: m=(1-b1)g, v=(1-b2)g^2, with bias
        # correction the update is -lr*(g/(|g|+eps)) - lr*wd*p (decoupled).
        cfg = OptimConfig(name="adamw", lr=0.1, weight_decay=0.01,
                          schedule="constant")
        tx, _ = make_optimizer(cfg, 10)
        p = {"w": np.float32(2.0)}
        g = {"w": np.float32(0.5)}
        st = tx.init(p)
        upd, _ = tx.update(g, st, p)
        expected = -0.1 * (0.5 / (0.5 + 1e-8)) - 0.1 * 0.01 * 2.0
        np.testing.assert_allclose(float(upd["w"]), expected, rtol=1e-5)

    def test_adamw_composes_with_param_groups(self):
        cfg = OptimConfig(name="adamw", lr=0.1, weight_decay=0.0,
                          schedule="constant", freeze=("frozen_tree",),
                          lr_mult={"head": 10.0})
        tx, _ = make_optimizer(cfg, 10)
        p = {"frozen_tree": {"w": np.float32(1.0)},
             "head": {"w": np.float32(1.0)},
             "base": {"w": np.float32(1.0)}}
        g = {k: {"w": np.float32(0.5)} for k in p}
        upd, _ = tx.update(g, tx.init(p), p)
        assert float(upd["frozen_tree"]["w"]) == 0.0
        np.testing.assert_allclose(
            float(upd["head"]["w"]), 10.0 * float(upd["base"]["w"]),
            rtol=1e-5)

    def test_unknown_optimizer_raises(self):
        with pytest.raises(ValueError, match="adamw"):
            make_optimizer(OptimConfig(name="lion"), 10)

    def test_sgd_weight_decay_matches_torch_semantics(self):
        # torch: grad <- grad + wd*p, then momentum buffer. One step from
        # zero momentum: update = -lr * (g + wd*p).
        cfg = OptimConfig(lr=0.1, momentum=0.9, weight_decay=0.01,
                          schedule="constant")
        tx, _ = make_optimizer(cfg, 10)
        p = {"w": np.float32(2.0)}
        g = {"w": np.float32(0.5)}
        st = tx.init(p)
        upd, _ = tx.update(g, st, p)
        expected = -0.1 * (0.5 + 0.01 * 2.0)
        np.testing.assert_allclose(float(upd["w"]), expected, rtol=1e-6)


class TestRunDirs:
    def test_auto_increment(self, tmp_path):
        d = str(tmp_path)
        assert next_run_dir(d).endswith("run_0")
        assert next_run_dir(d).endswith("run_1")
        assert next_run_dir(d, resume_run=0).endswith("run_0")


class TestCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        from distributedpytorch_tpu.parallel import create_train_state
        import flax.linen as nn

        class M(nn.Module):
            @nn.compact
            def __call__(self, x, train=False):
                return (nn.Dense(2)(x),)

        tx = optax.sgd(0.1, momentum=0.9)
        state = create_train_state(jax.random.PRNGKey(0), M(), tx, (1, 4))
        mgr = CheckpointManager(str(tmp_path / "ck"), keep_latest=2,
                                async_save=False)
        assert mgr.save(1, state, metric=0.5)        # first best
        assert not mgr.save(2, state, metric=0.4)    # not better
        assert mgr.save(3, state, metric=0.7)        # new best
        restored, meta = mgr.restore(state)
        assert meta["step"] == 3
        for a, b in zip(jax.tree.leaves(state.params),
                        jax.tree.leaves(restored.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        best, bmeta = mgr.restore(state, best=True)
        assert bmeta["metric"] == pytest.approx(0.7)
        mgr.close()


def make_tiny_cfg(work: str):
    """The canonical tiny trainer config every e2e test builds on."""
    cfg = Config()
    return dataclasses.replace(
        cfg,
        data=dataclasses.replace(
            cfg.data, fake=True, train_batch=8, val_batch=2, num_workers=2,
            crop_size=(64, 64), relax=10, area_thres=0),
        model=dataclasses.replace(cfg.model, backbone="resnet18",
                                  output_stride=8),
        optim=dataclasses.replace(cfg.optim, lr=1e-4, schedule="poly"),
        checkpoint=dataclasses.replace(cfg.checkpoint, async_save=False),
        epochs=2, eval_every=1, seed=0, work_dir=work,
        log_every_steps=1, debug_asserts=True,
    )


@pytest.fixture(scope="module")
def tiny_cfg(tmp_path_factory):
    return make_tiny_cfg(str(tmp_path_factory.mktemp("runs")))


class TestTrainerEndToEnd:
    def test_fit_runs_and_checkpoints(self, tiny_cfg):
        tr = Trainer(tiny_cfg)
        assert tr.n_params > 0
        history = tr.fit()
        assert len(history["train_loss"]) == 2
        assert all(np.isfinite(l) for l in history["train_loss"])
        assert len(history["val"]) == 2
        m = history["val"][-1]
        assert 0.0 <= m["jaccard"] <= 1.0
        assert set(m["jaccard_per_threshold"]) == {"0.3", "0.5", "0.8"}
        # artifacts: param report, config, metrics jsonl, checkpoints
        files = os.listdir(tr.run_dir)
        assert "config.json" in files and "experiment.txt" in files
        assert "metrics.jsonl" in files
        assert tr.ckpt.latest_step() == int(tr.state.step)
        tr.close()

    def test_resume_restores_exact_state(self, tiny_cfg):
        tr = Trainer(tiny_cfg)
        tr.fit()
        step = int(tr.state.step)
        ck_dir = os.path.join(tr.run_dir, "checkpoints")
        tr.close()

        cfg2 = dataclasses.replace(tiny_cfg, resume=ck_dir, epochs=2)
        tr2 = Trainer(cfg2)
        assert int(tr2.state.step) == step
        assert tr2.start_epoch == 2  # both epochs done; nothing left to run
        for a, b in zip(jax.tree.leaves(tr.state.params),
                        jax.tree.leaves(tr2.state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        tr2.close()


class TestBf16Config:
    """BASELINE config 3: bfloat16 mixed precision end-to-end."""

    def test_fit_one_epoch_bf16(self, tiny_cfg):
        import jax
        cfg = dataclasses.replace(
            tiny_cfg,
            model=dataclasses.replace(tiny_cfg.model, dtype="bfloat16"),
            epochs=1)
        tr = Trainer(cfg)
        # params stay f32 master copies; activations run bf16 via model dtype
        leaf = jax.tree.leaves(tr.state.params)[0]
        assert leaf.dtype == np.float32
        hist = tr.fit()
        assert np.isfinite(hist["train_loss"][0])
        assert 0.0 <= hist["val"][-1]["jaccard"] <= 1.0
        tr.close()


class TestDataEchoing:
    """data.echo (Choi et al. 2019): each loaded batch is stepped E times."""

    @pytest.mark.slow  # tier-1 budget (PR 18): full echoed fit
    # (~16s); the knob keeps its validation gate (test_echo_validated
    # below) and echo expansion stays covered by the governor
    # actuation tests and the slow sentinel/preemption echo suites
    def test_echo_multiplies_steps_per_epoch(self, tmp_path):
        base = make_tiny_cfg(str(tmp_path / "a"))
        cfg = dataclasses.replace(
            base, epochs=1,
            data=dataclasses.replace(base.data, echo=2,
                                     device_augment=True))
        tr = Trainer(cfg)
        n_batches = len(tr.train_loader)
        # the poly schedule must span echo x loader-length optimizer steps —
        # a schedule built without the echo factor clamps LR to 0 halfway
        assert float(tr.schedule(2 * n_batches - 1)) > 0.0
        tr.fit()
        assert int(tr.state.step) == 2 * n_batches
        tr.close()

    def test_echo_validated(self, tmp_path):
        cfg = make_tiny_cfg(str(tmp_path / "b"))
        cfg = dataclasses.replace(
            cfg, data=dataclasses.replace(cfg.data, echo=0))
        with pytest.raises(ValueError, match="echo"):
            Trainer(cfg)


class TestValPanels:
    """First-val-batch figure (reference train_pascal.py:257-278)."""

    def test_panels_from_evaluate_record(self, tiny_cfg):
        import matplotlib
        matplotlib.use("Agg", force=True)
        from distributedpytorch_tpu.train import evaluate, make_val_panels

        tr = Trainer(dataclasses.replace(tiny_cfg, epochs=1))
        with tr.mesh:
            metrics = evaluate(tr.eval_step, tr.state, tr.val_loader,
                               relax=tiny_cfg.data.relax, mesh=tr.mesh,
                               max_batches=1)
        first = metrics["_first_batch"]
        assert first is not None
        fig = make_val_panels(first, max_samples=2)
        # one row per sample, 4 panels: image+gt, fused, pam, cam
        assert len(fig.axes) % 4 == 0 and len(fig.axes) > 0
        # the image+gt overlay must be in imshow's float [0, 1] range — a
        # [0, 255] overlay clips to an all-white panel (regression)
        overlay = fig.axes[0].get_images()[0].get_array()
        assert float(overlay.max()) <= 1.0 + 1e-6
        assert float(overlay.min()) >= 0.0
        import matplotlib.pyplot as plt
        plt.close(fig)
        tr.close()


class TestCrashRecoveryTrajectory:
    """Crash-resume must be *exact*: train 2 epochs straight vs train 1,
    "crash", resume from the checkpoint, train 1 more — identical final
    params. Holds because the checkpoint carries optimizer state + RNG and
    the loader derives per-sample RNG from (seed, epoch, index), so the
    second epoch's data and noise are reproduced bit-for-bit. The reference
    could not make this guarantee (optimizer/RNG state never saved,
    SURVEY §3.5)."""

    @pytest.mark.slow  # tier-1 budget (PR 7): three full fits (~21s);
    # the fast gates are test_resume_restores_exact_state (exact
    # restore) + test_chaos's donation-safety regression unit
    def test_resumed_run_matches_straight_run(self, tiny_cfg):
        base = dataclasses.replace(
            tiny_cfg, eval_every=0, debug_asserts=False,
            checkpoint=dataclasses.replace(tiny_cfg.checkpoint,
                                           async_save=False,
                                           snapshot_every=1))
        # straight 2-epoch run
        tr_a = Trainer(dataclasses.replace(base, epochs=2))
        tr_a.fit()
        # interrupted run: 1 epoch, then resume into a fresh Trainer
        tr_b = Trainer(dataclasses.replace(base, epochs=1))
        tr_b.fit()
        ck = os.path.join(tr_b.run_dir, "checkpoints")
        tr_b.close()
        tr_c = Trainer(dataclasses.replace(base, epochs=2, resume=ck))
        assert tr_c.start_epoch == 1
        tr_c.fit()

        for a, c in zip(jax.tree.leaves(tr_a.state.params),
                        jax.tree.leaves(tr_c.state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
        for a, c in zip(jax.tree.leaves(tr_a.state.opt_state),
                        jax.tree.leaves(tr_c.state.opt_state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
        tr_a.close()
        tr_c.close()


class TestDeviceAugment:
    def test_host_flip_dropped_when_disabled(self):
        from distributedpytorch_tpu.data import build_train_transform
        from distributedpytorch_tpu.data import transforms as T
        stages = build_train_transform(flip=False).transforms
        assert not any(isinstance(s, T.RandomHorizontalFlip) for s in stages)
        stages_on = build_train_transform(flip=True).transforms
        assert any(isinstance(s, T.RandomHorizontalFlip) for s in stages_on)

    @pytest.mark.slow  # tier-1 budget (PR 20): full device-augment fit
    # (~10s); fast gate: test_device_guidance.py
    # test_e2e_device_guidance_with_device_augment +
    # test_grain_augment.py TestDeviceAugment units
    def test_fit_with_device_augment(self, tiny_cfg, tmp_path):
        cfg = dataclasses.replace(
            tiny_cfg,
            data=dataclasses.replace(tiny_cfg.data, device_augment=True),
            epochs=1, work_dir=str(tmp_path / "runs"))
        tr = Trainer(cfg)
        # The host pipeline must not flip (device stage owns it) ...
        from distributedpytorch_tpu.data import transforms as T
        assert not any(isinstance(s, T.RandomHorizontalFlip)
                       for s in tr.train_set.transform.transforms)
        hist = tr.fit()
        tr.close()
        assert np.isfinite(hist["train_loss"][0])
        assert 0.0 <= hist["val"][-1]["jaccard"] <= 1.0


class TestEmptyLoaderGuard:
    def test_oversized_batch_raises_at_construction(self, tiny_cfg, tmp_path):
        cfg = dataclasses.replace(
            tiny_cfg,
            data=dataclasses.replace(tiny_cfg.data, train_batch=512),
            work_dir=str(tmp_path / "runs"))
        with pytest.raises(ValueError, match="train loader is empty"):
            Trainer(cfg)


class TestProfileEpoch:
    @pytest.mark.slow  # tier-1 budget (PR 7): full fit under the
    # profiler (~22s); trace file writing stays fast-gated in
    # test_profiling.TestTrace
    def test_profile_epoch_writes_trace(self, tiny_cfg, tmp_path):
        cfg = dataclasses.replace(
            tiny_cfg, epochs=1, eval_every=0, work_dir=str(tmp_path / "runs"),
            profile_epoch=0)
        tr = Trainer(cfg)
        tr.fit()
        prof_dir = os.path.join(tr.run_dir, "profile")
        tr.close()
        assert os.path.isdir(prof_dir)
        found = []
        for dirpath, _, files in os.walk(prof_dir):
            found += [f for f in files if f.endswith(".xplane.pb")]
        assert found, "no xplane trace written"


class TestMoEConfig:
    """DANet-MoE variant end-to-end: router aux loss in the objective."""

    @pytest.mark.slow  # tier-1 budget (PR 7): full MoE fit (~9s);
    # router math/aux-loss semantics stay fast-gated in test_moe
    def test_fit_one_epoch_moe(self, tiny_cfg):
        cfg = dataclasses.replace(
            tiny_cfg,
            model=dataclasses.replace(tiny_cfg.model, moe_experts=2,
                                      moe_hidden=32,
                                      moe_capacity_factor=2.0),
            epochs=1)
        tr = Trainer(cfg)
        # expert-stacked params exist in the live state
        moe = tr.state.params["head"]["moe"]
        assert moe["w1"].shape[0] == 2
        history = tr.fit()
        assert all(np.isfinite(l) for l in history["train_loss"])
        assert 0.0 <= history["val"][-1]["jaccard"] <= 1.0
        tr.close()


class TestTorchWarmStart:
    """checkpoint.warm_start: the reference's unconditional .pth load
    (train_pascal.py:103) as a config knob."""

    def test_warm_start_imports_weights(self, tiny_cfg, tmp_path):
        import torch

        from distributedpytorch_tpu.utils.torch_interop import (
            params_to_torch_state_dict,
        )

        donor = Trainer(dataclasses.replace(tiny_cfg, epochs=1))
        # perturb the donor weights so the warm start provably overwrites
        # the (same-seed) fresh init
        donor_state = donor.state.replace(
            params=jax.tree.map(lambda x: x * 1.5 + 0.01,
                                donor.state.params))
        sd = params_to_torch_state_dict(donor_state.params,
                                        donor_state.batch_stats)
        pth = str(tmp_path / "donor.pth")
        torch.save({k: torch.from_numpy(np.asarray(v).copy())
                    for k, v in sd.items()}, pth)
        donor_params = jax.tree.leaves(donor_state.params)
        donor.close()

        cfg = dataclasses.replace(
            tiny_cfg,
            checkpoint=dataclasses.replace(tiny_cfg.checkpoint,
                                           warm_start=pth),
            epochs=1)
        tr = Trainer(cfg)
        for a, b in zip(donor_params, jax.tree.leaves(tr.state.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)
        assert int(tr.state.step) == 0  # weights only; fresh step/opt
        tr.close()

    def test_instance_task_requires_binary_head(self, tiny_cfg):
        cfg = dataclasses.replace(
            tiny_cfg, model=dataclasses.replace(tiny_cfg.model, nclass=2))
        with pytest.raises(ValueError, match="nclass=1"):
            Trainer(cfg)

    def test_warm_start_zero_matches_raises(self, tiny_cfg, tmp_path):
        import torch

        pth = str(tmp_path / "alien.pth")
        torch.save({"some.alien.weight": torch.zeros(3, 3)}, pth)
        cfg = dataclasses.replace(
            tiny_cfg,
            checkpoint=dataclasses.replace(tiny_cfg.checkpoint,
                                           warm_start=pth,
                                           warm_start_partial=True),
            epochs=1)
        with pytest.raises(ValueError, match="imported 0"):
            Trainer(cfg)


class TestCli:
    @pytest.mark.slow
    def test_module_cli_end_to_end(self, tmp_path):
        """python -m distributedpytorch_tpu must run on a forced-CPU env even
        when a site accelerator plugin overrides JAX_PLATFORMS."""
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=8",
                   PYTHONPATH=repo)
        r = subprocess.run(
            [sys.executable, "-m", "distributedpytorch_tpu", "--fake-data",
             "epochs=1", "data.train_batch=8", "data.val_batch=2",
             "data.crop_size=[64,64]", "data.relax=10", "data.area_thres=0",
             "model.backbone=resnet18", "model.output_stride=8",
             "optim.lr=1e-4", "checkpoint.async_save=false",
             f"work_dir={tmp_path}"],
            env=env, cwd=repo, capture_output=True, text=True, timeout=420)
        assert r.returncode == 0, r.stderr[-2000:]
        run_dir = os.path.join(tmp_path, "run_0")
        assert os.path.exists(os.path.join(run_dir, "config.json"))
        assert os.path.exists(os.path.join(run_dir, "metrics.jsonl"))


class TestCompileWatchdogIntegration:
    """The compiled train step must be steady-state: exactly ONE XLA
    compilation across a multi-step run.  The CompileWatchdog (runtime half
    of the analysis/jaxlint subsystem) turns a silent recompile — shape
    drift, donation mismatch, tracer branching — into a test failure."""

    def test_train_step_compiles_exactly_once_over_three_steps(self):
        import flax.linen as nn

        from distributedpytorch_tpu.parallel import (
            create_train_state,
            make_train_step,
        )
        from distributedpytorch_tpu.utils import CompileWatchdog

        class M(nn.Module):
            @nn.compact
            def __call__(self, x, train=False):
                return (nn.Conv(1, (3, 3))(x),)

        tx = optax.sgd(1e-3, momentum=0.9)
        model = M()
        state = create_train_state(jax.random.PRNGKey(0), model, tx,
                                   (1, 16, 16, 4))
        step = make_train_step(model, tx)
        r = np.random.RandomState(0)
        with CompileWatchdog(match="step_fn", max_compiles=1) as wd:
            for _ in range(3):
                batch = {
                    "concat": r.uniform(0, 255, (2, 16, 16, 4)
                                        ).astype(np.float32),
                    "crop_gt": (r.uniform(size=(2, 16, 16)) > 0.5
                                ).astype(np.float32),
                }
                state, loss = step(state, batch)
        # one compile at step 1, cache hits at steps 2-3 (max_compiles
        # would have raised otherwise; the exact-count assert documents it)
        assert wd.counts.get("step_fn") == 1
        assert np.isfinite(float(loss))


class TestAutoResume:
    @pytest.mark.slow  # tier-1 budget (PR 10): two-fit auto-resume
    # e2e (~12s); explicit-path resume keeps the fast gate
    # (test_resume_restores_exact_state) and resume=auto is exercised
    # by every fit_resume/supervise chaos scenario
    def test_resume_auto_finds_latest_run(self, tiny_cfg):
        work = tiny_cfg.work_dir
        tr = Trainer(dataclasses.replace(tiny_cfg, epochs=1))
        tr.fit()
        step = int(tr.state.step)
        tr.close()

        tr2 = Trainer(dataclasses.replace(tiny_cfg, epochs=2, resume="auto"))
        assert int(tr2.state.step) == step
        assert tr2.start_epoch == 1
        tr2.close()

    def test_resume_auto_fresh_when_no_checkpoints(self, tmp_path):
        cfg = dataclasses.replace(
            make_tiny_cfg(str(tmp_path)), epochs=1, resume="auto")
        tr = Trainer(cfg)
        assert int(tr.state.step) == 0 and tr.start_epoch == 0
        tr.close()


class TestDeviceGeomAugment:
    @pytest.mark.slow  # tier-1 budget (PR 7): full fit (~10s); the
    # device geom-augment fit path stays fast-gated by
    # test_grain_augment's semantic device-geom trainer fit
    def test_fit_with_on_device_scale_rotate(self, tiny_cfg):
        cfg = dataclasses.replace(
            tiny_cfg,
            data=dataclasses.replace(tiny_cfg.data, device_augment=True,
                                     device_augment_geom=True),
            epochs=1)
        tr = Trainer(cfg)
        hist = tr.fit()
        assert all(np.isfinite(l) for l in hist["train_loss"])
        tr.close()
