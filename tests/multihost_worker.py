"""Worker for the 2-process multi-host integration test.

Launched (twice) by tests/test_multihost.py with PROC_ID / NUM_PROCS /
COORD_ADDR / WORK_DIR / DATA_ROOT in the environment.  Each process gets 4
virtual CPU devices; ``jax.distributed.initialize`` joins them into one
8-device 2-host system — the same code path a real TPU pod takes (per-host
loader shards, ``make_array_from_process_local_data``, GSPMD collectives
across hosts, cross-process metric reduction, coordinated Orbax saves).

Prints one MULTIHOST_RESULT json line the parent asserts on.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    proc_id = int(os.environ["PROC_ID"])
    num_procs = int(os.environ["NUM_PROCS"])
    jax.distributed.initialize(
        coordinator_address=os.environ["COORD_ADDR"],
        num_processes=num_procs,
        process_id=proc_id,
    )
    assert jax.process_count() == num_procs
    assert jax.device_count() == 4 * num_procs

    import dataclasses

    from distributedpytorch_tpu.train import (
        Config,
        PreemptionGuard,
        Trainer,
        apply_overrides,
    )

    mode = os.environ.get("MODE", "train")
    overrides = [
        "data.train_batch=8", "data.val_batch=2", "data.crop_size=[48,48]",
        "data.relax=8", "data.area_thres=0", "data.num_workers=2",
        "model.backbone=resnet18", "model.output_stride=8",
        "optim.lr=1e-4", "checkpoint.async_save=false",
        "epochs=1", "eval_every=1", "log_every_steps=1",
    ]
    if mode == "preempt":
        overrides += ["epochs=200", "eval_every=0",
                      "checkpoint.snapshot_every=0", "log_every_steps=10000"]
    elif mode == "hybrid":
        # hierarchical DP over a 2-slice hybrid mesh (processes as DCN
        # granules — the documented fallback on platforms without
        # slice_index): same training, gradient all-reduce now spans an
        # intra-granule phase and a cross-granule phase
        overrides += ["mesh.slices=2"]
    elif mode == "prepared":
        # both processes share ONE prepared cache (train + eval) on the
        # common filesystem — the flock'd init and idempotent row fills
        # must survive two hosts racing, and the prepared VAL protocol
        # (uint8 wire + device guidance + packed full-res metric masks)
        # must reduce to identical global metrics on every host
        overrides += [
            "data.prepared_cache=" + os.path.join(
                os.environ["WORK_DIR"], "..", "prep_cache"),
            "data.uint8_transfer=true", "data.device_guidance=true",
            "data.packbits_masks=true",  # 1-bit crop_gt wire, both loops
            "data.val_max_im_size=[128,128]"]
    cfg = apply_overrides(Config(), overrides)
    cfg = dataclasses.replace(
        cfg, work_dir=os.environ["WORK_DIR"],
        data=dataclasses.replace(cfg.data, root=os.environ["DATA_ROOT"]))

    trainer = Trainer(cfg)
    if mode == "preempt":
        # The "signal" lands on process 1 ONLY; the consensus allgather must
        # stop BOTH processes at the same step, checkpoint once, and return.
        guard = PreemptionGuard(check_every=1)
        if proc_id == 1:
            import threading
            threading.Timer(8.0, guard.trip).start()
        with guard:
            history = trainer.fit(guard)
        extra = {
            "preempted": bool(history.get("preempted")),
            "locally_tripped": guard.triggered,
            "epochs_run": len(history["train_loss"]),
            "state_step": int(trainer.state.step),
        }
    else:
        history = trainer.fit()
        metrics = history["val"][-1]
        extra = {
            "n_local_devices": jax.local_device_count(),
            "train_loss": round(float(history["train_loss"][0]), 8),
            "jaccard": round(float(metrics["jaccard"]), 8),
            "n_samples": metrics["n_samples"],
            "train_batches": len(trainer.train_loader),
        }
    result = {
        "proc": proc_id,
        "run_dir": trainer.run_dir,
        "ckpt_step": trainer.ckpt.latest_step(),
        **extra,
    }
    trainer.close()
    print("MULTIHOST_RESULT " + json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
