"""Op-level device profile of the flagship train step (VERDICT item 2's
missing per-op evidence): run N steps under ``jax.profiler.trace``, convert
the XPlane capture to the XProf "hlo_stats" table, and print the top ops by
self time as JSON — plus write the raw trace for TensorBoard/xprof.

Usage:  python scripts/profile_step.py [--batch N] [--out DIR]
Writes <out>/plugins/profile/... (raw trace) and prints one JSON line with
the top-15 self-time ops and their category shares.
"""

from __future__ import annotations

import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_PYTHON_CLIENT_MEM_FRACTION", "0.92")
# tensorboard_plugin_profile's generated protos predate protobuf 4's C++
# fast path; pure-python parsing works and only runs at conversion time.
os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")

from distributedpytorch_tpu.backend_health import (  # noqa: E402
    ensure_backend_or_cpu_fallback,
    pin_requested_platform,
)

ensure_backend_or_cpu_fallback()

import jax  # noqa: E402

pin_requested_platform()

from distributedpytorch_tpu.backend_health import enable_compile_cache  # noqa: E402

enable_compile_cache()

import numpy as np  # noqa: E402
import optax  # noqa: E402

BATCH = 8
STEPS = 10
if "--batch" in sys.argv:
    BATCH = int(sys.argv[sys.argv.index("--batch") + 1])
OUT = "profile_step_out"
if "--out" in sys.argv:
    OUT = sys.argv[sys.argv.index("--out") + 1]
SCORE_DTYPE = None  # model.pam_score_dtype: profile the bf16-scores step
if "--score-dtype" in sys.argv:
    SCORE_DTYPE = sys.argv[sys.argv.index("--score-dtype") + 1]
#: --model deeplabv3 profiles BASELINE config 4 (DeepLabV3-R101 os=16 513²,
#: 21-class multi-output CE, 3-channel input) — the same shape bench.py's
#: DPTPU_BENCH_MODEL hook measures; VERDICT r3 item 2 wants its op table.
MODEL = "danet"
if "--model" in sys.argv:
    MODEL = sys.argv[sys.argv.index("--model") + 1]
ON_TPU = any(d.platform == "tpu" for d in jax.devices())
SEMANTIC = MODEL != "danet"
SIZE = (513 if SEMANTIC else 512) if ON_TPU else 64
BACKBONE = "resnet101" if ON_TPU else "resnet18"


def hlo_stats_table(trace_dir: str):
    """XPlane capture -> hlo_stats rows via the xprof conversion library."""
    from tensorflow.python.profiler.internal import (
        _pywrap_profiler_plugin as pp,
    )

    paths = sorted(glob.glob(
        os.path.join(trace_dir, "plugins", "profile", "*", "*.xplane.pb")))
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    data, _ = pp.xspace_to_tools_data([paths[-1]], "hlo_stats")
    if isinstance(data, bytes):
        data = data.decode("utf-8", "replace")
    return json.loads(data)


def top_ops(table, n: int = 15):
    """gviz-style {cols, rows} -> top-n rows by self time."""
    cols = [c.get("label") or c.get("id") for c in table["cols"]]

    def col(name_part):
        for i, c in enumerate(cols):
            if c and name_part.lower() in str(c).lower():
                return i
        return None

    i_name = col("hlo op name") or col("op name") or 0
    i_cat = col("category")
    i_self = col("self time")  # typically us
    i_frac = col("%")
    rows = []
    for r in table["rows"]:
        c = [x.get("v") if isinstance(x, dict) else x for x in r["c"]]
        rows.append({
            "op": c[i_name],
            "category": c[i_cat] if i_cat is not None else "",
            "self_time_us": c[i_self] if i_self is not None else None,
            "pct": c[i_frac] if i_frac is not None else None,
        })
    rows = [r for r in rows if isinstance(r["self_time_us"], (int, float))]
    rows.sort(key=lambda r: -r["self_time_us"])
    return rows[:n]


def category_totals(table):
    """Self-time summed per op category over the WHOLE table — the view
    that attributes a step's device time (the top-15 alone undercounts
    long-tail categories like data formatting)."""
    rows = top_ops(table, n=10**9)
    tot: dict[str, float] = {}
    for r in rows:
        tot[r["category"] or "?"] = (
            tot.get(r["category"] or "?", 0.0) + r["self_time_us"])
    total = sum(tot.values()) or 1.0
    return {k: {"self_time_us": round(v, 1), "pct": round(100 * v / total, 2)}
            for k, v in sorted(tot.items(), key=lambda kv: -kv[1])}


def main() -> None:
    from distributedpytorch_tpu.models import build_model
    from distributedpytorch_tpu.parallel import (
        create_train_state,
        make_mesh,
        make_train_step,
        shard_batch,
    )

    mesh = make_mesh()
    dtype = "bfloat16" if ON_TPU else "float32"
    in_ch, nclass = (3, 21) if SEMANTIC else (4, 1)
    if SEMANTIC:
        model = build_model(MODEL, nclass=nclass, backbone=BACKBONE,
                            output_stride=16, dtype=dtype, aux_head=True)
    else:
        model = build_model("danet", nclass=nclass, backbone=BACKBONE,
                            output_stride=8, dtype=dtype,
                            pam_score_dtype=SCORE_DTYPE)
    tx = optax.sgd(1e-3, momentum=0.9)
    r = np.random.RandomState(0)
    host_batch = {
        "concat": r.uniform(0, 255, (BATCH, SIZE, SIZE, in_ch)
                            ).astype(np.float32),
        "crop_gt": (
            r.randint(0, nclass, (BATCH, SIZE, SIZE)).astype(np.float32)
            if SEMANTIC else
            (r.uniform(size=(BATCH, SIZE, SIZE)) > 0.7).astype(np.float32)),
    }
    with mesh:
        state = create_train_state(jax.random.PRNGKey(0), model, tx,
                                   (1, SIZE, SIZE, in_ch), mesh=mesh)
        step = make_train_step(
            model, tx, mesh=mesh,
            loss_type="multi_softmax" if SEMANTIC else "multi_sigmoid")
        batch = shard_batch(mesh, host_batch)
        state, loss = step(state, batch)  # compile outside the trace
        jax.block_until_ready(loss)
        with jax.profiler.trace(OUT):
            for _ in range(STEPS):
                state, loss = step(state, batch)
            jax.block_until_ready(loss)

    rec = {"metric": f"{MODEL}_{BACKBONE}_{SIZE}px_b{BATCH}_profile",
           "trace_dir": OUT, "steps": STEPS,
           "score_dtype": SCORE_DTYPE,
           "platform": jax.devices()[0].platform}
    try:
        table = hlo_stats_table(OUT)
        rec["top_ops_by_self_time"] = top_ops(table)
        rec["category_totals"] = category_totals(table)
    except Exception as e:
        rec["hlo_stats_error"] = f"{type(e).__name__}: {str(e)[:200]}"
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
