#!/bin/bash
# Round-2 chip follow-ups that were queued when the axon tunnel wedged
# (>7 h on 2026-07-31).  Run on an IDLE host with a healthy tunnel; each
# step is independent — rerun any that fail.  Results go into BASELINE.md
# (sections reference these scripts by name).
set -x
cd "$(dirname "$0")/.."

# 1. op-level profile: top-op table for b8 and the b16 regression
python scripts/profile_step.py --batch 8  --out /tmp/prof_b8  | tee /tmp/prof_b8.json
python scripts/profile_step.py --batch 16 --out /tmp/prof_b16 | tee /tmp/prof_b16.json

# 2. convergence evidence (VERDICT r1 item 3): guided vs guidance-ablated,
#    then semantic DeepLabV3-R101 os=16 — ~15 min each
python scripts/convergence_runs.py a b --epochs 30 | tee /tmp/conv_ab.json
python scripts/convergence_runs.py c  --epochs 30 | tee /tmp/conv_c.json

# 3. e2e bench rows not yet measured clean: batched val (10), semantic
#    fast path (11), multi-step dispatch (12)
python scripts/bench_e2e.py 10 11 12 | tee /tmp/bench_e2e_new.json

# 4. the official step bench with the round-2 MFU/roofline fields
python bench.py | tee /tmp/bench_mfu.json
