"""Visual dataset smoke: overlay masks on a few samples, write PNGs.

The reference's only self-check was a matplotlib loop showing 4 samples
with mask overlays and category titles (reference pascal.py:269-290).
Headless equivalent: PNGs into --out, category in the filename.

    python scripts/visualize_samples.py --out /tmp/vis            # fake fixture
    python scripts/visualize_samples.py --out vis --root /data/voc --split val
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
from PIL import Image

from distributedpytorch_tpu.data import VOCInstanceSegmentation, make_fake_voc
from distributedpytorch_tpu.data.voc import CATEGORY_NAMES
from distributedpytorch_tpu.utils.helpers import overlay_mask


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--out", required=True, help="output dir for PNGs")
    ap.add_argument("--root", help="VOC root (default: synthetic fixture)")
    ap.add_argument("--split", default="train")
    ap.add_argument("--n", type=int, default=4,
                    help="samples to render (reference showed 4)")
    args = ap.parse_args()

    tmp = None
    root = args.root
    if root is None:
        tmp = tempfile.mkdtemp()
        # size the fixture so the REQUESTED split holds >= n images
        n_val = max(args.n, 2) if args.split == "val" else 2
        root = make_fake_voc(os.path.join(tmp, "voc"),
                             n_images=max(args.n, 4) + n_val,
                             size=(240, 320), n_val=n_val, seed=0)
    ds = VOCInstanceSegmentation(root, split=args.split)
    os.makedirs(args.out, exist_ok=True)
    for i in range(min(args.n, len(ds))):
        s = ds[i]
        cat = CATEGORY_NAMES[int(s["meta"]["category"])]
        over = overlay_mask(s["image"] / 255.0, s["gt"] > 0.5)
        name = f"{i:02d}_{s['meta']['image']}_obj{s['meta']['object']}_{cat}.png"
        Image.fromarray((np.clip(over, 0, 1) * 255).astype(np.uint8)
                        ).save(os.path.join(args.out, name))
        print(name)
    if tmp:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
