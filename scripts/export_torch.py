"""Export a training run's weights to a torch ``state_dict`` ``.pth``.

The reverse of ``checkpoint.warm_start`` / ``Predictor.from_torch``: users
migrating to this framework keep a way back to their torch tooling (the
reference ecosystem's checkpoint format, train_pascal.py:103).  Layout
conversion (HWIO->OIHW convs, BN naming) lives in utils/torch_interop.

    python scripts/export_torch.py work/run_0 danet_export.pth [--latest]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("run_dir", help="training run dir (config.json + "
                                    "checkpoints/)")
    ap.add_argument("out", help="output .pth path")
    ap.add_argument("--latest", action="store_true",
                    help="export the latest checkpoint instead of the "
                         "best-metric one")
    args = ap.parse_args()

    import jax
    jax.config.update("jax_platforms", "cpu")  # weights-only host job

    import numpy as np
    import torch

    from distributedpytorch_tpu.predict import load_run
    from distributedpytorch_tpu.utils.torch_interop import (
        params_to_torch_state_dict,
    )

    # load_run restores the full TrainState (params + BN stats + SGD
    # momentum); the momentum copy is discarded below.  A params-only
    # partial Orbax restore would save ~1x params of IO/host memory but
    # needs version-sensitive restore plumbing — not worth it for an
    # offline export job.
    _, _, state = load_run(args.run_dir, best=not args.latest)
    sd = params_to_torch_state_dict(state.params, state.batch_stats)
    torch.save({k: torch.from_numpy(np.asarray(v)) for k, v in sd.items()},
               args.out)
    print(f"exported {len(sd)} tensors (step {int(state.step)}) "
          f"-> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
