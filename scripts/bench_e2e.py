"""End-to-end Trainer throughput on the real chip: host input pipeline
(decode -> augment -> crop -> resize -> guidance -> batch) overlapped with
the compiled train step, measured together through ``Trainer.train_epoch``.

``bench.py`` measures the step alone (data pre-placed); ``bench_input.py``
measures the host pipeline alone.  This script measures what a user actually
gets: the two running concurrently through the prefetch/overlap machinery.
Prints one JSON line per variant.

TPU-only, like scripts/perf_sweep.py: the variants are full-size
DANet-R101 512px configs.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_PYTHON_CLIENT_MEM_FRACTION", "0.92")

from distributedpytorch_tpu.backend_health import (  # noqa: E402
    ensure_backend_or_cpu_fallback,
    pin_requested_platform,
)

ensure_backend_or_cpu_fallback()

import jax  # noqa: E402

pin_requested_platform()

from distributedpytorch_tpu.backend_health import enable_compile_cache  # noqa: E402

enable_compile_cache()

CPU_SMOKE = "--cpu-smoke" in sys.argv
if CPU_SMOKE:
    sys.argv.remove("--cpu-smoke")
elif not any(d.platform == "tpu" for d in jax.devices()):
    print(json.dumps({"error": "no TPU available (e2e bench is TPU-only; "
                      "--cpu-smoke runs a downsized flow check)"}))
    sys.exit(1)

from distributedpytorch_tpu.data.fake import make_fake_voc  # noqa: E402
from distributedpytorch_tpu.train import Config, Trainer, apply_overrides  # noqa: E402

# VOC-like image sizes (VOC2012 images are ~500x375) so decode/crop/resize
# cost what it costs on the real dataset.
N_IMAGES = 20 if CPU_SMOKE else 144  # keeps 104 TRAIN images (the round-3
                                 # workload) now that N_VAL is 40 —
                                 # make_fake_voc carves val out of n_images
N_VAL = 2 if CPU_SMOKE else 40   # enough val samples for a stable val rate
                                 # (val >= 10 imgs/s needs > a few seconds
                                 # of samples to time honestly)
IMG_SIZE = (96, 128) if CPU_SMOKE else (375, 500)
BATCH = 8  # also divides the smoke run's 8-device CPU mesh
EPOCHS_TIMED = 1 if CPU_SMOKE else 2  # after a warmup epoch (compile + caches)


def run(fixture_root: str, overrides: dict) -> dict:
    work = tempfile.mkdtemp(prefix="bench_e2e_")
    overrides = dict(overrides)
    schedule = overrides.pop("_schedule", None)  # not a Config field
    if str(overrides.get("data.prepared_cache", "")).startswith("AUTO"):
        # shared across variants on purpose: same crop config -> same
        # fingerprint -> later variants start warm (like a user's epoch 2+)
        overrides["data.prepared_cache"] = os.path.join(
            fixture_root, "prepared")
    cfg = apply_overrides(Config(), {
        "data.root": fixture_root,
        "data.train_batch": BATCH,
        "model.dtype": "float32" if CPU_SMOKE else "bfloat16",
        "optim.lr": 1e-4,
        "work_dir": work,
        "epochs": 1,
        "log_writers": [],
        **overrides,
        # smoke downsizing wins over variant shapes (513^2 on CPU is not a
        # flow check)
        **({"model.backbone": "resnet18", "data.crop_size": [64, 64],
            "model.dtype": "float32"} if CPU_SMOKE else {}),
    })
    try:
        trainer = Trainer(cfg)
        n_batches = len(trainer.train_loader)
        if schedule:
            return run_schedule(trainer, cfg, n_batches, schedule)
        trainer.train_epoch(0)  # warmup: compile + any decode cache fill
        t0 = time.perf_counter()
        for ep in range(1, 1 + EPOCHS_TIMED):
            trainer.train_epoch(ep)
        # train_epoch defers syncs; one param read closes the timed region.
        jax.block_until_ready(jax.tree.leaves(trainer.state.params)[0])
        dt = time.perf_counter() - t0
        echo = cfg.data.echo
        steps = EPOCHS_TIMED * n_batches * echo
        # Fresh-image rate (echoed repeats are NOT fresh data — same rule as
        # the trainer's train/imgs_per_sec); the step rate is what the
        # optimizer sees and is the number data echoing improves.  Count
        # with the variant's EFFECTIVE batch, not the module default — a
        # train_batch override (variant 9) would otherwise under-report by
        # exactly the ratio (round 2's b16 row was halved this way).
        fresh = EPOCHS_TIMED * n_batches * cfg.data.train_batch
        rec = {"imgs_per_sec_per_chip": round(
                   fresh / dt / jax.device_count(), 2),
               "steps": steps}
        if echo > 1:
            rec["step_imgs_per_sec_per_chip"] = round(
                fresh * echo / dt / jax.device_count(), 2)
        # Val-epoch rate (the full protocol: forward + host paste-back +
        # threshold-swept Jaccard); first call compiles the eval step, the
        # second is the steady-state number.
        trainer.validate(log_panels=False)
        vm = trainer.validate(log_panels=False)
        rec["val_imgs_per_sec_per_chip"] = round(
            vm["n_samples"] / vm["seconds"] / jax.device_count(), 2)
        rec["val_seconds"] = round(vm["seconds"], 2)
        return rec
    finally:
        shutil.rmtree(work, ignore_errors=True)


def run_schedule(trainer: Trainer, cfg, n_batches: int,
                 schedule: str) -> dict:
    """Epoch wall-clock INCLUDING validation, for the serial vs
    val_overlap A/B: the plain variants time train epochs and val epochs
    separately, which cannot show what overlap hides.

    Symmetry rules (the A/B is meaningless without them): both schedules
    run EPOCHS_TIMED train epochs and EPOCHS_TIMED evaluations, neither
    pays checkpoint/panel costs inside the timed region (``_eval_metrics``
    / ``finish=False``), and every overlapped validation is joined AFTER a
    timed train epoch it could hide behind — the steady-state pipeline
    shape, achieved by launching the first val just before the clock
    starts and not launching one after the last train epoch."""
    trainer.train_epoch(0)
    trainer._eval_metrics(trainer.state)      # warm eval program + caches
    overlap = schedule == "overlap"
    if overlap:
        trainer._launch_overlapped_val(0, int(trainer.state.step))
    t0 = time.perf_counter()
    for ep in range(1, 1 + EPOCHS_TIMED):
        trainer.train_epoch(
            ep, abort_check=(trainer._poll_overlapped_val_error
                             if overlap else None))
        if overlap:
            trainer._join_overlapped_val(None, finish=False)
            if ep < EPOCHS_TIMED:
                trainer._launch_overlapped_val(
                    ep, int(trainer.state.step))
        else:
            trainer._eval_metrics(trainer.state)
    jax.block_until_ready(jax.tree.leaves(trainer.state.params)[0])
    dt = time.perf_counter() - t0
    fresh = EPOCHS_TIMED * n_batches * cfg.data.train_batch
    return {"schedule": schedule,
            "epoch_incl_val_seconds": round(dt / EPOCHS_TIMED, 2),
            "epoch_incl_val_imgs_per_sec_per_chip": round(
                fresh / dt / jax.device_count(), 2)}


if __name__ == "__main__":
    fixture = tempfile.mkdtemp(prefix="bench_e2e_voc_")
    make_fake_voc(fixture, n_images=N_IMAGES, size=IMG_SIZE, max_objects=2,
                  n_val=N_VAL)
    variants = [
        # reference-shape host pipeline: guidance synthesized on host
        dict(),
        # guidance fused into the compiled step (data.device_guidance)
        {"data.device_guidance": True},
        # + decode-once cache sized to the whole fixture
        {"data.device_guidance": True, "data.decode_cache": N_IMAGES},
        # + data echoing: each loaded batch steps twice
        {"data.device_guidance": True, "data.decode_cache": N_IMAGES,
         "data.echo": 2},
        # everything movable moved on-device: flip + rotate/scale + guidance
        # all inside the compiled step; host does decode -> crop -> resize
        {"data.device_guidance": True, "data.decode_cache": N_IMAGES,
         "data.device_augment": True, "data.device_augment_geom": True},
        # prepared-sample disk cache: decode/crop/resize mmap-read after the
        # fill epoch; host does flip + rotate/scale on the crop + guidance
        {"data.prepared_cache": "AUTO"},
        # + guidance on device: host is flip + rotate/scale + collate only
        {"data.prepared_cache": "AUTO", "data.device_guidance": True},
        # + flip and rotate/scale on device too: host is mmap-read + collate
        {"data.prepared_cache": "AUTO", "data.device_guidance": True,
         "data.device_augment": True, "data.device_augment_geom": True},
        # + uint8 wire format: 4x fewer H2D bytes and host memcpys
        {"data.prepared_cache": "AUTO", "data.device_guidance": True,
         "data.uint8_transfer": True},
        # the full package at global batch 16 (fewer dispatches per image)
        {"data.prepared_cache": "AUTO", "data.device_guidance": True,
         "data.uint8_transfer": True, "data.train_batch": 16},
        # fast path + batched val: the reference protocol is bs=1 (dispatch-
        # bound through the tunnel); val_batch=8 amortizes it
        {"data.prepared_cache": "AUTO", "data.device_guidance": True,
         "data.uint8_transfer": True, "data.val_batch": 8},
        # + multi-step dispatch: 3 optimizer steps per compiled call
        {"data.prepared_cache": "AUTO", "data.device_guidance": True,
         "data.uint8_transfer": True, "data.steps_per_dispatch": 3},
        # semantic task on its prepared+uint8 fast path (DeepLabV3-R101
        # os=16 513^2 — BASELINE config 4's model at the e2e level)
        {"task": "semantic", "model.name": "deeplabv3", "model.nclass": 21,
         "model.in_channels": 3, "model.output_stride": 16,
         "data.crop_size": [513, 513], "data.val_batch": 8,
         "data.prepared_cache": "AUTO_SEM", "data.uint8_transfer": True},
        # fast path + 1-bit mask wire (data.packbits_masks): ~22% fewer
        # H2D bytes — the lever when placement (a sagging tunnel) bounds
        # e2e (BASELINE.md round-3 breakdown)
        {"data.prepared_cache": "AUTO", "data.device_guidance": True,
         "data.uint8_transfer": True, "data.packbits_masks": True},
        # 14: the stacked headline (VERDICT r3 item 6): fast path +
        # packbits wire + bf16 PAM scores, in the same sequential run as
        # its controls
        {"data.prepared_cache": "AUTO", "data.device_guidance": True,
         "data.uint8_transfer": True, "data.packbits_masks": True,
         "model.pam_score_dtype": "bfloat16"},
        # 15: val-path A/B control — fast path with the OLD plain val
        # (data.val_prepared=false); variants 8/10 minus this row isolate
        # the prepared-val win within one run
        {"data.prepared_cache": "AUTO", "data.device_guidance": True,
         "data.uint8_transfer": True, "data.val_batch": 8,
         "data.val_prepared": False},
        # 16: semantic val-path A/B control (the round-3 1.0 imgs/s row's
        # config, now with val_prepared off vs variant 12's on)
        {"task": "semantic", "model.name": "deeplabv3", "model.nclass": 21,
         "model.in_channels": 3, "model.output_stride": 16,
         "data.crop_size": [513, 513], "data.val_batch": 8,
         "data.prepared_cache": "AUTO_SEM", "data.uint8_transfer": True,
         "data.val_prepared": False},
        # 17: the FULL-RES semantic protocol (metric at native size) on
        # the prepared val path — gt_full served from padded uint8 rows
        {"task": "semantic", "model.name": "deeplabv3", "model.nclass": 21,
         "model.in_channels": 3, "model.output_stride": 16,
         "data.crop_size": [513, 513], "data.val_batch": 8,
         "eval_full_res": True,
         "data.prepared_cache": "AUTO_SEM", "data.uint8_transfer": True},
        # 18: full-res control (plain ragged val path)
        {"task": "semantic", "model.name": "deeplabv3", "model.nclass": 21,
         "model.in_channels": 3, "model.output_stride": 16,
         "data.crop_size": [513, 513], "data.val_batch": 8,
         "eval_full_res": True,
         "data.prepared_cache": "AUTO_SEM", "data.uint8_transfer": True,
         "data.val_prepared": False},
        # 19/20: epoch wall INCLUDING validation, serial vs val_overlap —
        # the overlap hides the val epoch behind the next train epoch
        {"data.prepared_cache": "AUTO", "data.device_guidance": True,
         "data.uint8_transfer": True, "data.val_batch": 8,
         "_schedule": "serial"},
        {"data.prepared_cache": "AUTO", "data.device_guidance": True,
         "data.uint8_transfer": True, "data.val_batch": 8,
         "val_overlap": True, "_schedule": "overlap"},
        # 21: stacked headline + K-step dispatch.  The tunnel serializes
        # H2D/dispatch RPCs against the running step (no true overlap:
        # measured wall/step == step + place + dispatch even with the
        # placement thread ahead), so a K=3 program keeps the chip busy
        # 3 steps per round trip and hides 2/3 of that serial overhead.
        {"data.prepared_cache": "AUTO", "data.device_guidance": True,
         "data.uint8_transfer": True, "data.packbits_masks": True,
         "model.pam_score_dtype": "bfloat16",
         "data.steps_per_dispatch": 3},
        # 22: same with K=6 (half an epoch per dispatch at the bench size)
        {"data.prepared_cache": "AUTO", "data.device_guidance": True,
         "data.uint8_transfer": True, "data.packbits_masks": True,
         "model.pam_score_dtype": "bfloat16",
         "data.steps_per_dispatch": 6},
        # 23: stacked headline + the coalesced one-buffer wire
        # (data.coalesce_wire): one H2D RPC per batch instead of three —
        # the lever when the tunnel's per-RPC latency (not bandwidth)
        # bounds placement (BASELINE.md round-4 wire study)
        {"data.prepared_cache": "AUTO", "data.device_guidance": True,
         "data.uint8_transfer": True, "data.packbits_masks": True,
         "model.pam_score_dtype": "bfloat16", "data.coalesce_wire": True},
    ]
    sel = sys.argv[1:]
    try:
        for i, ov in enumerate(variants):
            if sel and str(i) not in sel:
                continue
            rec = {"variant": i, **{k: v for k, v in ov.items()}}
            try:
                rec.update(run(fixture, ov))
            except Exception as e:
                rec["error"] = str(e)[:200]
            print(json.dumps(rec), flush=True)
    finally:
        shutil.rmtree(fixture, ignore_errors=True)
