"""Compare two `scripts/profile_step.py` outputs (e.g. b8 vs b16) and name
what regressed — the analysis half of VERDICT round-2 item 4 ("explain b16
and b4 with the op profiles").

Raw HLO op names don't line up across batch sizes (XLA re-fuses and
renumbers: ``fusion.123`` at b8 is not ``fusion.123`` at b16), so the
stable comparison units are (1) the op *category* (convolution, fusion,
all-reduce, copy, ...) and (2) a fuzzy op key — the category plus the
name with trailing ``.N`` digits stripped.  Times are normalized
per-image (self_time / batch) so "regression" means what the batch table
means: more device time per unit of work.

Usage:  python scripts/profile_diff.py A.json B.json
  A/B are the JSON lines printed by profile_step.py (``--batch`` encoded
  in their "metric" field).  Prints one human table per comparison axis
  and one machine JSON line; values are always per-image normalized.
"""

from __future__ import annotations

import json
import re
import sys


def load(path: str) -> dict:
    with open(path) as f:
        txt = f.read().strip()
    # profile_step prints exactly one JSON object; tolerate tee'd noise
    # around it by grabbing the last line that parses.
    for line in reversed(txt.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    raise ValueError(f"no JSON record in {path}")


def batch_of(rec: dict) -> int:
    m = re.search(r"_b(\d+)_profile", rec.get("metric", ""))
    return int(m.group(1)) if m else 1


def fuzzy_key(op: dict) -> str:
    name = re.sub(r"[.\d]+$", "", str(op.get("op", "")))
    return f"{op.get('category', '')}:{name}"


def by(rows: list[dict], keyfn) -> dict[str, float]:
    agg: dict[str, float] = {}
    for r in rows:
        t = r.get("self_time_us")
        if isinstance(t, (int, float)):
            agg[keyfn(r)] = agg.get(keyfn(r), 0.0) + float(t)
    return agg


def table(title: str, a: dict[str, float], b: dict[str, float],
          na: str, nb: str, scale_a: float, scale_b: float) -> list[dict]:
    keys = sorted(set(a) | set(b),
                  key=lambda k: -(b.get(k, 0.0) * scale_b
                                  - a.get(k, 0.0) * scale_a))
    out = []
    print(f"\n== {title} (per-image us, {na} -> {nb}) ==")
    print(f"{'key':48s} {na:>10s} {nb:>10s} {'delta':>10s}")
    for k in keys:
        va, vb = a.get(k, 0.0) * scale_a, b.get(k, 0.0) * scale_b
        print(f"{k[:48]:48s} {va:10.1f} {vb:10.1f} {vb - va:+10.1f}")
        out.append({"key": k, na: round(va, 1), nb: round(vb, 1),
                    "delta": round(vb - va, 1)})
    return out


def main() -> None:
    paths = [a for a in sys.argv[1:] if not a.startswith("--")]
    if len(paths) != 2:
        sys.exit("usage: profile_diff.py A.json B.json")
    ra, rb = load(paths[0]), load(paths[1])
    ops_a = ra.get("top_ops_by_self_time") or []
    ops_b = rb.get("top_ops_by_self_time") or []
    if not ops_a or not ops_b:
        sys.exit(f"missing top_ops tables ({paths[0]}: {len(ops_a)} rows, "
                 f"{paths[1]}: {len(ops_b)} rows)")
    na, nb = f"b{batch_of(ra)}", f"b{batch_of(rb)}"
    if na == nb:
        # same-batch comparison (e.g. a score-dtype A/B at b8): distinct
        # column keys, or the output dicts would silently keep only B
        na, nb = na + "_a", nb + "_b"
    # per-image normalization; profile_step runs STEPS steps inside the
    # trace, identical for both captures, so steps cancel out.
    sa, sb = 1.0 / batch_of(ra), 1.0 / batch_of(rb)
    cats = table("by category", by(ops_a, lambda r: r["category"] or "?"),
                 by(ops_b, lambda r: r["category"] or "?"), na, nb, sa, sb)
    ops = table("by fuzzy op", by(ops_a, fuzzy_key), by(ops_b, fuzzy_key),
                na, nb, sa, sb)
    tot_a = sum(v for v in by(ops_a, lambda r: "t").values()) * sa
    tot_b = sum(v for v in by(ops_b, lambda r: "t").values()) * sb
    print(f"\ntotal top-op self time per image: {na} {tot_a:.1f} us, "
          f"{nb} {tot_b:.1f} us ({(tot_b / tot_a - 1) * 100:+.1f}%)")
    print(json.dumps({"a": paths[0], "b": paths[1],
                      "per_image_us": {na: round(tot_a, 1),
                                       nb: round(tot_b, 1)},
                      "by_category": cats, "top_regressions": ops[:8]}))


if __name__ == "__main__":
    main()
