"""Convergence evidence runs (VERDICT r1 item 3): prove the model learns
from pixels, not just from the guidance channel.

Real-chip runs a-d share a 200-image fake-VOC at real image sizes
(opt-in run e builds its own 1,000-image fixture):

  a. flagship guided: DANet-R101 512² b8 bf16, n-ellipse+gaussian guidance
     (the round-1 recipe, now on the prepared+uint8 fast path);
  b. guidance ablation: identical but ``data.guidance=none`` (3-channel
     input) — if this matches (a), the guided result proves nothing;
  c. semantic: DeepLabV3-R101 os=16 513², 21-class mIoU on the same images'
     class masks;
  d. bf16 PAM scores: identical to (a) but ``model.pam_score_dtype=
     bfloat16`` — the roofline lever's accuracy side (its speed side is
     perf_sweep variants 11-12); compare curve (d) against curve (a);
  e. large-fixture semantic plateau: DeepLabV3-R101 on a 1,000-image
     fake-VOC to a non-trivial mIoU plateau — the learning-from-pixels
     evidence VERDICT r2 item 2 prescribes if ablation (b) tracks (a)
     (guidance-copying); report epochs-to-plateau.  NOT in the default
     selection (run only when the a/b outcome calls for it):
     ``python scripts/convergence_runs.py e --epochs 60``.

  f. small-scale semantic: DeepLabV3-R18 256² b16 lr 0.02 on the
     1,000-image fixture — semantic learning at a from-scratch-learnable
     scale (c's 513² R101 stays all-background in 750 steps, the expected
     from-scratch outcome; the reference only ever fine-tuned a
     pretrained .pth).

  g. bf16 BN batch stats: run (a)'s config with ``model.bn_fp32_stats=
     false`` stacked on bf16 PAM scores — the accuracy gate for the
     round-4 convert_reduce_fusion attack; compare against curves (a)
     and (d).

Prints one JSON line per run with the per-epoch val metric curve.
Usage: python scripts/convergence_runs.py [a b c d e f g] [--epochs N]
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_PYTHON_CLIENT_MEM_FRACTION", "0.92")

from distributedpytorch_tpu.backend_health import (  # noqa: E402
    ensure_backend_or_cpu_fallback,
    pin_requested_platform,
)

ensure_backend_or_cpu_fallback()

import jax  # noqa: E402

pin_requested_platform()

from distributedpytorch_tpu.backend_health import enable_compile_cache  # noqa: E402

enable_compile_cache()

import numpy as np  # noqa: E402

CPU_SMOKE = "--cpu-smoke" in sys.argv
if CPU_SMOKE:
    sys.argv.remove("--cpu-smoke")

EPOCHS = 30
if "--epochs" in sys.argv:
    i = sys.argv.index("--epochs")
    EPOCHS = int(sys.argv[i + 1])
    del sys.argv[i:i + 2]
if CPU_SMOKE:
    EPOCHS = min(EPOCHS, 2)

from distributedpytorch_tpu.data.fake import make_fake_voc  # noqa: E402
from distributedpytorch_tpu.train import Config, Trainer, apply_overrides  # noqa: E402

# val >= 200 (VERDICT r3 item 7): a 20-50-image val split oscillates
# +-0.05-0.10 mIoU from single-class flips late-epoch; 200 images makes the
# curves quotable at the precision BASELINE.md quotes them.  Train counts
# stay what rounds 1-3 used (180 small / 1000 big) so curve comparisons
# against the committed artifacts remain train-scale-identical.
N_IMAGES = 16 if CPU_SMOKE else 380
N_VAL = 3 if CPU_SMOKE else 200
IMG_SIZE = (96, 128) if CPU_SMOKE else (375, 500)
# smoke runs on the 8-device CPU mesh: batch must divide over the data axis
SMALL = {"model.backbone": "resnet18", "data.crop_size": [64, 64],
         "model.dtype": "float32"} if CPU_SMOKE else {}


def run(name: str, fixture: str, overrides: dict) -> dict:
    work = tempfile.mkdtemp(prefix=f"conv_{name}_")
    cfg = apply_overrides(Config(), {
        "data.root": fixture,
        "data.train_batch": 8,
        "data.area_thres": 0,
        "data.prepared_cache": os.path.join(work, "prep"),
        "data.uint8_transfer": True,
        "model.dtype": "bfloat16",
        "optim.lr": 0.007, "optim.schedule": "poly",
        "epochs": EPOCHS, "eval_every": 1,
        "log_writers": ["jsonl"],
        **SMALL,
        **overrides,
    })
    cfg = dataclasses.replace(cfg, work_dir=work)
    tr = Trainer(cfg)
    hist = tr.fit()
    tr.close()
    key = "jaccard"
    curve = [round(float(m[key]), 4) for m in hist["val"]]
    best = max(curve) if curve else float("nan")
    # epochs-to-plateau: first epoch within 1% (relative) of the best
    plateau = next((i for i, v in enumerate(curve) if v >= best * 0.99),
                   None)
    # epochs = what actually trained; the curve has one point per EVAL
    # (eval_every may be > 1 — runs e/f), so the plateau index is in
    # eval-point units and eval_every is recorded for conversion
    rec = {"run": name, "epochs": cfg.epochs,
           "eval_every": cfg.eval_every, "evals": len(curve),
           "val_curve": curve, "best": best,
           "evals_to_within_1pct_of_best": plateau,
           "final_train_loss": round(float(hist["train_loss"][-1]), 4)
           if hist["train_loss"] else None}
    # semantic runs: pixel accuracy is the floor-free secondary signal —
    # all-background scores ~the bg pixel fraction; learning lifts it
    if any("pixel_acc" in m for m in hist["val"]):
        rec["pixel_acc_curve"] = [round(float(m["pixel_acc"]), 4)
                                  for m in hist["val"] if "pixel_acc" in m]
    return rec


if __name__ == "__main__":
    sel = [a for a in sys.argv[1:]
           if a in ("a", "b", "c", "d", "e", "f", "g")] \
        or ["a", "b", "c", "d"]  # e is opt-in: 5x the fixture, ~4x the wall
    fixture = None
    if set(sel) - {"e", "f"}:
        fixture = tempfile.mkdtemp(prefix="conv_voc_")
        make_fake_voc(fixture, n_images=N_IMAGES, size=IMG_SIZE,
                      max_objects=2, n_val=N_VAL, seed=7)
    fixture_big = None
    if set("ef") & set(sel):
        fixture_big = tempfile.mkdtemp(prefix="conv_voc_big_")
        make_fake_voc(fixture_big, n_images=40 if CPU_SMOKE else 1200,
                      size=IMG_SIZE, max_objects=2,
                      n_val=8 if CPU_SMOKE else 200, seed=11)
    runs = {
        "a_guided": {"data.device_guidance": True},
        "b_guidance_none": {"data.guidance": "none",
                            "model.in_channels": 3},
        "c_semantic_deeplab": {
            "task": "semantic", "model.name": "deeplabv3",
            "model.nclass": 21, "model.output_stride": 16,
            "model.aux_head": True, "model.in_channels": 3,
            "data.val_batch": 8,  # semantic val batches cleanly
            **({} if CPU_SMOKE else {"data.crop_size": [513, 513]}),
        },
        "d_bf16_scores": {"data.device_guidance": True,
                          "model.pam_score_dtype": "bfloat16"},
        # g: the accuracy gate for model.bn_fp32_stats=false (VERDICT r3
        # item 5): run a's config with BN batch stats in bf16, stacked
        # with bf16 PAM scores — compare best/plateau vs runs a and d.
        # bf16 fast-variance cancels hardest on the raw-[0,255] stem BN
        # (test_models pins ~5-10% relative variance error); this run
        # answers whether that moves the trained metric.
        "g_bf16_bn_stats": {"data.device_guidance": True,
                            "model.pam_score_dtype": "bfloat16",
                            "model.bn_fp32_stats": False},
    }
    # e extends c's semantic evidence to the big fixture: SAME model
    # config by construction, so the plateau comparison stays valid if c
    # is ever retuned.  eval_every=3 keeps the full-res val loop (the
    # dominant cost at 50 val images) to ~20 evals over a long run.
    runs["e_semantic_plateau_1k"] = dict(runs["c_semantic_deeplab"],
                                         **{"eval_every": 3})
    # f: semantic learning at a FROM-SCRATCH-learnable scale.  Run c's
    # result (flat mIoU 0.0386 = all-background at 513² R101, 750 steps)
    # is the expected from-scratch outcome at that scale — the reference
    # itself only ever fine-tuned a pretrained .pth (train_pascal.py:103).
    # f shrinks the problem until 60 epochs CAN move it: R18 backbone,
    # 256² crops, batch 16, lr 0.02 — the floor-free learning evidence.
    runs["f_semantic_small"] = {
        "task": "semantic", "model.name": "deeplabv3",
        "model.nclass": 21, "model.output_stride": 16,
        "model.backbone": "resnet18", "model.aux_head": True,
        "model.in_channels": 3, "data.val_batch": 8,
        "data.train_batch": 16, "optim.lr": 0.02,
        "eval_every": 2,
        **({} if CPU_SMOKE else {"data.crop_size": [256, 256]}),
    }
    for name, ov in runs.items():
        if name[0] not in sel:
            continue
        try:
            rec = run(name, fixture_big if name[0] in "ef" else fixture, ov)
        except Exception as e:
            rec = {"run": name,
                   "error": f"{type(e).__name__}: {str(e)[:300]}"}
        print(json.dumps(rec), flush=True)
