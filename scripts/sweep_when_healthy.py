"""Poll the TPU tunnel; when it heals, run the pending PAM-variant sweep.

One-shot session utility around scripts/perf_sweep.py's `run()`: the axon
tunnel wedges for hours at a time (BASELINE.md), so chip experiments queue
here instead of blocking a session.  Each probe is a subprocess with a hard
timeout — a wedged backend init cannot take the poller down with it.

Writes one JSON line per variant to --out as results land.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from distributedpytorch_tpu.backend_health import tpu_reachable  # noqa: E402

# Reuse perf_sweep.run() — one benchmark definition (per-chip normalized,
# device-count-scaled batch); importing perf_sweep also runs its bounded
# backend probe and exits non-zero when no TPU is reachable, which is
# exactly the child behavior this poller wants.
VARIANT = """
import json, sys
sys.path.insert(0, %(scripts)r)
sys.path.insert(0, %(repo)r)
from perf_sweep import run
v = run(batch=%(batch)d, pam_impl=%(impl)r, block=%(block)r, remat=False,
        os_=%(os_)d)
print(json.dumps({"impl": %(impl)r, "block": %(block)r, "batch": %(batch)d,
                  "os": %(os_)d, "imgs_per_sec_per_chip": v}))
"""

VARIANTS = [
    {"impl": "einsum", "block": 2048, "batch": 8, "os_": 8},
    {"impl": "einsum", "block": 1024, "batch": 8, "os_": 8},
    {"impl": "flash", "block": 1024, "batch": 8, "os_": 8},
    {"impl": "flash", "block": 256, "batch": 8, "os_": 8},
    {"impl": "einsum", "block": None, "batch": 8, "os_": 16},
]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="/tmp/pam_sweep_results.jsonl")
    ap.add_argument("--poll-seconds", type=int, default=600)
    ap.add_argument("--max-hours", type=float, default=8.0)
    args = ap.parse_args()

    deadline = time.time() + args.max_hours * 3600
    while time.time() < deadline:
        if tpu_reachable(timeout_s=180):
            break
        time.sleep(args.poll_seconds)
    else:
        print("tunnel never healed within the window")
        return 1

    with open(args.out, "a") as f:
        for v in VARIANTS:
            code = VARIANT % {"repo": REPO,
                              "scripts": os.path.join(REPO, "scripts"), **v}
            # error lines share the success lines' key schema ("os", not
            # the python-keyword-dodging "os_")
            rec = {**v, "os": v["os_"]}
            del rec["os_"]
            try:
                r = subprocess.run([sys.executable, "-c", code],
                                   capture_output=True, text=True,
                                   timeout=900)
                line = (r.stdout.strip().splitlines() or ["{}"])[-1]
                if r.returncode != 0:
                    line = json.dumps({**rec, "error": r.stderr[-300:]})
            except subprocess.TimeoutExpired:
                line = json.dumps({**rec, "error": "timeout"})
            print(line)
            f.write(line + "\n")
            f.flush()
    return 0


if __name__ == "__main__":
    sys.exit(main())
