"""Position-attention crossover sweep: XLA einsum vs blocked vs Pallas flash
as the token count grows.

The flagship shape (512² crop, output-stride 8) gives 64² = 4096 tokens,
where the fully-fused XLA einsum wins (BASELINE.md).  Flash attention's
regime is larger token counts — 1024² crops at os=8, or os=4, give 16k-64k
tokens where the materialized N² score matrix first saturates HBM bandwidth
and then simply does not fit.  This sweep measures forward+backward time per
implementation per token count on the real chip and prints one JSON line per
cell — the measured basis for ``model.pam_impl=auto``'s switch point.

PAM inner shapes follow models/danet.py: q/k project to C/8, v keeps C
(C=512 after the head's channel reduction), bf16 inputs.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_PYTHON_CLIENT_MEM_FRACTION", "0.92")

from distributedpytorch_tpu.backend_health import (  # noqa: E402
    ensure_backend_or_cpu_fallback,
    pin_requested_platform,
)

ensure_backend_or_cpu_fallback()

import jax  # noqa: E402

pin_requested_platform()

from distributedpytorch_tpu.backend_health import enable_compile_cache  # noqa: E402

enable_compile_cache()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

CPU_SMOKE = "--cpu-smoke" in sys.argv
if CPU_SMOKE:
    sys.argv.remove("--cpu-smoke")
elif not any(d.platform == "tpu" for d in jax.devices()):
    print(json.dumps({"error": "no TPU (pass --cpu-smoke for a flow check)"}))
    sys.exit(1)

from distributedpytorch_tpu.ops.attention import (  # noqa: E402
    blocked_position_attention,
    position_attention,
)
from distributedpytorch_tpu.ops.pallas_attention import (  # noqa: E402
    flash_position_attention,
)
from distributedpytorch_tpu.utils.profiling import throughput  # noqa: E402

CK, CV = 64, 512  # danet.py PAM: q/k at C/8, v at C (C=512)
TOKENS = [64, 256] if CPU_SMOKE else [4096, 8192, 16384, 32768, 65536]
STEPS = 2 if CPU_SMOKE else 10
WARMUP = 1 if CPU_SMOKE else 2


def impls(n):
    out = {"einsum": lambda q, k, v: position_attention(q, k, v),
           "blocked1024": lambda q, k, v:
               blocked_position_attention(q, k, v, min(1024, n)),
           "flash512": lambda q, k, v:
               flash_position_attention(q, k, v, min(512, n), min(512, n))}
    if not CPU_SMOKE:
        out["flash1024"] = lambda q, k, v: \
            flash_position_attention(q, k, v, min(1024, n), min(1024, n))
    return out


def bench_cell(name, fn, n):
    r = np.random.RandomState(0)
    dt = jnp.bfloat16 if not CPU_SMOKE else jnp.float32
    q = jnp.asarray(r.normal(size=(1, n, CK)), dt)
    k = jnp.asarray(r.normal(size=(1, n, CK)), dt)
    v = jnp.asarray(r.normal(size=(1, n, CV)), dt)

    @jax.jit
    def fwd_bwd(q, k, v):
        def loss(q, k, v):
            return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)
        l, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
        return l, grads

    stats = throughput(lambda: fwd_bwd(q, k, v), steps=STEPS, warmup=WARMUP,
                       items_per_step=1)
    ms = 1000.0 / stats["items_per_sec"]
    return {"impl": name, "tokens": n, "fwd_bwd_ms": round(ms, 2)}


if __name__ == "__main__":
    for n in TOKENS:
        for name, fn in impls(n).items():
            try:
                rec = bench_cell(name, fn, n)
            except Exception as e:
                rec = {"impl": name, "tokens": n,
                       "error": f"{type(e).__name__}: {str(e)[:160]}"}
            print(json.dumps(rec), flush=True)
