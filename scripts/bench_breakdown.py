"""Per-stage budget of the end-to-end fast path (VERDICT item 6).

``bench_e2e.py`` measures the overlapped pipeline as a user gets it; this
script measures each stage of the SAME config in isolation, so the gap
between the e2e number and its theoretical ceiling can be attributed:

  host  — the Trainer's own train loader (prepared cache prebuilt in a
          warmup epoch): mmap read + per-epoch random stage + collate.
          CPU-safe, no accelerator touched (the model is swapped for a
          tiny one — it never runs).
  place — ``shard_batch`` on one real host batch, looped: the placement
          thread's per-batch capacity (layout/copy + H2D DMA).  TPU.
  step  — the compiled train step on one pre-placed batch, looped:
          ``bench.py``'s chip rate re-measured inside this exact config.
          With ``data.steps_per_dispatch=K`` this measures the K-step
          program (items_per_step = K*batch), so the K-step executable's
          chip-side efficiency can be compared against K singles.  TPU.
  dispatch — host-blocking time of *issuing* one step call (sync, then
          time the async enqueue alone).  This is the per-step host cost
          that ``data.steps_per_dispatch`` amortizes; measuring it tells
          whether K-step dispatch can pay on this host at all.  TPU.
  valhost — the Trainer's VAL loader iterated alone (decode + eval
          transform + collate; no device).  Val has no prepared cache by
          design, so this stage names how much of a slow measured val
          rate (e.g. the 1 img/s semantic row, BASELINE.md) is host-side
          before any caching work is considered.  CPU-safe.

Under perfect overlap e2e == min(host, place, step); the printed
``ideal_overlap_imgs_per_sec`` vs the measured bench_e2e row is the
overlap slack worth engineering at, and the slowest stage is the lever.

Usage:
  python scripts/bench_breakdown.py host            # CPU-safe stage
  python scripts/bench_breakdown.py place step      # chip stages
  python scripts/bench_breakdown.py host place step dispatch [k=v ...]
Default config = bench_e2e variant 8 (prepared + device guidance + uint8
wire), the measured-48.7 row.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_PYTHON_CLIENT_MEM_FRACTION", "0.92")

from distributedpytorch_tpu.backend_health import (  # noqa: E402
    ensure_backend_or_cpu_fallback,
    pin_requested_platform,
)

STAGES = [a for a in sys.argv[1:]
          if a in ("host", "place", "step", "dispatch", "valhost",
                   "valplace", "valstep", "valmetric")]
OVERRIDES = [a for a in sys.argv[1:] if "=" in a]
CPU_SMOKE = "--cpu-smoke" in sys.argv
if not STAGES:
    STAGES = ["host", "place", "step"]

NEEDS_TPU = bool({"place", "step", "dispatch", "valplace", "valstep",
                  "valmetric"} & set(STAGES)) and not CPU_SMOKE
if not NEEDS_TPU:
    # Host-only run must never block on a wedged tunnel.  FORCE the
    # override — the site-installed accelerator plugin sets JAX_PLATFORMS
    # at interpreter startup, so setdefault would keep the tunneled
    # platform and the Trainer's first jax.process_index() would hang on
    # backend init.
    os.environ["JAX_PLATFORMS"] = "cpu"
else:
    ensure_backend_or_cpu_fallback()

import jax  # noqa: E402

pin_requested_platform()

from distributedpytorch_tpu.backend_health import enable_compile_cache  # noqa: E402

enable_compile_cache()

if NEEDS_TPU and not any(d.platform == "tpu" for d in jax.devices()):
    print(json.dumps({"error": "place/step stages are TPU-only; "
                      "run `bench_breakdown.py host` for the CPU stage"}))
    sys.exit(1)

import numpy as np  # noqa: E402

from distributedpytorch_tpu.data.fake import make_fake_voc  # noqa: E402
from distributedpytorch_tpu.parallel import shard_batch  # noqa: E402
from distributedpytorch_tpu.train import Config, Trainer, apply_overrides  # noqa: E402
from distributedpytorch_tpu.utils.profiling import throughput  # noqa: E402

N_IMAGES = 8 if CPU_SMOKE else 120
IMG_SIZE = (96, 128) if CPU_SMOKE else (375, 500)
BATCH = 8  # divides the smoke run's 8-device CPU mesh too
DEVICE_KEYS = ("concat", "crop_gt", "crop_void")


def make_trainer(fixture: str, work: str, tiny_model: bool) -> Trainer:
    cfg = apply_overrides(Config(), [
        f"data.root={fixture}",
        f"data.train_batch={BATCH}",
        "data.area_thres=0",
        # bench_e2e variant 8 — the measured-48.7 fast path
        f"data.prepared_cache={os.path.join(fixture, 'prepared')}",
        "data.device_guidance=true",
        "data.uint8_transfer=true",
        "model.dtype=" + ("float32" if tiny_model else "bfloat16"),
        "optim.lr=1e-4",
        "epochs=1", "log_writers=[]",
        *(["model.backbone=resnet18", "model.output_stride=8",
           "data.crop_size=[64,64]", "model.dtype=float32"]
          if (tiny_model and CPU_SMOKE) else
          ["model.backbone=resnet18", "model.output_stride=8"]
          if tiny_model else []),
        # user overrides LAST (apply_overrides is last-write-wins): the
        # printed `overrides` record must be the config that actually ran
        *OVERRIDES,
    ])
    import dataclasses
    return Trainer(dataclasses.replace(cfg, work_dir=work))


def one_host_batch(tr: Trainer) -> dict:
    tr.train_loader.set_epoch(0)
    batch = next(iter(tr.train_loader))
    return {k: v for k, v in batch.items() if k in DEVICE_KEYS}


def stage_host(fixture: str, work: str) -> dict:
    tr = make_trainer(fixture, work, tiny_model=True)
    loader = tr.train_loader
    n_batches = len(loader)
    loader.set_epoch(0)            # warmup epoch fills the prepared cache
    for _ in loader:
        pass
    t0 = time.perf_counter()
    epochs = 2
    for ep in range(1, 1 + epochs):
        loader.set_epoch(ep)
        for _ in loader:
            pass
    dt = time.perf_counter() - t0
    tr.close()
    bs = tr.cfg.data.train_batch
    return {"host_imgs_per_sec": round(epochs * n_batches * bs / dt, 2),
            "host_ms_per_batch": round(dt / (epochs * n_batches) * 1e3, 1)}


def stage_valhost(fixture: str, work: str) -> dict:
    """Val loader alone: decode -> eval transform (incl. ragged full-res
    gt passthrough when configured) -> collate, two passes."""
    tr = make_trainer(fixture, work, tiny_model=True)
    loader = tr.val_loader
    n = 0
    for b in loader:       # warm OS page cache like a 2nd-epoch val
        n += b[next(iter(b))].shape[0] if hasattr(
            b[next(iter(b))], "shape") else len(b[next(iter(b))])
    t0 = time.perf_counter()
    n = 0
    for b in loader:
        first = b[next(iter(b))]
        n += first.shape[0] if hasattr(first, "shape") else len(first)
    dt = time.perf_counter() - t0
    tr.close()
    return {"valhost_imgs_per_sec": round(n / dt, 2),
            "valhost_ms_per_img": round(dt / max(n, 1) * 1e3, 1)}


def stage_place(tr: Trainer, batch: dict, prefix: str = "",
                n_real: int | None = None) -> dict:
    """H2D placement rate of ``batch``; shared by the train and val
    (``prefix='val'``) pipelines.  ``n_real`` counts only genuine samples
    when the batch carries pad rows (the evaluator discards them, so a
    padded-row rate would overstate val throughput by the pad factor)."""
    mesh = tr.mesh
    nbytes = sum(np.asarray(v).nbytes for v in batch.values())
    with mesh:
        shard_batch(mesh, batch)   # warm layouts
        reps = 5 if CPU_SMOKE else 30
        t0 = time.perf_counter()
        for _ in range(reps):
            placed = shard_batch(mesh, batch)
            jax.block_until_ready(placed)
        dt = time.perf_counter() - t0
    bs = n_real if n_real is not None \
        else next(iter(batch.values())).shape[0]
    return {f"{prefix}place_imgs_per_sec": round(reps * bs / dt, 2),
            f"{prefix}place_ms_per_batch": round(dt / reps * 1e3, 1),
            (f"{prefix}_batch_mb" if prefix else "batch_mb"):
                round(nbytes / 2**20, 2)}


def stage_step(tr: Trainer, batch: dict) -> dict:
    mesh = tr.mesh
    k = tr.cfg.data.steps_per_dispatch
    with mesh:
        placed = shard_batch(mesh, batch)
        box = [tr.state]

        if tr.multi_train_step is not None:
            # K-step program: one compiled call consumes K batches (the
            # same placed batch K times is fine — batches are read-only;
            # only the state arg is donated).
            def one():
                box[0], lv = tr.multi_train_step(box[0],
                                                 *([placed] * k))
                return lv
        else:
            def one():
                box[0], loss = tr.train_step(box[0], placed)
                return loss

        bs = next(iter(batch.values())).shape[0]
        stats = throughput(one, steps=5 if CPU_SMOKE else 20,
                           warmup=2, items_per_step=bs * k)
        # the step donates its state arg: the trainer's original buffers
        # are gone after the first call — hand the live state back so a
        # later stage (dispatch) doesn't touch deleted arrays.
        tr.state = box[0]
    # per-BATCH ms (÷k) so the field stays comparable with host_/place_
    # ms_per_batch across K; the per-call time is the K-step program's
    # whole dispatch.
    ms_per_call = bs * k / stats["items_per_sec"] * 1e3
    return {"step_imgs_per_sec": round(stats["items_per_sec"], 2),
            "step_ms_per_batch": round(ms_per_call / k, 1),
            "step_ms_per_call": round(ms_per_call, 1),
            "steps_per_dispatch": k}


def one_val_batch(tr: Trainer) -> tuple[dict, dict, int]:
    """(full val batch, placed-shape device subset, REAL sample count) —
    the evaluator's own split and padding (evaluate.py pads to the mesh's
    device multiple before sharding; without it a val_batch of 1 cannot
    shard).  Rates must count only the real samples: the evaluator
    discards the pad rows."""
    from distributedpytorch_tpu.parallel import pad_to_multiple
    batch = next(iter(tr.val_loader))
    dev = {k: v for k, v in batch.items() if k in DEVICE_KEYS}
    n_real = next(iter(dev.values())).shape[0]
    dev, _ = pad_to_multiple(dev, tr.mesh.devices.size)
    return batch, dev, n_real


def stage_valstep(tr: Trainer, dev: dict, n_real: int) -> dict:
    """The jitted eval forward alone (loss + logits), pre-placed batch."""
    mesh = tr.mesh
    with mesh:
        placed = shard_batch(mesh, dev)

        def one():
            outputs, loss = tr.eval_step(tr.state, placed)
            return loss, outputs[0]

        stats = throughput(one, steps=5 if CPU_SMOKE else 20, warmup=2,
                           items_per_step=n_real)
    return {"valstep_imgs_per_sec": round(stats["items_per_sec"], 2),
            "valstep_ms_per_batch": round(
                n_real / stats["items_per_sec"] * 1e3, 1)}


def stage_valmetric(tr: Trainer, batch: dict, dev: dict) -> dict:
    """D2H readback of the primary logits + the host paste-back/threshold
    sweep — the two val terms no forward overlap hides.  Instance protocol
    only (the semantic path scores its confusion matrix on device).

    Mirrors evaluate()'s own loop via its helpers (_sigmoid/_as_list,
    bbox-or-get_bbox fallback) and the trainer's ACTUAL eval config — a
    hardcoded workload here would attribute numbers to a config that
    never ran."""
    if tr.cfg.task != "instance":
        return {"valmetric_skipped": "instance-only stage"}
    import numpy as _np

    from distributedpytorch_tpu.ops.metrics import np_jaccard_thresholds
    from distributedpytorch_tpu.train.evaluate import _as_list, _sigmoid
    from distributedpytorch_tpu.utils.helpers import (
        crop2fullmask,
        get_bbox,
        tens2image,
    )
    thresholds = tuple(tr.cfg.eval_thresholds)
    relax = tr.cfg.data.relax
    zero_pad = tr.cfg.data.zero_pad
    mesh = tr.mesh
    with mesh:
        import jax.numpy as jnp

        def fetch(out0):
            # mirror the evaluator's wire: eval_bf16_probs (default on)
            # casts the logit volume to bf16 ON DEVICE before the D2H
            if tr.cfg.eval_bf16_probs:
                out0 = out0.astype(jnp.bfloat16)
            return _np.asarray(jax.device_get(out0), _np.float32)

        placed = shard_batch(mesh, dev)
        outputs, _ = tr.eval_step(tr.state, placed)
        fetch(outputs[0])                   # compile + settle
        # forward + D2H readback together (a tunneled device has no
        # reliable sync point to isolate the read); subtract
        # valstep_ms_per_batch to get the readback term alone
        reps = 3 if CPU_SMOKE else 10
        t0 = time.perf_counter()
        for _ in range(reps):
            outputs, _ = tr.eval_step(tr.state, placed)
            logits = fetch(outputs[0])
        dt_read = (time.perf_counter() - t0) / reps
    probs = _sigmoid(logits)  # fetch() already widened to f32
    n = len(batch["gt"]) if isinstance(batch["gt"], list) \
        else batch["gt"].shape[0]
    gts = _as_list(batch["gt"], n)
    voids = _as_list(batch.get("void_pixels", [None] * n), n)
    bboxes = _as_list(batch["bbox"], n) if "bbox" in batch else [None] * n
    t0 = time.perf_counter()
    reps_m = 3 if CPU_SMOKE else 10
    for _ in range(reps_m):
        for j in range(n):
            gt = tens2image(_np.asarray(gts[j]))
            if gt.max() <= 0.5:
                continue
            if bboxes[j] is not None:
                bbox = tuple(int(v) for v in _np.asarray(bboxes[j]))
            else:
                bbox = get_bbox(gt > 0.5, pad=relax, zero_pad=zero_pad)
            pred = tens2image(probs[j])
            full = crop2fullmask(pred, bbox, gt.shape[:2],
                                 zero_pad=zero_pad, relax=relax)
            void = None if voids[j] is None \
                else tens2image(_np.asarray(voids[j]))
            np_jaccard_thresholds(full, thresholds, gt > 0.5, void)
    dt_metric = (time.perf_counter() - t0) / reps_m
    return {"valfwdread_ms_per_batch": round(dt_read * 1e3, 1),
            "valmetric_ms_per_batch": round(dt_metric * 1e3, 1),
            "valmetric_imgs_per_sec": round(n / dt_metric, 2)}


def stage_dispatch(tr: Trainer, batch: dict) -> dict:
    """Host-blocking cost of issuing one (possibly K-step) train-step call.

    Sync the device first, then time the call itself: JAX dispatch is
    async, so the timed interval is trace-cache lookup + arg handling +
    runtime enqueue — pure host work, none of the chip's compute.  This is
    the term ``data.steps_per_dispatch`` divides by K; if it is already
    small next to the step's chip time, K-step dispatch has nothing to
    amortize (and its burstier K-batch consumption can make e2e WORSE on
    a 1-core host)."""
    mesh = tr.mesh
    k = tr.cfg.data.steps_per_dispatch
    step = tr.multi_train_step if tr.multi_train_step is not None \
        else tr.train_step
    with mesh:
        args = [shard_batch(mesh, batch)] * k
        box = [tr.state]
        box[0], out = step(box[0], *args)   # compile
        jax.device_get(out)
        reps = 3 if CPU_SMOKE else 15
        issue = 0.0
        for _ in range(reps):
            t0 = time.perf_counter()
            box[0], out = step(box[0], *args)
            issue += time.perf_counter() - t0
            # drain via device_get, NOT block_until_ready: on the tunneled
            # platform block_until_ready has been observed returning before
            # the computation exists anywhere (utils/profiling.throughput's
            # docstring), which would turn the timed calls into unsynced
            # back-to-back enqueues and inflate the number toward full step
            # time once the in-flight limit is hit.  device_get of the loss
            # output really waits, so each timed call starts on an idle
            # queue and measures pure enqueue cost.
            jax.device_get(out)
        tr.state = box[0]   # state was donated; keep the live one
    return {"dispatch_ms_per_call": round(issue / reps * 1e3, 2),
            "dispatch_calls_timed": reps}


def main() -> int:
    fixture = tempfile.mkdtemp(prefix="bench_breakdown_voc_")
    work = tempfile.mkdtemp(prefix="bench_breakdown_")
    try:
        # val stages need a real val split; keep n_val tiny otherwise so
        # the train-stage workload stays identical to earlier rounds'
        # committed breakdowns
        n_val = 24 if any(s.startswith("val") for s in STAGES) else 2
        make_fake_voc(fixture, n_images=N_IMAGES + (n_val - 2),
                      size=IMG_SIZE, max_objects=2, n_val=n_val, seed=0)
        rec: dict = {"variant": "e2e-fast-path(prepared+devguid+uint8)",
                     "overrides": OVERRIDES, "batch": BATCH}
        def add(stage_rec: dict) -> None:
            # incremental: a late-stage crash must not lose earlier
            # measurements (each partial is a valid JSON line; the last
            # line printed is the most complete record)
            rec.update(stage_rec)
            print(json.dumps(rec), flush=True)

        if "host" in STAGES:
            add(stage_host(fixture, work))
        if "valhost" in STAGES:
            add(stage_valhost(fixture, work))
        if {"place", "step", "dispatch", "valplace", "valstep",
                "valmetric"} & set(STAGES):
            tr = make_trainer(fixture, work, tiny_model=CPU_SMOKE)
            batch = one_host_batch(tr)
            if "place" in STAGES:
                add(stage_place(tr, batch))
            if "step" in STAGES:
                add(stage_step(tr, batch))
            if "dispatch" in STAGES:
                add(stage_dispatch(tr, batch))
            if {"valplace", "valstep", "valmetric"} & set(STAGES):
                vbatch, vdev, n_real = one_val_batch(tr)
                if "valplace" in STAGES:
                    add(stage_place(tr, vdev, prefix="val",
                                    n_real=n_real))
                if "valstep" in STAGES:
                    add(stage_valstep(tr, vdev, n_real))
                if "valmetric" in STAGES:
                    add(stage_valmetric(tr, vbatch, vdev))
            tr.close()
        # train-path stages only: the val stages are a separate pipeline
        # and must not drag the train overlap ceiling down
        rates = [v for k, v in rec.items()
                 if k in ("host_imgs_per_sec", "place_imgs_per_sec",
                          "step_imgs_per_sec")]
        if len(rates) > 1:
            rec["ideal_overlap_imgs_per_sec"] = round(min(rates), 2)
            print(json.dumps(rec), flush=True)
        return 0
    finally:
        shutil.rmtree(fixture, ignore_errors=True)
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
