"""Export a training run's compiled inference as a portable StableHLO
artifact (``jax.export``) — the deployment story: one file, weights +
graph frozen, loadable by ANY jax process (none of this package's code on
the consumer side), lowered for cpu AND tpu in the same artifact, batch
dimension symbolic by default so one artifact serves every batch size.

    python scripts/export_stablehlo.py work/run_0 danet.stablehlo
    python scripts/export_stablehlo.py work/run_0 out.bin --batch 8 --latest

Consumer side:

    from distributedpytorch_tpu.predict import load_serialized  # or inline:
    # fn = jax.jit(jax.export.deserialize(open(p,'rb').read()).call)
    prob = fn(batch)                      # instance: sigmoid maps
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("run_dir")
    ap.add_argument("out")
    ap.add_argument("--batch", type=int, default=None,
                    help="pin the batch dim (default: symbolic 'b')")
    ap.add_argument("--latest", action="store_true",
                    help="export the latest checkpoint, not the best")
    ap.add_argument("--platforms", default="cpu,tpu",
                    help="comma-separated lowering targets")
    args = ap.parse_args()

    import jax
    jax.config.update("jax_platforms", "cpu")  # tracing-only host job

    from distributedpytorch_tpu.predict import (
        Predictor,
        SemanticPredictor,
        export_serialized,
        load_run_config,
    )

    cfg = load_run_config(args.run_dir)
    cls = SemanticPredictor if cfg.task == "semantic" else Predictor
    pred = cls.from_run(args.run_dir, best=not args.latest, cfg=cfg)
    info = export_serialized(pred, args.out, batch=args.batch,
                             platforms=tuple(args.platforms.split(",")))
    print(json.dumps({"task": cfg.task, **info}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
