"""Host input-pipeline throughput: decode -> augment -> guidance -> batch.

The device step consumes batches faster than one host core can produce them
(bench.py: ~68 imgs/s/chip on the v5e for DANet-R101 512²), so the host
pipeline's imgs/sec bounds end-to-end training unless loader workers +
decode caching + native kernels close the gap.  This script measures that
bound on VOC-sized synthetic images across the pipeline's own knobs.

Prints one JSON line per variant:
    {"variant": "...", "imgs_per_sec": N}

CPU-only by design — no accelerator is touched.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributedpytorch_tpu import native_ops  # noqa: E402
from distributedpytorch_tpu.data import (  # noqa: E402
    DataLoader,
    VOCInstanceSegmentation,
    build_train_transform,
    make_fake_voc,
)


def measure(ds, batch: int, workers: int, epochs: int = 2) -> float:
    loader = DataLoader(ds, batch_size=batch, shuffle=True, drop_last=True,
                        num_workers=workers)
    n = 0
    t0 = time.perf_counter()
    for epoch in range(epochs):
        loader.set_epoch(epoch)
        for b in loader:
            n += b["concat"].shape[0]
    return n / (time.perf_counter() - t0)


def main() -> int:
    with tempfile.TemporaryDirectory() as tmp:
        # VOC-realistic image sizes; enough images that the LRU matters and
        # enough objects that instance indexing revisits images.
        root = make_fake_voc(os.path.join(tmp, "voc"), n_images=24,
                             size=(375, 500), n_val=4, seed=0)
        tf = build_train_transform(crop_size=(512, 512))
        # the host side of data.device_guidance=true: guidance + concat
        # move into the compiled step, the host stops at the 512² crops
        tf_devg = build_train_transform(crop_size=(512, 512),
                                        guidance="none")
        # + data.fused_crop_resize: crop+resize as one native-kernel pass
        tf_devg_fused = build_train_transform(crop_size=(512, 512),
                                              guidance="none",
                                              fused_crop_resize=True)

        def ds(cache: int, t):
            return VOCInstanceSegmentation(root, split="train", transform=t,
                                           decode_cache=cache)

        variants = [
            ("workers2", dict(cache=0, workers=2)),
            ("workers2+decode_cache", dict(cache=64, workers=2)),
            ("workers4+decode_cache", dict(cache=64, workers=4)),
            ("workers0", dict(cache=0, workers=0)),
            ("workers2+device_guidance", dict(cache=0, workers=2, t=tf_devg)),
            ("workers0+device_guidance", dict(cache=0, workers=0, t=tf_devg)),
            ("workers0+device_guidance+fused_crop_resize",
             dict(cache=0, workers=0, t=tf_devg_fused)),
            ("workers0+device_guidance+fused+decode_cache",
             dict(cache=64, workers=0, t=tf_devg_fused)),
        ]
        for name, v in variants:
            ips = measure(ds(v["cache"], v.get("t", tf)), batch=8,
                          workers=v["workers"])
            print(json.dumps({"variant": name,
                              "native_kernels": native_ops.enabled(),
                              "imgs_per_sec": round(ips, 2)}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
