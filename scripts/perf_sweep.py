"""Perf sweep on the real chip: bench.py's config across batch size and
PAM attention implementations.  Prints one JSON line per variant.

TPU-only: the variants are full-size DANet-R101 512px configs that would
take hours per step on CPU, so unlike bench.py (which downsizes and still
reports), the sweep exits when no TPU is available.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_PYTHON_CLIENT_MEM_FRACTION", "0.92")

# Bounded tunnel-health probe (shared with bench.py) — without it an
# unhealthy tunnel wedges the sweep indefinitely at jax.devices().
from distributedpytorch_tpu.backend_health import (  # noqa: E402
    ensure_backend_or_cpu_fallback,
    pin_requested_platform,
)

ensure_backend_or_cpu_fallback()

import jax

pin_requested_platform()

from distributedpytorch_tpu.backend_health import enable_compile_cache  # noqa: E402

enable_compile_cache()

if not any(d.platform == "tpu" for d in jax.devices()):
    print(json.dumps({"error": "no TPU available (sweep is TPU-only; "
                      "bench.py covers the CPU-fallback path)"}))
    sys.exit(1)

import numpy as np
import optax

from distributedpytorch_tpu.models import build_model
from distributedpytorch_tpu.parallel import (
    create_train_state,
    make_mesh,
    make_train_step,
    shard_batch,
)
from distributedpytorch_tpu.utils.profiling import throughput

SIZE = 512


def run(batch: int, pam_impl: str, block: int | None, remat: bool,
        os_: int = 8, device_guidance: bool = False,
        score_dtype: str | None = None) -> float:
    mesh = make_mesh()
    n = mesh.devices.size
    model = build_model("danet", nclass=1, backbone="resnet101",
                        output_stride=os_, dtype="bfloat16",
                        pam_impl=pam_impl, pam_block_size=block, remat=remat,
                        pam_score_dtype=score_dtype)
    tx = optax.sgd(1e-3, momentum=0.9)
    r = np.random.RandomState(0)
    in_ch = 3 if device_guidance else 4
    host = {
        "concat": r.uniform(0, 255, (batch * n, SIZE, SIZE, in_ch)
                            ).astype(np.float32),
        "crop_gt": (r.uniform(size=(batch * n, SIZE, SIZE)) > 0.7
                    ).astype(np.float32),
    }
    augment = None
    if device_guidance:  # the fused 4th-channel synthesis (ops/guidance_device)
        from distributedpytorch_tpu.ops.guidance_device import (
            make_device_guidance,
        )
        augment = make_device_guidance()
    with mesh:
        state = create_train_state(jax.random.PRNGKey(0), model, tx,
                                   (1, SIZE, SIZE, 4), mesh=mesh)
        step = make_train_step(model, tx, mesh=mesh, augment=augment)
        b = shard_batch(mesh, host)
        box = [state]

        def one():
            box[0], loss = step(box[0], b)
            return loss, jax.tree.leaves(box[0].params)[0]

        stats = throughput(one, steps=20, warmup=3, items_per_step=batch * n)
    return stats["items_per_sec"] / n


if __name__ == "__main__":
    variants = [
        dict(batch=8, pam_impl="einsum", block=None, remat=False),
        dict(batch=16, pam_impl="einsum", block=None, remat=False),
        dict(batch=8, pam_impl="flash", block=512, remat=False),
        dict(batch=16, pam_impl="flash", block=512, remat=False),
        dict(batch=32, pam_impl="einsum", block=None, remat=False),
        # online-softmax blocked einsum (no N x N scores materialized) and
        # alternate flash tiles — 2026-07-30 sweep data: full einsum b8 67.5
        # beat flash(512) 62.2; these probe whether other tilings close it.
        # (measured 2026-07-31: blocked 2048/1024 -> 62.5/63.9, flash
        # 1024/256 -> 62.3/63.2 vs in-run einsum 66.4 — they don't; at 4096
        # tokens the N x N scores fit HBM fine and XLA's fusion wins)
        dict(batch=8, pam_impl="einsum", block=2048, remat=False),
        dict(batch=8, pam_impl="einsum", block=1024, remat=False),
        dict(batch=8, pam_impl="flash", block=1024, remat=False),
        dict(batch=8, pam_impl="flash", block=256, remat=False),
        # the documented speed knob: os=16 quarters the head's token count
        # and the dilated-stage activation footprint (PAM scores 1024^2
        # instead of 4096^2)
        dict(batch=8, pam_impl="einsum", block=None, remat=False, os_=16),
        # on-device guidance synthesis fused into the step (measured
        # 2026-07-31: 65.4 vs 66.1 plain — ~1% for a 2.3x host-pipeline
        # rate; the host-side win is measured by scripts/bench_input.py)
        dict(batch=8, pam_impl="einsum", block=None, remat=False,
             device_guidance=True),
        # the roofline lever (BASELINE.md): bf16 score materialization
        # halves the PAM's N^2 HBM round trip, softmax math stays f32 —
        # variants 11/12 A/B this against rows 0/1
        dict(batch=8, pam_impl="einsum", block=None, remat=False,
             score_dtype="bfloat16"),
        dict(batch=16, pam_impl="einsum", block=None, remat=False,
             score_dtype="bfloat16"),
        # remat: per-block recompute (models/resnet.py nn.remat).  The r3
        # op profiles say the step runs at ~84% of peak HBM bandwidth with
        # 43% of MXU idle — remat trades exactly the abundant resource
        # (FLOPs) for the scarce one (activation HBM round trips between
        # forward and backward), so it can WIN on wall clock here, not
        # just on memory.  Variants 13-16 A/B it at b8/b16, alone and
        # stacked with bf16 scores; 17 probes whether b32 becomes
        # compilable/competitive once remat shrinks live activations.
        dict(batch=8, pam_impl="einsum", block=None, remat=True),
        dict(batch=16, pam_impl="einsum", block=None, remat=True),
        dict(batch=8, pam_impl="einsum", block=None, remat=True,
             score_dtype="bfloat16"),
        dict(batch=16, pam_impl="einsum", block=None, remat=True,
             score_dtype="bfloat16"),
        dict(batch=32, pam_impl="einsum", block=None, remat=True),
    ]
    sel = sys.argv[1:]
    for i, v in enumerate(variants):
        if sel and str(i) not in sel:
            continue
        # uniform output schema: every line carries "os" (the python-keyword-
        # dodging "os_" kwarg never leaks into the JSONL)
        rec = {k: val for k, val in v.items() if k != "os_"}
        rec["os"] = v.get("os_", 8)
        rec["device_guidance"] = v.get("device_guidance", False)
        rec["score_dtype"] = v.get("score_dtype")
        try:
            ips = run(**v)
            print(json.dumps({**rec, "imgs_per_sec_per_chip": round(ips, 2)}),
                  flush=True)
        except Exception as e:  # OOM etc.
            print(json.dumps({**rec, "error": str(e)[:200]}), flush=True)
