"""Op-level device profile of the jitted EVAL step (the r4 val breakdown
measured valstep at 202 ms/batch on the semantic 513² config — ~15x the
expected forward cost; this names the ops responsible).

Builds the real Trainer for the bench_e2e variant-12 config (or the
instance fast path with --task instance), traces N eval-step calls on a
pre-placed batch, and prints the hlo_stats top ops as one JSON line —
the same report shape as profile_step.py.
"""

from __future__ import annotations

import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_PYTHON_CLIENT_MEM_FRACTION", "0.92")
os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")

from distributedpytorch_tpu.backend_health import (  # noqa: E402
    ensure_backend_or_cpu_fallback,
    pin_requested_platform,
)

ensure_backend_or_cpu_fallback()

import jax  # noqa: E402

pin_requested_platform()

from distributedpytorch_tpu.backend_health import enable_compile_cache  # noqa: E402

enable_compile_cache()

import dataclasses  # noqa: E402
import tempfile  # noqa: E402

import numpy as np  # noqa: E402

TASK = "semantic"
if "--task" in sys.argv:
    TASK = sys.argv[sys.argv.index("--task") + 1]
OUT = "profile_eval_out"
if "--out" in sys.argv:
    OUT = sys.argv[sys.argv.index("--out") + 1]
STEPS = 10
ON_TPU = any(d.platform == "tpu" for d in jax.devices())


def main() -> None:
    from distributedpytorch_tpu.parallel import (
        INPUT_KEY,
        pad_to_multiple,
        shard_batch,
    )
    from distributedpytorch_tpu.train import Config, Trainer, apply_overrides

    size = 513 if ON_TPU else 64
    overrides = [
        "data.fake=true", "data.train_batch=4", "data.val_batch=8",
        "model.dtype=" + ("bfloat16" if ON_TPU else "float32"),
        "checkpoint.async_save=false", "epochs=1",
    ]
    if TASK == "semantic":
        overrides += [
            "task=semantic", "model.name=deeplabv3", "model.nclass=21",
            "model.in_channels=3", "model.output_stride=16",
            f"data.crop_size=[{size},{size}]",
        ]
    else:
        overrides += [
            f"data.crop_size=[{size - 1},{size - 1}]",
            "model.output_stride=8",
        ]
    if not ON_TPU:
        overrides += ["model.backbone=resnet18"]
    cfg = apply_overrides(Config(), overrides)
    cfg = dataclasses.replace(cfg, work_dir=tempfile.mkdtemp())
    tr = Trainer(cfg)
    b = 8
    r = np.random.RandomState(0)
    in_ch = cfg.model.in_channels
    batch = {
        INPUT_KEY: r.uniform(0, 255, (b, size, size, in_ch)
                             ).astype(np.float32),
        "crop_gt": (
            r.randint(0, cfg.model.nclass, (b, size, size)).astype(np.int32)
            if TASK == "semantic" else
            (r.uniform(size=(b, size, size)) > 0.7).astype(np.float32)),
    }
    with tr.mesh:
        padded, _ = pad_to_multiple(batch, tr.mesh.devices.size)
        placed = shard_batch(tr.mesh, padded)
        outputs, loss = tr.eval_step(tr.state, placed)  # compile
        jax.block_until_ready(loss)
        with jax.profiler.trace(OUT):
            for _ in range(STEPS):
                outputs, loss = tr.eval_step(tr.state, placed)
            jax.block_until_ready((outputs, loss))
    tr.close()

    from tensorflow.python.profiler.internal import (
        _pywrap_profiler_plugin as pp,
    )
    paths = sorted(glob.glob(
        os.path.join(OUT, "plugins", "profile", "*", "*.xplane.pb")))
    data, _ = pp.xspace_to_tools_data([paths[-1]], "hlo_stats")
    t = json.loads(data.decode() if isinstance(data, bytes) else data)
    cols = [c.get("label") or c.get("id") for c in t["cols"]]

    def ci(name):
        return cols.index(name)

    rows = []
    for row in t["rows"]:
        c = [x.get("v") if isinstance(x, dict) else x for x in row["c"]]
        rows.append(c)
    rows.sort(key=lambda c: -float(c[ci("Total self time (us)")] or 0))
    total = sum(float(c[ci("Total self time (us)")] or 0) for c in rows)
    report = {
        "metric": f"{TASK}_eval_step_profile",
        "platform": "tpu" if ON_TPU else "cpu",
        "steps": STEPS,
        "total_self_us_per_step": round(total / STEPS),
        "top_ops": [
            {
                "us_per_step": round(
                    float(c[ci("Total self time (us)")]) / STEPS),
                "op": c[ci("HLO op name")],
                "fw_op": str(c[ci("Framework op name")])[:110],
                "bound_by": c[ci("Bound by")],
                "bw_gibs": round(
                    float(c[ci("Measured memory BW (GiB/s)")] or 0), 1),
                "src": str(c[ci("Source Info")]).split("/")[-1],
            }
            for c in rows[:12]
        ],
    }
    print(json.dumps(report))


if __name__ == "__main__":
    main()
