#!/bin/bash
# VERDICT r3 item 2: op-level profile of the semantic flagship (config 4)
# — only the DANet shape has profiles so far; explain the 63.6 GB/step.
set -eo pipefail
set -x
cd /root/repo
python scripts/profile_step.py --model deeplabv3 --batch 8 --out /tmp/prof_dl_b8 | tee artifacts/r4/prof_deeplab_b8.json
# second half of VERDICT item 2: attribute the DANet+bf16-scores residual
python scripts/profile_step.py --score-dtype bfloat16 --batch 8 --out /tmp/prof_danet_bf16s | tee artifacts/r4/prof_danet_bf16scores_b8.json
