#!/bin/bash
# VERDICT r3 item 7: re-quote f' at the 200-image val split
set -eo pipefail
set -x
cd /root/repo
export DPTPU_BENCH_RECOVERY_MINUTES=2
python scripts/convergence_runs.py f --epochs 60 | tee artifacts/r4/conv_f_v200.jsonl
