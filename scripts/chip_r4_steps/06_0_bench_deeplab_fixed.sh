#!/bin/bash
# Re-measure DeepLab config-4 after the CE-gather -> select-reduce fix
# (step 03's number measured the gather-bound code).
set -eo pipefail
set -x
cd /root/repo
DPTPU_BENCH_RECOVERY_MINUTES=2 DPTPU_BENCH_MODEL=deeplabv3 python bench.py | tee artifacts/r4/bench_mfu_deeplab_fixedloss.json
