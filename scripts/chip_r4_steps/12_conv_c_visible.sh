#!/bin/bash
# VERDICT r3 item 4: flagship-shape semantic convergence on the VISIBLE
# fixture (DeepLabV3-R101 513^2, 1000 train images, 60 epochs)
set -eo pipefail
set -x
cd /root/repo
export DPTPU_BENCH_RECOVERY_MINUTES=2
python scripts/convergence_runs.py e --epochs 60 | tee artifacts/r4/conv_c_visible.jsonl
