#!/bin/bash
# Why is the semantic eval step 202 ms/batch (~15x its expected forward
# cost)?  Trace the jitted eval step and name the ops.
set -eo pipefail
set -x
cd /root/repo
python scripts/profile_eval_step.py --task semantic --out /tmp/prof_eval_sem | tee artifacts/r4/prof_eval_semantic.json
