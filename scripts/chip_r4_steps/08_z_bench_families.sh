#!/bin/bash
# Post-gather-fix semantic family table: every context-head family at the
# BASELINE config-4 shape (R101 os=16 513² b8 bf16, aux head), one run.
set -eo pipefail
set -x
cd /root/repo
export DPTPU_BENCH_RECOVERY_MINUTES=2
for m in deeplabv3plus fcn pspnet ccnet encnet; do
  DPTPU_BENCH_MODEL=$m python bench.py | tee artifacts/r4/bench_family_$m.json
done
