#!/bin/bash
# VERDICT r3 item 5: the b16 fixes the op profiles prescribe, A/B'd with
# the official harness (cost-model + roofline fields in every record).
set -eo pipefail
set -x
cd /root/repo
export DPTPU_BENCH_RECOVERY_MINUTES=2
DPTPU_BENCH_BATCH=16 python bench.py | tee artifacts/r4/bench_b16_base.json
DPTPU_BENCH_BATCH=16 DPTPU_BENCH_BN_STATS=compute python bench.py | tee artifacts/r4/bench_b16_bnstats.json
DPTPU_BENCH_BATCH=16 DPTPU_BENCH_REMAT=1 DPTPU_BENCH_REMAT_POLICY=dots_saveable python bench.py | tee artifacts/r4/bench_b16_rematdots.json
DPTPU_BENCH_BATCH=16 DPTPU_BENCH_SCORE_DTYPE=bfloat16 python bench.py | tee artifacts/r4/bench_b16_bf16scores.json
DPTPU_BENCH_BATCH=16 DPTPU_BENCH_SCORE_DTYPE=bfloat16 DPTPU_BENCH_BN_STATS=compute python bench.py | tee artifacts/r4/bench_b16_bnstats_bf16scores.json
DPTPU_BENCH_BN_STATS=compute python bench.py | tee artifacts/r4/bench_b8_bnstats.json
DPTPU_BENCH_BN_STATS=compute DPTPU_BENCH_SCORE_DTYPE=bfloat16 python bench.py | tee artifacts/r4/bench_b8_bnstats_bf16scores.json
