#!/bin/bash
set -eo pipefail
set -x
cd /root/repo
DPTPU_BENCH_RECOVERY_MINUTES=2 DPTPU_BENCH_SCORE_DTYPE=bfloat16 python bench.py | tee artifacts/r4/bench_mfu_bf16scores.json
