#!/bin/bash
# VERDICT r3 item 3: per-stage val budgets — instance fast path, semantic
# crop-res fast path, and the full-res protocol's decode-heavy front
set -eo pipefail
set -x
cd /root/repo
export DPTPU_BENCH_RECOVERY_MINUTES=2
python scripts/bench_breakdown.py valhost valplace valstep valmetric data.val_batch=8 | tee artifacts/r4/breakdown_val_instance.json
python scripts/bench_breakdown.py valhost valplace valstep task=semantic model.name=deeplabv3 model.nclass=21 model.in_channels=3 model.output_stride=16 "data.crop_size=[513,513]" data.val_batch=8 data.device_guidance=false | tee artifacts/r4/breakdown_val_semantic.json
python scripts/bench_breakdown.py valhost task=semantic model.name=deeplabv3 model.nclass=21 model.in_channels=3 model.output_stride=16 "data.crop_size=[513,513]" data.val_batch=8 data.device_guidance=false eval_full_res=true | tee artifacts/r4/breakdown_val_semantic_fullres.json
