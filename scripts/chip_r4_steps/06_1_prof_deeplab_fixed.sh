#!/bin/bash
# Post-fix op profile: confirm the gather fusions are gone and find the
# next residual on the config-4 shape.
set -eo pipefail
set -x
cd /root/repo
python scripts/profile_step.py --model deeplabv3 --batch 8 --out /tmp/prof_dl_fixed | tee artifacts/r4/prof_deeplab_fixedloss_b8.json
