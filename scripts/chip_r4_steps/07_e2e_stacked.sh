#!/bin/bash
# VERDICT r3 items 3+6: val fast path rows + the stacked e2e headline,
# all in ONE sequential run (tunnel drift makes cross-run e2e deltas noise)
set -eo pipefail
set -x
cd /root/repo
export DPTPU_BENCH_RECOVERY_MINUTES=2
python scripts/bench_e2e.py 8 10 12 14 15 16 17 18 19 20 | tee artifacts/r4/bench_e2e_r4.jsonl
