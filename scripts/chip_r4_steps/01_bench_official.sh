#!/bin/bash
# Official bench, default config — highest-value artifact (writes the
# replay sidecar so BENCH_r04.json survives a wedged round-end window).
set -eo pipefail
set -x
cd /root/repo
DPTPU_BENCH_RECOVERY_MINUTES=2 python bench.py | tee artifacts/r4/bench_mfu.json
