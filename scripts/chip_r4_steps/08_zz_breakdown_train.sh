#!/bin/bash
# Train-side stage budget of the stacked headline config (v14): under
# perfect overlap e2e == min(host, place, step); names the binding stage
# at the achieved 48.0 imgs/s.
set -eo pipefail
set -x
cd /root/repo
export DPTPU_BENCH_RECOVERY_MINUTES=2
python scripts/bench_breakdown.py host place step dispatch data.packbits_masks=true model.pam_score_dtype=bfloat16 | tee artifacts/r4/breakdown_train_stacked.json
