#!/bin/bash
# Refresh the default-config bench + replay sidecar at queue tail so the
# round-end record measures the session's FINAL code state.
set -eo pipefail
set -x
cd /root/repo
DPTPU_BENCH_RECOVERY_MINUTES=2 python bench.py | tee artifacts/r4/bench_mfu_final.json
