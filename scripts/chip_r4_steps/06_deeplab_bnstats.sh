#!/bin/bash
# VERDICT r3 item 2: attack the semantic flagship's above-roofline bytes
set -eo pipefail
set -x
cd /root/repo
export DPTPU_BENCH_RECOVERY_MINUTES=2
DPTPU_BENCH_MODEL=deeplabv3 DPTPU_BENCH_BN_STATS=compute python bench.py | tee artifacts/r4/bench_deeplab_bnstats.json
