#!/bin/bash
# Full suite green-gate before the final bench refresh (runs on the idle
# host the queue guarantees between chip steps).
set -eo pipefail
set -x
cd /root/repo
python -m pytest tests/ -q 2>&1 | tail -5 | tee artifacts/r4/suite_final.txt
