#!/bin/bash
# accuracy gate for model.bn_fp32_stats=false (stacked with bf16 scores)
set -eo pipefail
set -x
cd /root/repo
export DPTPU_BENCH_RECOVERY_MINUTES=2
python scripts/convergence_runs.py g --epochs 30 | tee artifacts/r4/conv_g_bnstats.jsonl
