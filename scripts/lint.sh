#!/usr/bin/env bash
# jaxlint over everything device-adjacent: the package (serve/ included —
# the batcher feeds a jitted forward and is exactly the code whose silent
# retraces the rules exist to catch; telemetry/ included — instrumentation
# sits at step-loop boundaries and must never smuggle a host sync into
# them) plus bench.py, the official record.
# Mirror of the tier-1 gate (tests/test_lint_clean.py); run it before
# pushing anything that touches device code:
#
#     scripts/lint.sh                # whole surface
#     scripts/lint.sh --select JL002 # one rule
#
# Extra args pass through to the linter CLI (--select/--ignore/paths).
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m distributedpytorch_tpu.analysis \
    distributedpytorch_tpu bench.py "$@"
