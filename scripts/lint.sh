#!/usr/bin/env bash
# The static-analysis gate, all four layers in one command:
#
#   1. jaxlint — AST-level TPU hazards over everything device-adjacent:
#      the package (serve/ included — the batcher feeds a jitted forward
#      and is exactly the code whose silent retraces the rules exist to
#      catch, and serve/sessions.py + serve/swap.py specifically: the
#      session feature cache holds device buffers across requests and
#      the swap pool routes between per-generation compiled programs,
#      both one silent retrace away from a latency cliff; telemetry/
#      included — instrumentation sits at step-loop boundaries and must
#      never smuggle a host sync into them; chaos/ included — its
#      injection sites are woven INTO those loops and the disabled path
#      must stay one attribute check, no host syncs; train/sentinel.py +
#      train/supervise.py included — the sentinel's verdicts consume
#      ONLY the trainer's existing loss readbacks (no new host syncs
#      inside compiled programs, the JL-rule gate pins it) and the
#      supervisor must stay a stdlib process; train/precision.py +
#      ops/pallas_attention.py included — the mixed-precision policy
#      and the fused dual-attention kernels ARE the hot path, and a
#      host sync or silent retrace there costs every step;
#      parallel/plan.py included — the sharding-strategy planner
#      resolves every run's mesh + composed state layout, and its
#      memory-model arithmetic must stay pure host code: no device
#      touches, no traces at plan time; data/governor.py included —
#      the feed governor's tick rides INSIDE the step loop at the log
#      cadence, so it must stay pure perf-counter bookkeeping: no
#      device touches, no host syncs (consensus mode's allgather is
#      the one sanctioned, cadence-bounded exception — the preemption
#      guard's own contract), and its actuations must land only at
#      the epoch-boundary seam; parallel/consensus.py +
#      train/elastic.py included — replicated_decision is a host-sync
#      collective whose call sites must stay OUTSIDE the canonical
#      step programs (the checked-in cpu8 contracts pin exactly that:
#      consensus allgathers never appear in a compiled step), and the
#      elastic supervisor must stay a stdlib process that never
#      imports jax; data/packed.py included — the packed data plane's
#      reader sits on the loader hot path (one crc32 + one memcpy per
#      record, numpy + stdlib ONLY: it must stay importable pre-jax,
#      and its chaos seam must cost one attribute check disabled) and
#      the dptpu-pack CLI never touches a device; serve/quantize.py +
#      serve/aot.py included — the quantized forward's QTensor
#      dequant-at-use MUST stay jnp (numpy arithmetic on closure
#      constants folds eagerly at trace time and would silently bake
#      the f32 kernels back in), and the AOT cache's load path sits on
#      the replica boot path: crc + fallback logic only, no device
#      touches beyond deserialization, and `dptpu-aot --verify` stays
#      a pure-host sweep; serve/session_log.py + data/sessions.py +
#      train/continuous.py included — the flywheel's three legs: the
#      sink's offer() runs ON the serve worker between dispatches
#      (numpy + stdlib appends under one lock, no device touches, no
#      re-hashing), the session-log reader sits on the loader hot path
#      like data/packed.py (crc32 + memcpy per record, importable
#      pre-jax), and the continuous-mode supervisor is a host-side
#      polling loop that must never smuggle a sync into the fits it
#      launches — and the flywheel adds NO new jitted programs, so the
#      jaxaudit contract set below is unchanged by it;
#      telemetry/events.py + telemetry/doctor.py included — the flight
#      recorder's emit() rides every instrumented seam (its armed cost
#      is pinned <=2% of step and the unconfigured path is ONE list
#      check, no host syncs, no device touches) and the recorder +
#      timeline + doctor triple must stay stdlib+numpy importable
#      pre-jax: the supervisor publishes into the same log, and a dead
#      run dir must be diagnosable from any machine with no
#      accelerator stack — and the recorder adds NO new jitted
#      programs, so the jaxaudit contract set below is unchanged by it
#      too; serve/router.py + serve/fleet.py included — the fleet
#      front is pure host code by contract (stdlib http + subprocess:
#      routing hashes, the replica state machine, the health loop) and
#      must STAY that way: no device touches, no jax imports at module
#      scope, blocking I/O only outside the registry's lock (jaxrace
#      JR004 pins that), and the front adds NO new jitted programs —
#      the replicas it routes to own every compile, so the jaxaudit
#      contract set below is unchanged by it as well) plus bench.py,
#      the official record.
#      `jaxlint --stats` then polices the suppressions themselves: a
#      `# jaxlint:`/`# jaxguard:` disable whose rule no longer fires is
#      a dead waiver waiting to swallow the next real finding — it
#      fails the gate with the exact file:line to delete.
#   2. jaxguard check — cross-program SPMD-divergence + donation
#      safety (analysis/spmd.py + analysis/donation.py): JG001
#      host-divergent control over collective-issuing calls (the
#      silent multi-host deadlock; replicated_decision is the one
#      sanctioned laundering point), JG003/JG004 donation aliasing
#      across the trace boundary (the Orbax-restore segfault /
#      warm-start NaN class), and JG002 ordered per-mesh-axis
#      collective schedules cross-checked pairwise over the plan
#      ladder against tests/contracts/guard_schedules.<key>.json.
#      After a REVIEWED schedule change, regenerate with
#      `python -m distributedpytorch_tpu.analysis --guard update`.
#   3. jaxaudit check — IR-level compile contracts: the canonical
#      train/eval/serve programs (incl. the session split's
#      encode_step/decode_step, train_step_bf16 — the mixed-
#      precision bucketed-reduce fast path, JA002-audited against the
#      policy's declared accumulation points, its psum buckets pinned —
#      the int8-quantized serve programs serve_forward_int8_b1/b8 +
#      decode_int8, JA002-audited against QuantPolicy's declared
#      dequant points with the ~4x const-byte shrink pinned,
#      AND the per-strategy plan programs train_step_dp_tp /
#      train_step_dp_zero1 / train_step_dp_tp_zero1, whose contracts
#      pin the PER-MESH-AXIS collective inventory so a 2-D-mesh step
#      silently regressing to replicated fails on its vanished
#      model-axis collectives) are re-traced on the pinned 8-device
#      CPU topology and diffed against tests/contracts/ (collective
#      counts incl. async -start forms, output shapes, donation
#      aliasing, baked constants, FLOPs bounds).  After a REVIEWED
#      program change, regenerate with
#      `python -m distributedpytorch_tpu.analysis --ir update`.
#   4. jaxrace check — host-concurrency layer (analysis/race.py): the
#      serve stack is a multi-threaded HOST program (submit threads +
#      worker + hot-swap + signal handlers) and none of the jax-level
#      layers can see its hazards.  JR001 guarded-by discipline
#      (declared via `# jaxrace: guarded-by=self._lock` or
#      majority-inferred), JR002 lock-order inversion against the
#      blessed order, JR003 blocking/lock-taking signal handlers,
#      JR004 blocking calls under a held lock.  The guard map + lock
#      order are pinned in tests/contracts/threads.json (no platform
#      key — host threads are topology-independent); after a REVIEWED
#      threading change, regenerate with
#      `python -m distributedpytorch_tpu.analysis --race update`.
#      Runtime witness: DPTPU_THREADSAN=1 makes the under-load serve
#      tests validate the pinned guard map against real schedules.
#      `jaxlint --stats` polices `# jaxrace:` disables for staleness
#      alongside the other grammars.
#
# Mirror of the tier-1 gates (tests/test_lint_clean.py +
# tests/test_jaxguard.py + tests/test_jaxaudit.py +
# tests/test_jaxrace.py); run it before pushing anything that touches
# device code:
#
#     scripts/lint.sh                # all four layers
#     scripts/lint.sh --guard        # the AST-only layers (jaxlint +
#                                    # jaxguard AST half + jaxrace) —
#                                    # no jax import, pre-commit speed
#     scripts/lint.sh --select JL002 # one lint rule (skips IR gates)
#
# Extra args pass through to the LINTER CLI (--select/--ignore/paths)
# and skip the compile-backed halves (a scoped lint run shouldn't pay a
# trace).
set -euo pipefail
cd "$(dirname "$0")/.."
if [ "$#" -eq 1 ] && [ "$1" = "--guard" ]; then
    # fast pre-commit path: both AST layers, no backend
    python -m distributedpytorch_tpu.analysis \
        distributedpytorch_tpu bench.py
    python -m distributedpytorch_tpu.analysis --guard check --no-ir \
        distributedpytorch_tpu bench.py
    python -m distributedpytorch_tpu.analysis --race check \
        distributedpytorch_tpu bench.py
    exit 0
fi
python -m distributedpytorch_tpu.analysis \
    distributedpytorch_tpu bench.py "$@"
if [ "$#" -eq 0 ]; then
    python -m distributedpytorch_tpu.analysis --stats \
        distributedpytorch_tpu bench.py
    python -m distributedpytorch_tpu.analysis --guard check \
        distributedpytorch_tpu bench.py
    python -m distributedpytorch_tpu.analysis --race check \
        distributedpytorch_tpu bench.py
    python -m distributedpytorch_tpu.analysis --ir check
fi
