"""Health-gated chip work queue for the axon-tunneled TPU.

The tunnel wedges for hours at a time (BASELINE.md "tunnel hygiene"); chip
experiments therefore queue here instead of blocking a session.  Drop
numbered ``*.sh`` files into ``--queue-dir``; the runner polls backend
health with a hard-timeout subprocess probe (a wedged backend init cannot
take the poller down), and when the tunnel answers it executes queued files
in sorted order, one at a time, on an otherwise-idle host.  Completed files
are renamed ``<name>.done`` (or ``.fail``); per-step output is appended to
``<name>.log`` next to the queue file.  New files may be enqueued while the
runner is alive — it keeps draining until ``--max-hours`` elapses.

A ``RUNNING`` flag file is held in the queue dir while a step executes so a
concurrent session can avoid launching host-heavy work that would
cross-contaminate the measurement (numbers collapse ~2-3x when pytest runs
alongside a bench — BASELINE.md).

Generalizes the round-2 one-shot ``sweep_when_healthy.py`` pattern.
"""

from __future__ import annotations

import argparse
import os
import re
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from distributedpytorch_tpu.backend_health import tpu_reachable  # noqa: E402


def host_busy() -> str | None:
    """Name a host-loading process (pytest, another bench/sweep) if one is
    running — measurements taken alongside one collapse 2-3x on this
    1-core host (BASELINE.md), so the queue waits for an idle host."""
    try:
        out = subprocess.run(["ps", "-eo", "args"], capture_output=True,
                             text=True, timeout=10).stdout
    except Exception:
        return None
    # Anchor on the interpreter token, then scan the remaining argv tokens —
    # a bare whole-line substring scan would match unrelated processes whose
    # argv merely *mentions* these names (observed: a session wrapper whose
    # prompt text contains them), while a rigid positional regex misses
    # interpreter flags with separate arguments ("python -X faulthandler
    # scripts/...") and "python -c ... import perf_sweep ..." workers.
    markers = ("pytest", "bench.py", "bench_e2e", "bench_input",
               "pam_crossover", "perf_sweep", "profile_step",
               "convergence_runs", "bench_breakdown")
    for line in out.splitlines():
        toks = line.split()
        if not toks:
            continue
        interp = os.path.basename(toks[0])
        if interp.startswith("pytest"):
            return line.strip()[:120]
        if not re.fullmatch(r"python[\d.]*", interp):
            continue
        # Scan only the token that names WHAT python is running — a marker
        # anywhere in argv would wedge the queue behind an unrelated
        # daemon whose file argument merely mentions a bench name, while a
        # rigid positional scan misses interpreter flags with separate
        # arguments.  Three invocation shapes:
        #   python -m <module> ...   -> the module token
        #   python -c <code>         -> the code (imports benches by name)
        #   python [flags] script.py -> first token that looks like a path
        args = toks[1:]
        if "-m" in args:
            i = args.index("-m")
            probe = [args[i + 1]] if i + 1 < len(args) else []
        elif "-c" in args:
            probe = args
        else:
            nonflags = [t for t in args if not t.startswith("-")]
            probe = [next((t for t in nonflags
                           if t.endswith(".py") or "/" in t),
                          nonflags[0] if nonflags else "")]
        if any(m in t for m in markers for t in probe):
            return line.strip()[:120]
    return None


def _natural_key(name: str):
    """Numeric-aware sort: 2_x.sh before 10_x.sh (plain sorted() would run
    10 first and break producer→consumer step ordering)."""
    return [int(p) if p.isdigit() else p
            for p in re.split(r"(\d+)", name)]


def pending(queue_dir: str, settle_seconds: float = 5.0) -> list[str]:
    """Queued step files in natural-numeric order.

    Files modified within the last ``settle_seconds`` are held back: a file
    still being written (cat >, scp) would otherwise execute as a truncated
    prefix — bash runs a half-written script cleanly up to the cut and the
    runner would mark it .done.  Writers that rename into place are picked
    up immediately on the next poll anyway.
    """
    now = time.time()
    names = []
    for f in os.listdir(queue_dir):
        if not f.endswith(".sh"):
            continue
        try:
            if now - os.path.getmtime(os.path.join(queue_dir, f)) \
                    < settle_seconds:
                continue
        except OSError:
            continue
        names.append(f)
    return sorted(names, key=_natural_key)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--queue-dir", required=True)
    ap.add_argument("--poll-seconds", type=int, default=300)
    ap.add_argument("--probe-timeout", type=int, default=240)
    ap.add_argument("--step-timeout", type=int, default=7200)
    ap.add_argument("--max-hours", type=float, default=11.0)
    args = ap.parse_args()

    os.makedirs(args.queue_dir, exist_ok=True)
    running_flag = os.path.join(args.queue_dir, "RUNNING")
    deadline = time.time() + args.max_hours * 3600

    # SIGTERM must unwind like an exception, not die in place: the default
    # handler would skip the finally blocks below, stranding the RUNNING
    # flag and the detached step process group — a restarted runner would
    # then launch the same step alongside the orphan.
    def _term(signum, frame):
        raise KeyboardInterrupt(f"signal {signum}")

    signal.signal(signal.SIGTERM, _term)

    while time.time() < deadline:
        steps = pending(args.queue_dir)
        if not steps:
            time.sleep(args.poll_seconds)
            continue
        busy = host_busy()
        if busy is not None:
            print("[chip_queue] host busy (%s); waiting" % busy, flush=True)
            time.sleep(args.poll_seconds)
            continue
        if not tpu_reachable(args.probe_timeout):
            print("[chip_queue] tunnel unhealthy; %d step(s) waiting"
                  % len(steps), flush=True)
            time.sleep(args.poll_seconds)
            continue
        step = os.path.join(args.queue_dir, steps[0])
        log = step + ".log"
        print("[chip_queue] running %s" % step, flush=True)
        open(running_flag, "w").close()
        try:
            with open(log, "a") as lf:
                # Own process group (start_new_session): a step timeout must
                # kill the step's WHOLE tree, not just the bash wrapper — an
                # orphaned benchmark child would keep loading the chip/host
                # while the next step runs, the exact cross-contamination
                # the RUNNING flag exists to prevent.
                proc = subprocess.Popen(["bash", step], stdout=lf,
                                        stderr=subprocess.STDOUT, cwd=REPO,
                                        start_new_session=True)
                try:
                    ok = proc.wait(timeout=args.step_timeout) == 0
                except subprocess.TimeoutExpired:
                    lf.write("\n[chip_queue] step timeout; killing group\n")
                    try:
                        os.killpg(proc.pid, signal.SIGKILL)
                    except ProcessLookupError:
                        pass
                    proc.wait()
                    ok = False
                except BaseException:
                    # runner interrupted (SIGTERM/Ctrl-C) mid-step: take
                    # the detached step group down with us — an orphan
                    # would contaminate whatever runs next on this host.
                    # The step file stays *.sh so a restart re-runs it.
                    try:
                        os.killpg(proc.pid, signal.SIGKILL)
                    except ProcessLookupError:
                        pass
                    raise
        finally:
            if os.path.exists(running_flag):
                os.remove(running_flag)
        try:
            os.rename(step, step + (".done" if ok else ".fail"))
        except FileNotFoundError:
            # the step file vanished mid-run (an operator renamed/removed
            # it) — a missing source must not take the whole runner down;
            # whatever replaced it will be picked up by the next poll
            print("[chip_queue] %s vanished during run; continuing" % step,
                  flush=True)
        else:
            print("[chip_queue] %s -> %s" % (step, "done" if ok else "FAIL"),
                  flush=True)
    print("[chip_queue] window elapsed; %d step(s) left"
          % len(pending(args.queue_dir)), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
