"""CLI driver: ``python -m distributedpytorch_tpu [--config c.json] [k=v ...]``.

The runnable equivalent of ``python train_pascal.py`` (the reference's only
entry point — a module-level script with inline constants,
train_pascal.py:41-309), but configured by JSON + dotted-path overrides:

    python -m distributedpytorch_tpu data.root=/data/voc optim.lr=1e-7
    python -m distributedpytorch_tpu --config exp.json epochs=50
    python -m distributedpytorch_tpu --fake-data epochs=2   # smoke run

Multi-host: launch the same command on every host of the pod;
``jax.distributed.initialize`` handles rendezvous, the loaders shard by
process index, and only process 0 writes logs/checkpoint metadata.
"""

from __future__ import annotations

import argparse
import sys

from .backend_health import pin_requested_platform
from .train import Config, Trainer, apply_overrides, from_json


def main(argv: list[str] | None = None) -> int:
    # Serve mode delegates wholesale: the inference service has its own
    # argument surface (serve/__main__.py), and mixing it into the training
    # parser would tangle two unrelated CLIs.  `--serve` must lead.
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv[:1] == ["--serve"]:
        from .serve.__main__ import main as serve_main
        return serve_main(argv[1:])
    # An env-requested platform (JAX_PLATFORMS=cpu for smoke runs) can be
    # overridden by a site-installed accelerator plugin during interpreter
    # startup; re-pin it before any backend init, or the run hangs trying to
    # reach an accelerator the user explicitly opted out of.
    pin_requested_platform()
    parser = argparse.ArgumentParser(
        prog="distributedpytorch_tpu",
        description="TPU-native interactive-segmentation training",
        epilog="Serving: `python -m distributedpytorch_tpu --serve ...` "
               "(equivalently `python -m distributedpytorch_tpu.serve`) "
               "starts the batched inference service; see its --help.")
    parser.add_argument("--config", help="JSON config file")
    parser.add_argument("--fake-data", action="store_true",
                        help="synthetic VOC fixture (smoke runs, no dataset)")
    parser.add_argument("--validate-only", action="store_true",
                        help="run the eval protocol once and exit")
    parser.add_argument("--predict", metavar="IMAGE",
                        help="inference mode: segment IMAGE from --points "
                             "clicks using the run in --run-dir")
    parser.add_argument("--run-dir",
                        help="training run dir (config.json + checkpoints/) "
                             "for --predict")
    parser.add_argument("--points",
                        help='4 extreme-point clicks "x1,y1 x2,y2 x3,y3 '
                             'x4,y4" for --predict on instance-task runs '
                             "(semantic runs segment the whole image)")
    parser.add_argument("--out", default="mask.png",
                        help="output mask PNG for --predict")
    parser.add_argument("--overlay",
                        help="also write an RGB overlay PNG (--predict)")
    parser.add_argument("--slide", action="store_true",
                        help="semantic runs: sliding-window full-resolution "
                             "inference instead of whole-image resize")
    parser.add_argument("--threshold", type=float, default=None,
                        help="binarization threshold for --predict on "
                             "instance-task runs (default 0.5)")
    parser.add_argument("--distributed", action="store_true",
                        help="call jax.distributed.initialize() first "
                             "(multi-host pods)")
    parser.add_argument("overrides", nargs="*",
                        help="dotted config overrides, e.g. optim.lr=1e-7")
    args = parser.parse_args(argv)

    # Predict mode first: it must not fall into the multi-host rendezvous
    # below (jax.distributed.initialize() blocks waiting for peers).
    if args.predict:
        if not args.run_dir:
            parser.error("--predict requires --run-dir (--points too for "
                         "instance-task runs)")
        if args.config or args.fake_data or args.validate_only \
                or args.distributed or args.overrides:
            parser.error(
                "--predict reads its configuration from <run-dir>/"
                "config.json; --config/--fake-data/--validate-only/"
                "--distributed/overrides do not apply (got "
                f"{args.overrides or 'training-mode flags'})")
        from .predict import predict_cli
        try:
            summary = predict_cli(args.run_dir, args.predict, args.points,
                                  args.out, threshold=args.threshold,
                                  overlay_path=args.overlay,
                                  slide=args.slide)
        except ValueError as e:  # missing points / bad clicks / wrong task
            parser.error(str(e))
        print(summary)
        return 0

    if args.distributed:
        import jax
        jax.distributed.initialize()

    cfg = from_json(args.config) if args.config else Config()
    if args.fake_data:
        cfg = apply_overrides(cfg, {"data.fake": True})
    if args.overrides:
        cfg = apply_overrides(cfg, args.overrides)

    trainer = Trainer(cfg)
    try:
        if args.validate_only:
            metrics = trainer.validate()
            print({k: v for k, v in metrics.items() if k != "_first_batch"})
        else:
            trainer.fit()
    finally:
        trainer.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
