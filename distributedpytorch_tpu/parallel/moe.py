"""Expert parallelism: a GShard/Switch-style Mixture-of-Experts layer.

The reference has no MoE (SURVEY.md §2.5 marks EP "ABSENT"), but expert
parallelism completes this framework's parallelism set (data — parallel.step,
tensor — parallel.tp, pipeline — parallel.pipeline, sequence — parallel.ring
/ parallel.ulysses).

TPU-native design — the GShard dense-dispatch idiom, not dynamic routing:

* routing is *static-shaped*: every token gets a one-hot dispatch tensor
  (tokens × experts × capacity) built from a top-1 (Switch) or top-2 router
  with a fixed per-expert capacity; overflow tokens are dropped (combine
  weight 0) so no shape ever depends on the data — XLA requirement;
* expert FFN parameters are one stacked pytree (E, d, h)/(E, h, d) whose
  leading (expert) dim is sharded over an ``expert`` mesh axis; the dispatch/
  combine einsums are partitioned by GSPMD, which inserts the all-to-alls
  that move token slots to their expert's device and back — no hand-written
  communication;
* the router's load-balancing auxiliary loss (Shazeer et al.) keeps the
  dispatch near-uniform so per-expert capacity (and thus per-device compute)
  stays balanced.

``MoEMlp`` wraps the functional core as a Flax module for use inside model
heads; :func:`ep_param_specs` + :func:`make_moe_apply` give the meshed
expert-parallel execution path.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import make_mesh_1d

#: canonical expert axis name
EXPERT_AXIS = "expert"


def make_expert_mesh(experts: int, devices=None) -> Mesh:
    """A 1-D ``(expert,)`` mesh of ``experts`` devices — one expert each."""
    return make_mesh_1d(experts, EXPERT_AXIS, devices)


def expert_capacity(n_tokens: int, n_experts: int,
                    capacity_factor: float) -> int:
    """Per-expert token slots: ceil(tokens/experts · factor), min 1."""
    return max(1, math.ceil(n_tokens / n_experts * capacity_factor))


def router(x: jax.Array, w_gate: jax.Array, *, k: int,
           capacity: int) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Top-``k`` token→expert routing with fixed capacity.

    ``x``: (N, d) tokens; ``w_gate``: (d, E).  Returns
    ``(dispatch, combine, aux_loss)`` with ``dispatch``: (N, E, C) one-hot
    slot assignment, ``combine``: (N, E, C) gate-weighted dispatch, and the
    load-balancing auxiliary loss (scalar, ≥ 1 at perfect balance for k=1).

    Slot assignment is a cumsum over token order per expert (GShard's
    position-in-expert); tokens past ``capacity`` get all-zero rows — dropped,
    exactly like Switch's overflow (the caller's residual path carries them).
    """
    n, _ = x.shape
    n_experts = w_gate.shape[-1]
    if k > n_experts:
        # Beyond E rounds every expert is masked to -inf and argmax would
        # silently re-pick expert 0, double-dispatching tokens.
        raise ValueError(f"top-k routing needs k ({k}) <= experts "
                         f"({n_experts})")
    logits = jnp.einsum("nd,de->ne", x, w_gate,
                        preferred_element_type=jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)  # (N, E)

    dispatch = jnp.zeros((n, n_experts, capacity), jnp.float32)
    combine = jnp.zeros((n, n_experts, capacity), jnp.float32)
    # Slots consumed per expert by earlier-priority rounds, so the k=2 second
    # choice allocates after the first choice's tokens.
    prior_alloc = jnp.zeros((n_experts,), jnp.float32)
    masked_probs = probs
    frac_dispatched = jnp.zeros((n_experts,), jnp.float32)
    for _ in range(k):
        choice = jnp.argmax(masked_probs, axis=-1)  # (N,)
        onehot = jax.nn.one_hot(choice, n_experts)  # (N, E)
        gate = (probs * onehot).sum(-1)  # (N,)
        # Slot index = same-expert tokens ahead of me (+ earlier-round
        # claims); exclusive cumsum keeps it static-shaped.
        ahead = jnp.cumsum(onehot, axis=0) - onehot + prior_alloc[None, :]
        pos = (ahead * onehot).sum(-1).astype(jnp.int32)  # (N,)
        # one_hot of an out-of-capacity position is the zero row — overflow
        # tokens drop out of dispatch/combine with no dynamic shapes.
        slot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)  # (N, C)
        d = onehot[:, :, None] * slot[:, None, :]
        dispatch = dispatch + d
        combine = combine + gate[:, None, None] * d
        frac_dispatched = frac_dispatched + onehot.mean(0)
        prior_alloc = prior_alloc + onehot.sum(0)
        # the next round must pick a different expert per token
        masked_probs = jnp.where(onehot > 0, -jnp.inf, masked_probs)
    # Load-balancing loss: E · Σ_e (token fraction to e) · (mean prob of e).
    aux = n_experts * jnp.sum((frac_dispatched / k) * probs.mean(0))
    return dispatch, combine, aux


def moe_ffn(stacked: dict[str, jax.Array], x: jax.Array, *, k: int = 1,
            capacity_factor: float = 1.25,
            mesh: Mesh | None = None) -> tuple[jax.Array, jax.Array]:
    """The functional MoE FFN: route, dispatch, per-expert MLP, combine.

    ``stacked``: {'w_gate': (d, E), 'w1': (E, d, h), 'b1': (E, h),
    'w2': (E, h, d), 'b2': (E, d)}.  ``x``: (N, d) tokens.  Returns
    ``(y, aux_loss)`` with ``y``: (N, d); dropped tokens produce zero rows
    (callers keep a residual connection, as in Switch).

    With ``mesh``, expert-dim intermediates are sharding-constrained to the
    ``expert`` axis so GSPMD runs each expert's matmuls on its own device and
    inserts the dispatch/return all-to-alls.
    """
    n, d = x.shape
    n_experts = stacked["w1"].shape[0]
    capacity = expert_capacity(n, n_experts, capacity_factor)
    dispatch, combine, aux = router(x, stacked["w_gate"], k=k,
                                    capacity=capacity)
    # (N,E,C)·(N,d) -> (E,C,d): the all-to-all boundary under EP.
    expert_in = jnp.einsum("nec,nd->ecd", dispatch, x,
                           preferred_element_type=jnp.float32)
    if mesh is not None:
        expert_in = jax.lax.with_sharding_constraint(
            expert_in, NamedSharding(mesh, P(EXPERT_AXIS)))
    h = jax.nn.relu(
        jnp.einsum("ecd,edh->ech", expert_in, stacked["w1"],
                   preferred_element_type=jnp.float32)
        + stacked["b1"][:, None, :])
    out = jnp.einsum("ech,ehd->ecd", h, stacked["w2"],
                     preferred_element_type=jnp.float32) \
        + stacked["b2"][:, None, :]
    if mesh is not None:
        out = jax.lax.with_sharding_constraint(
            out, NamedSharding(mesh, P(EXPERT_AXIS)))
    y = jnp.einsum("nec,ecd->nd", combine, out,
                   preferred_element_type=jnp.float32)
    return y.astype(x.dtype), aux


def ep_param_specs(stacked: dict[str, Any]) -> dict[str, P]:
    """PartitionSpec pytree: expert-stacked leaves sharded on their leading
    (expert) dim; the router gate replicated."""
    return {
        k: (P() if k == "w_gate"
            else P(*([EXPERT_AXIS] + [None] * (v.ndim - 1))))
        for k, v in stacked.items()
    }


def make_moe_apply(mesh: Mesh, *, k: int = 1, capacity_factor: float = 1.25):
    """Jitted expert-parallel ``(stacked_params, tokens) -> (y, aux)``:
    expert-stacked params sharded over the ``expert`` axis, tokens
    replicated in/out.  GSPMD owns the all-to-alls."""

    def global_fn(stacked, x):
        return moe_ffn(stacked, x, k=k, capacity_factor=capacity_factor,
                       mesh=mesh)

    def place(stacked):
        specs = ep_param_specs(stacked)
        return {kk: jax.device_put(v, NamedSharding(mesh, specs[kk]))
                for kk, v in stacked.items()}

    return jax.jit(global_fn), place


def init_moe_params(rng: jax.Array, *, d: int, hidden: int,
                    n_experts: int) -> dict[str, jax.Array]:
    """LeCun-normal expert stacks + zero biases + small router."""
    kg, k1, k2 = jax.random.split(rng, 3)
    init = nn.initializers.lecun_normal()
    return {
        "w_gate": init(kg, (d, n_experts), jnp.float32),
        "w1": init(k1, (n_experts, d, hidden), jnp.float32),
        "b1": jnp.zeros((n_experts, hidden), jnp.float32),
        "w2": init(k2, (n_experts, hidden, d), jnp.float32),
        "b2": jnp.zeros((n_experts, d), jnp.float32),
    }


class MoEMlp(nn.Module):
    """Flax wrapper: tokens (B, N, d) -> (B, N, d) with a residual carrying
    dropped tokens; stores the aux loss in the ``losses`` collection so a
    training loss can add ``aux_weight * moe_aux``."""

    n_experts: int
    hidden: int
    k: int = 1
    capacity_factor: float = 1.25

    @nn.compact
    def __call__(self, x):
        b, n, d = x.shape
        stacked = {
            "w_gate": self.param("w_gate", nn.initializers.lecun_normal(),
                                 (d, self.n_experts)),
            "w1": self.param("w1", nn.initializers.lecun_normal(),
                             (self.n_experts, d, self.hidden)),
            "b1": self.param("b1", nn.initializers.zeros,
                             (self.n_experts, self.hidden)),
            "w2": self.param("w2", nn.initializers.lecun_normal(),
                             (self.n_experts, self.hidden, d)),
            "b2": self.param("b2", nn.initializers.zeros,
                             (self.n_experts, d)),
        }
        y, aux = moe_ffn(stacked, x.reshape(b * n, d), k=self.k,
                         capacity_factor=self.capacity_factor)
        self.sow("losses", "moe_aux", aux)
        return x + y.reshape(b, n, d)
