"""The sharding-strategy planner: one declarative knob for the parallelism zoo.

The axes this package grew one at a time — batch sharding over ``data``
(:mod:`mesh`), tensor parallelism over ``model`` (:mod:`tp`), ZeRO-1
optimizer-state sharding (:mod:`zero`), hierarchical multi-slice meshes
(:func:`mesh.make_hybrid_mesh`) — each work, but composing them meant
hand-wiring four low-level ``mesh.*`` booleans plus the matching
``state_shardings`` and step kwargs, and nothing validated the result.
This module makes the composition declarative: the ``parallel`` config
section names a **strategy** and the planner resolves it into a
validated, executable :class:`Plan` —

* the mesh shape (``data x model``, hybrid over DCN slices when
  ``mesh.slices > 1``);
* the composed state layout: ``tp_param_specs`` over ``model`` and
  ``zero_opt_specs`` over ``data`` merged on ONE spec tree (the two
  rules were individually green since their PRs but never combined into
  a single source of truth);
* the matching train/eval step builders (state shardings threaded, so a
  2-D plan's compiled step consumes and produces exactly the layout the
  plan created);
* a JSON-able :meth:`Plan.block` recorded in ``fit_summary.json``,
  checkpoint metas and bench records, so every artifact names the plan
  that produced it.

Strategies (the mesh-shape ladder, smallest model axis first)::

    dp            (n, 1)   replicated state, GSPMD gradient all-reduce
    dp_zero1      (n, 1)   + optimizer state sharded over `data`
    dp_tp         (d, m)   + kernels/momentum sharded over `model`
    dp_tp_zero1   (d, m)   both: opt leaves shard over data AND model
    auto                   walk the ladder with the memory model below

``strategy=auto`` estimates per-device bytes — params, grads, optimizer
state (each divided by exactly the axes its spec shards it over), the
batch shard, and an activation term (the XLA cost-analysis cache's
bytes-accessed figure when a lowered program is available, a documented
parametric bound otherwise) — against the chip's HBM and picks the
first rung that fits.  Detection is pure (no devices touched), so a CPU
host can plan a TPU-pod layout and tests pin the ladder without
hardware.

Every resolvable strategy is also a **named canonical program**
(``train_step_dp_tp``, ``train_step_dp_zero1``, ``train_step_dp_tp_zero1``
— :mod:`analysis.contracts`) with a checked-in jaxaudit contract pinning
per-mesh-axis collective counts, so a 2-D-mesh step silently regressing
to replicated is a contract failure, not a vibe.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Mapping

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .consensus import replicated_decision
from .mesh import DATA_AXIS, MODEL_AXIS, make_hybrid_mesh, make_mesh
from .tp import tp_param_specs
from .zero import zero_opt_specs

#: resolvable strategies, in ladder order (auto resolves to one of these)
STRATEGIES = ("dp", "dp_zero1", "dp_tp", "dp_tp_zero1")

#: which strategies shard what
_SHARD_PARAMS = {"dp_tp", "dp_tp_zero1"}
_SHARD_OPT = {"dp_zero1", "dp_tp_zero1"}

#: strategies the bucketed overlapped all-reduce (train.reduce_buckets)
#: composes with: the shard_map region owns only params (replicated) and
#: the batch shard, and ZeRO-1 lives entirely in the optimizer update
#: OUTSIDE that region — so dp and dp_zero1 compose.  TP does not: its
#: params are model-axis sharded, which the region's replicated in_specs
#: cannot express (and per-device fwd/bwd over sharded kernels is a
#: different algorithm, not a layout).
BUCKET_COMPATIBLE = ("dp", "dp_zero1")

#: reduce_buckets rejection: the nearest strategy that keeps the buckets
NEAREST_BUCKET_STRATEGY = {"dp_tp": "dp", "dp_tp_zero1": "dp_zero1"}

#: auto's activation-residency fallback when no lowered program exists in
#: the cost-analysis cache: live activation bytes ~= this many bytes per
#: input-tensor byte on the device's batch shard.  Measured on the
#: flagship step (DANet-R101 512px f32, peak_bytes_in_use minus
#: state+batch, cpu8 and TPU within ~30% of each other); deliberately a
#: conservative over-estimate — auto moving up the ladder one rung early
#: costs a little collective traffic, under-estimating OOMs the run.
ACTIVATION_BYTES_PER_INPUT_BYTE = 24.0

#: auto's HBM fallback when the backend exposes no bytes_limit (CPU dev
#: boxes): the smallest per-chip HBM of the supported TPU generations
#: (v2's 8 GiB is retired; v3 16 GiB is the floor we plan for)
DEFAULT_HBM_BYTES = 16 * 2**30


class PlanError(ValueError):
    """An unresolvable or inconsistent parallel plan — every message
    names the nearest supported alternative, so the error is a route,
    not a wall."""


def topology_fingerprint(n_devices: int | None = None) -> str:
    """The live topology's identity, ``"<platform>:<n_devices>/p<procs>"``
    (e.g. ``cpu:8/p1``) — what elastic membership change means: a plan
    stamped with one fingerprint restored under another IS a topology
    crossing, even when the *layout* normalizes equal (a legacy
    ``data=None`` dp plan resolves to "all devices" on any topology, so
    the layout alone cannot see a shrink).  Stamped into every
    :meth:`Plan.block` the trainer resolves, and thereby into every
    checkpoint meta and fit summary — the supervisor-side re-plan
    trigger reads it without Orbax."""
    import jax

    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    return f"{devs[0].platform}:{int(n_devices)}/p{jax.process_count()}"


def fingerprint_devices(fp) -> int | None:
    """The device count a :func:`topology_fingerprint` names (None for
    malformed/absent fingerprints) — lets cross-plan detection resolve a
    saved ``data=None`` layout against the topology it was SAVED under,
    not the one it is restoring onto."""
    try:
        return int(str(fp).split(":", 1)[1].split("/", 1)[0])
    except (IndexError, ValueError):
        return None


@dataclasses.dataclass(frozen=True)
class _AxisMesh:
    """Duck-typed stand-in for :class:`jax.sharding.Mesh` where only the
    axis sizes matter (``tp_param_specs`` / ``zero_opt_specs`` read
    ``mesh.shape[axis]`` and ``mesh.axis_names``) — lets the planner and
    its memory model reason about topologies this host cannot build
    (planning a tpu32 layout from a CPU box, unit tests without
    devices)."""

    shape: Mapping[str, int]

    @property
    def axis_names(self) -> tuple:
        return tuple(self.shape)


@dataclasses.dataclass(frozen=True)
class Plan:
    """One resolved, validated parallel layout.

    ``strategy`` is always concrete (never ``auto``); ``data`` may be
    ``None`` meaning "every device not claimed by ``model``" (resolved
    by ``make_mesh`` at construction).  Frozen and JSON-able via
    :meth:`block` — the form recorded in fit summaries, checkpoint metas
    and bench records.
    """

    strategy: str
    data: int | None = None
    model: int = 1
    slices: int = 1
    process_is_granule: bool | None = None
    #: the topology this plan was resolved AGAINST
    #: (:func:`topology_fingerprint`) — None for hand-built plans and
    #: planning-only resolutions; ``plan_from_config`` (the trainer
    #: entry) always stamps it, so live runs' metas carry it
    topology: str | None = None

    @property
    def shard_params(self) -> bool:
        return self.strategy in _SHARD_PARAMS

    @property
    def shard_opt_state(self) -> bool:
        return self.strategy in _SHARD_OPT

    @property
    def sharded(self) -> bool:
        """Whether the state carries any non-replicated layout (the
        condition for threading ``state_shardings`` into the steps)."""
        return self.shard_params or self.shard_opt_state

    def block(self) -> dict:
        """The JSON record block (schema-stable keys)."""
        return {
            "strategy": self.strategy,
            "data": self.data,
            "model": self.model,
            "slices": self.slices,
            "shard_params": self.shard_params,
            "shard_opt_state": self.shard_opt_state,
            "topology": self.topology,
        }

    def describe(self) -> str:
        d = self.data if self.data is not None else "*"
        s = f"{self.strategy} (data={d} x model={self.model}"
        if self.slices != 1:
            s += f" x slices={self.slices}"
        return s + ")"

    # ------------------------------------------------------------- mesh
    def make_mesh(self, devices=None) -> Mesh:
        """The plan's mesh: plain 2-D ``(data, model)``, or the hybrid
        ICI+DCN layout when the plan spans slices."""
        if self.slices != 1:
            return make_hybrid_mesh(
                self.slices, data=self.data, model=self.model,
                devices=devices,
                process_is_granule=self.process_is_granule)
        return make_mesh(data=self.data, model=self.model, devices=devices)

    def axis_sizes(self, n_devices: int | None = None) -> dict:
        """``{"data": d, "model": m}`` with ``data`` resolved against
        ``n_devices`` when the plan left it implicit.  The ``data`` size
        includes the DCN (slices) factor — hybrid meshes fold slices
        into the data axis (:func:`mesh.make_hybrid_mesh`)."""
        data = self.data
        if data is None:
            if n_devices is None:
                n_devices = len(jax.devices())
            if n_devices % (self.model * self.slices):
                raise PlanError(
                    f"{n_devices} devices not divisible by "
                    f"model={self.model} x slices={self.slices}")
            data = n_devices // (self.model * self.slices)
        return {DATA_AXIS: data * self.slices, MODEL_AXIS: self.model}

    # -------------------------------------------------------- shardings
    def state_specs(self, state: Any, mesh: Mesh | None = None) -> Any:
        """The COMPOSED ``PartitionSpec`` tree for a ``TrainState`` (or
        any pytree with ``params``/``opt_state``/``batch_stats`` attrs):
        ``tp_param_specs`` over ``model`` on params and momentum,
        ``zero_opt_specs`` over ``data`` layered on the optimizer leaves
        — the one place both rules meet one tree.  ``state`` may hold
        arrays or ``ShapeDtypeStruct`` templates; ``mesh`` may be a real
        mesh or None (axis sizes come from the plan)."""
        sizes = mesh.shape if mesh is not None else self.axis_sizes()
        am = _AxisMesh(dict(sizes))
        repl = lambda tree: jax.tree.map(lambda _: P(), tree)  # noqa: E731
        params = tp_param_specs(state.params, am) if self.shard_params \
            else repl(state.params)
        opt_base = tp_param_specs(state.opt_state, am) \
            if self.shard_params else None
        if self.shard_opt_state:
            opt = zero_opt_specs(state.opt_state, am, base_specs=opt_base)
        else:
            opt = opt_base if opt_base is not None \
                else repl(state.opt_state)
        return state.replace(
            step=P(), rng=P(), params=params,
            batch_stats=repl(state.batch_stats), opt_state=opt)

    def state_shardings(self, state: Any, mesh: Mesh) -> Any | None:
        """The sharding pytree ``make_train_step`` pins the state with:
        ``None`` for unsharded plans (the replicated default), the live
        arrays' own shardings when ``state`` holds them (exact — what
        ``create_train_state`` actually placed), the spec-derived
        ``NamedSharding`` tree for struct-only states (the canonical
        contract programs, which never initialize weights)."""
        if not self.sharded:
            return None
        leaves = jax.tree.leaves(state)
        if leaves and isinstance(leaves[0], jax.Array):
            from .tp import state_shardings as live_shardings

            return live_shardings(state)
        specs = self.state_specs(state, mesh)
        return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                            is_leaf=lambda x: isinstance(x, P))

    # ---------------------------------------------------------- builders
    def build_state(self, rng, model, tx, input_shape: tuple,
                    mesh: Mesh | None = None):
        """``create_train_state`` under this plan's layout."""
        from .step import create_train_state

        mesh = mesh if mesh is not None else self.make_mesh()
        with mesh:
            return create_train_state(
                rng, model, tx, input_shape, mesh=mesh,
                shard_params=self.shard_params,
                shard_opt_state=self.shard_opt_state)

    def abstract_state(self, model, tx, input_shape: tuple,
                       mesh: Mesh | None = None):
        """Shape/dtype-only ``TrainState`` template under this plan
        (``jax.eval_shape`` — no weights initialized, no compile): what
        the memory model and the canonical contract programs consume."""
        from .step import create_train_state

        mesh = mesh if mesh is not None else self.make_mesh()
        with mesh:
            return jax.eval_shape(lambda: create_train_state(
                jax.random.PRNGKey(0), model, tx, input_shape, mesh=mesh,
                shard_params=self.shard_params,
                shard_opt_state=self.shard_opt_state))

    def make_train_step(self, model, tx, *, mesh: Mesh, state: Any,
                        **kwargs):
        """The plan-matched jitted train step: ``make_train_step`` with
        this plan's mesh and state shardings threaded.  ``state`` may be
        live or abstract (see :meth:`state_shardings`); every other
        kwarg passes through."""
        from .step import make_train_step

        return make_train_step(
            model, tx, mesh=mesh,
            state_shardings=self.state_shardings(state, mesh), **kwargs)

    def make_eval_step(self, model, *, mesh: Mesh, state: Any, **kwargs):
        from .step import make_eval_step

        return make_eval_step(
            model, mesh=mesh,
            state_shardings=self.state_shardings(state, mesh), **kwargs)


# ----------------------------------------------------------- resolution

def resolve_plan(strategy: str, n_devices: int | None = None,
            data: int | None = None, model: int = 0, slices: int = 1,
            process_is_granule: bool | None = None) -> Plan:
    """One concrete strategy -> a validated :class:`Plan`.

    ``model=0`` derives the axis: 1 for the dp family, 2 (the smallest
    live tensor-parallel degree) for the tp family.  Divisibility is
    checked here, against ``n_devices`` (default: the live device
    count), so a bad request fails at plan time with the ladder spelled
    out — not at mesh construction with a bare arithmetic error.
    """
    if strategy not in STRATEGIES:
        raise PlanError(
            f"unknown parallel.strategy {strategy!r} — pick one of "
            f"{list(STRATEGIES)} (or 'auto' to let the memory model "
            "walk that ladder)")
    wants_tp = strategy in _SHARD_PARAMS
    if model == 0:
        model = 2 if wants_tp else 1
    if wants_tp and model < 2:
        raise PlanError(
            f"strategy {strategy!r} shards params over the model axis "
            f"but model={model}; give parallel.model >= 2, or use "
            f"{'dp_zero1' if strategy == 'dp_tp_zero1' else 'dp'} for a "
            "1-wide model axis")
    if not wants_tp and model != 1:
        raise PlanError(
            f"strategy {strategy!r} has a 1-wide model axis but "
            f"parallel.model={model} — use "
            f"{'dp_tp_zero1' if strategy == 'dp_zero1' else 'dp_tp'} to "
            "make the model axis live")
    if n_devices is None:
        n_devices = len(jax.devices())
    if slices < 1 or n_devices % slices:
        raise PlanError(
            f"{n_devices} devices not divisible into {slices} slices")
    per_slice = n_devices // slices
    if per_slice % model:
        raise PlanError(
            f"model={model} does not divide the {per_slice} devices per "
            f"slice ({n_devices} total / {slices} slices) — model axes "
            f"that fit: {[m for m in _divisors(per_slice) if m > 1]}")
    if data is None:
        data = per_slice // model
    if data * model != per_slice:
        raise PlanError(
            f"plan {data}x{model} (x{slices} slices) covers "
            f"{data * model * slices} devices but {n_devices} are "
            "requested — drop parallel.data to derive it")
    return Plan(strategy=strategy, data=data, model=model, slices=slices,
                process_is_granule=process_is_granule)


def plan_from_config(cfg, n_devices: int | None = None,
                     memory_inputs: Callable[[], tuple] | None = None
                     ) -> Plan:
    """The trainer's entry: ``cfg.parallel`` -> :class:`Plan`.

    With ``parallel.strategy`` unset the legacy ``mesh.*`` knobs still
    name the layout (``shard_params``/``shard_opt_state`` map onto the
    ladder), so every run — old configs included — carries a plan.  A
    set strategy OWNS the layout: legacy sharding knobs alongside it are
    a config contradiction and fail loudly.

    ``memory_inputs`` (required for ``strategy=auto``) returns
    ``(state_struct, batch_bytes)`` — a shape-only ``TrainState`` and
    the global batch's byte count — the :func:`auto_plan` memory-model
    inputs.
    """
    p = cfg.parallel
    m = cfg.mesh
    if n_devices is None:
        n_devices = len(jax.devices())
    # every trainer-resolved plan is stamped with the topology it was
    # resolved against — the elastic restore path's crossing detector
    # (see topology_fingerprint; planning-only resolve_plan/auto_plan
    # calls stay unstamped, a CPU box planning a TPU pod has no live
    # fingerprint to claim)
    stamp = lambda plan: dataclasses.replace(  # noqa: E731
        plan, topology=topology_fingerprint(n_devices))
    if not p.strategy:
        strategy = {(False, False): "dp", (True, False): "dp_tp",
                    (False, True): "dp_zero1", (True, True): "dp_tp_zero1"
                    }[(m.shard_params, m.shard_opt_state)]
        if m.shard_params and m.model < 2:
            raise PlanError(
                "mesh.shard_params needs a live model axis "
                f"(mesh.model >= 2, got {m.model}) — or say it "
                "declaratively: parallel.strategy=dp_tp")
        # legacy meshes may carry a model axis the params don't shard
        # over (ring PAM's sequence parallelism) — the plan records the
        # axis; the strategy names only the STATE layout
        return stamp(Plan(strategy=strategy, data=m.data, model=m.model,
                          slices=m.slices,
                          process_is_granule=m.process_is_granule))
    if m.shard_params or m.shard_opt_state or m.model != 1 \
            or m.data is not None:
        raise PlanError(
            f"parallel.strategy={p.strategy!r} owns the mesh layout, "
            "but legacy mesh knobs are also set "
            f"(mesh.data={m.data}, mesh.model={m.model}, "
            f"shard_params={m.shard_params}, "
            f"shard_opt_state={m.shard_opt_state}) — clear them, or "
            "unset parallel.strategy to keep driving the low-level "
            "knobs")
    if getattr(cfg.model, "pam_impl", "") == "ring":
        raise PlanError(
            "model.pam_impl=ring is sequence parallelism over the model "
            "axis, not a state-sharding strategy — it is configured via "
            "the legacy mesh.model knob; unset parallel.strategy for "
            "ring-PAM runs")
    if p.strategy == "auto":
        if memory_inputs is None:
            raise PlanError(
                "strategy=auto needs the memory model's inputs "
                "(state struct + batch bytes) — construct the plan via "
                "Trainer, or call auto_plan() directly")
        state_struct, batch_bytes = memory_inputs()
        return stamp(auto_plan(
            n_devices=n_devices, state_struct=state_struct,
            batch_bytes=batch_bytes, slices=m.slices,
            hbm_bytes=(int(p.hbm_budget_gb * 2**30)
                       if p.hbm_budget_gb else None),
            process_is_granule=m.process_is_granule))
    return stamp(resolve_plan(
        p.strategy, n_devices=n_devices, data=p.data,
        model=p.model, slices=m.slices,
        process_is_granule=m.process_is_granule))


def _divisors(n: int) -> list[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def normalized_block(block: Mapping, n_devices: int) -> dict:
    """A :meth:`Plan.block` dict with an implicit ``data=None`` resolved
    against ``n_devices`` — the comparison form.  A legacy-derived plan
    carries ``data=None`` while ``resolve_plan`` stamps the concrete
    size; both describe the same physical layout on the same topology
    and must compare equal (cross-plan restore detection keys on this)."""
    out = dict(block)
    if out.get("data") is None:
        model = int(out.get("model") or 1)
        slices = int(out.get("slices") or 1)
        if n_devices % (model * slices) == 0:
            out["data"] = n_devices // (model * slices)
    return out


def plans_differ(saved: Mapping | None, live: Mapping | None,
                 n_devices: int) -> bool:
    """Does a restore from a checkpoint saved under ``saved`` into a run
    planned as ``live`` cross plans?  The restore-announcement
    discriminator (trainer + chaos invariants key on it).

    Layouts compare in :func:`normalized_block` form — each side's
    implicit ``data=None`` resolved against the topology IT names
    (``saved`` against its own stamped fingerprint when present, so a
    dp8 checkpoint restored on 4 devices never normalizes into a false
    match), falling back to the live count.  The ``topology``
    fingerprint joins the comparison only when BOTH sides carry one:
    metas written before the fingerprint existed must not read as a
    crossing on every resume."""
    if not saved or not live:
        return False
    a = normalized_block(saved,
                         fingerprint_devices(saved.get("topology"))
                         or n_devices)
    b = normalized_block(live, n_devices)
    if a.get("topology") is None or b.get("topology") is None:
        a.pop("topology", None)
        b.pop("topology", None)
    return a != b


# --------------------------------------------------------- memory model

def _tree_bytes(tree) -> int:
    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(np.prod(shape, dtype=np.int64)) \
            * np.dtype(dtype).itemsize
    return total


def _sharded_tree_bytes(tree, specs, sizes: Mapping[str, int]) -> int:
    """Per-device bytes of ``tree`` under ``specs``: each leaf's bytes
    divided by the product of the axis sizes its spec shards it over."""
    total = 0.0
    spec_leaves = jax.tree.leaves(specs,
                                  is_leaf=lambda x: isinstance(x, P))
    leaves = jax.tree.leaves(tree)
    for leaf, spec in zip(leaves, spec_leaves):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        nbytes = int(np.prod(shape, dtype=np.int64)) \
            * np.dtype(dtype).itemsize
        div = 1
        for part in (spec or ()):
            for ax in (part if isinstance(part, (tuple, list))
                       else (part,)):
                if ax is not None:
                    div *= sizes.get(ax, 1)
        total += nbytes / div
    return int(math.ceil(total))


def estimate_plan_memory(plan: Plan, state_struct, batch_bytes: int,
                         n_devices: int | None = None,
                         activation_bytes: int | None = None) -> dict:
    """Per-device HBM estimate for one step under ``plan``.

    * **params / opt_state** — exact: the struct's byte counts divided by
      the axes the plan's composed specs shard each leaf over;
    * **grads** — one params-sized buffer in the params layout (GSPMD
      materializes the full gradient tree between backward and update;
      ZeRO-1 shards optimizer state, not gradients);
    * **batch** — the global batch's bytes over the data axis;
    * **activations** — ``activation_bytes`` when the caller has a real
      figure (e.g. the XLA cost-analysis cache's bytes-accessed for an
      already-lowered program, see :func:`activation_bytes_from_cost`),
      else ``ACTIVATION_BYTES_PER_INPUT_BYTE x`` the batch shard — a
      documented conservative bound.

    Pure arithmetic over shapes: no devices touched, no compile.
    """
    sizes = plan.axis_sizes(n_devices)
    # thread the RESOLVED sizes into the spec computation — a data=None
    # plan estimated for n_devices != the live host's count must shard
    # (and divide) against the caller's topology, not len(jax.devices())
    specs = plan.state_specs(state_struct, mesh=_AxisMesh(dict(sizes)))
    params = _sharded_tree_bytes(state_struct.params, specs.params, sizes)
    grads = params
    opt = _sharded_tree_bytes(state_struct.opt_state, specs.opt_state,
                              sizes)
    stats = _tree_bytes(state_struct.batch_stats)
    batch = int(math.ceil(batch_bytes / sizes[DATA_AXIS]))
    if activation_bytes is None:
        activation_bytes = int(batch * ACTIVATION_BYTES_PER_INPUT_BYTE)
    out = {"params": params, "grads": grads, "opt_state": opt,
           "batch_stats": stats, "batch": batch,
           "activations": int(activation_bytes)}
    out["total"] = sum(out.values())
    return out


def activation_bytes_from_cost(fn, args: tuple) -> int | None:
    """Activation proxy from the existing XLA cost-analysis cache
    (:mod:`telemetry.lowering`): the compiled program's bytes-accessed
    figure.  HBM *traffic* upper-bounds live residency, so this refines
    auto's parametric fallback wherever a lowered program already exists
    (bench re-planning, post-hoc analysis); ``None`` when the backend
    has no cost model."""
    from ..telemetry.lowering import lower_cached

    try:
        cost = lower_cached(fn, *args).cost()
    except Exception:
        return None
    b = cost.get("bytes")
    return int(b) if b else None


def detect_hbm_bytes() -> int | None:
    """The per-device HBM budget the live backend reports
    (``memory_stats()['bytes_limit']``); ``None`` on backends without
    memory stats (CPU)."""
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    limit = stats.get("bytes_limit")
    return int(limit) if limit else None


def auto_plan(n_devices: int, state_struct, batch_bytes: int,
              hbm_bytes: int | None = None, slices: int = 1,
              activation_bytes: int | None = None,
              process_is_granule: bool | None = None) -> Plan:
    """``strategy=auto``: walk the mesh-shape ladder and return the
    first plan whose :func:`estimate_plan_memory` fits ``hbm_bytes``.

    The walk prefers the smallest model axis (TP pays an all-gather per
    BN boundary on convnets — :mod:`tp`'s own caveat), and at each model
    size tries the cheap memory lever first: plain layout, then ZeRO-1
    (one param-sized all-gather per step buys an optimizer-state-sized
    saving).  Nothing fitting is a loud :class:`PlanError` carrying the
    best rung's shortfall — never a silent OOM at step 1.
    """
    if hbm_bytes is None:
        hbm_bytes = detect_hbm_bytes() or DEFAULT_HBM_BYTES
    # CONSENSUS (parallel/consensus.py): the budget is DETECTED per
    # host, and hosts walking the ladder against different budgets would
    # resolve different plans — i.e. compile different collectives and
    # deadlock at the first one.  The min across hosts is the binding
    # constraint (a plan must fit the smallest chip), and the pure
    # ladder walk below is then identical everywhere by construction.
    # Single-process the gather is [hbm_bytes] and min is the identity —
    # auto ALWAYS routes through the primitive.
    hbm_bytes = int(replicated_decision(int(hbm_bytes), reduce="min",
                                        label="plan/hbm_budget"))
    per_slice = n_devices // slices
    walked = []
    for model in _divisors(per_slice):
        for strategy in (("dp", "dp_zero1") if model == 1
                         else ("dp_tp", "dp_tp_zero1")):
            plan = resolve_plan(strategy, n_devices=n_devices, model=model,
                           slices=slices,
                           process_is_granule=process_is_granule)
            mem = estimate_plan_memory(
                plan, state_struct, batch_bytes, n_devices=n_devices,
                activation_bytes=activation_bytes)
            walked.append((plan, mem["total"]))
            if mem["total"] <= hbm_bytes:
                # the verification half: every host must have picked
                # THIS rung — divergence here (a non-budget input
                # differing per host) is a loud ConsensusError, never
                # a silent per-host plan
                replicated_decision(plan.block(), reduce="same",
                                    label="plan/auto_rung")
                return plan
    best_plan, best_bytes = min(walked, key=lambda x: x[1])
    raise PlanError(
        f"strategy=auto: no rung of the ladder fits — the leanest "
        f"({best_plan.describe()}) still needs "
        f"{best_bytes / 2**30:.2f} GiB/device against a "
        f"{hbm_bytes / 2**30:.2f} GiB budget; shrink the batch/crop, "
        "enable remat, or add devices")


# --------------------------------------------------- step-compat errors

def reduce_buckets_conflict(strategy: str) -> PlanError:
    """The actionable rejection for ``train.reduce_buckets`` under a
    model-axis-sharded plan — names the nearest strategy that keeps the
    buckets (satellite of the planner: rejections route through here
    instead of bare ValueErrors)."""
    nearest = NEAREST_BUCKET_STRATEGY.get(strategy, "dp")
    return PlanError(
        f"train.reduce_buckets is incompatible with strategy "
        f"{strategy!r}: the bucketed reduce runs fwd/bwd per-device in "
        "a shard_map whose replicated in_specs cannot express "
        "model-axis-sharded params (TP keeps the GSPMD-implicit "
        f"reduce).  Nearest supported: parallel.strategy={nearest!r} "
        f"(buckets compose with {list(BUCKET_COMPATIBLE)} — ZeRO-1 "
        "lives in the optimizer update outside the shard_map region), "
        "or drop train.reduce_buckets to keep the TP layout")


def shardings_use_axis(shardings, axis: str) -> bool:
    """Whether any ``NamedSharding``/``PartitionSpec`` leaf in the tree
    shards over ``axis`` — the step's TP-vs-ZeRO discriminator."""
    def spec_of(leaf):
        if isinstance(leaf, P):
            return leaf
        return getattr(leaf, "spec", None)

    for leaf in jax.tree.leaves(
            shardings,
            is_leaf=lambda x: isinstance(x, P) or hasattr(x, "spec")):
        spec = spec_of(leaf)
        if spec is None:
            continue
        for part in spec:
            parts = part if isinstance(part, (tuple, list)) else (part,)
            if axis in parts:
                return True
    return False


def plan_record_block(plan: Plan | None) -> dict | None:
    """The bench-record ``plan`` block: ``None`` for the trivial
    single-axis pure-DP default (the schema convention precision set:
    null means "the default regime", so committed pre-planner history
    stays comparable), the full :meth:`Plan.block` otherwise."""
    if plan is None:
        return None
    if plan.strategy == "dp" and plan.model == 1 and plan.slices == 1 \
            and plan.data is None:
        return None
    if plan.strategy == "dp" and plan.model == 1 and plan.slices == 1 \
            and plan.data == len(jax.devices()):
        return None
    return plan.block()
