"""The compiled train/eval steps — the framework's hot loop.

One function, ``make_train_step``, replaces the reference's whole per-batch
body (reference train_pascal.py:185-226: H2D copy → ``DataParallel`` scatter →
forward → multi-output loss → backward → SGD step) with a single ``jit``'d
program over the mesh:

* the batch arrives batch-dim sharded (``mesh.shard_batch``); every op on it
  is partitioned by GSPMD, so the forward/backward run data-parallel with the
  gradient all-reduce inserted by the compiler — the "DDP" of the reference's
  checklist (train_pascal.py:1-8) with no NCCL code;
* loss, grads, optimizer update and BatchNorm running-stat updates all happen
  on device inside one XLA executable — nothing bounces to host between
  micro-steps;
* gradient accumulation (the reference's ``nAveGrad`` knob whose loop
  machinery was commented out, train_pascal.py:67,215-225) is a
  ``lax.scan`` over micro-batches inside the same program, so accumulation
  costs no extra dispatches;
* under batch sharding, BatchNorm's batch-mean is a mean over a
  GSPMD-partitioned axis — the compiler turns it into a cross-replica
  reduction automatically, so BN statistics are *global-batch* by
  construction.  (The reference used per-replica BN only because syncing was
  hard on GPUs — ``sync_bn=False``, train_pascal.py:85; on TPU the synced
  version is the free default.)

Donation: the previous ``TrainState`` buffers are donated to the step, so
params/opt-state are updated in place in HBM — peak memory is one set of
params + grads, not two.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from flax.core import unfreeze

from ..ops import multi_output_loss, se_presence_loss, softmax_xent_ignore
from . import mesh as mesh_lib

Batch = Mapping[str, jax.Array]


def _to_compute_dtype(batch: Batch) -> dict:
    """Dequantize uint8 wire-format leaves to float32 on device.

    The host may ship batches as uint8 (data.uint8_transfer: 4x fewer bytes
    through PCIe/tunnel H2D, 4x less host memcpy) — values are integer-
    valued [0,255] image channels and {0,1} masks, so the cast is lossless.
    Inside jit the cast fuses into the first consumer and costs ~nothing."""
    return {k: (v.astype(jnp.float32) if v.dtype == jnp.uint8 else v)
            for k, v in batch.items()}


def _unpack_mask_bits(batch: Batch) -> dict:
    """Inverse of the host's ``np.packbits`` wire (data.packbits_masks).

    ``crop_gt`` arrives as ``(B, ceil(H*W/8))`` uint8; H and W come
    statically from the ``concat`` tensor's shape, so everything here is
    shape-static under jit.  MSB-first shifts mirror np.packbits'
    big-endian bit order.  The whole unpack is broadcast/bitwise/reshape —
    XLA fuses it into the mask's first consumer; the win is the 8x smaller
    H2D transfer that already happened."""
    packed = batch[TARGET_KEY]
    h, w = batch[INPUT_KEY].shape[1:3]
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = (packed[:, :, None] >> shifts) & jnp.uint8(1)
    flat = bits.reshape(packed.shape[0], -1)[:, :h * w]
    out = dict(batch)
    out[TARGET_KEY] = flat.reshape(packed.shape[0], h, w, 1)
    return out

#: batch keys consumed by the step — the reference's stringly-typed contract
#: (``sample['concat']`` / ``sample['crop_gt']``, train_pascal.py:187) made
#: explicit in one place.
INPUT_KEY = "concat"
TARGET_KEY = "crop_gt"

#: key under which a coalesced batch ships (data.coalesce_wire)
WIRE_KEY = "wire"

#: the device-bound train keys, in wire order — everything else a loader
#: yields (meta, host-side lists) stays on host
DEVICE_KEYS = ("concat", "crop_gt", "crop_void")


def pack_wire(batch: Mapping, keys: tuple[str, ...]) -> tuple[dict, tuple]:
    """Coalesce ``keys`` of a host batch into one ``(B, bytes)`` uint8 buffer.

    One buffer = ONE H2D transfer (one RPC on a tunneled/remoted device)
    instead of one per key — the per-transfer link latency, which flaps
    5→160 ms on minute timescales through a tunnel (BASELINE.md round-4),
    is paid once per batch.  Leaves are flattened per-sample and
    concatenated along axis 1, so the batch dim stays the leading (sharded)
    axis.  Returns ``({WIRE_KEY: buf}, spec)`` where ``spec`` is the static
    ``((key, per_sample_shape), ...)`` layout ``unpack_wire`` inverts; a
    batch whose shapes match the spec of a previous call round-trips
    exactly (uint8 is bit-preserved).

    All leaves must already be uint8 — the data.uint8_transfer wire format
    (validated at Trainer init; float leaves would need a bitcast whose
    semantics this deliberately avoids).
    """
    parts, spec = [], []
    for k in keys:
        if k not in batch:
            continue
        v = np.asarray(batch[k])
        if v.dtype != np.uint8:
            raise ValueError(
                f"data.coalesce_wire: leaf {k!r} is {v.dtype}, not uint8 — "
                "the coalesced wire requires data.uint8_transfer's uint8 "
                "batch format")
        parts.append(v.reshape(v.shape[0], -1))
        spec.append((k, tuple(v.shape[1:])))
    if not parts:
        raise ValueError(
            f"pack_wire: none of {keys} present in the batch "
            f"(batch keys: {sorted(batch)})")
    buf = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=1)
    return {WIRE_KEY: np.ascontiguousarray(buf)}, tuple(spec)


def unpack_wire(batch: Batch, spec: tuple) -> dict:
    """Inverse of :func:`pack_wire`, inside jit: static strided slices of
    the ``(B, bytes)`` buffer back into the per-key uint8 leaves.  XLA
    fuses each slice+reshape into the leaf's first consumer, so the
    round-trip costs nothing on device — the win is the single H2D RPC
    that already happened."""
    buf = batch[WIRE_KEY]
    out = {k: v for k, v in batch.items() if k != WIRE_KEY}
    off = 0
    for key, shape in spec:
        n = 1
        for d in shape:
            n *= d
        out[key] = buf[:, off:off + n].reshape((buf.shape[0],) + shape)
        off += n
    if off != buf.shape[1]:
        # a spec from a different wire layout underrunning the buffer
        # would otherwise slice misaligned leaves silently
        raise ValueError(
            f"unpack_wire: spec covers {off} bytes/sample but the buffer "
            f"carries {buf.shape[1]} — spec and wire were built from "
            "different batch layouts")
    return out


def bucket_grad_leaves(leaves: list, n_buckets: int) -> list[list[int]]:
    """Partition gradient-leaf INDICES into ``n_buckets`` byte-balanced
    buckets in reverse-topological order.

    The flattened param tree sorts backbone-before-head; backward
    produces gradients output-side first, so the REVERSED flat order
    approximates the order grads become available during the backward
    pass.  Bucket 0 therefore holds the head/classifier grads — the
    ones ready earliest — and its all-reduce is schedulable while the
    backbone backward is still computing: the bucketed-overlap recipe
    of "Efficient Training of CNNs on Large Distributed Systems"
    (arxiv 1711.00705), expressed as dataflow XLA's latency-hiding
    scheduler can exploit.  Buckets are cut at byte-balanced boundaries
    so no single reduce dominates the tail."""
    if n_buckets < 1:
        raise ValueError(f"reduce_buckets must be >= 1 (got {n_buckets})")
    order = list(range(len(leaves)))[::-1]
    sizes = [int(np.prod(leaves[i].shape, dtype=np.int64))
             * jnp.dtype(leaves[i].dtype).itemsize for i in order]
    total = sum(sizes)
    n_buckets = min(n_buckets, len(order)) or 1
    per = max(1, total // n_buckets)
    buckets: list[list[int]] = []
    cur: list[int] = []
    acc = 0
    for i, s in zip(order, sizes):
        cur.append(i)
        acc += s
        if acc >= per and len(buckets) < n_buckets - 1:
            buckets.append(cur)
            cur, acc = [], 0
    if cur:
        buckets.append(cur)
    return buckets


def _bucketed_psum(grads, n_buckets: int, axis_name: str):
    """All-reduce a gradient pytree in reverse-topo buckets: one
    ``lax.psum`` per bucket (independent equations — no dataflow edge
    forces bucket K to wait for bucket K-1, so async lowerings overlap
    them with each other and with the still-running backward that feeds
    the later buckets)."""
    leaves, treedef = jax.tree.flatten(grads)
    out = list(leaves)
    for bucket in bucket_grad_leaves(leaves, n_buckets):
        reduced = jax.lax.psum([leaves[i] for i in bucket], axis_name)
        for i, g in zip(bucket, reduced):
            out[i] = g
    return jax.tree.unflatten(treedef, out)


class TrainState(struct.PyTreeNode):
    """Everything that evolves during training, as one pytree.

    Unlike the reference — which persisted only ``net.state_dict()`` and lost
    optimizer/epoch/RNG state on every restart (train_pascal.py:301-304, §3.5
    of SURVEY.md) — the full state is one checkpointable object.
    """

    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: optax.OptState
    rng: jax.Array


def create_train_state(
    rng: jax.Array,
    model,
    tx: optax.GradientTransformation,
    input_shape: tuple[int, ...],
    mesh=None,
    shard_params: bool = False,
    shard_opt_state: bool = False,
) -> TrainState:
    """Initialize params/batch-stats with a dummy batch and wrap with the
    optimizer state.  ``input_shape`` is (N, H, W, C) — NHWC, the TPU-native
    layout (the reference's NCHW ``ToTensor`` transpose has no analogue
    here; conv layouts are XLA's concern).

    With ``mesh``, every leaf is created directly as a *global* array.
    Multi-host this is required: a host-local single-device array is
    neither a valid input to the sharded train step nor serializable by
    Orbax's coordinated save.

    ``shard_params=True`` turns on tensor parallelism: kernel output
    channels are partitioned over the ``model`` axis (see
    :mod:`parallel.tp`); momentum inherits the layout through propagation.

    ``shard_opt_state=True`` is the ZeRO-1 layout: optimizer-state leaves
    partitioned over the ``data`` axis (:mod:`parallel.zero`), composing
    with the TP layout when both are on.  Default is fully replicated —
    the reference-parity data-parallel state.
    """
    if shard_opt_state and mesh is None:
        raise ValueError("shard_opt_state requires a mesh (the data axis "
                         "it shards over)")
    init_rng, state_rng = jax.random.split(rng)

    def make_state():
        variables = model.init(init_rng, jnp.zeros(input_shape, jnp.float32),
                               train=False)
        params = unfreeze(variables["params"])
        batch_stats = unfreeze(variables.get("batch_stats", {}))
        opt_state = tx.init(params)
        from .tp import constrain, tp_param_specs
        opt_base = None
        if mesh is not None and shard_params:
            params = constrain(params, mesh, tp_param_specs(params, mesh))
            # Momentum traces share the kernels' shapes, so the same
            # shape-based rule shards optimizer memory identically.
            opt_base = tp_param_specs(opt_state, mesh)
        if mesh is not None and shard_opt_state:
            from .zero import zero_opt_specs
            # ZeRO-1 on top of whatever TP pinned: `data` goes on each
            # leaf's largest still-free divisible dimension.
            opt_state = constrain(
                opt_state, mesh,
                zero_opt_specs(opt_state, mesh, base_specs=opt_base))
        elif opt_base is not None:
            opt_state = constrain(opt_state, mesh, opt_base)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=batch_stats,
            opt_state=opt_state,
            rng=state_rng,
        )

    if mesh is None:
        return make_state()
    if not (shard_params or shard_opt_state):
        return jax.jit(make_state,
                       out_shardings=mesh_lib.replicated_sharding(mesh))()
    # Sharded layouts: let XLA propagate the constrained layouts; pin the
    # small unconstrained leaves (step/rng/batch_stats) to replicated
    # afterwards via an identity reshard where needed.
    with mesh:
        state = jax.jit(make_state)()
    repl = mesh_lib.replicated_sharding(mesh)
    fixed = state.replace(
        step=jax.device_put(state.step, repl),
        rng=jax.device_put(state.rng, repl),
        batch_stats=jax.tree.map(
            lambda x: jax.device_put(x, repl), state.batch_stats),
    )
    if not shard_params:
        # ZeRO-only: params must stay replicated (XLA may have propagated
        # the opt-state layout backward into the init graph)
        fixed = fixed.replace(params=jax.tree.map(
            lambda x: jax.device_put(x, repl), fixed.params))
    return fixed


def _compute_loss(outputs, batch: Batch, weights, loss_type: str):
    """Loss over a model's output tuple.

    ``multi_sigmoid`` — the reference's weighted multi-output balanced BCE
    (binary interactive segmentation, SegmentationMultiLosses semantics).
    ``multi_softmax`` — per-output softmax CE with ignore_index=255 (the
    multi-class DeepLabV3 configs; aux outputs default to 0.4 weight).
    """
    inputs = batch[INPUT_KEY]
    target = batch[TARGET_KEY]
    void = batch.get("crop_void")
    if weights is not None and len(weights) != len(outputs):
        # zip would silently truncate — e.g. EncNet's (map, aux, se) tuple
        # under loss_weights=[1.0,0.4] would drop the SE-presence loss and
        # never train the context-encoding branch
        raise ValueError(
            f"model.loss_weights has {len(weights)} entries but the model "
            f"emits {len(outputs)} outputs — give every output a weight")
    if loss_type == "multi_sigmoid":
        if target.ndim == inputs.ndim - 1:  # (B,H,W) vs (B,H,W,C) logits
            target = target[..., None]
        if void is not None and void.ndim == inputs.ndim - 1:
            void = void[..., None]
        return multi_output_loss(outputs, target, void=void, weights=weights)
    if loss_type == "multi_softmax":
        labels = target
        if labels.ndim == outputs[0].ndim:  # squeeze trailing channel axis
            labels = labels[..., 0]
        labels = labels.astype(jnp.int32)
        if weights is None:
            # map aux heads 0.4 (DeepLab recipe); a 2D SE output gets the
            # EncNet paper's 0.2
            weights = (1.0,) + tuple(
                0.2 if o.ndim == 2 else 0.4 for o in outputs[1:])
        total = jnp.float32(0.0)
        for out, w in zip(outputs, weights):
            if out.ndim == 2:
                # (B, C) vector head: EncNet's semantic-encoding branch —
                # class-presence BCE, not a per-pixel CE
                total = total + w * se_presence_loss(out, labels)
            else:
                total = total + w * softmax_xent_ignore(out, labels)
        return total
    raise ValueError(f"unknown loss_type: {loss_type!r}")


def _loss_and_updates(model, params, batch_stats, batch: Batch, rng,
                      loss_weights, train: bool, loss_type: str,
                      aux_loss_weight: float = 0.0, precision=None):
    """Forward + loss; returns (loss, new_batch_stats).

    ``aux_loss_weight`` scales any auxiliary losses the model ``sow``s into
    its ``losses`` collection (e.g. the MoE router's load-balancing term,
    parallel/moe.py) into the training objective.

    ``precision`` (train.precision policy): the two declared dtype
    boundaries of the mixed regime live HERE — inputs cast down to the
    compute dtype before the model (halving the input tensor's HBM
    read; the first conv would cast anyway, after paying f32 bytes) and
    outputs cast up to the loss dtype after it (the explicit
    bf16-compute → f32-loss accumulation seam JA002 audits).  The loss
    kernels upcast defensively regardless; under a policy the boundary
    is explicit and auditable.
    """
    variables = {"params": params, "batch_stats": batch_stats}
    inputs = batch[INPUT_KEY]
    if precision is not None:
        inputs = precision.cast_to_compute(inputs)
    if train:
        outputs, mutated = model.apply(
            variables, inputs, train=True,
            mutable=["batch_stats", "losses"], rngs={"dropout": rng},
        )
        new_stats = unfreeze(mutated["batch_stats"])
        aux = sum((jnp.sum(x) for x in
                   jax.tree.leaves(mutated.get("losses", {}))),
                  jnp.float32(0.0))
    else:
        outputs = model.apply(variables, inputs, train=False)
        new_stats = batch_stats
        aux = jnp.float32(0.0)
    if precision is not None:
        outputs = precision.cast_to_loss(outputs)
    loss = _compute_loss(outputs, batch, loss_weights, loss_type)
    if aux_loss_weight:
        loss = loss + aux_loss_weight * aux
    return loss, new_stats


def make_train_step(
    model,
    tx: optax.GradientTransformation,
    loss_weights: tuple[float, ...] | None = None,
    accum_steps: int = 1,
    mesh=None,
    donate: bool = True,
    loss_type: str = "multi_sigmoid",
    augment: Callable[[Batch, jax.Array], Batch] | None = None,
    state_shardings=None,
    aux_loss_weight: float = 0.0,
    loss_scale: float = 1.0,
    steps_per_call: int = 1,
    packbits_masks: bool = False,
    wire_spec: tuple | None = None,
    sentinel_metrics: bool = False,
    precision=None,
    reduce_buckets: int = 0,
) -> Callable[..., tuple[TrainState, jax.Array]]:
    """Build the jitted ``(state, batch) -> (state, loss)`` train step.

    ``state_shardings``: a sharding pytree shaped like the state (e.g.
    ``tp.state_shardings(state)``) for tensor-parallel layouts; ``None``
    keeps the replicated data-parallel default.

    With ``accum_steps > 1`` the global batch is split into that many
    micro-batches and scanned, averaging gradients — BASELINE.md config 5's
    "grad-accum to global batch 256" path.  The micro-batch dim stays sharded
    over ``data``, so each scan iteration is itself data-parallel.

    ``augment`` is an optional on-device ``(batch, rng) -> batch`` stage
    (see ops.augment) traced into the same program — flip/crop/normalize
    fuse into the forward pass and cost ~nothing.

    ``loss_scale`` (static loss scaling, optim.loss_scale): the backward
    pass differentiates ``loss * scale`` and the gradients are divided back
    — numerically a no-op in exact arithmetic, but it lifts tiny
    activations-gradients above the underflow floor in low-precision
    regimes.  The returned loss is always unscaled.

    ``steps_per_call > 1`` returns a MULTI-step program instead:
    ``(state, b1, ..., bK) -> (state, (K,) losses)`` — K full optimizer
    steps scanned inside one executable (data.steps_per_dispatch), cutting
    per-step dispatch overhead K-fold on dispatch-bound hosts.

    ``wire_spec`` (data.coalesce_wire): the step consumes a coalesced
    ``{WIRE_KEY: (B, bytes) uint8}`` batch and restores the named leaves
    with :func:`unpack_wire` before any other stage — composes with
    ``packbits_masks`` (the packed row rides the buffer) and with the
    multi-step program (the scan body unpacks each step's buffer).

    ``sentinel_metrics`` (sentinel.monitor_grads): the step's second
    output becomes ``(loss, aux)`` with ``aux = [grad_norm,
    ||update||/||param||]`` — the divergence signals the step-health
    sentinel judges.  Both norms are computed from arrays the update
    already produced, so the cost is a handful of fused reductions; the
    readback stays on the trainer's existing loss-fetch boundary (no
    extra host syncs).  Multi-step programs return ``((K,), (K, 2))``.

    ``precision`` (train.precision policy, train/precision.py): the
    mixed-precision dtype boundaries — inputs cast to the compute dtype
    at the model, outputs upcast to f32 at the loss.  The model itself
    must be built with ``dtype=policy.compute_dtype`` (the trainer
    couples both from one knob); grads/optimizer math stay f32 because
    the master params are f32 — nothing here to get wrong.

    ``reduce_buckets > 0`` (train.reduce_buckets): the gradient
    all-reduce is restructured for comm/compute overlap.  The
    forward+backward run per-device inside a ``shard_map`` over the
    ``data`` axis (each device differentiates ITS batch shard — local
    grads, exactly DDP's structure) and the grads are then explicitly
    ``psum``-reduced in ``reduce_buckets`` reverse-topological buckets:
    bucket 0 (head params, produced earliest in backward) has no
    dataflow dependence on the backbone backward still running, so an
    async-collective backend (TPU: all-reduce-start/-done + the
    latency-hiding scheduler) overlaps its reduce with the remaining
    compute instead of serializing one fused all-reduce after the whole
    backward.  Semantics shift to DDP's: the loss is the mean of
    per-shard losses (per-shard normalization — balanced-BCE
    denominators are shard-local), dropout draws per-device streams,
    and BN batch stats must psum explicitly — the model MUST be built
    with ``bn_cross_replica_axis='data'`` (validated).  Composes with
    accum/echo/multi-step/wire stages AND with ZeRO-1
    (``plan.BUCKET_COMPATIBLE``: the shard_map region owns only the
    replicated params and the batch shard, while ZeRO's data-sharded
    optimizer leaves live entirely in the update OUTSIDE it — GSPMD
    partitions that elementwise update over the shards as usual).  NOT
    with tensor parallelism or ring PAM: model-axis-sharded params
    cannot enter the region's replicated in_specs, and per-device
    fwd/bwd over sharded kernels would be a different algorithm, not a
    layout — rejected through the planner with the nearest supported
    strategy named.
    """
    if reduce_buckets:
        from .plan import PlanError, reduce_buckets_conflict, \
            shardings_use_axis

        if mesh is None:
            raise ValueError("reduce_buckets needs a mesh (the data axis "
                             "the buckets psum over)")
        if mesh_lib.MODEL_AXIS in mesh.shape and \
                mesh.shape[mesh_lib.MODEL_AXIS] > 1:
            raise PlanError(
                "train.reduce_buckets needs a 1-wide model axis: the "
                "shard_map region owns the data axis and would "
                "silently replicate compute across a live model axis "
                f"(mesh is {dict(mesh.shape)}) — use "
                "parallel.strategy=dp or dp_zero1, or drop "
                "train.reduce_buckets for model-axis plans")
        if state_shardings is not None and \
                shardings_use_axis(state_shardings, mesh_lib.MODEL_AXIS):
            # TP layout: route the rejection through the planner so the
            # error names the nearest strategy that keeps the buckets
            raise reduce_buckets_conflict(
                "dp_tp_zero1" if shardings_use_axis(
                    state_shardings, mesh_lib.DATA_AXIS) else "dp_tp")
        if getattr(model, "bn_cross_replica_axis", None) != \
                mesh_lib.DATA_AXIS:
            raise ValueError(
                "reduce_buckets runs the forward per-device inside "
                "shard_map, so BatchNorm batch stats must reduce "
                "explicitly: build the model with "
                f"bn_cross_replica_axis={mesh_lib.DATA_AXIS!r} (the "
                "trainer couples this automatically)")

    def grads_of(params, batch_stats, batch, rng):
        def loss_fn(p):
            loss, new_stats = _loss_and_updates(
                model, p, batch_stats, batch, rng, loss_weights, train=True,
                loss_type=loss_type, aux_loss_weight=aux_loss_weight,
                precision=precision)
            return loss * loss_scale, (loss, new_stats)
        (_, (loss, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        if loss_scale != 1.0:
            grads = jax.tree.map(lambda g: g / loss_scale, grads)
        return loss, new_stats, grads

    def accum_grads_of(params, batch_stats, batch, rng):
        """(loss, new_stats, grads) over the (possibly accumulated)
        batch — the whole differentiation stage, shared verbatim by the
        GSPMD path and the shard_map body (where ``batch`` is the
        device-local shard and the grads come back unreduced)."""
        if accum_steps == 1:
            return grads_of(params, batch_stats, batch, rng)
        # (B, ...) -> (accum, B/accum, ...): scan carries running grad
        # sum + evolving BN stats; XLA keeps it one fused program.
        def resh(x):
            return x.reshape((accum_steps, x.shape[0] // accum_steps)
                             + x.shape[1:])
        micro = jax.tree.map(resh, dict(batch))
        rngs = jax.random.split(rng, accum_steps)
        zero_grads = jax.tree.map(jnp.zeros_like, params)

        def body(carry, xs):
            gsum, stats = carry
            mb, r = xs
            loss, new_stats, g = grads_of(params, stats, mb, r)
            gsum = jax.tree.map(jnp.add, gsum, g)
            return (gsum, new_stats), loss

        (gsum, new_stats), losses = jax.lax.scan(
            body, (zero_grads, batch_stats), (micro, rngs))
        grads = jax.tree.map(lambda g: g / accum_steps, gsum)
        return losses.mean(), new_stats, grads

    def bucketed_grads_of(params, batch_stats, batch, rng):
        """The shard_map twin of :func:`accum_grads_of`: per-device
        fwd+bwd over the local batch shard, then the reverse-topo
        bucketed psum.  Gradients come back pmean'd (psum / axis size —
        DDP averaging), the loss as the mean of per-shard losses; BN
        stats reduced inside the model (bn_cross_replica_axis)."""
        from jax.sharding import PartitionSpec as P

        def body(params, batch_stats, batch, rng):
            # de-correlate per-device dropout/augment draws: each shard
            # is a different slice of the batch and must not share masks
            rng = jax.random.fold_in(
                rng, jax.lax.axis_index(mesh_lib.DATA_AXIS))
            loss, new_stats, grads = accum_grads_of(
                params, batch_stats, batch, rng)
            n = mesh_lib.axis_size(mesh_lib.DATA_AXIS)
            grads = _bucketed_psum(grads, reduce_buckets,
                                   mesh_lib.DATA_AXIS)
            grads = jax.tree.map(lambda g: g / n, grads)
            loss = jax.lax.pmean(loss, mesh_lib.DATA_AXIS)
            # new_stats are already identical across devices (the model's
            # cross-replica BN pmean'd them) — returned replicated as-is
            return loss, new_stats, grads

        return mesh_lib.shard_map(
            body, mesh=mesh,
            in_specs=(P(), P(), P(mesh_lib.DATA_AXIS), P()),
            out_specs=(P(), P(), P()),
            check_vma=False)(params, batch_stats, batch, rng)

    def step_fn(state: TrainState, batch: Batch):
        if wire_spec is not None:
            # first: the coalesced buffer (data.coalesce_wire) restores the
            # named leaves every later stage keys on
            batch = unpack_wire(batch, wire_spec)
        if packbits_masks:
            # before the dtype pass: the packed row must stay integer for
            # the bit shifts (data.packbits_masks wire)
            batch = _unpack_mask_bits(batch)
        batch = _to_compute_dtype(batch)
        rng, new_rng = jax.random.split(state.rng)
        if augment is not None:
            rng, aug_rng = jax.random.split(rng)
            batch = augment(batch, aug_rng)
        differentiate = bucketed_grads_of if reduce_buckets \
            else accum_grads_of
        loss, new_stats, grads = differentiate(
            state.params, state.batch_stats, dict(batch), rng)

        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt,
            rng=new_rng,
        )
        if sentinel_metrics:
            # sentinel.monitor_grads: global grad norm + the update/param
            # ratio (a single update rewriting a macroscopic fraction of
            # the weights is divergence even at a plausible loss)
            gnorm = optax.global_norm(grads)
            ratio = optax.global_norm(updates) / (
                optax.global_norm(state.params) + 1e-12)
            return new_state, (loss, jnp.stack([gnorm, ratio]))
        return new_state, loss

    if steps_per_call > 1:
        # Multi-step dispatch: K optimizer steps in ONE compiled call — a
        # lax.scan over K batches passed as separate (batch-sharded) args
        # and stacked at trace time.  Per-step dispatch overhead (~54 ms
        # through a tunneled chip) drops K-fold; losses come back as a (K,)
        # vector.  The scan body IS step_fn, so semantics (BN stats, RNG
        # advance, schedules, accum) are exactly K sequential steps.
        def multi_fn(state: TrainState, *batches: Batch):
            stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *batches)

            def body(st, b):
                st, loss = step_fn(st, b)
                return st, loss

            state, losses = jax.lax.scan(body, state, stacked)
            return state, losses
    else:
        multi_fn = None

    if mesh is None:
        if multi_fn is not None:
            return jax.jit(multi_fn, donate_argnums=(0,) if donate else ())
        return jax.jit(step_fn, donate_argnums=(0,) if donate else ())

    repl = mesh_lib.replicated_sharding(mesh)
    data = mesh_lib.batch_sharding(mesh)
    if state_shardings is None:
        state_in, state_out = repl, repl
    else:
        # TP (or any custom layout): consume and produce the state exactly
        # as created — params stay model-axis sharded across steps.
        state_in = state_out = state_shardings
    if multi_fn is not None:
        return jax.jit(
            multi_fn,
            in_shardings=(state_in,) + (data,) * steps_per_call,
            out_shardings=(state_out, repl),
            donate_argnums=(0,) if donate else (),
        )
    return jax.jit(
        step_fn,
        in_shardings=(state_in, data),
        out_shardings=(state_out, repl),
        donate_argnums=(0,) if donate else (),
    )


def make_eval_step(model, loss_weights: tuple[float, ...] | None = None,
                   mesh=None, loss_type: str = "multi_sigmoid",
                   preprocess: Callable[[Batch], Batch] | None = None,
                   state_shardings=None, packbits_masks: bool = False):
    """Jitted ``(state, batch) -> (outputs, loss)`` inference step
    (reference val loop body, train_pascal.py:245-254).  Outputs are the
    model's logit tuple; sigmoid/thresholding happen in the evaluator, which
    needs probabilities host-side for the full-res paste-back anyway.

    ``packbits_masks`` mirrors the train step's 1-bit ``crop_gt`` wire for
    the prepared val path (data.val_prepared + data.packbits_masks): the
    mask is 25% of the 3-channel uint8 val batch's bytes."""

    def step_fn(state: TrainState, batch: Batch):
        if packbits_masks:
            batch = _unpack_mask_bits(batch)
        batch = _to_compute_dtype(batch)
        if preprocess is not None:  # must mirror the train augment's
            batch = preprocess(batch)  # deterministic normalization
        variables = {"params": state.params,
                     "batch_stats": state.batch_stats}
        outputs = model.apply(variables, batch[INPUT_KEY], train=False)
        loss = _compute_loss(outputs, batch, loss_weights, loss_type)
        return outputs, loss

    if mesh is None:
        return jax.jit(step_fn)
    repl = mesh_lib.replicated_sharding(mesh)
    data = mesh_lib.batch_sharding(mesh)
    state_in = repl if state_shardings is None else state_shardings
    return jax.jit(step_fn, in_shardings=(state_in, data),
                   out_shardings=(data, repl))
