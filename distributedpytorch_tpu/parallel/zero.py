"""ZeRO-1-style optimizer-state sharding over the ``data`` mesh axis.

The reference is plain ``nn.DataParallel`` (train_pascal.py:92): every
GPU holds the full optimizer state.  Replicated momentum is also this
framework's default — for the reference's model sizes it is the right
call.  This module makes the memory trade available when it isn't: with
``mesh.shard_opt_state=true`` each optimizer-state leaf is partitioned
over the DATA axis, so per-device optimizer memory drops by the
data-parallel degree (the ZeRO stage-1 recipe, expressed the GSPMD way).

How it works here — no hand-written scatter/gather, matching the
framework's "the compiler owns communication" rule (DESIGN.md):

* state creation places each large optimizer leaf with a
  ``PartitionSpec`` that shards its largest free dimension over ``data``
  (:func:`zero_opt_specs`);
* the train step pins those shardings via ``state_shardings`` in/out, so
  GSPMD partitions the optimizer update elementwise over the shards —
  each device updates 1/Nth of the momentum — and inserts the
  all-gather that rebuilds the replicated parameter update;
* grads are already replicated after the data-parallel all-reduce, so
  correctness is untouched: the same numbers, a different layout.

Composes with tensor parallelism: a leaf the TP rule shards over
``model`` (trailing/output channels — parallel/tp.py) gets ``data``
on its largest *other* divisible dimension, sharding over both axes.

Cost model, stated plainly: ZeRO-1 trades one parameter-sized
all-gather per step for an optimizer-state-sized memory saving.  Worth
it when optimizer memory (momentum; Adam doubles it) crowds out batch
or activation memory at scale; pointless for models that fit easily —
hence default off, like every other sharding knob.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import DATA_AXIS

#: leaves smaller than this stay replicated — sharding a bias vector
#: saves nothing and costs a collective
MIN_LEAF_ELEMENTS = 65536


def zero_opt_specs(opt_state: Any, mesh: Mesh, base_specs: Any = None,
                   min_size: int = MIN_LEAF_ELEMENTS) -> Any:
    """PartitionSpec pytree sharding optimizer-state leaves over ``data``.

    Each leaf's spec starts from ``base_specs`` (the TP layout, when
    tensor parallelism is on) or fully replicated, then the largest
    dimension that (a) is not already sharded and (b) divides the data
    axis size gets ``DATA_AXIS`` — provided the leaf has at least
    ``min_size`` elements.  Scalars, counts and small vectors replicate.

    ``opt_state`` may be a pytree of arrays or ``ShapeDtypeStruct``.
    """
    if DATA_AXIS not in mesh.axis_names:
        raise ValueError(
            f"shard_opt_state shards over the '{DATA_AXIS}' mesh axis, but "
            f"this mesh has axes {mesh.axis_names} — build it with "
            "make_mesh(data=..., model=...)")
    data = mesh.shape[DATA_AXIS]

    def spec_of(leaf, base) -> P:
        shape = getattr(leaf, "shape", ())
        size = 1
        for d in shape:
            size *= d
        parts = list(base) if base is not None else []
        parts += [None] * (len(shape) - len(parts))
        if data <= 1 or size < min_size:
            return P(*parts)
        best = None
        for i, d in enumerate(shape):
            if parts[i] is None and d % data == 0 and \
                    (best is None or d > shape[best]):
                best = i
        if best is None:
            return P(*parts)
        parts[best] = DATA_AXIS
        return P(*parts)

    if base_specs is None:
        return jax.tree.map(lambda l: spec_of(l, None), opt_state)
    return jax.tree.map(spec_of, opt_state, base_specs)
