"""Tensor parallelism: parameter sharding over the ``model`` mesh axis.

The reference is pure data parallel (``nn.DataParallel``,
train_pascal.py:92; SURVEY.md §2.5 marks TP "ABSENT"), and for its model
sizes replication is the right call.  This module makes the mesh's reserved
``model`` axis *live* for when it isn't: parameters whose output-channel
dimension divides the axis size are sharded over it, and GSPMD partitions
the matmuls/convs that consume them (each device holds and computes 1/Nth of
the output channels) and inserts the boundary collectives.

The GSPMD idiom, not a hand-sharded model: the model code is unchanged;
sharding enters only as (a) ``PartitionSpec`` constraints on the parameter
pytree at init (:func:`tp_param_specs` + ``create_train_state``) and (b) the
train step's input shardings derived from the live state
(:func:`state_shardings`).  Optimizer state (momentum) inherits the param
layout through propagation, so optimizer memory is sharded too — the
"ZeRO-3-ish for free" property of the XLA partitioner.

Convnet reality check: with BatchNorm between layers, TP inserts an
all-gather per BN boundary, so this pays off only for attention-heavy heads
or very wide layers.  The knob (``mesh.shard_params``) defaults off; data
parallel stays the reference-parity configuration.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import MODEL_AXIS


def tp_param_specs(params: Any, mesh: Mesh, min_dim: int = 64) -> Any:
    """PartitionSpec pytree for ``params``: shard the trailing
    (output-channel) dim of every rank>=2 kernel over ``model`` when it
    divides the axis size and is at least ``min_dim`` wide; everything else
    (biases, BN scales, gammas) replicated.

    ``params`` may be a pytree of arrays or of ``ShapeDtypeStruct``.
    """
    model = mesh.shape[MODEL_AXIS]

    def spec_of(leaf):
        shape = leaf.shape
        if (model > 1 and len(shape) >= 2 and shape[-1] >= min_dim
                and shape[-1] % model == 0):
            return P(*([None] * (len(shape) - 1) + [MODEL_AXIS]))
        return P()

    return jax.tree.map(spec_of, params)


def state_shardings(state) -> Any:
    """The live state's sharding pytree — feed to ``make_train_step`` so the
    compiled step consumes/produces exactly the layout ``create_train_state``
    built (replicated for DP, param-sharded for TP)."""
    return jax.tree.map(lambda x: x.sharding, state)


def constrain(tree: Any, mesh: Mesh, specs: Any):
    """``with_sharding_constraint`` a pytree with a matching spec pytree."""
    return jax.tree.map(
        lambda x, s: jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, s)),
        tree, specs)
