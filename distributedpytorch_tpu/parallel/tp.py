"""Tensor parallelism: parameter sharding over the ``model`` mesh axis.

The reference is pure data parallel (``nn.DataParallel``,
train_pascal.py:92; SURVEY.md §2.5 marks TP "ABSENT"), and for its model
sizes replication is the right call.  This module makes the mesh's reserved
``model`` axis *live* for when it isn't: parameters whose output-channel
dimension divides the axis size are sharded over it, and GSPMD partitions
the matmuls/convs that consume them (each device holds and computes 1/Nth of
the output channels) and inserts the boundary collectives.

The GSPMD idiom, not a hand-sharded model: the model code is unchanged;
sharding enters only as (a) ``PartitionSpec`` constraints on the parameter
pytree at init (:func:`tp_param_specs` + ``create_train_state``) and (b) the
train step's input shardings derived from the live state
(:func:`state_shardings`).  Optimizer state (momentum) inherits the param
layout through propagation, so optimizer memory is sharded too — the
"ZeRO-3-ish for free" property of the XLA partitioner.

Convnet reality check: with BatchNorm between layers, TP inserts an
all-gather per BN boundary, so this pays off only for attention-heavy heads
or very wide layers.  The knob (``mesh.shard_params``) defaults off; data
parallel stays the reference-parity configuration.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import MODEL_AXIS


def tp_param_specs(params: Any, mesh: Mesh, min_dim: int = 64) -> Any:
    """PartitionSpec pytree for ``params``: shard the trailing
    (output-channel) dim of every rank>=2 kernel over ``model`` when it
    divides the axis size and is at least ``min_dim`` wide; everything else
    (biases, BN scales, gammas) replicated.

    MoE exception (expert parallelism in the trainer): leaves under a
    ``moe`` module shard their *leading* (expert) dim over ``model`` when it
    divides — one expert group per device slice, matching
    :mod:`parallel.moe`'s EP layout — so ``mesh.shard_params=true`` with
    ``model.moe_experts`` gives expert-sharded FFN stacks and GSPMD inserts
    the dispatch all-to-alls.  The router gate stays replicated.

    ``params`` may be a pytree of arrays or of ``ShapeDtypeStruct``.
    """
    model = mesh.shape[MODEL_AXIS]
    # MoEMlp's expert-stacked leaves, by name (mirrors moe.ep_param_specs'
    # w_gate exclusion) — the EP rule must not sweep up other params that
    # merely live under a module named "moe".
    moe_expert_leaves = {"w1", "b1", "w2", "b2"}

    def spec_of(path, leaf):
        shape = leaf.shape
        in_moe = any(getattr(k, "key", None) == "moe" for k in path)
        leaf_name = getattr(path[-1], "key", None) if path else None
        if in_moe and leaf_name == "w_gate":
            # The router gate is always replicated (every device routes all
            # its tokens) — without this, a wide (d, E>=min_dim) gate would
            # fall through to the trailing-dim rule and split the expert
            # logits across devices.
            return P()
        if (in_moe and leaf_name in moe_expert_leaves and model > 1
                and len(shape) >= 1 and shape[0] % model == 0):
            return P(*([MODEL_AXIS] + [None] * (len(shape) - 1)))
        # Generic trailing-dim rule — also the fallback when the expert
        # count does not divide the axis (keeps the wide FFN dims sharded
        # instead of silently replicating the whole expert stack).
        if (model > 1 and len(shape) >= 2 and shape[-1] >= min_dim
                and shape[-1] % model == 0):
            return P(*([None] * (len(shape) - 1) + [MODEL_AXIS]))
        return P()

    return jax.tree_util.tree_map_with_path(spec_of, params)


def state_shardings(state) -> Any:
    """The live state's sharding pytree — feed to ``make_train_step`` so the
    compiled step consumes/produces exactly the layout ``create_train_state``
    built (replicated for DP, param-sharded for TP)."""
    return jax.tree.map(lambda x: x.sharding, state)


def constrain(tree: Any, mesh: Mesh, specs: Any):
    """``with_sharding_constraint`` a pytree with a matching spec pytree."""
    return jax.tree.map(
        lambda x, s: jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, s)),
        tree, specs)
