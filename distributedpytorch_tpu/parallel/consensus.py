"""Replicated decisions: one allgather, one deterministic reduce, ONE answer.

Several subsystems make host-local judgements that every process of a
multi-host job must nonetheless AGREE on before the next collective:
``parallel.strategy=auto`` resolves a plan from a locally-detected HBM
budget (heterogeneous detection would compile different programs per
host — a silent distributed deadlock), and the feed governor's ladder
acts on a host-local stall fraction (hosts disagreeing about the echo
factor desynchronize optimizer step counts).  The preemption guard
solved the same problem for its stop flag with a tiny consensus
allgather; this module is that idiom promoted to a primitive:

    decided = replicated_decision(local_value, reduce="max")

Every process contributes its local value, every process receives the
full per-process list **in process-index order**, and every process
applies the same deterministic reduce to it — so the decision is
identical everywhere *by construction*, with no coordinator to elect,
time out on, or partition away from (the reason this is an allgather
and not a leader: the job's collectives already require every process
to be live and in lockstep, so a leaderless symmetric decision adds no
new failure mode).

``reduce="same"`` is the verification form: it demands the inputs
already agree and raises a loud :class:`ConsensusError` naming every
process's value when they do not — for decisions that must never be
papered over by averaging (e.g. two hosts resolving different plans).

Contract (the ``PreemptionGuard.should_stop`` contract, restated):
every process must call ``replicated_decision`` at the same program
point with the same ``reduce`` — it is a collective.  Values must be
JSON-encodable (the wire format; tuples come back as lists).  On a
single-process job the gather degenerates to ``[value]`` and the reduce
is applied unchanged, so callers route through the primitive
unconditionally and the multi-host semantics are exercised everywhere.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Sequence


class ConsensusError(RuntimeError):
    """Per-process inputs diverged and the reduce cannot reconcile them
    (``reduce="same"``) — the loud form of "these hosts are about to
    desynchronize"."""

    def __init__(self, label: str, values: Sequence[Any]):
        self.label = label
        self.values = list(values)
        shown = ", ".join(f"p{i}={v!r}" for i, v in enumerate(values))
        super().__init__(
            f"replicated_decision({label!r}): per-process values diverged "
            f"and reduce='same' cannot reconcile them: {shown[:800]}")


def _same(label: str, values: list) -> Any:
    # canonical JSON form: dict key order / int-vs-float spelling must
    # not fake a divergence between genuinely-equal values
    keys = [json.dumps(v, sort_keys=True) for v in values]
    if any(k != keys[0] for k in keys[1:]):
        raise ConsensusError(label, values)
    return values[0]


#: named reduces — each deterministic over the process-index-ordered
#: gather, so every process computes the identical decision
REDUCERS: dict[str, Callable[[list], Any]] = {
    "max": max,
    "min": min,
    "sum": sum,
    "mean": lambda vs: sum(vs) / len(vs),
    "any": lambda vs: bool(any(vs)),
    "all": lambda vs: bool(all(vs)),
}


def reduce_decision(values: Sequence[Any], reduce: str | Callable = "same",
                    label: str = "decision") -> Any:
    """The pure core: one decision from the gathered per-process values.

    ``reduce`` is a name from :data:`REDUCERS`, ``"same"`` (verify the
    values already agree; :class:`ConsensusError` otherwise), or a
    deterministic callable ``list -> decision``.  Deterministic matters:
    the gathered list is identical (same order) on every process, so a
    deterministic reduce IS the consensus — a randomized one would
    un-replicate the decision it exists to replicate."""
    values = list(values)
    if not values:
        raise ValueError(f"replicated_decision({label!r}): empty gather")
    if callable(reduce):
        return reduce(values)
    if reduce == "same":
        return _same(label, values)
    try:
        fn = REDUCERS[reduce]
    except KeyError:
        raise ValueError(
            f"unknown reduce {reduce!r} — one of "
            f"{['same', *REDUCERS]} or a deterministic callable") from None
    return fn(values)


def gather_values(value: Any) -> list:
    """Every process's ``value``, in process-index order, on every
    process.  Single-process: ``[value]`` with no communication.

    Multi-host wire: the JSON encoding rides two ``process_allgather``
    calls — fixed-shape lengths first, then the byte payloads padded to
    the global max (allgather needs congruent shapes; the length vector
    is what makes the padding decodable)."""
    import jax

    if jax.process_count() == 1:
        return [value]
    import numpy as np
    from jax.experimental import multihost_utils

    payload = np.frombuffer(
        json.dumps(value, sort_keys=True).encode(), np.uint8)
    lengths = np.asarray(multihost_utils.process_allgather(
        np.int32(payload.size))).reshape(-1)
    buf = np.zeros(int(lengths.max()), np.uint8)
    buf[:payload.size] = payload
    rows = np.asarray(multihost_utils.process_allgather(buf))
    rows = rows.reshape(lengths.size, -1)
    return [json.loads(rows[p, :int(lengths[p])].tobytes().decode())
            for p in range(lengths.size)]


def replicated_decision(value: Any, reduce: str | Callable = "same", *,
                        label: str = "decision",
                        _gather: Callable[[Any], list] | None = None) -> Any:
    """One decision, identical on every process: allgather ``value``
    from all processes, apply the deterministic ``reduce``, return the
    result (see module docstring for the contract).

    ``_gather`` is the test seam: inject a fake per-process gather to
    pin multi-host semantics without multiple processes."""
    import contextlib

    values = (_gather or gather_values)(value)
    ctx = contextlib.nullcontext()
    if len(values) > 1:
        try:  # the allgather is a host sync: named in the trace like
            # the preemption consensus, so its cost stays attributable
            from ..telemetry import span
            from ..telemetry.registry import is_enabled

            if is_enabled():
                ctx = span(f"consensus/{label}")
        except Exception:
            pass  # telemetry must never decide the decision's fate
    with ctx:
        return reduce_decision(values, reduce, label)
