"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.5 marks PP "ABSENT" —
its DANet replica fits one GPU), but a complete distributed story needs the
third classic axis next to data (parallel.step) and tensor (parallel.tp)
parallelism, so this framework makes it first-class.

TPU-native construction — no send/recv, no process ranks, no schedules-as-
threads.  A ``pipe`` mesh axis holds one *stage* per device; stage parameters
are one stacked pytree whose leading dim is sharded over that axis (the same
stacked-layer layout LLM pipelining uses for repeated blocks).  Inside
``shard_map`` each device owns its stage's slice, and the GPipe schedule is a
single ``lax.scan`` over ``n_micro + n_stages - 1`` ticks:

* tick t: stage 0 ingests microbatch t (while one exists), every stage applies
  its block to its current activation, and ``lax.ppermute`` shifts activations
  one hop along the ICI ring to the next stage;
* the last stage scatters each finished microbatch into an output buffer;
  a ``psum`` at the end replicates the assembled output (all other stages
  contribute zeros);
* the pipeline bubble (stages idling for ``n_stages - 1`` ticks) is the usual
  GPipe cost — amortized by ``n_micro >> n_stages``.

Everything in the schedule (``scan``, ``ppermute``, masked writes) is
differentiable, so ``jax.grad`` through :func:`make_pipeline_apply` yields
pipeline-parallel *training*: the backward pass runs the ring in reverse
(``ppermute``'s transpose is the inverse permutation) with grads landing on
each stage's own parameter shard.  :func:`make_pipeline_train_step` packages
that into the framework's usual ``(state, batch) -> (state, loss)`` contract.

Stages must be shape-preserving ((mb, ...) -> (mb, ...)) so activations can
ride a fixed ppermute buffer — true for the repeated-block use case this
targets; put shape-changing stems/heads outside the pipelined body (they are
cheap and replicated).
"""

from __future__ import annotations

import functools
from collections.abc import Mapping
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import axis_size, make_mesh_1d, shard_map

#: canonical pipeline-stage axis name
PIPE_AXIS = "pipe"


def make_pipe_mesh(stages: int, devices=None) -> Mesh:
    """A 1-D ``(pipe,)`` mesh of ``stages`` devices — each device one stage,
    neighbouring stages ICI neighbours so the per-tick activation shift is a
    single-hop ``collective_permute``."""
    return make_mesh_1d(stages, PIPE_AXIS, devices)


def stage_param_specs(stacked_params: Any) -> Any:
    """PartitionSpec pytree for stacked stage params: leading (stage) dim
    sharded over ``pipe``, rest replicated."""
    return jax.tree.map(
        lambda x: P(*([PIPE_AXIS] + [None] * (x.ndim - 1))), stacked_params)


def pipeline_apply_local(stage_fn: Callable[[Any, jax.Array], jax.Array],
                         stacked_params: Any, microbatches: jax.Array,
                         axis_name: str = PIPE_AXIS) -> jax.Array:
    """Per-device GPipe body.  Call inside ``shard_map``; use
    :func:`make_pipeline_apply` for the meshed wrapper.

    ``stacked_params``: this device's stage slice, leading dim 1 (the
    shard_map split of the (S, ...) stack) — squeezed before ``stage_fn``.
    ``microbatches``: (M, mb, ...) — replicated; every device sees all
    microbatches but only stage 0 ingests them.
    Returns (M, mb, ...) — the last stage's outputs, replicated via psum.
    """
    n_stages = axis_size(axis_name)
    stage_idx = jax.lax.axis_index(axis_name)
    params = jax.tree.map(lambda x: x[0], stacked_params)
    n_micro = microbatches.shape[0]
    n_ticks = n_micro + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        acts, outputs = carry
        # Stage 0 pulls microbatch t from the feed; later stages consume the
        # activation ppermuted in from their predecessor last tick.
        feed = microbatches[jnp.clip(t, 0, n_micro - 1)]
        inp = jnp.where(stage_idx == 0, feed, acts)
        out = stage_fn(params, inp)
        # The last stage finishes microbatch t-(S-1); masked scatter keeps
        # the write static-shaped (invalid ticks rewrite an existing row).
        out_idx = t - (n_stages - 1)
        safe = jnp.clip(out_idx, 0, n_micro - 1)
        valid = (stage_idx == n_stages - 1) & (out_idx >= 0)
        row = jnp.where(valid, out, outputs[safe])
        outputs = jax.lax.dynamic_update_index_in_dim(outputs, row, safe, 0)
        acts = jax.lax.ppermute(out, axis_name, perm)
        return (acts, outputs), None

    mb_shape = microbatches.shape[1:]
    acts0 = jnp.zeros(mb_shape, microbatches.dtype)
    out0 = jnp.zeros((n_micro,) + mb_shape, microbatches.dtype)
    (_, outputs), _ = jax.lax.scan(
        tick, (acts0, out0), jnp.arange(n_ticks))
    # Only the last stage wrote anything; psum replicates it everywhere.
    return jax.lax.psum(
        jnp.where(stage_idx == n_stages - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)


def _meshed_apply(mesh: Mesh, stage_fn: Callable[[Any, jax.Array], jax.Array],
                  stacked_params: Any, microbatches: jax.Array,
                  axis_name: str) -> jax.Array:
    """The (unjitted) meshed pipeline forward shared by
    :func:`make_pipeline_apply` and :func:`make_pipeline_train_step`."""
    specs = stage_param_specs(stacked_params)
    fn = shard_map(
        functools.partial(pipeline_apply_local, stage_fn,
                          axis_name=axis_name),
        mesh=mesh, in_specs=(specs, P()), out_specs=P(),
        check_vma=False,
    )
    return fn(stacked_params, microbatches)


def make_pipeline_apply(mesh: Mesh,
                        stage_fn: Callable[[Any, jax.Array], jax.Array],
                        axis_name: str = PIPE_AXIS):
    """Jitted ``(stacked_params, microbatches) -> outputs`` over global
    arrays: params' stage dim sharded on ``axis_name``, microbatches and
    outputs replicated.  Differentiable — wrap in ``jax.grad`` for
    pipeline-parallel training."""

    def global_fn(stacked_params, microbatches):
        return _meshed_apply(mesh, stage_fn, stacked_params, microbatches,
                             axis_name)

    return jax.jit(global_fn)


def sequential_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                     stacked_params: Any, x: jax.Array) -> jax.Array:
    """Ground truth for the pipeline: fold ``stage_fn`` over the stage dim on
    one device.  (M, mb, ...) in/out, matching :func:`make_pipeline_apply`."""
    n_stages = jax.tree.leaves(stacked_params)[0].shape[0]
    for s in range(n_stages):
        params = jax.tree.map(lambda p: p[s], stacked_params)
        x = jax.vmap(lambda mb: stage_fn(params, mb))(x)
    return x


def init_stacked_stage_params(rng: jax.Array, block, n_stages: int,
                              sample_input: jax.Array,
                              all_collections: bool = False) -> Any:
    """Stacked params for ``n_stages`` copies of a Flax ``block``: every leaf
    gains a leading stage dim (shard it with :func:`stage_param_specs`).

    Each stage gets its own init key; the block must be shape-preserving.
    ``all_collections=True`` stacks the block's full variables dict (params
    AND e.g. frozen BatchNorm ``batch_stats``) — how the real backbone's
    bottleneck blocks pipeline in inference mode; the default stacks only
    ``params`` (stateless blocks: GroupNorm/LayerNorm).  Pair with
    :func:`flax_stage_fn` using the same flag.
    """
    rngs = jax.random.split(rng, n_stages)

    def init_one(r):
        variables = block.init(r, sample_input)
        return dict(variables) if all_collections else variables["params"]

    return jax.vmap(init_one)(rngs)


def flax_stage_fn(block, all_collections: bool = False
                  ) -> Callable[[Any, jax.Array], jax.Array]:
    """Adapt a Flax module to the ``(stage_params, x) -> y`` contract of
    :func:`make_pipeline_apply` / :func:`make_pipeline_train_step`."""

    def stage_fn(params, x):
        variables = params if all_collections else {"params": params}
        return block.apply(variables, x)

    return stage_fn


def make_pipeline_train_step(mesh: Mesh,
                             stage_fn: Callable[[Any, jax.Array], jax.Array],
                             loss_fn: Callable[[jax.Array, jax.Array],
                                               jax.Array],
                             tx, axis_name: str = PIPE_AXIS):
    """Pipeline-parallel ``((params, opt_state), micro_x, micro_y) ->
    ((params, opt_state), loss)`` step: forward through the GPipe schedule,
    backward through its transpose, optimizer update on each stage's own
    parameter shard (optimizer state inherits the stage sharding — per-stage
    optimizer memory, the PP analogue of tp.py's sharded momentum).

    Every leaf of the stage params is trained — pass only the ``params``
    collection (stateless-norm blocks).  ``all_collections=True`` stacks are
    inference-only and rejected here: the optimizer would silently update
    the frozen BatchNorm running stats they carry.
    """

    def step(carry, micro_x, micro_y):
        params, opt_state = carry
        # A full variables stack (dict OR FrozenDict) always carries a
        # top-level 'params' collection; a bare params tree never does
        # (flax auto-names are Conv_0/BatchNorm_0/...).  Rejecting on that
        # key covers batch_stats and any other non-trainable collection.
        if isinstance(params, Mapping) and "params" in params:
            raise ValueError(
                "stage params look like a full variables dict "
                "(all_collections=True stack) — the optimizer would update "
                "its non-trainable collections (e.g. frozen BN "
                "batch_stats); train with the 'params' collection only "
                "(use stateless norms in pipelined blocks)")

        def objective(p):
            return loss_fn(_meshed_apply(mesh, stage_fn, p, micro_x,
                                         axis_name), micro_y)

        loss, grads = jax.value_and_grad(objective)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), loss

    return jax.jit(step, donate_argnums=(0,))
