"""Device mesh construction and sharding layouts.

This module is the framework's entire "distributed communication backend".
The reference had none in-repo: its inter-device traffic lived inside
``torch.nn.DataParallel`` (reference train_pascal.py:92 — per-step replica
broadcast + scatter/gather on CUDA streams) and the NCCL/DDP backend it
planned in the comment checklist (train_pascal.py:1-8) was never built.

The TPU-native design inverts that: **the mesh is the topology and the
compiler owns communication.** We build one ``jax.sharding.Mesh`` with a
``data`` axis (batch parallelism over ICI) and a reserved ``model`` axis
(tensor parallelism — unused for reference parity but first-class in the
layout so wider models can shard without restructuring).  The train step is
``jit``-compiled with ``NamedSharding`` annotations; GSPMD inserts the
gradient all-reduces the reference's checklist called "DDP" and the
input scatter ``DataParallel`` did by hand.  There is no explicit
scatter/gather/broadcast code anywhere in this framework.

Multi-host: ``initialize_distributed`` wraps ``jax.distributed.initialize``
(the TCP rendezvous the reference sketched as "port setup",
train_pascal.py:8), and ``shard_batch`` uses
``jax.make_array_from_process_local_data`` so each host contributes only its
own shard of the global batch — the "distributed loader sampler" of
train_pascal.py:3, realized in ``data.pipeline.DataLoader``'s
process-sharded index streams.
"""

from __future__ import annotations

import math
from typing import Mapping

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..chaos import sites as chaos_sites

#: version-portable shard_map: the top-level ``jax.shard_map`` only exists
#: on jax >= 0.5; older versions (this image ships 0.4.37) house it under
#: jax.experimental and spell ``check_vma`` as ``check_rep``.  Every
#: per-device-code module (ring, ulysses, pipeline) imports THIS name so
#: the version probe lives in one place.
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:
    from jax.experimental.shard_map import shard_map as _shard_map

import inspect as _inspect

_SHARD_MAP_KWARGS = frozenset(
    _inspect.signature(_shard_map).parameters)


def shard_map(*args, **kwargs):
    if "check_vma" in kwargs and "check_vma" not in _SHARD_MAP_KWARGS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(*args, **kwargs)


def axis_size(axis_name: str):
    """``jax.lax.axis_size`` where it exists; the static ``psum(1, axis)``
    idiom (constant-folded at trace time, no runtime collective) on the
    0.4.x line that predates it."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)

#: canonical axis names, in mesh order
DATA_AXIS = "data"
MODEL_AXIS = "model"


def initialize_distributed(coordinator_address: str | None = None,
                           num_processes: int | None = None,
                           process_id: int | None = None) -> None:
    """Multi-host rendezvous (no-op on a single process).

    TPU pods discover topology from the environment, so bare
    ``jax.distributed.initialize()`` is usually enough; the explicit arguments
    cover DCN / non-TPU clusters.
    """
    if num_processes is not None and num_processes > 1 or coordinator_address:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )


def make_mesh_1d(size: int, axis_name: str, devices=None) -> Mesh:
    """A 1-D mesh of ``size`` devices over one named axis, in
    ``jax.devices()`` order so neighbouring mesh coordinates are ICI
    neighbours (single-hop ``ppermute``s for pipeline/ring schedules).
    Backs ``pipeline.make_pipe_mesh`` and ``moe.make_expert_mesh``."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    if devices.size != size:
        raise ValueError(f"{devices.size} devices != {size} {axis_name}s")
    return Mesh(devices.reshape(size), (axis_name,))


def make_mesh(data: int | None = None, model: int = 1,
              devices=None) -> Mesh:
    """A 2-D ``(data, model)`` mesh over all (or the given) devices.

    ``data=None`` means "everything not claimed by ``model``".  Device order
    comes from ``jax.devices()``, which enumerates contiguously over ICI so
    neighbouring mesh coordinates are ICI neighbours and GSPMD collectives
    ride ICI, not DCN.
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = devices.size
    if data is None:
        if n % model:
            raise ValueError(f"{n} devices not divisible by model={model}")
        data = n // model
    if data * model != n:
        raise ValueError(f"mesh {data}x{model} != {n} devices")
    return Mesh(devices.reshape(data, model), (DATA_AXIS, MODEL_AXIS))


def make_hybrid_mesh(slices: int, data: int | None = None, model: int = 1,
                     devices=None, process_is_granule: bool | None = None
                     ) -> Mesh:
    """A ``(data, model)`` mesh over a MULTI-SLICE topology (ICI + DCN).

    Multi-slice TPU systems (and any multi-host cluster without a single
    ICI domain) have two networks: fast ICI within a slice, slower DCN
    between slices.  The scaling recipe is hierarchical data parallelism:
    keep the ``model`` axis and the inner factor of the ``data`` axis
    within a slice, and let only the OUTER factor of ``data`` span DCN —
    GSPMD then lowers the gradient all-reduce to an intra-slice reduce
    (ICI), a small cross-slice phase (DCN), and an intra-slice broadcast.

    The returned mesh has the same ``(data, model)`` axis names as
    ``make_mesh``, so every train step, sharding rule, and checkpoint
    layout in this framework works unchanged — the hierarchy lives purely
    in the device ORDER, which ``mesh_utils.create_hybrid_device_mesh``
    arranges so that mesh coordinates varying fastest stay ICI-local.

    ``slices`` is the DCN factor of the data axis; ``data`` the per-slice
    factor (``None`` = everything left).  ``process_is_granule=None``
    auto-detects: device ``slice_index`` attributes when the runtime
    exposes them (real multi-slice TPU), else processes as granules (the
    documented fallback, also what CPU multi-process tests exercise).

    The reference has no counterpart (its parallelism never left one
    host, reference train_pascal.py:92); this completes the DCN half of
    the "NCCL/MPI backend" story TPU-natively (SURVEY.md §2.6, §5.8).
    """
    from jax.experimental import mesh_utils

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if slices < 1 or n % slices:
        raise ValueError(f"{n} devices not divisible into {slices} slices")
    per_slice = n // slices
    if data is None:
        if per_slice % model:
            raise ValueError(
                f"{per_slice} devices/slice not divisible by model={model}")
        data = per_slice // model
    if data * model != per_slice:
        raise ValueError(
            f"per-slice mesh {data}x{model} != {per_slice} devices/slice")
    if slices == 1:
        # one granule: no DCN dimension exists; the plain ICI mesh IS the
        # hybrid mesh (and create_hybrid_device_mesh would reject granule
        # detection on single-slice platforms that expose no slice_index)
        return make_mesh(data=data, model=model, devices=devices)
    if process_is_granule is None:
        # Slice granules when the runtime exposes a real multi-slice
        # structure matching the request; processes when devices carry no
        # slice structure at all (or a single degenerate slice 0, as the
        # multi-process CPU backend does).  A PRESENT-but-mismatched slice
        # structure is a misconfiguration — falling back to hosts there
        # would silently treat intra-slice ICI links as the DCN phase.
        idx = {getattr(d, "slice_index", None) for d in devices}
        on_tpu = any(getattr(d, "platform", None) == "tpu" for d in devices)
        if None in idx or (len(idx) == 1 and not on_tpu):
            # no slice structure at all, or the degenerate all-slice-0
            # of non-TPU backends (multi-process CPU): hosts are the DCN
            # granules
            process_is_granule = True
        elif len(idx) == slices:
            process_is_granule = False
        else:
            # PRESENT slice structure contradicting the request — incl.
            # a real single-slice TPU asked for slices>1, whose hosts
            # are ICI-connected, not DCN
            raise ValueError(
                f"requested slices={slices} but the devices expose "
                f"{len(idx)} distinct slice_index value(s); pass "
                "process_is_granule=True explicitly to group by host "
                "instead")
    arr = mesh_utils.create_hybrid_device_mesh(
        (data, model), (slices, 1), devices,
        process_is_granule=process_is_granule)
    # (slices*data, model): outer (DCN) factor varies slowest, so rows of
    # the data axis within one slice stay contiguous -> ICI-local
    return Mesh(arr.reshape(slices * data, model), (DATA_AXIS, MODEL_AXIS))


def batch_spec() -> P:
    """Batch arrays: leading (batch) dim split over ``data``; spatial and
    channel dims replicated (a 512×512 conv input shards naturally on batch
    only — spatial sharding is the ring-attention analogue we reserve for
    long-context work, see ``ops.attention.blocked_position_attention``)."""
    return P(DATA_AXIS)


def replicated_spec() -> P:
    """Parameters / optimizer state / scalars: fully replicated.  For
    reference parity (pure data parallel) params live on every chip; the
    ``model`` axis is where a tensor-parallel partitioning would go."""
    return P()


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_spec())


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, replicated_spec())


def shard_batch(mesh: Mesh, batch: Mapping[str, np.ndarray]) -> dict:
    """Place a host-local batch dict onto the mesh, batch-dim sharded.

    Single-process: a plain ``device_put`` with the batch sharding (XLA slices
    locally).  Multi-process: every host holds only its shard of the global
    batch, so assemble the global array from per-process data — the TPU
    equivalent of the reference's planned distributed sampler + DataParallel
    scatter (train_pascal.py:3,92) with zero data motion (each host's shard is
    already on its own chips).
    """
    sharding = batch_sharding(mesh)
    if jax.process_count() == 1:
        return {k: jax.device_put(v, sharding) for k, v in batch.items()}
    return {
        k: jax.make_array_from_process_local_data(sharding, np.asarray(v))
        for k, v in batch.items()
    }


def prefetch_to_device(batches, mesh: Mesh, size: int = 2,
                       keys: tuple[str, ...] | None = None,
                       transform=None):
    """Iterate ``batches`` with up to ``size`` of them already placed on the
    mesh (batch-dim sharded) ahead of consumption.

    ``jax.device_put`` is asynchronous, so keeping a small window of batches
    in flight hides the H2D transfer behind the previous step's compute —
    the reference bought the same overlap with DataLoader worker processes +
    ``non_blocking=True`` H2D copies (its checklist item,
    train_pascal.py:5); here the overlap is explicit and sized.

    ``keys`` filters each dict to the device-bound arrays (eval batches
    carry ragged host-side lists that cannot be placed).  ``size=0``
    degrades to synchronous per-step placement.  ``size`` may also be a
    zero-arg callable returning the CURRENT window depth — re-read every
    iteration, so the feed governor's hot resize (data/governor.py)
    applies mid-epoch: a grow admits deeper pipelining immediately, a
    shrink just drains the window to the new bound (never below 1).

    Placement runs on a dedicated thread: ``device_put`` of a large batch
    is far from free on the calling thread (layout/copy work before the DMA
    — ~146 ms for a 33 MB float batch through a tunneled chip), and done
    inline it serializes against the step dispatch this prefetcher exists
    to overlap.  One worker keeps placements ordered.

    ``transform`` is an optional host-side ``batch -> batch`` stage run on
    that same worker thread just before placement (after the ``keys``
    filter would be pointless — it may introduce new keys, so it runs
    first).  Used by data.coalesce_wire to keep the full-batch pack memcpy
    off the dispatch thread.
    """
    import collections
    import concurrent.futures as cf

    def place(batch):
        if transform is not None:
            batch = transform(batch)
        if keys is not None:
            batch = {k: v for k, v in batch.items() if k in keys}
        # chaos seam: latency here is a slow H2D pipe, raised errors are
        # a dying transfer, poisoning tears the host batch pre-placement
        batch = chaos_sites.fire("device/put", payload=batch)
        return shard_batch(mesh, batch)

    if not callable(size) and size <= 0:  # synchronous degradation
        for batch in batches:
            yield place(batch)
        return
    live_size = size if callable(size) else (lambda: size)

    futures: collections.deque = collections.deque()
    with cf.ThreadPoolExecutor(max_workers=1) as pool:
        try:
            for batch in batches:
                futures.append(pool.submit(place, batch))
                while len(futures) > max(1, int(live_size())):
                    yield futures.popleft().result()
            while futures:
                yield futures.popleft().result()
        finally:
            # abandoned generator (early break/exception upstream): drop
            # queued placements so shutdown doesn't run them pointlessly
            while futures:
                futures.popleft().cancel()


def pad_to_multiple(batch: Mapping[str, np.ndarray], multiple: int
                    ) -> tuple[dict, int]:
    """Pad the batch dim up to ``multiple`` (device count) by repeating the
    last sample; returns (padded batch, original size).  Needed for the val
    loader's ragged final batch — the train loader drops it instead
    (``drop_last``, matching reference train_pascal.py:161)."""
    first = next(iter(batch.values()))
    n = first.shape[0]
    target = math.ceil(n / multiple) * multiple
    if target == n:
        return dict(batch), n
    pad = target - n
    out = {}
    for k, v in batch.items():
        reps = np.concatenate([v, np.repeat(v[-1:], pad, axis=0)], axis=0)
        out[k] = reps
    return out, n
