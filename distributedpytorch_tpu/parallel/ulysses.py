"""Ulysses-style all-to-all sequence parallelism.

The second of the two standard long-context constructions (the first, ring
attention, lives in :mod:`parallel.ring`).  The reference has no sequence
dimension (fixed 512x512 crops, train_pascal.py:127; SURVEY.md §2.5 marks
SP/CP "ABSENT"), but long-context support is first-class in this framework,
and the two schemes trade off differently on TPU:

* **ring** keeps tokens resident and cycles K/V blocks around the ICI ring —
  communication grows with ``axis_size`` hops of the K/V block, compute
  overlaps transfer, works for any head count (even 1, like DANet's PAM);
* **ulysses** (DeepSpeed-Ulysses) re-shards *once*: an ``all_to_all`` swaps
  the token sharding for a head sharding, each device then runs ordinary
  full attention over ALL tokens for its subset of heads, and a second
  ``all_to_all`` swaps back.  Two collectives total regardless of axis size,
  but the head count must be divisible by the axis size.

Per-device code via ``shard_map``; the exchanges are ``jax.lax.all_to_all``
(tiled), which XLA lowers to the native ICI all-to-all.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, axis_size, shard_map


def _heads_attention(q, k, v, scale: float | None):
    """Full attention with explicit heads: (B, N, H, D) -> (B, N, H, Dv).

    Scores/normalization accumulate in f32 (bf16-safe), matching
    ops.attention semantics — unscaled energies unless ``scale`` is given
    (the DANet PAM convention; pass ``1/sqrt(D)`` for transformer-style).
    """
    scores = jnp.einsum("bnhd,bmhd->bhnm", q, k,
                        preferred_element_type=jnp.float32)
    if scale is not None:
        scores = scores * scale
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhnm,bmhd->bnhd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.astype(v.dtype)


def ulysses_attention_local(q, k, v, axis_name: str = DATA_AXIS,
                            scale: float | None = None):
    """Per-device body: exact multi-head attention over a token axis sharded
    on ``axis_name``.  Call inside ``shard_map``; use
    :func:`make_ulysses_attention` for the meshed wrapper.

    ``q``/``k``/``v``: (B, N_local, H, D*) — the local token block, all
    heads.  H must be divisible by the axis size.  Returns
    (B, N_local, H, Dv), bit-matching full attention over the global token
    axis (up to f32 accumulation order).
    """
    ax = axis_size(axis_name)
    h = q.shape[2]
    if h % ax:
        raise ValueError(
            f"ulysses needs heads ({h}) divisible by axis size ({ax}); "
            "use ring attention for indivisible/single-head cases")

    def seq_to_heads(x):  # (B, N/ax, H, D) -> (B, N, H/ax, D)
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def heads_to_seq(x):  # (B, N, H/ax, D) -> (B, N/ax, H, D)
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    out = _heads_attention(seq_to_heads(q), seq_to_heads(k), seq_to_heads(v),
                           scale)
    return heads_to_seq(out)


def make_ulysses_attention(mesh: Mesh, axis_name: str = DATA_AXIS,
                           scale: float | None = None):
    """Jitted ``(q, k, v) -> out`` over global (B, N, H, D) arrays with the
    token axis sharded on ``axis_name`` of ``mesh`` — the all-to-all
    long-context configuration (two ICI collectives per call)."""
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        functools.partial(ulysses_attention_local, axis_name=axis_name,
                          scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    sharding = NamedSharding(mesh, spec)
    return jax.jit(fn, in_shardings=(sharding,) * 3,
                   out_shardings=sharding)
