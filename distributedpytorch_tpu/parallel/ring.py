"""Ring attention: sequence/context parallelism over a mesh axis.

The reference has no sequence dimension at all (fixed 512x512 crops,
train_pascal.py:127; SURVEY.md §2.5 marks SP/CP "ABSENT") — but its
position-attention module is full self-attention over H/8 x W/8 spatial
tokens, the quadratic-memory part of the model.  This module is the TPU-native
scaling path for that attention when token counts outgrow one chip's HBM
(bigger crops, 3D volumes, or any long-sequence head built on these ops):

* the token axis is *sharded over a mesh axis*; each device holds one block
  of Q and one block of K/V;
* each device computes online-softmax attention of its Q block against the
  K/V block it currently holds, then passes that K/V block to its ring
  neighbour with ``jax.lax.ppermute`` — after ``axis_size`` hops every Q
  block has seen every K/V block;
* the carried state is the flash-attention (running-max, running-sum,
  accumulator) triple, so no N x N score matrix ever exists anywhere;
* compute and the ICI transfer overlap: XLA schedules the next hop's
  ``ppermute`` concurrently with the current block's einsum (the
  collective-permute latency hides behind the matmul at realistic sizes).

This is the "ring attention" construction (Liu et al.) expressed with XLA
collectives instead of hand-written RDMA: ``shard_map`` gives per-device
code, ``ppermute`` rides the ICI ring the mesh axis was laid out on
(parallel.mesh builds meshes in ICI-contiguous device order).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, axis_size, shard_map


def _online_block(q, k_blk, v_blk, m, s, acc, scale: float | None):
    """One online-softmax update of (m, s, acc) with a new K/V block.

    ``q``: (B, Nq, Ck); ``k_blk``/``v_blk``: (B, Nb, Ck)/(B, Nb, Cv);
    ``m``/``s``: (B, Nq, 1) running max / normalizer; ``acc``: (B, Nq, Cv).
    Scores accumulate in f32 (bf16-safe), matching ops.attention semantics
    (unscaled DANet energies unless ``scale`` is given).
    """
    scores = jnp.einsum("bnc,bmc->bnm", q, k_blk,
                        preferred_element_type=jnp.float32)
    if scale is not None:
        scores = scores * scale
    m_new = jnp.maximum(m, scores.max(axis=-1, keepdims=True))
    corr = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new)
    s_new = s * corr + p.sum(axis=-1, keepdims=True)
    # P·V accumulates in f32 regardless of input dtype (like
    # blocked_position_attention / the pallas kernel) — in bf16 the per-hop
    # products would drift, and the drift compounds with ring size.
    acc_new = acc * corr + jnp.einsum(
        "bnm,bmc->bnc", p, v_blk.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    return m_new, s_new, acc_new


def ring_attention_local(q, k, v, axis_name: str = DATA_AXIS,
                         scale: float | None = None):
    """Per-device body: full attention over a token axis sharded on
    ``axis_name``.  Call inside ``shard_map`` (or ``pmap``); use
    :func:`make_ring_attention` for the meshed convenience wrapper.

    ``q``/``k``/``v``: (B, N_local, C*) — the local token block.
    Returns (B, N_local, Cv), bit-matching full softmax attention over the
    global token axis (up to f32 accumulation order).
    """
    n_hops = axis_size(axis_name)
    b, nq, _ = q.shape
    cv = v.shape[-1]
    m0 = jnp.full((b, nq, 1), -jnp.inf, jnp.float32)
    s0 = jnp.zeros((b, nq, 1), jnp.float32)
    acc0 = jnp.zeros((b, nq, cv), jnp.float32)
    perm = [(i, (i + 1) % n_hops) for i in range(n_hops)]

    def hop(carry, _):
        m, s, acc, k_cur, v_cur = carry
        m, s, acc = _online_block(q, k_cur, v_cur, m, s, acc, scale)
        # Pass K/V to the next device on the ring. The last hop's permute is
        # redundant but keeps the loop uniform; XLA overlaps it with the
        # einsum above.
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m, s, acc, k_nxt, v_nxt), None

    (m, s, acc, _, _), _ = jax.lax.scan(
        hop, (m0, s0, acc0, k, v), None, length=n_hops)
    return (acc / jnp.maximum(s, 1e-30)).astype(v.dtype)


def make_ring_attention_inline(mesh: Mesh, axis_name: str = DATA_AXIS,
                               scale: float | None = None,
                               batch_axis: str | None = None):
    """Unjitted shard_map ring attention, for embedding inside a larger
    traced program (e.g. the DANet head's ``pam_impl='ring'`` path).

    ``batch_axis`` optionally shards the leading batch dim over a second
    mesh axis (the flagship's ``data`` axis); token axis rides
    ``axis_name``.
    """
    spec = P(batch_axis, axis_name, None)
    return shard_map(
        functools.partial(ring_attention_local, axis_name=axis_name,
                          scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )


def make_ring_attention(mesh: Mesh, axis_name: str = DATA_AXIS,
                        scale: float | None = None):
    """Jitted ``(q, k, v) -> out`` with the token axis sharded over
    ``axis_name`` of ``mesh``; batch/feature axes replicated.

    The returned function accepts *global* (B, N, C) arrays and computes
    exact attention while each device only ever materializes its
    N/axis_size token slice of K/V — the long-context configuration.
    """
    fn = make_ring_attention_inline(mesh, axis_name, scale)
    sharding = NamedSharding(mesh, P(None, axis_name, None))
    return jax.jit(fn, in_shardings=(sharding,) * 3,
                   out_shardings=sharding)
