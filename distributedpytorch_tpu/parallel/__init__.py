"""Parallelism subsystem: mesh topology, shardings, compiled train/eval steps.

The TPU-native replacement for the reference's ``torch.nn.DataParallel``
wrapper (reference train_pascal.py:92) and its planned-but-never-built
NCCL/DDP backend (train_pascal.py:1-8).
"""

from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    batch_sharding,
    batch_spec,
    initialize_distributed,
    make_hybrid_mesh,
    make_mesh,
    pad_to_multiple,
    prefetch_to_device,
    replicated_sharding,
    replicated_spec,
    shard_batch,
)
from .moe import (
    EXPERT_AXIS,
    MoEMlp,
    ep_param_specs,
    init_moe_params,
    make_expert_mesh,
    make_moe_apply,
    moe_ffn,
)
from .pipeline import (
    PIPE_AXIS,
    flax_stage_fn,
    init_stacked_stage_params,
    make_pipe_mesh,
    make_pipeline_apply,
    make_pipeline_train_step,
    stage_param_specs,
)
from .ring import (
    make_ring_attention,
    make_ring_attention_inline,
    ring_attention_local,
)
from .consensus import (
    ConsensusError,
    reduce_decision,
    replicated_decision,
)
from .plan import (
    BUCKET_COMPATIBLE,
    STRATEGIES,
    Plan,
    PlanError,
    auto_plan,
    estimate_plan_memory,
    plan_from_config,
    plan_record_block,
    resolve_plan,
)
from .tp import state_shardings, tp_param_specs
from .zero import zero_opt_specs
from .ulysses import make_ulysses_attention, ulysses_attention_local
from .step import (
    DEVICE_KEYS,
    INPUT_KEY,
    TARGET_KEY,
    WIRE_KEY,
    TrainState,
    create_train_state,
    make_eval_step,
    make_train_step,
    pack_wire,
    unpack_wire,
)

__all__ = [
    "BUCKET_COMPATIBLE",
    "STRATEGIES",
    "ConsensusError",
    "reduce_decision",
    "replicated_decision",
    "Plan",
    "PlanError",
    "auto_plan",
    "estimate_plan_memory",
    "plan_from_config",
    "plan_record_block",
    "resolve_plan",
    "DATA_AXIS",
    "EXPERT_AXIS",
    "MODEL_AXIS",
    "PIPE_AXIS",
    "INPUT_KEY",
    "TARGET_KEY",
    "MoEMlp",
    "TrainState",
    "ep_param_specs",
    "flax_stage_fn",
    "init_moe_params",
    "init_stacked_stage_params",
    "make_expert_mesh",
    "make_moe_apply",
    "make_pipe_mesh",
    "make_pipeline_apply",
    "make_pipeline_train_step",
    "moe_ffn",
    "stage_param_specs",
    "batch_sharding",
    "batch_spec",
    "create_train_state",
    "initialize_distributed",
    "make_eval_step",
    "make_hybrid_mesh",
    "make_mesh",
    "make_ring_attention",
    "make_ring_attention_inline",
    "make_ulysses_attention",
    "make_train_step",
    "DEVICE_KEYS",
    "WIRE_KEY",
    "pack_wire",
    "unpack_wire",
    "ring_attention_local",
    "ulysses_attention_local",
    "pad_to_multiple",
    "prefetch_to_device",
    "replicated_sharding",
    "replicated_spec",
    "shard_batch",
    "state_shardings",
    "tp_param_specs",
    "zero_opt_specs",
]
