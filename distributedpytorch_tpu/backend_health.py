"""Bounded health probing for a (possibly tunneled) accelerator backend.

A tunneled TPU plugin can hang indefinitely at backend init when the tunnel
is unhealthy (observed: >4 min inside ``jax.devices()``).  Probing in a
throwaway child process bounds the damage: on timeout/failure the caller
falls back to CPU and still produces output instead of wedging.

Import-light on purpose (no jax/numpy at module scope): callers run
:func:`ensure_backend_or_cpu_fallback` BEFORE importing jax so the
``JAX_PLATFORMS`` fallback takes effect.  Shared by ``bench.py`` and
``scripts/perf_sweep.py``.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time


def pin_requested_platform() -> None:
    """Re-pin an env-requested platform via jax.config, AFTER importing jax.

    A site-installed plugin (sitecustomize) may override ``JAX_PLATFORMS``
    during interpreter startup; the explicit config update restores what the
    environment asked for.  Shared by bench.py, scripts/perf_sweep.py, and
    the probe child below — one owner for the pinning rule.
    """
    p = os.environ.get("JAX_PLATFORMS")
    if p:
        import jax

        jax.config.update("jax_platforms", p)


def _probe(tail_code: str, timeout_s: int):
    """Run a backend probe in a throwaway subprocess with a hard timeout.

    The child sys.paths the repo and pins any explicitly-requested platform
    exactly as the parent will (:func:`pin_requested_platform`), then
    ``import jax`` followed by ``tail_code``.  One owner for the probe
    prologue — every health question in this module (and the pollers built
    on it) must ask it the same way.  Returns the ``CompletedProcess``, or
    ``None`` on timeout.
    """
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        return subprocess.run(
            [sys.executable, "-c",
             f"import sys; sys.path.insert(0, {root!r});"
             "from distributedpytorch_tpu.backend_health import "
             "pin_requested_platform;"
             "pin_requested_platform();"
             "import jax;" + tail_code],
            timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return None


def accelerator_healthy(timeout_s: int = 240) -> tuple[bool, str]:
    """Probe the default jax backend in a throwaway subprocess.

    The probe validates the backend the caller will actually run on.
    Returns ``(healthy, reason)``.
    """
    probe = _probe("assert len(jax.devices()) >= 1", timeout_s)
    if probe is None:
        return False, f"backend init exceeded {timeout_s}s"
    if probe.returncode == 0:
        return True, ""
    lines = (probe.stderr or "").strip().splitlines()
    return False, lines[-1] if lines else "probe failed"


def tpu_reachable(timeout_s: int = 240) -> bool:
    """True when the default backend resolves to a real TPU right now.

    Same bounding as :func:`accelerator_healthy`, but the question is
    stricter: pollers queueing chip work (scripts/chip_queue.py, scripts/
    sweep_when_healthy.py) must not fire on a CPU fallback — a CPU number
    is worse than waiting.
    """
    probe = _probe("sys.exit(0 if any(d.platform == 'tpu' "
                   "for d in jax.devices()) else 1)", timeout_s)
    return probe is not None and probe.returncode == 0


def device_op_alive(timeout_s: float = 5.0) -> tuple[bool, str]:
    """In-process liveness: one trivial device computation, hard-bounded.

    The serving complement of :func:`accelerator_healthy`: that probe pays
    a full backend init in a throwaway child (right for a cold start,
    ~seconds), while a liveness endpoint polled every few seconds needs
    the question "can THIS process still run device work right now"
    answered in milliseconds.  The op runs on a daemon thread with a join
    timeout, so a wedged runtime yields ``(False, reason)`` instead of
    hanging the probe (the stuck daemon thread is abandoned — acceptable
    for a process whose orchestrator is about to restart it anyway).

    Returns ``(alive, reason)``; reason is empty when alive.
    """
    import threading

    out: dict = {}

    def run() -> None:
        try:
            import jax

            # tiny but real: touches dispatch, device math, and D2H
            out["value"] = float(jax.device_get(
                jax.numpy.ones(()) + jax.numpy.ones(())))
        except Exception as e:  # noqa: BLE001 — any failure means dead
            out["error"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        return False, f"device op exceeded {timeout_s}s"
    if "error" in out:
        return False, out["error"]
    if out.get("value") != 2.0:
        return False, f"device op returned {out.get('value')!r}, not 2.0"
    return True, ""


def ensure_backend_or_cpu_fallback(
        recovery_minutes: float | None = None, *,
        ignore_env: bool = False,
        backoff_base: float = 5.0,
        backoff_cap: float = 60.0) -> bool:
    """Probe (with a bounded recovery poll) and fall back to CPU if the
    backend stays down.

    Returns True when the default backend is usable (or the probe was
    skipped), False when the fallback to CPU was taken.  Skipped entirely
    when CPU is already forced (the hang cannot occur and the fallback is in
    effect) or ``DPTPU_BENCH_PROBE=0`` (healthy hosts pay a second backend
    init for the probe child; opt out when the accelerator is known good).

    A wedged tunnel has been observed to recover within minutes-to-tens-of-
    minutes, and a CPU number can cost a whole benchmark round — so instead
    of a fixed retry count, the probe POLLS until ``recovery_minutes`` of
    wall clock have elapsed (env ``DPTPU_BENCH_RECOVERY_MINUTES`` overrides
    unless ``ignore_env`` — the escape hatch for an explicit CLI flag like
    bench.py's ``--wait-for-backend``; default 2 — a couple of fast-fail
    probes for interactive scripts.  ``bench.py`` passes a much longer
    window because its output is the round's official record).  Each
    individual probe stays hard-bounded in a child process, so a wedged
    backend init cannot take the poller down.

    Retries back off exponentially from ``backoff_base`` seconds to
    ``backoff_cap``: a tunnel that recovers in seconds is caught within
    seconds (the fixed 60 s nap used to eat most of short windows), while
    a long outage converges to the old one-probe-a-minute cadence.
    """
    if os.environ.get("DPTPU_BENCH_PROBE") == "0" or \
            os.environ.get("JAX_PLATFORMS") == "cpu":
        return True
    env_min = os.environ.get("DPTPU_BENCH_RECOVERY_MINUTES")
    if ignore_env:
        pass  # explicit caller flag beats ambient env configuration
    elif env_min is not None:
        try:
            recovery_minutes = float(env_min)
        except ValueError:
            pass
    elif os.environ.get("DPTPU_BENCH_PROBE_RETRIES") is not None:
        # Honor the pre-poll knob's contract literally: N probes spaced
        # ~60 s apart == an (N-1)-minute window (N=1 -> single probe,
        # fast fallback).  The legacy fixed cadence, not the fast ramp —
        # so both the probe count AND the recovery window stay what the
        # knob documented.
        try:
            n = float(os.environ["DPTPU_BENCH_PROBE_RETRIES"])
            if n != n:            # NaN would poison the deadline math
                raise ValueError(n)
            recovery_minutes = max(0.0, n - 1)
            backoff_base = backoff_cap
        except ValueError:
            pass
    if recovery_minutes is None or recovery_minutes != recovery_minutes:
        # None and NaN both mean the default (a NaN window would make the
        # deadline comparison below always-false and the poll infinite)
        recovery_minutes = 2.0
    deadline = time.time() + recovery_minutes * 60
    attempt = 0
    while True:
        attempt += 1
        ok, why = accelerator_healthy()
        if ok:
            return True
        remaining = deadline - time.time()
        print(f"backend probe: unhealthy ({why}), attempt {attempt}, "
              f"{max(0, remaining) / 60:.1f} min of recovery window left",
              file=sys.stderr)
        if remaining <= 0:
            break
        # exponent clamped so an unbounded poll can't overflow float math
        backoff = min(backoff_cap,
                      backoff_base * (2 ** min(attempt - 1, 30)))
        time.sleep(min(backoff, max(1.0, remaining)))
    print("backend probe: falling back to CPU", file=sys.stderr)
    os.environ["JAX_PLATFORMS"] = "cpu"
    return False


def enable_compile_cache(root: str | None = None) -> None:
    """Turn on JAX's persistent compilation cache under ``<root>/.jax_cache``
    (default: the repo root).  One owner for every entry point — the test
    suite, bench.py, and the perf sweep all recompile identical programs
    run-to-run; caching them cuts minutes of XLA work per invocation.
    Call after ``import jax`` and before the first compilation."""
    import jax

    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(root, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
