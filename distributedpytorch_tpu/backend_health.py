"""Bounded health probing for a (possibly tunneled) accelerator backend.

A tunneled TPU plugin can hang indefinitely at backend init when the tunnel
is unhealthy (observed: >4 min inside ``jax.devices()``).  Probing in a
throwaway child process bounds the damage: on timeout/failure the caller
falls back to CPU and still produces output instead of wedging.

Import-light on purpose (no jax/numpy at module scope): callers run
:func:`ensure_backend_or_cpu_fallback` BEFORE importing jax so the
``JAX_PLATFORMS`` fallback takes effect.  Shared by ``bench.py`` and
``scripts/perf_sweep.py``.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time


def pin_cpu8_topology(env: dict | None = None) -> dict:
    """Pin the canonical 8-device CPU topology (tests/conftest.py's) into
    ``env`` (default ``os.environ``) BEFORE jax initializes — the one
    owner of the rule standalone CLIs (jaxaudit, dptpu-chaos) and chaos
    child processes share.  A no-op when jax is already imported (the
    process owns its topology) or when the caller pinned another
    platform (``JAX_PLATFORMS=tpu jaxaudit update``).  Returns ``env``.
    """
    if env is None:
        if "jax" in sys.modules:
            return os.environ
        env = os.environ
    plat = env.get("JAX_PLATFORMS", "")
    if plat and plat != "cpu":
        return env
    env.setdefault("JAX_PLATFORMS", "cpu")
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    return env


def pin_requested_platform() -> None:
    """Re-pin an env-requested platform via jax.config, AFTER importing jax.

    A site-installed plugin (sitecustomize) may override ``JAX_PLATFORMS``
    during interpreter startup; the explicit config update restores what the
    environment asked for.  Shared by bench.py, scripts/perf_sweep.py, and
    the probe child below — one owner for the pinning rule.
    """
    p = os.environ.get("JAX_PLATFORMS")
    if p:
        import jax

        jax.config.update("jax_platforms", p)


def _probe(tail_code: str, timeout_s: int):
    """Run a backend probe in a throwaway subprocess with a hard timeout.

    The child sys.paths the repo and pins any explicitly-requested platform
    exactly as the parent will (:func:`pin_requested_platform`), then
    ``import jax`` followed by ``tail_code``.  One owner for the probe
    prologue — every health question in this module (and the pollers built
    on it) must ask it the same way.  Returns the ``CompletedProcess``, or
    ``None`` on timeout.
    """
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        return subprocess.run(
            [sys.executable, "-c",
             f"import sys; sys.path.insert(0, {root!r});"
             "from distributedpytorch_tpu.backend_health import "
             "pin_requested_platform;"
             "pin_requested_platform();"
             "import jax;" + tail_code],
            timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return None


def accelerator_healthy(timeout_s: int = 240) -> tuple[bool, str]:
    """Probe the default jax backend in a throwaway subprocess.

    The probe validates the backend the caller will actually run on.
    Returns ``(healthy, reason)``.
    """
    probe = _probe("assert len(jax.devices()) >= 1", timeout_s)
    if probe is None:
        return False, f"backend init exceeded {timeout_s}s"
    if probe.returncode == 0:
        return True, ""
    lines = (probe.stderr or "").strip().splitlines()
    return False, lines[-1] if lines else "probe failed"


def tpu_reachable(timeout_s: int = 240) -> bool:
    """True when the default backend resolves to a real TPU right now.

    Same bounding as :func:`accelerator_healthy`, but the question is
    stricter: pollers queueing chip work (scripts/chip_queue.py, scripts/
    sweep_when_healthy.py) must not fire on a CPU fallback — a CPU number
    is worse than waiting.
    """
    probe = _probe("sys.exit(0 if any(d.platform == 'tpu' "
                   "for d in jax.devices()) else 1)", timeout_s)
    return probe is not None and probe.returncode == 0


def device_op_alive(timeout_s: float = 5.0) -> tuple[bool, str]:
    """In-process liveness: one trivial device computation, hard-bounded.

    The serving complement of :func:`accelerator_healthy`: that probe pays
    a full backend init in a throwaway child (right for a cold start,
    ~seconds), while a liveness endpoint polled every few seconds needs
    the question "can THIS process still run device work right now"
    answered in milliseconds.  The op runs on a daemon thread with a join
    timeout, so a wedged runtime yields ``(False, reason)`` instead of
    hanging the probe (the stuck daemon thread is abandoned — acceptable
    for a process whose orchestrator is about to restart it anyway).

    Returns ``(alive, reason)``; reason is empty when alive.
    """
    from .chaos.policies import PolicyTimeoutError, Timeout

    def run() -> float:
        import jax

        # tiny but real: touches dispatch, device math, and D2H
        return float(jax.device_get(
            jax.numpy.ones(()) + jax.numpy.ones(())))

    try:
        # daemon-thread timeout (chaos/policies): a wedged runtime yields
        # (False, reason) and the stuck worker is abandoned, exactly the
        # hand-rolled semantics this helper had before the consolidation
        value = Timeout(timeout_s).call(run)
    except PolicyTimeoutError:
        return False, f"device op exceeded {timeout_s}s"
    except KeyboardInterrupt:
        # Ctrl-C lands in the CALLER's frame (Timeout's join), not the
        # probe — the user is aborting the process, not the backend dying
        raise
    except BaseException as e:  # noqa: BLE001 — ANY probe failure means
        # dead: Timeout.call re-raises even SystemExit from a plugin's
        # init, and a probe must report (False, why), never crash serving
        return False, f"{type(e).__name__}: {e}"
    if value != 2.0:
        return False, f"device op returned {value!r}, not 2.0"
    return True, ""


def ensure_backend_or_cpu_fallback(
        recovery_minutes: float | None = None, *,
        ignore_env: bool = False,
        backoff_base: float = 5.0,
        backoff_cap: float = 60.0) -> bool:
    """Probe (with a bounded recovery poll) and fall back to CPU if the
    backend stays down.

    Returns True when the default backend is usable (or the probe was
    skipped), False when the fallback to CPU was taken.  Skipped entirely
    when CPU is already forced (the hang cannot occur and the fallback is in
    effect) or ``DPTPU_BENCH_PROBE=0`` (healthy hosts pay a second backend
    init for the probe child; opt out when the accelerator is known good).

    A wedged tunnel has been observed to recover within minutes-to-tens-of-
    minutes, and a CPU number can cost a whole benchmark round — so instead
    of a fixed retry count, the probe POLLS until ``recovery_minutes`` of
    wall clock have elapsed (env ``DPTPU_BENCH_RECOVERY_MINUTES`` overrides
    unless ``ignore_env`` — the escape hatch for an explicit CLI flag like
    bench.py's ``--wait-for-backend``; default 2 — a couple of fast-fail
    probes for interactive scripts.  ``bench.py`` passes a much longer
    window because its output is the round's official record).  Each
    individual probe stays hard-bounded in a child process, so a wedged
    backend init cannot take the poller down.

    Retries back off exponentially from ``backoff_base`` seconds to
    ``backoff_cap``: a tunnel that recovers in seconds is caught within
    seconds (the fixed 60 s nap used to eat most of short windows), while
    a long outage converges to the old one-probe-a-minute cadence.
    """
    if os.environ.get("DPTPU_BENCH_PROBE") == "0" or \
            os.environ.get("JAX_PLATFORMS") == "cpu":
        return True
    env_min = os.environ.get("DPTPU_BENCH_RECOVERY_MINUTES")
    if ignore_env:
        pass  # explicit caller flag beats ambient env configuration
    elif env_min is not None:
        try:
            recovery_minutes = float(env_min)
        except ValueError:
            pass
    elif os.environ.get("DPTPU_BENCH_PROBE_RETRIES") is not None:
        # Honor the pre-poll knob's contract literally: N probes spaced
        # ~60 s apart == an (N-1)-minute window (N=1 -> single probe,
        # fast fallback).  The legacy fixed cadence, not the fast ramp —
        # so both the probe count AND the recovery window stay what the
        # knob documented.
        try:
            n = float(os.environ["DPTPU_BENCH_PROBE_RETRIES"])
            if n != n:            # NaN would poison the deadline math
                raise ValueError(n)
            recovery_minutes = max(0.0, n - 1)
            backoff_base = backoff_cap
        except ValueError:
            pass
    if recovery_minutes is None or recovery_minutes != recovery_minutes:
        # None and NaN both mean the default (a NaN window would make the
        # deadline comparison below always-false and the poll infinite)
        recovery_minutes = 2.0

    # The poll is chaos/policies.Retry in poll mode (until=healthy): same
    # cadence as the hand-rolled loop it replaced — exponential backoff
    # from base to cap, each nap floored at 1 s and capped by the
    # remaining window, budget exhaustion returning the last (unhealthy)
    # answer rather than raising.  clock/sleep are passed from the time
    # module HERE so the bench-record tests' time patches keep driving
    # the cadence they pin.
    from .chaos.policies import Retry

    def on_attempt(attempt, outcome, remaining):
        print(f"backend probe: unhealthy ({outcome[1]}), "
              f"attempt {attempt}, {max(0, remaining) / 60:.1f} min of "
              "recovery window left", file=sys.stderr)

    # retry_on=(): an exception FROM the probe propagates immediately,
    # exactly as the hand-rolled loop behaved (the probe child already
    # contains backend failures; an exception here is the poller itself
    # breaking, which the CPU fallback must not paper over) — and
    # on_attempt can therefore assume a (healthy, why) tuple outcome
    ok, _why = Retry(
        base_s=backoff_base, cap_s=backoff_cap,
        deadline_s=recovery_minutes * 60, min_sleep_s=1.0,
        clock=time.time, sleep=time.sleep,
    ).call(lambda: accelerator_healthy(), retry_on=(),
           until=lambda r: r[0], on_attempt=on_attempt)
    if ok:
        return True
    print("backend probe: falling back to CPU", file=sys.stderr)
    os.environ["JAX_PLATFORMS"] = "cpu"
    return False


def enable_compile_cache(root: str | None = None) -> None:
    """Turn on JAX's persistent compilation cache under ``<root>/.jax_cache``
    (default: the repo root).  One owner for every entry point — the test
    suite, bench.py, and the perf sweep all recompile identical programs
    run-to-run; caching them cuts minutes of XLA work per invocation.
    Call after ``import jax`` and before the first compilation."""
    import jax

    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(root, ".jax_cache"))
    # persist EVERY executable, not just the >2s ones: the test suite
    # compiles hundreds of small programs that individually cost
    # 50-500ms of XLA work and repeat identically run-to-run — below
    # any per-program threshold, but minutes in aggregate.  Disk is
    # cheap; the wall-clock of the tier-1 gate is not.  (Compile-count
    # watchdogs are unaffected: jax_log_compiles fires on cache hits
    # too — the trace/lower happens either way.)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
