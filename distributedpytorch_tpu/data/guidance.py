"""Guidance-map synthesis: extreme points, n-ellipses, confidence maps.

The reference's guidance channel (the 4th input channel the model consumes,
reference train_pascal.py:131-133) is produced by modules the author never
committed (``dataloaders.nellipse``, ``dataloaders.skewed_axes_weight_map`` —
SURVEY.md §2.4).  This module is a from-scratch design of that contract:

* :func:`extreme_points` / :func:`extreme_points_fixed` — the 4 extreme pixels
  of a binary mask (left/top/right/bottom), randomized vs deterministic
  (contract at reference custom_transforms.py:19-21,40-42).
* :func:`compute_nellipse` — a 4-focal *n-ellipse* (multifocal ellipse) soft
  indicator through the extreme points (contract at custom_transforms.py:25).
* :func:`compute_nellipse_gaussian_hm` — the n-ellipse plus a gaussian
  point-heatmap, the pair combined by the NEllipseWithGaussians transform
  (contract at custom_transforms.py:45) — this is the live guidance channel.
* :func:`generate_mvgauss_image` / :func:`generate_mv_l1l2_image_skewed_axes`
  / :func:`normalize_wt_map` — the confidence-map family behind the (inactive)
  AddConfidenceMap transform (contract at custom_transforms.py:283-290).

All functions are pure numpy with explicit ``np.random.Generator`` arguments —
no hidden global RNG state, so data pipelines are reproducible per-sample and
safe under multi-worker / multi-host sharding.
"""

from __future__ import annotations

import numpy as np

from ..utils.helpers import make_gt


# ---------------------------------------------------------------------------
# extreme points
# ---------------------------------------------------------------------------

def _find_point(ids_x, ids_y, selector) -> tuple[int, int]:
    sel = selector(len(ids_x))
    return int(ids_x[sel]), int(ids_y[sel])


def _extreme_point_candidates(mask: np.ndarray, pert: int):
    """For each side, the candidate pixel set within ``pert`` px of the extreme."""
    ys, xs = np.where(mask > 0.5)
    out = []
    for vals, other, extreme in (
        (xs, ys, xs.min()),   # leftmost
        (ys, xs, ys.min()),   # topmost
        (xs, ys, xs.max()),   # rightmost
        (ys, xs, ys.max()),   # bottommost
    ):
        sel = np.abs(vals - extreme) <= pert
        out.append((vals[sel], other[sel]))
    return out


def extreme_points(mask: np.ndarray, pert: int, rng: np.random.Generator | None = None) -> np.ndarray:
    """Randomized 4 extreme points of ``mask`` as (4, 2) array of (x, y).

    Among mask pixels within ``pert`` px of each side's extreme coordinate, one
    is chosen uniformly at random — the training-time jitter of the reference's
    ``extreme_points`` contract.
    """
    rng = rng or np.random.default_rng()
    pts = []
    for i, (vals, other) in enumerate(_extreme_point_candidates(mask, pert)):
        k = int(rng.integers(0, len(vals)))
        v, o = int(vals[k]), int(other[k])
        pts.append((v, o) if i in (0, 2) else (o, v))  # (x, y) ordering
    return np.asarray(pts, dtype=np.int64)


def extreme_points_fixed(mask: np.ndarray, pert: int = 0) -> np.ndarray:
    """Deterministic 4 extreme points (median candidate per side) — the
    validation-time ``extreme_points_fixed`` contract."""
    pts = []
    for i, (vals, other) in enumerate(_extreme_point_candidates(mask, pert)):
        k = len(vals) // 2
        order = np.argsort(other)
        v, o = int(vals[order[k]]), int(other[order[k]])
        pts.append((v, o) if i in (0, 2) else (o, v))
    return np.asarray(pts, dtype=np.int64)


# ---------------------------------------------------------------------------
# n-ellipse (multifocal ellipse) guidance
# ---------------------------------------------------------------------------

def _sum_of_distances(x_range, y_range, points) -> np.ndarray:
    """d[i, j] = sum_k || (x_j, y_i) - p_k ||  over the focal points."""
    xx = np.asarray(x_range, dtype=np.float32)
    yy = np.asarray(y_range, dtype=np.float32)
    X, Y = np.meshgrid(xx, yy)  # (len(y), len(x))
    d = np.zeros_like(X)
    for px, py in np.asarray(points, dtype=np.float32):
        d += np.sqrt((X - px) ** 2 + (Y - py) ** 2)
    return d


def compute_nellipse(
    x_range, y_range, points, softness: float = 0.05
) -> np.ndarray:
    """Soft indicator of the n-ellipse with foci at ``points``, in [0, 1].

    The boundary is the multifocal-ellipse level set passing through the
    outermost extreme point (so all four click points lie inside or on it);
    the indicator decays smoothly across the boundary with relative width
    ``softness``.  Mirrors the NEllipse transform's use at reference
    custom_transforms.py:23-25 (x_range/y_range are pixel index ranges; the
    caller scales the [0,1] map by 255).
    """
    points = np.asarray(points, dtype=np.float32)
    if points.size == 0:
        # Keep backends identical: the numpy path would raise from max([]),
        # the native kernel would return an all-ones map.
        raise ValueError("compute_nellipse requires at least one focal point")
    xx = np.asarray(x_range)
    yy = np.asarray(y_range)
    from .. import native_ops
    if (native_ops.enabled() and xx.ndim == 1 and yy.ndim == 1
            and xx.size and yy.size
            and np.array_equal(xx, np.arange(xx.size))
            and np.array_equal(yy, np.arange(yy.size))):
        # The hot path: full 0-based pixel grids (every transform call site)
        # go to the native rasterizer — the numpy form below dominates the
        # per-sample augmentation budget at 512² otherwise.
        return native_ops.nellipse(points[:, :2], (yy.size, xx.size),
                                   softness)
    d = _sum_of_distances(x_range, y_range, points)
    # Sum-of-distances value at each focal point; the largest defines the
    # boundary constant so every click point is enclosed.
    per_point = [
        sum(np.hypot(px - qx, py - qy) for qx, qy in points) for px, py in points
    ]
    c = float(max(per_point))
    if c <= 0:  # degenerate: all four points coincide
        z = np.zeros_like(d)
        z[d == 0] = 1.0
        return z
    tau = softness * c
    z = 1.0 / (1.0 + np.exp(np.clip((d - c) / tau, -50.0, 50.0)))
    return z.astype(np.float32)


def compute_nellipse_gaussian_hm(
    x_range, y_range, points, sigma: float = 10.0, softness: float = 0.05
) -> tuple[np.ndarray, np.ndarray]:
    """(n-ellipse indicator, gaussian point heatmap), both in [0, 1].

    The fast-variant contract at reference custom_transforms.py:45
    (``compute_nellipse_gaussianHM_fast``): the pair is combined as
    ``z1 + alpha * z2`` and rescaled by the NEllipseWithGaussians transform.
    """
    z1 = compute_nellipse(x_range, y_range, points, softness=softness)
    size = (len(y_range), len(x_range))
    # make_gt owns the max-combined gaussian (and its native dispatch).
    z2 = make_gt(np.zeros(size, np.float32), points, sigma=sigma)
    return z1, z2


def nellipse_map(shape_hw: tuple[int, int], points) -> np.ndarray:
    """The plain n-ellipse guidance channel, float32 in [0, 255].

    Single owner of the NEllipse transform's scaling rule (reference
    custom_transforms.py:9-27: [0,1] indicator x 255) — shared by the
    training transform and the inference path (predict.py).
    """
    h, w = shape_hw
    z = compute_nellipse(np.arange(w), np.arange(h),
                         np.asarray(points, np.float64))
    return (z * 255.0).astype(np.float32)


def extreme_points_map(shape_hw: tuple[int, int], points,
                       sigma: float = 10.0) -> np.ndarray:
    """The DEXTR gaussian-heatmap guidance channel, float32 in [0, 1].

    Single owner of the ExtremePoints transform's map (reference
    custom_transforms.py:221-251: max-combined gaussians, UNSCALED — the
    one guidance family the reference kept in [0, 1]) — shared by the
    training transform and the inference path (predict.py).
    """
    return make_gt(np.zeros(shape_hw, np.float32), points, sigma=sigma)


def nellipse_gaussians_map(
    shape_hw: tuple[int, int], points, alpha: float = 0.6,
    sigma: float = 10.0
) -> np.ndarray:
    """The live guidance channel as one map: ``z1 + alpha*z2`` rescaled to
    peak at exactly 255, float32 in [0, 255].

    Single owner of the combine/rescale rule at reference
    custom_transforms.py:45-50 — both the ``NEllipseWithGaussians`` training
    transform and the inference path (predict.py) call this, so the two can
    never drift apart.  The [0, 255] range is a hard input contract (driver
    asserts, reference train_pascal.py:188).
    """
    h, w = shape_hw
    z1, z2 = compute_nellipse_gaussian_hm(
        np.arange(w), np.arange(h), np.asarray(points, np.float64),
        sigma=sigma)
    z = z1 * 255.0 + z2 * 255.0 * alpha
    z *= 255.0 / z.max()
    # float32 rounding can overshoot 255 by an ulp; clip to the contract.
    return np.clip(z, 0.0, 255.0).astype(np.float32)


# ---------------------------------------------------------------------------
# click-space guidance: the serve/replay shared seam
# ---------------------------------------------------------------------------

#: guidance families computable from the 4 clicks alone — the ones
#: click-based inference (predict.py) and session-log replay
#: (data/sessions.py) can serve.  Confidence maps need the gt mask and
#: 'none' has no channel, so neither appears here.  Single source of
#: truth: predict.py's pre-restore guards and its dispatch both read
#: this table (re-exported there as ``_POINT_GUIDANCE``).
POINT_GUIDANCE = {
    # the live reference path (custom_transforms.py:45-50)
    "nellipse_gaussians":
        lambda shape, pts, alpha: nellipse_gaussians_map(
            shape, pts, alpha=alpha),
    # n-ellipse indicator scaled to [0, 255] (custom_transforms.py:9-27)
    "nellipse":
        lambda shape, pts, alpha: nellipse_map(shape, pts),
    # DEXTR gaussian heatmap in [0, 1], matching the ExtremePoints
    # transform's unscaled output (custom_transforms.py:221-251)
    "extreme_points":
        lambda shape, pts, alpha: extreme_points_map(shape, pts),
}


def guidance_from_points(
    shape_hw: tuple[int, int], points: np.ndarray, alpha: float = 0.6,
    family: str = "nellipse_gaussians"
) -> np.ndarray:
    """Crop-space guidance map from extreme points, float32.

    ``family`` selects the same guidance channel a run was trained with
    (``data.guidance`` in the config; pipeline.py:_guidance_stage),
    computed from the clicked points instead of gt-derived ones — one of
    ``POINT_GUIDANCE``.
    """
    points = np.asarray(points, np.float64)
    try:
        build = POINT_GUIDANCE[family]
    except KeyError:
        raise ValueError(
            f"unknown guidance family: {family!r} "
            f"({' | '.join(POINT_GUIDANCE)})") from None
    return build(shape_hw, points, alpha)


def scale_points_to_crop(points: np.ndarray,
                         bbox: tuple[int, int, int, int],
                         resolution: tuple[int, int]) -> np.ndarray:
    """Full-image xy points into resized-crop coordinates.

    The FixedResize scaling rule for point coords (reference
    custom_transforms.py:168-173) — the ONE owner of the rule, called by
    ``prepare_input``, ``Predictor.prepare_guidance`` (the warm-session
    decode path) and session-log replay, so serve-time and replay-time
    guidance can never drift by a rounding rule.
    """
    points = np.asarray(points, np.float64)
    res_h, res_w = resolution
    scale = np.array([res_w / (bbox[2] - bbox[0] + 1),
                      res_h / (bbox[3] - bbox[1] + 1)])
    crop_pts = (points - np.array([bbox[0], bbox[1]])) * scale
    return np.clip(crop_pts, 0, [res_w - 1, res_h - 1])


def crop_point_guidance(points: np.ndarray,
                        bbox: tuple[int, int, int, int],
                        resolution: tuple[int, int],
                        alpha: float = 0.6,
                        family: str = "nellipse_gaussians") -> np.ndarray:
    """Full-image clicks + crop bbox -> the crop-space guidance channel,
    float32 at ``resolution`` — scale + synthesize in one call.  This is
    the bit-identity seam the flywheel's replay pins itself to: the live
    serve path and ``SessionLogDataset`` replay both compose exactly
    ``scale_points_to_crop`` -> ``guidance_from_points``."""
    crop_pts = scale_points_to_crop(points, bbox, resolution)
    return guidance_from_points(resolution, crop_pts, alpha=alpha,
                                family=family)


# ---------------------------------------------------------------------------
# confidence-map family (skewed-axes weight maps)
# ---------------------------------------------------------------------------

def normalize_wt_map(wt_map: np.ndarray) -> np.ndarray:
    """Min-max normalize a weight map to [0, 1] (``normalize_wtMap`` contract)."""
    lo, hi = float(wt_map.min()), float(wt_map.max())
    return (wt_map - lo) / (hi - lo + 1e-10)


def generate_mvgauss_image(
    mask: np.ndarray, FULL_IMAGE_WEIGHTS: int = 1, tau: float = 0.5
) -> np.ndarray:
    """Multivariate gaussian confidence map fitted to the mask's pixel cloud.

    Mean/covariance are the first/second moments of the foreground pixels; the
    map is the (unnormalized) gaussian density over the full image raised to
    ``tau`` (temperature).  Contract at reference custom_transforms.py:289.
    """
    ys, xs = np.where(mask > 0.5)
    pts = np.stack([xs, ys], axis=1).astype(np.float64)
    mean = pts.mean(axis=0)
    if pts.shape[0] < 2:
        # A single-pixel mask has no sample covariance (np.cov -> NaN);
        # use an isotropic unit covariance centered on the pixel instead.
        cov = np.eye(2)
    else:
        cov = np.cov(pts.T) + np.eye(2) * 1e-3
    icov = np.linalg.inv(cov)
    h, w = mask.shape[:2]
    X, Y = np.meshgrid(np.arange(w), np.arange(h))
    dx = X - mean[0]
    dy = Y - mean[1]
    m = icov[0, 0] * dx * dx + (icov[0, 1] + icov[1, 0]) * dx * dy + icov[1, 1] * dy * dy
    out = np.exp(-0.5 * tau * m)
    if not FULL_IMAGE_WEIGHTS:
        out = out * (mask > 0.5)
    return out.astype(np.float32)


def generate_mv_l1l2_image_skewed_axes(
    mask: np.ndarray,
    extreme_points: np.ndarray,
    FULL_IMAGE_WEIGHTS: int = 1,
    d2_THRESH: float | None = None,
    tau: float = 1.0,
):
    """L1+L2 confidence map along the (possibly non-orthogonal) axes defined
    by the extreme points.

    The two skewed axes are left→right and top→bottom chords of the object;
    each pixel gets affine coordinates (u, v) along those axes (|u|,|v| <= 1 on
    the chords) and weight ``exp(-tau * ((|u|+|v|) + sqrt(u²+v²)) / 2)`` — an
    L1/L2 blend.  Returns ``(h_map, d1, d2)`` matching the 3-tuple unpacking at
    reference custom_transforms.py:283.
    """
    pts = np.asarray(extreme_points, dtype=np.float64)
    left, top, right, bottom = pts[0], pts[1], pts[2], pts[3]
    center = pts.mean(axis=0)
    a1 = (right - left) / 2.0
    a2 = (bottom - top) / 2.0
    A = np.stack([a1, a2], axis=1)  # columns are the axes
    if abs(np.linalg.det(A)) < 1e-6:
        A = A + np.eye(2) * 1e-3
    Ainv = np.linalg.inv(A)

    h, w = mask.shape[:2]
    X, Y = np.meshgrid(np.arange(w, dtype=np.float64), np.arange(h, dtype=np.float64))
    dx = X - center[0]
    dy = Y - center[1]
    u = Ainv[0, 0] * dx + Ainv[0, 1] * dy
    v = Ainv[1, 0] * dx + Ainv[1, 1] * dy

    l1 = np.abs(u) + np.abs(v)
    l2 = np.sqrt(u * u + v * v)
    h_map = np.exp(-tau * (l1 + l2) / 2.0)
    if d2_THRESH is not None:
        h_map = np.where(l2 > d2_THRESH, 0.0, h_map)
    if not FULL_IMAGE_WEIGHTS:
        h_map = h_map * (mask > 0.5)
    return h_map.astype(np.float32), u.astype(np.float32), v.astype(np.float32)
