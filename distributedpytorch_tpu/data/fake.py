"""Synthetic tiny-VOC fixture.

Generates an on-disk directory tree with the exact VOC2012 layout the dataset
class reads (JPEGImages / SegmentationObject / SegmentationClass /
ImageSets/Segmentation), populated with random multi-object scenes — the
test-fixture replacement for the reference's MD5-verified 2 GB tar
(SURVEY.md §4: "a tiny-fake-VOC fixture replacing the MD5'd tar").

Objects are random filled ellipses/rectangles drawn back-to-front; the
instance PNG stores object ids 1..N, the class PNG stores a category id per
object, and a 255-valued void ring is drawn around each object boundary just
like VOC's ignore regions.
"""

from __future__ import annotations

import os

import cv2
import numpy as np
from PIL import Image

from .voc import BASE_DIR


def _class_color(cat: int) -> np.ndarray:
    """Deterministic, well-separated RGB base color for category ``cat``
    (1..20): hues spaced around the wheel at fixed saturation/value."""
    import colorsys

    r, g, b = colorsys.hsv_to_rgb((cat - 1) / 20.0, 0.75, 0.9)
    return np.array([r * 255, g * 255, b * 255], np.float32)


def make_fake_voc(
    root: str,
    n_images: int = 6,
    size: tuple[int, int] = (120, 160),
    max_objects: int = 3,
    n_val: int = 2,
    seed: int = 0,
    void_ring: bool = True,
    visible_objects: bool = True,
) -> str:
    """Create a fake VOC tree under ``root``; returns ``root``.

    Image ids are ``fake_000000`` …; the first ``n_images - n_val`` go to the
    ``train`` split, the rest to ``val``.

    ``visible_objects`` paints each object's region with a deterministic
    class-correlated color (plus texture noise) so the task is LEARNABLE
    from pixels: segmentation/classification of the regions has real
    evidence in the image.  The original fixture drew masks over pure
    blurred noise — objects were invisible, so any pixels-only model's
    optimum was degenerate: semantic runs c/e/f measured all-background
    exactly, and the unguided instance run b flatlined at a shape-prior
    optimum (round-3 convergence artifacts); pass
    ``visible_objects=False`` to reproduce that adversarial regime
    deliberately.
    """
    rng = np.random.default_rng(seed)
    voc = os.path.join(root, BASE_DIR)
    dirs = {
        "img": os.path.join(voc, "JPEGImages"),
        "inst": os.path.join(voc, "SegmentationObject"),
        "cls": os.path.join(voc, "SegmentationClass"),
        "sets": os.path.join(voc, "ImageSets", "Segmentation"),
    }
    for d in dirs.values():
        os.makedirs(d, exist_ok=True)

    h, w = size
    ids = [f"fake_{i:06d}" for i in range(n_images)]
    for im_id in ids:
        img = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
        inst = np.zeros((h, w), dtype=np.uint8)
        cls = np.zeros((h, w), dtype=np.uint8)
        n_obj = int(rng.integers(1, max_objects + 1))
        for obj in range(1, n_obj + 1):
            cat = int(rng.integers(1, 21))
            shape_mask = np.zeros((h, w), dtype=np.uint8)
            cx = int(rng.integers(w // 4, 3 * w // 4))
            cy = int(rng.integers(h // 4, 3 * h // 4))
            ax = int(rng.integers(max(6, w // 10), w // 3))
            ay = int(rng.integers(max(6, h // 10), h // 3))
            if rng.random() < 0.5:
                cv2.ellipse(shape_mask, (cx, cy), (ax, ay),
                            float(rng.uniform(0, 180)), 0, 360, 1, -1)
            else:
                cv2.rectangle(shape_mask, (cx - ax, cy - ay), (cx + ax, cy + ay), 1, -1)
            if visible_objects:
                # class-correlated appearance: base color + texture noise,
                # so the region AND its category are inferable from pixels
                sel = shape_mask == 1
                tex = (_class_color(cat)
                       + rng.normal(0.0, 14.0, (int(sel.sum()), 3)))
                img[sel] = np.clip(tex, 0, 255).astype(np.uint8)
            inst[shape_mask == 1] = obj
            cls[shape_mask == 1] = cat
            if void_ring:
                ring = cv2.dilate(shape_mask, np.ones((3, 3), np.uint8)) - shape_mask
                inst[ring == 1] = 255
                cls[ring == 1] = 255

        # Smooth so cubic warps behave like photos, not noise (after
        # drawing: object edges blur a little, like real photographs).
        img = cv2.GaussianBlur(img, (7, 7), 0)
        Image.fromarray(img).save(os.path.join(dirs["img"], im_id + ".jpg"))
        Image.fromarray(inst).save(os.path.join(dirs["inst"], im_id + ".png"))
        Image.fromarray(cls).save(os.path.join(dirs["cls"], im_id + ".png"))

    n_train = n_images - n_val
    with open(os.path.join(dirs["sets"], "train.txt"), "w") as f:
        f.write("\n".join(ids[:n_train]) + "\n")
    with open(os.path.join(dirs["sets"], "val.txt"), "w") as f:
        f.write("\n".join(ids[n_train:]) + "\n")
    return root


def make_fake_sbd(
    root: str,
    n_images: int = 4,
    size: tuple[int, int] = (120, 160),
    max_objects: int = 3,
    n_val: int = 1,
    seed: int = 0,
    overlap_ids: list[str] | None = None,
) -> str:
    """Create a fake SBD tree (benchmark_RELEASE/dataset layout, .mat
    structs) under ``root``; returns ``root``.

    ``overlap_ids`` names extra images to ALSO emit under these exact ids —
    the SBD-overlaps-VOC-val situation the reference's ``CombineDBs``
    exclusion list existed for (train_pascal.py:152).
    """
    import scipy.io

    from .sbd import BASE_DIR as SBD_BASE

    rng = np.random.default_rng(seed)
    base = os.path.join(root, SBD_BASE)
    img_dir = os.path.join(base, "img")
    inst_dir = os.path.join(base, "inst")
    cls_dir = os.path.join(base, "cls")
    for d in (img_dir, inst_dir, cls_dir):
        os.makedirs(d, exist_ok=True)

    h, w = size
    base_ids = [f"sbd_{i:06d}" for i in range(n_images)]
    # overlap ids always land in TRAIN — they exist to exercise the
    # CombinedDataset exclusion, which reads the train split
    train_ids = base_ids[: n_images - n_val] + list(overlap_ids or [])
    val_ids = base_ids[n_images - n_val:] if n_val else []
    ids = train_ids + val_ids
    for im_id in ids:
        img = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
        img = cv2.GaussianBlur(img, (7, 7), 0)
        inst = np.zeros((h, w), dtype=np.uint8)
        cls = np.zeros((h, w), dtype=np.uint8)
        n_obj = int(rng.integers(1, max_objects + 1))
        cats = []
        for obj in range(1, n_obj + 1):
            cat = int(rng.integers(1, 21))
            cats.append(cat)
            shape_mask = np.zeros((h, w), dtype=np.uint8)
            cx = int(rng.integers(w // 4, 3 * w // 4))
            cy = int(rng.integers(h // 4, 3 * h // 4))
            ax = int(rng.integers(max(6, w // 10), w // 3))
            ay = int(rng.integers(max(6, h // 10), h // 3))
            cv2.ellipse(shape_mask, (cx, cy), (ax, ay),
                        float(rng.uniform(0, 180)), 0, 360, 1, -1)
            inst[shape_mask == 1] = obj
            cls[shape_mask == 1] = cat
            ring = cv2.dilate(shape_mask, np.ones((3, 3), np.uint8)) \
                - shape_mask
            inst[ring == 1] = 255
            cls[ring == 1] = 255

        Image.fromarray(img).save(os.path.join(img_dir, im_id + ".jpg"))
        # the GTinst/GTcls struct layout scipy round-trips (dict -> struct)
        scipy.io.savemat(os.path.join(inst_dir, im_id + ".mat"),
                         {"GTinst": {"Segmentation": inst,
                                     "Categories": np.array(cats)}})
        scipy.io.savemat(os.path.join(cls_dir, im_id + ".mat"),
                         {"GTcls": {"Segmentation": cls}})

    with open(os.path.join(base, "train.txt"), "w") as f:
        f.write("\n".join(train_ids) + "\n" if train_ids else "")
    with open(os.path.join(base, "val.txt"), "w") as f:
        f.write("\n".join(val_ids) + "\n" if val_ids else "")
    return root
