"""Input-feed governor: the feedback loop from measured stall to actuation.

Every mechanism the roadmap names for killing input stalls already exists
as a *static, opt-in* knob — host/device prefetch depth, the on-device
augmentation + guidance stages, the prepared-sample cache, data echoing —
and the telemetry layer already measures ``input_wait`` as a first-class
goodput bucket that nothing acts on.  The :class:`FeedGovernor` closes
the loop: it watches the windowed stall fraction (a
:class:`~..telemetry.goodput.FeedWindow` fed from the goodput snapshots
the trainer already takes at the log cadence — no new host syncs) and
works the knobs through an **escalation ladder with hysteresis**:

0. **Pack recommendation** (first escalation, once per run): when the
   stalled source is not already packed (``data.source=fs``), log the
   exact ``dptpu-pack`` invocation — pre-decoding into mmap records
   (data/packed.py) deletes the decode+walk cost every rung above this
   one merely tunes around.  Operator-actuated, like the flip
   recommendation; a packed source starts the ladder at rung 1.
1. **Hot prefetch resize** (any tick): double host + device prefetch
   depth, bounded.  Cheap (host RAM / HBM for a few more in-flight
   batches), reversible, and applies immediately — both prefetchers read
   their depth live.
2. **Device-path flip** (epoch boundaries — the recompile-safe seam):
   move augmentation + guidance synthesis on device when the config
   allows it (plain thread-loader pipeline, device-supported guidance
   family).  When the config does NOT allow it (prepared cache / grain
   loader / unsupported family), the governor logs a *recommendation*
   naming the exact config keys instead — the operator's move, loudly.
3. **Arm data echoing** (epoch boundaries): step each loaded batch
   ``ceil(1 / (1 - stall))`` times (Choi et al., arXiv:1907.05550 — the
   factor that recovers step throughput when the pipeline, not the chip,
   is the bound), clamped to ``data.max_echo``.  Echoed steps are real
   optimizer steps with fresh on-device augmentation randomness; later
   boundaries may raise the factor (target-aware) while the stall
   persists.
4. **Disarm with hysteresis**: once the windowed stall holds below
   ``disarm_factor x target`` for ``disarm_patience`` ticks, echo
   returns to its configured base at the next boundary.  Flips are
   never reverted (strictly better); prefetch stays raised (idle depth
   is free).
5. **Persistent shortfall**: stalled at the top of the ladder, the
   governor reports loudly (stderr + ledger + counter) — never hidden.

Modes (``data.governor``): ``off`` | ``observe`` (default — every
decision is logged to ``run_dir/governor.jsonl`` and the registry, but
nothing is actuated; the ladder advances *virtually* so the log shows
the full would-be sequence) | ``auto`` (decisions applied).  ``auto``
decisions derive from host wall-clock, which is not replicated — so on
multi-host runs every decision input routes through
:func:`~..parallel.consensus.replicated_decision` (``consensus=True``,
armed by the trainer): the stall fraction reduces by **max** across
hosts (the most-starved host governs — it is the one gating the
collective), the escalation request by **any**, and the hysteresis
counters then advance identically everywhere, so hosts can never
disagree about the echo factor and desynchronize collective step
counts.  The consensus calls are collectives: every host must tick the
governor at the same step cadence (the trainer's log-cadence crossing
already guarantees it).  ``observe`` stays main-process-local — it
actuates nothing, so there is nothing to agree on.

FFCV's thesis (arXiv:2306.12517) is that data-bottleneck removal must
be *measured*, not assumed — hence ``observe`` as the default, and the
bench record's ``feed`` block + ``--check-regression`` gate as the
mechanical form of the roadmap's "input_wait ≈ 0" acceptance.
"""

from __future__ import annotations

import json
import math
import sys
import time

GOVERNOR_MODES = ("off", "observe", "auto")

#: rung-1 bounds: prefetch depth doubles up to these caps (batches)
MAX_HOST_PREFETCH = 8
MAX_DEVICE_PREFETCH = 8

#: ladder actions, as they appear in governor.jsonl / the actions counter.
#: ``pack_recommendation`` is rung 0 (data/packed.py): when the stalled
#: source is NOT already packed, the first escalation names the exact
#: ``dptpu-pack`` invocation that deletes the stall at its source —
#: cheaper than every actuation above it.  A packed source skips
#: straight to rung 1 (prefetch).
ACTIONS = ("pack_recommendation", "raise_prefetch", "flip_device_path",
           "recommend", "arm_echo", "raise_echo", "disarm_echo",
           "shortfall")


def governor_consensus(value, reduce: str, label: str):
    """The governor's one door to :func:`replicated_decision`
    (parallel/consensus.py) — a module seam so tests can simulate
    divergent per-host inputs without processes.  Lazy import keeps
    this module importable pre-jax."""
    from ..parallel.consensus import replicated_decision

    return replicated_decision(value, reduce=reduce, label=label)


def echo_factor(stall: float, max_echo: int, current: int = 1,
                target: float | None = None) -> int:
    """The echo factor for a measured stall fraction.

    Unarmed (``current == 1``): the Choi et al. arming factor
    ``ceil(1 / (1 - stall))`` — each loaded batch stepped that many
    times amortizes the per-batch wait over as many optimizer steps as
    the stall ratio says were lost.  Already armed: the target-aware
    escalation ``ceil(current * stall * (1 - target) / (target * (1 -
    stall)))`` — the factor that brings the *armed* measurement (whose
    waits are already amortized over ``current`` echoes) down to
    ``target``.  Clamped to ``[current, max_echo]``; a stall at or past
    1.0 pins the top.
    """
    max_echo = max(1, int(max_echo))
    if stall >= 1.0:
        return max_echo
    if stall <= 0.0:
        return max(1, int(current))
    if current <= 1:
        want = math.ceil(1.0 / (1.0 - stall))
    else:
        t = min(max(target if target is not None else 0.1, 1e-3), 0.999)
        want = math.ceil(current * stall * (1.0 - t) / (t * (1.0 - stall)))
    return max(max(1, int(current)), min(max_echo, int(want)))


class FeedActuators:
    """The knobs the governor works, duck-typed so tests can stub them.

    The trainer implements this over its live feed state (host/device
    prefetch depth, the effective echo factor, the device-path flip);
    ``observe`` mode never calls the setters.  Every getter must be
    cheap — they run at the tick cadence.
    """

    def get_prefetch(self) -> tuple[int, int]:
        raise NotImplementedError

    def set_prefetch(self, host: int, device: int) -> None:
        raise NotImplementedError

    def flip_available(self) -> tuple[bool, str]:
        """(eligible, reason/recommendation).  ``reason`` names the
        config keys the operator would flip when ineligible."""
        raise NotImplementedError

    def flip_device_path(self) -> None:
        raise NotImplementedError

    def get_echo(self) -> int:
        raise NotImplementedError

    def base_echo(self) -> int:
        raise NotImplementedError

    def can_set_echo(self) -> tuple[bool, str]:
        raise NotImplementedError

    def set_echo(self, factor: int) -> None:
        raise NotImplementedError

    def pack_status(self) -> tuple[bool, str | None]:
        """Rung 0 (data/packed.py): ``(already_packed,
        recommendation)``.  When the source is not packed, the
        recommendation names the exact ``dptpu-pack`` invocation(s).
        Default says "packed" so duck-typed actuators that predate the
        rung keep their ladder unchanged."""
        return True, None


class FeedGovernor:
    """Escalation-ladder controller over the windowed input-stall signal.

    ``tick(busy_s, wait_s, ...)`` at the log cadence pushes one window
    sample and may hot-apply rung 1; ``epoch_boundary(...)`` applies the
    recompile-unsafe rungs (flip, echo) and the disarm.  Every decision
    — applied or observed — lands as one JSONL line and one
    ``train_governor_actions_total{action}`` increment; the rolling
    stall fraction is published to the ``train_feed_stall_fraction``
    gauge and the armed echo factor to ``train_feed_echo_armed``.
    """

    def __init__(self, mode: str, target: float,
                 actuators: FeedActuators, *,
                 max_echo: int = 4,
                 window=None,
                 jsonl_path: str | None = None,
                 min_samples: int = 2,
                 patience: int = 2,
                 disarm_factor: float = 0.5,
                 disarm_patience: int = 4,
                 telemetry: bool = True,
                 consensus: bool = False,
                 clock=time.time):
        from ..telemetry.goodput import FeedWindow

        if mode not in GOVERNOR_MODES:
            raise ValueError(f"data.governor must be one of "
                             f"{GOVERNOR_MODES}, got {mode!r}")
        if not 0.0 < target < 1.0:
            raise ValueError(
                f"data.governor_target must be in (0, 1), got {target}")
        if max_echo < 1:
            raise ValueError(f"data.max_echo must be >= 1, got {max_echo}")
        self.mode = mode
        self.target = float(target)
        self.actuators = actuators
        self.max_echo = int(max_echo)
        self.window = window if window is not None else FeedWindow()
        self.jsonl_path = jsonl_path
        self.min_samples = int(min_samples)
        self.patience = int(patience)
        self.disarm_factor = float(disarm_factor)
        self.disarm_patience = int(disarm_patience)
        self._telemetry = telemetry
        #: multi-host auto mode: decision inputs route through
        #: replicated_decision so the ladder state is identical on every
        #: host (see the module docstring).  Each tick/boundary then IS
        #: a collective — the caller owes a replicated call cadence.
        self.consensus = bool(consensus)
        self._clock = clock
        # hysteresis counters: consecutive ticks above target / below the
        # disarm threshold; the band between them holds both at zero
        self._above = 0
        self._below = 0
        #: rung-1 state in observe mode advances virtually (the log shows
        #: the full would-be ladder without touching the live knobs)
        self._virtual_prefetch: tuple[int, int] | None = None
        self._virtual_echo: int | None = None
        self._flip_attempted = False
        self._pack_noted = False
        self._echo_armed = False
        self._wants_escalation = False
        self._shortfall = False
        self.decisions: list[dict] = []
        self.actions_count: dict[str, int] = {}

    # ------------------------------------------------------------ helpers
    @property
    def applies(self) -> bool:
        return self.mode == "auto"

    def stall_fraction(self) -> float | None:
        return self.window.stall_fraction()

    def _decided_stall(self, stall: float | None) -> float | None:
        """The stall fraction the ladder acts on: the local window's
        under single-host, the MAX across hosts under consensus (the
        most-starved host is the one gating every collective — its
        stall is the job's stall).  "No reading yet" encodes as -1 so a
        host below min_samples still joins the allgather (every host
        must make the same number of consensus calls) without vetoing
        hosts that have one."""
        if not self.consensus:
            return stall
        decided = float(governor_consensus(
            -1.0 if stall is None else float(stall), "max",
            "governor/stall"))
        return None if decided < 0.0 else decided

    def _get_prefetch(self) -> tuple[int, int]:
        if not self.applies and self._virtual_prefetch is not None:
            return self._virtual_prefetch
        return self.actuators.get_prefetch()

    def _get_echo(self) -> int:
        if not self.applies and self._virtual_echo is not None:
            return self._virtual_echo
        return self.actuators.get_echo()

    def _decide(self, action: str, *, step: int, epoch: int,
                stall: float | None, applied: bool, detail) -> dict:
        rec = {"ts": round(float(self._clock()), 3), "step": int(step),
               "epoch": int(epoch), "action": action,
               "applied": bool(applied),
               "stall": (round(stall, 4) if stall is not None else None),
               "target": self.target, "detail": detail}
        self.decisions.append(rec)
        self.actions_count[action] = self.actions_count.get(action, 0) + 1
        if self.jsonl_path:
            try:
                with open(self.jsonl_path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
            except OSError as e:  # a full disk must not kill training
                print(f"governor: could not append to {self.jsonl_path}: "
                      f"{e}", file=sys.stderr)
        if self._telemetry:
            from ..telemetry import get_registry
            from ..telemetry.registry import is_enabled

            if is_enabled():
                get_registry().counter(
                    "train_governor_actions_total",
                    "Feed-governor ladder decisions (data/governor.py)",
                    labels={"action": action}).inc()
        # flight recorder (telemetry/events.py): the decision, mirrored —
        # governor.jsonl stays the authoritative ledger
        from ..telemetry import events as events_lib

        events_lib.emit("governor", action, step=int(step),
                        epoch=int(epoch),
                        payload={"stall": rec["stall"],
                                 "target": self.target,
                                 "applied": bool(applied),
                                 "detail": detail})
        return rec

    def _publish_gauges(self, stall: float | None) -> None:
        if not self._telemetry:
            return
        from ..telemetry import get_registry
        from ..telemetry.registry import is_enabled

        if not is_enabled():
            return
        reg = get_registry()
        if stall is not None:
            reg.gauge("train_feed_stall_fraction",
                      "Rolling input-stall fraction over the feed window"
                      ).set(stall)
        reg.gauge("train_feed_echo_armed",
                  "Governor-armed echo factor (0 = not armed)"
                  ).set(self._get_echo() if self._echo_armed else 0)

    # --------------------------------------------------------------- tick
    def tick(self, busy_s: float, wait_s: float, *, step: int,
             epoch: int) -> None:
        """One log-cadence observation: push the goodput delta, update
        the hysteresis counters, and (rung 1) hot-resize prefetch.

        Under ``consensus`` a zero delta still ticks (the trainer calls
        at the replicated cadence regardless) — the sample is dropped
        but the host joins the stall allgather, so consensus calls stay
        congruent across hosts."""
        if busy_s + wait_s > 0:
            self.window.push(busy_s, wait_s)
        local = self.window.stall_fraction()
        ready = local is not None and len(self.window) >= self.min_samples
        stall = self._decided_stall(local if ready else None)
        self._publish_gauges(stall if stall is not None else local)
        if stall is None:
            return
        if stall > self.target:
            self._above += 1
            self._below = 0
        elif stall < self.target * self.disarm_factor:
            self._below += 1
            self._above = 0
        else:  # hysteresis band: hold
            self._above = 0
            self._below = 0
        if self._above >= self.patience:
            self._above = 0
            self._rung0_pack(step=step, epoch=epoch, stall=stall)
            host, dev = self._get_prefetch()
            if host < MAX_HOST_PREFETCH or dev < MAX_DEVICE_PREFETCH:
                # never below current: an operator-configured depth
                # above the governor's cap stays put (the raise rung
                # must not SHRINK the pipeline mid-stall)
                new = (max(host, min(MAX_HOST_PREFETCH, max(1, host) * 2)),
                       max(dev, min(MAX_DEVICE_PREFETCH, max(1, dev) * 2)))
                if self.applies:
                    self.actuators.set_prefetch(*new)
                else:
                    self._virtual_prefetch = new
                self._decide(
                    "raise_prefetch", step=step, epoch=epoch, stall=stall,
                    applied=self.applies,
                    detail={"host": [host, new[0]], "device": [dev, new[1]]})
            else:
                # rung 1 exhausted: the recompile-unsafe rungs wait for
                # the epoch boundary
                self._wants_escalation = True

    def _rung0_pack(self, *, step: int, epoch: int,
                    stall: float | None) -> None:
        """Rung 0, emitted once per run at the FIRST escalation: when
        the stalled source is not already packed, log the exact
        ``dptpu-pack`` invocation that removes the stall at its source
        (pre-decoded mmap records — data/packed.py).  Never actuated
        (packing is the operator's move, like the flip recommendation);
        packed sources skip straight to rung 1.  Config-derived on
        every host, so no consensus is needed for a log-only line."""
        if self._pack_noted:
            return
        self._pack_noted = True
        status = getattr(self.actuators, "pack_status", None)
        if status is None:
            return
        packed, recommendation = status()
        if packed or not recommendation:
            return
        self._decide("pack_recommendation", step=step, epoch=epoch,
                     stall=stall, applied=False, detail=recommendation)

    # ---------------------------------------------------------- boundary
    def epoch_boundary(self, *, epoch: int, step: int) -> list[dict]:
        """The recompile-safe seam: flip / arm / raise / disarm echo.
        Returns the decisions made at this boundary."""
        made: list[dict] = []
        stall = self._decided_stall(self.window.stall_fraction())

        def decide(action, applied, detail):
            made.append(self._decide(action, step=step, epoch=epoch,
                                     stall=stall, applied=applied,
                                     detail=detail))

        # a mid-epoch escalation request whose stall has since cleared
        # (fault ended late in the epoch, window drained) is dropped —
        # it must not shadow the disarm check below.  Consensus: ANY
        # host's escalation request escalates everywhere — the echo
        # factor the rung sets must land identically on every host, or
        # optimizer step counts desynchronize at the next epoch.
        wants_esc = self._wants_escalation
        if self.consensus:
            wants_esc = bool(governor_consensus(
                bool(wants_esc), "any", "governor/escalate"))
        wants = wants_esc and stall is not None and stall > self.target
        self._wants_escalation = False
        if wants:
            escalated = False
            if not self._flip_attempted:
                self._flip_attempted = True
                ok, reason = self.actuators.flip_available()
                if ok and self.applies:
                    self.actuators.flip_device_path()
                    decide("flip_device_path", True, reason)
                    escalated = True  # give the flip an epoch to measure
                elif ok:
                    decide("flip_device_path", False, reason)
                    escalated = True
                else:
                    # config does not allow the flip: recommend, loudly,
                    # and fall through to the echo rung at THIS boundary
                    decide("recommend", False, reason)
            if not escalated:
                can, why = self.actuators.can_set_echo()
                cur = self._get_echo()
                if not can:
                    decide("shortfall", False,
                           f"stall {stall:.2f} > target {self.target} at "
                           f"the top of the ladder and echo is "
                           f"unavailable ({why})")
                    self._shout(stall, why)
                else:
                    want = echo_factor(stall, self.max_echo, current=cur,
                                       target=self.target)
                    if want > cur:
                        if self.applies:
                            self.actuators.set_echo(want)
                        else:
                            self._virtual_echo = want
                        decide("arm_echo" if not self._echo_armed
                               else "raise_echo", self.applies,
                               {"factor": [cur, want],
                                "max_echo": self.max_echo})
                        self._echo_armed = True
                    else:
                        detail = (f"stall {stall:.2f} > target "
                                  f"{self.target} with echo already at "
                                  f"{cur}/{self.max_echo} — the ladder "
                                  "is out of rungs (raise data.max_echo, "
                                  "add loader workers, or move to a "
                                  "prepared cache)")
                        decide("shortfall", False, detail)
                        self._shout(stall, detail)
        if not wants and self._echo_armed \
                and self._below >= self.disarm_patience:
            base = self.actuators.base_echo()
            cur = self._get_echo()
            if self.applies:
                self.actuators.set_echo(base)
            else:
                self._virtual_echo = base
            decide("disarm_echo", self.applies,
                   {"factor": [cur, base]})
            self._echo_armed = False
            self._shortfall = False
            self._below = 0
        self._publish_gauges(stall)
        return made

    def _shout(self, stall: float, detail: str) -> None:
        """A shortfall the ladder cannot fix is reported loudly, never
        hidden — once per escalation episode, not per boundary."""
        if self._shortfall:
            return
        self._shortfall = True
        print(f"governor: PERSISTENT INPUT SHORTFALL — windowed stall "
              f"{stall:.2f} above target {self.target} with every rung "
              f"exhausted ({detail})", file=sys.stderr, flush=True)

    # ---------------------------------------------------------- reporting
    def summary_block(self) -> dict:
        """The fit-history / fit_summary ``feed`` block."""
        return {
            "mode": self.mode,
            "target": self.target,
            "input_wait_fraction": self.window.stall_fraction(),
            "echo_effective": self.actuators.get_echo(),
            "echo_armed": self._echo_armed,
            "shortfall": self._shortfall,
            "actions": dict(self.actions_count),
        }


def feed_block(goodput_report: dict | None, governor: str | None = None,
               echo_effective: int | None = None,
               source: str = "fs") -> dict:
    """The bench record's ``feed`` block — keys ALWAYS present (the PR 4
    schema-stability convention), null-valued when off/unknowable.

    ``input_wait_fraction`` is derived from a goodput report's buckets
    (wait / (wait + step + compile)); ``governor`` names the governing
    mode conditioning the record (null = ungoverned); ``echo_effective``
    is the echo factor in effect (null when echoing is off/NA);
    ``source`` names the data plane feeding the record (``fs`` |
    ``packed`` — data/packed.py): --check-regression's same-config
    filter keys on it, so a packed record never baselines an fs one.
    Pre-pack committed history carries no ``source`` key; the filter
    normalizes that to ``fs``.
    """
    frac = None
    buckets = (goodput_report or {}).get("buckets") or {}
    busy = (buckets.get("step", 0.0) or 0.0) \
        + (buckets.get("compile", 0.0) or 0.0)
    wait = buckets.get("input_wait", 0.0) or 0.0
    if busy + wait > 0:
        frac = round(wait / (busy + wait), 4)
    return {
        "input_wait_fraction": frac,
        "governor": governor,
        "echo_effective": echo_effective,
        "source": source,
    }
