"""Prepared-sample disk cache: decode→crop→resize stored once, mmap-read after.

LEGACY prepared format: the packed data plane (``data/packed.py``,
``dptpu-pack``) is the ONE prepared format going forward — it
pre-decodes the whole source (not just the crop front), checksums every
record, shards reads by host and gives the governor/sentinel O(1) seek.
Configs setting ``data.prepared_cache`` get a loud migration pointer at
trainer build.  These wrappers still work — and compose OVER a packed
source (``data.source=packed`` + ``prepared_cache``) when caching the
deterministic crop stage on top is still wanted.

The end-to-end bound on a weak host is the deterministic front of the train
pipeline — JPEG/PNG decode, mask-bbox crop, fixed resize (BASELINE.md: ~19
fresh imgs/s e2e vs a ~65 imgs/s chip).  That front is *identical every
epoch*: given the sample and the crop config it has no randomness.  So run
it once, store the result compactly on disk, and serve every later epoch
from an ``np.memmap`` read — the FFCV recipe (PAPERS.md) applied to the
reference's host pipeline (/root/reference/train_pascal.py:123-134,
pascal.py:232-263).

What is cached per sample (all fixed-shape):

* ``crop_image`` — (H, W, 3) uint8 (the [0,255] contract of reference
  train_pascal.py:188 makes uint8 lossless up to rounding);
* ``crop_gt``   — H·W bits, ``np.packbits`` of the binary mask (32 KB for a
  512² crop instead of 1 MB float32);
* ``bbox``      — the (relaxed) crop box, for eval-style paste-back;
* ``im_size``   — the source image's (H, W), reconstructing ``meta``.

Randomness is *not* cached: flip / scale-rotate / guidance synthesis run
per epoch downstream of the cache (``post_transform``), so augmentation
stays fresh.  Consequence, stated plainly: the random geometric stage
operates on the fixed-size *crop* rather than the pre-crop full image —
the same semantics as the on-device augmentation path
(``data.device_augment_geom``); the flip commutes with the crop exactly
(zero-padded boxes are symmetric), the rotation does not (pixels that a
full-image rotation would bring into the crop window are zeros here).

Cache identity: a fingerprint over the dataset identity and every config
knob that changes the cached bytes (crop size, relax, zero_pad, fused
kernel, imaging backend).  Each fingerprint gets its own subdirectory, so
changing the config *invalidates by construction* — a new config simply
builds a new cache and never reads stale rows.

Concurrency: rows are written at distinct offsets (one row per sample
index) with a ``valid`` byte flipped after the row lands; racing fillers
(loader threads, grain worker processes) recompute the same deterministic
bytes, so last-writer-wins is idempotent.  The memmaps are reopened after
pickling (grain workers) rather than shipped.
"""

from __future__ import annotations

import fcntl
import hashlib
import json
import os

import numpy as np

from .. import imaging
from . import transforms as T

#: bump when the cached layout/semantics change
_FORMAT_VERSION = 1


def _content_stamp(dataset) -> list:
    """Cheap content probe of the underlying files: (path, size, mtime_ns)
    of a handful of the dataset's image AND label files.  Catches a dataset
    *regenerated in place* with the same name/split/count (same ``str`` and
    ``len``) but different pixels/labels — which the identity fields alone
    would silently alias to stale cached rows."""
    if hasattr(dataset, "datasets"):  # CombinedDataset: walk constituents
        return [s for ds in dataset.datasets for s in _content_stamp(ds)]
    stamp = []
    # every file-list attribute the dataset classes expose: images, the
    # instance/semantic label files (masks/categories/labels)
    for attr in ("images", "masks", "categories", "labels"):
        paths = getattr(dataset, attr, None)
        if not isinstance(paths, list) or not paths \
                or not isinstance(paths[0], str):
            continue
        for p in {paths[0], paths[len(paths) // 2], paths[-1]}:
            try:
                st = os.stat(p)
                stamp.append([p, st.st_size, st.st_mtime_ns])
            except OSError:
                stamp.append([p, -1, -1])
    return sorted(stamp)


def cache_fingerprint(dataset, crop_size, relax: int, zero_pad: bool,
                      fused_crop_resize: bool) -> str:
    """Identity of the cached bytes: dataset + every knob that changes them.

    ``str(dataset)`` covers splits/area-thres (VOC/SBD ``__str__`` encode
    them); ``len`` catches a changed instance list under the same name; the
    content stamp catches same-name same-count regenerated files; the
    imaging backend matters because cv2 and the native kernels differ in
    the last ulp of cubic taps.
    """
    ident = json.dumps({
        "format": _FORMAT_VERSION,
        "dataset": str(dataset),
        "n": len(dataset),
        "content": _content_stamp(dataset),
        "crop_size": list(crop_size),
        "relax": int(relax),
        "zero_pad": bool(zero_pad),
        "fused_crop_resize": bool(fused_crop_resize),
        "imaging_backend": imaging.backend(),
    }, sort_keys=True)
    return hashlib.sha256(ident.encode()).hexdigest()[:16]


def _needs_init(meta_path: str, expect_meta: dict) -> bool:
    """True when the cache layout must be (re)created: meta.json missing,
    unreadable, or describing a different layout than ``expect_meta``."""
    if not os.path.isfile(meta_path):
        return True
    try:
        with open(meta_path) as f:
            return json.load(f) != expect_meta
    except (ValueError, OSError):
        return True


def _open_maps(cache_dir: str, expect_meta: dict, layout) -> dict:
    """Open (or create/reset) the cache's memmaps under ``cache_dir``.

    ``expect_meta`` mismatching the stored meta.json resets every file —
    and the valid map is (re)created LAST so a half-written images file
    from a crashed builder is never trusted.

    Creation is serialized across processes with an exclusive ``flock``:
    two racing openers (grain workers, concurrent runs) that both observe a
    missing/stale meta.json would otherwise both recreate the files with
    ``mode='w+'``, each truncating rows the other had already written —
    including a window where one process's valid byte survives a zeroed
    data file.  The second opener re-checks freshness *under the lock* and
    finds the first's meta.json already landed.  ``flock`` (not O_EXCL) so
    a crashed creator's lock is released by the kernel, never left stale.
    """
    os.makedirs(cache_dir, exist_ok=True)
    meta_path = os.path.join(cache_dir, "meta.json")
    if _needs_init(meta_path, expect_meta):
        lock_fd = os.open(os.path.join(cache_dir, ".init.lock"),
                          os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(lock_fd, fcntl.LOCK_EX)
            if _needs_init(meta_path, expect_meta):  # lost the race?
                for name, shape, dtype in layout:
                    mm = np.memmap(os.path.join(cache_dir, name), mode="w+",
                                   dtype=dtype, shape=shape)
                    del mm  # creation (ftruncate to size) is all needed
                with open(meta_path + ".tmp", "w") as f:
                    json.dump(expect_meta, f)
                os.replace(meta_path + ".tmp", meta_path)
        finally:
            fcntl.flock(lock_fd, fcntl.LOCK_UN)
            os.close(lock_fd)
    return {
        name: np.memmap(os.path.join(cache_dir, name), mode="r+",
                        dtype=dtype, shape=shape)
        for name, shape, dtype in layout
    }


class _PreparedCacheBase:
    """Shared machinery of the prepared caches: pickling (grain process
    workers reopen the memmaps rather than ship them), row counting, eager
    prebuild, and the ordered crash-safe flush.  Subclasses define
    ``_open_or_create``/``_fill``/``__getitem__`` over their own layout."""

    # the files are the shared state, not the handles
    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("_maps")
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._open_or_create()

    def __len__(self) -> int:
        return len(self.dataset)

    def sample_image_id(self, index: int) -> str:
        return self.dataset.sample_image_id(index)

    @property
    def n_prepared(self) -> int:
        """Rows already cached (diagnostic / test hook)."""
        return int(np.count_nonzero(self._maps["valid.u8"]))

    def prebuild(self, num_workers: int = 0) -> None:
        """Eagerly fill every missing row (optional — training's first epoch
        does the same lazily)."""
        missing = np.flatnonzero(self._maps["valid.u8"] == 0)
        if num_workers > 0:
            import concurrent.futures as cf
            with cf.ThreadPoolExecutor(max_workers=num_workers) as pool:
                list(pool.map(self._fill, missing.tolist()))
        else:
            for i in missing.tolist():
                self._fill(i)
        self.flush()

    def flush(self) -> None:
        """msync the maps — durability for readers in other processes/runs.

        Data maps flush BEFORE the valid map: a host crash mid-writeback
        must never persist a valid byte whose row bytes didn't land (the
        page cache orders nothing on its own)."""
        for name, mm in self._maps.items():
            if name != "valid.u8":
                mm.flush()
        self._maps["valid.u8"].flush()


class PreparedInstanceDataset(_PreparedCacheBase):
    """Wrap an instance dataset with a prepared-sample disk cache.

    ``dataset`` must be constructed with ``transform=None`` (this class owns
    the whole transform story: the deterministic crop stage feeds the cache,
    ``post_transform`` runs per epoch on the cached crop).  Any source with
    the instance sample contract works — VOC, SBD, ``CombinedDataset``.

    First access of an index computes decode→crop→resize, writes the row,
    and marks it valid; every later access (any epoch, any process) is a
    memmap read.  A full first epoch therefore fills the cache as a side
    effect of training — no separate build pass needed (``prebuild()``
    exists for warming explicitly).
    """

    def __init__(self, dataset, cache_dir: str,
                 crop_size=(512, 512), relax: int = 50,
                 zero_pad: bool = True, fused_crop_resize: bool = False,
                 post_transform=None, uint8_arrays: bool = False,
                 eval_protocol: bool = False,
                 max_im_size=(512, 512)):
        if getattr(dataset, "transform", None) is not None:
            raise ValueError(
                "PreparedInstanceDataset wraps the *untransformed* dataset "
                "(construct it with transform=None); the crop stage it would "
                "run is exactly what this cache replaces")
        self.dataset = dataset
        self.crop_size = tuple(int(v) for v in crop_size)
        self.relax = int(relax)
        self.zero_pad = bool(zero_pad)
        self.fused_crop_resize = bool(fused_crop_resize)
        self.post_transform = post_transform
        #: serve uint8 crop arrays as-is (the data.uint8_transfer wire
        #: format — skips two full-array float casts per sample; all host
        #: transforms downstream are uint8-safe: flip, the uint8-casting
        #: warp, guidance-from-binary-mask)
        self.uint8_arrays = bool(uint8_arrays)
        #: eval mode (data.val_prepared): additionally cache the FULL-RES
        #: gt and void masks as packed bits (1 bit/pixel, padded rows of
        #: ceil(max_h*max_w/8) bytes) so the threshold-swept paste-back
        #: metric (reference train_pascal.py:280-291) never re-decodes the
        #: source PNGs; __getitem__ then emits the evaluator's host-side
        #: keys (``gt``/``void_pixels``/``bbox``) alongside the wire keys.
        self.eval_protocol = bool(eval_protocol)
        self.max_im_size = tuple(int(v) for v in max_im_size)

        # THE shared crop front (pipeline.build_crop_stage): one definition
        # keeps the cached bytes from diverging from the live pipeline.
        from .pipeline import build_crop_stage
        self._stage1 = T.Compose(build_crop_stage(
            self.crop_size, relax, zero_pad, fused=fused_crop_resize,
            clamp=True))

        self.fingerprint = cache_fingerprint(
            dataset, self.crop_size, relax, zero_pad, fused_crop_resize)
        # eval caches live beside the train cache, never aliased: same
        # fingerprint inputs but an extra layout (full-res bit rows)
        suffix = "-eval" if self.eval_protocol else ""
        self.cache_dir = os.path.join(cache_dir, self.fingerprint + suffix)
        self._open_or_create()

    # -- cache files ---------------------------------------------------------

    def _open_or_create(self) -> None:
        n = len(self.dataset)
        h, w = self.crop_size
        self._npack = (h * w + 7) // 8
        mh, mw = self.max_im_size
        self._npack_full = (mh * mw + 7) // 8
        meta = {"format": _FORMAT_VERSION, "fingerprint": self.fingerprint,
                "n": n, "crop_size": [h, w]}
        if self.eval_protocol:
            meta["eval"] = True
            meta["max_im_size"] = [mh, mw]
        self._maps = _open_maps(self.cache_dir, meta, self._layout(n, h, w))

    def _layout(self, n, h, w):
        layout = [
            ("images.u8", (n, h, w, 3), np.uint8),
            ("masks.u8", (n, self._npack), np.uint8),
            ("bboxes.i64", (n, 4), np.int64),
            ("sizes.i32", (n, 2), np.int32),
            ("valid.u8", (n,), np.uint8),
        ]
        if self.eval_protocol:
            layout += [
                ("fullgt.u8", (n, self._npack_full), np.uint8),
                ("fullvoid.u8", (n, self._npack_full), np.uint8),
            ]
        return layout

    # -- dataset protocol: pickling/len/ids/prebuild/flush in the base ------

    def _fill(self, index: int) -> tuple[np.ndarray, np.ndarray, np.ndarray,
                                         tuple[int, int]]:
        raw = self.dataset.__getitem__(index)
        sample = self._stage1(dict(raw), None)
        h, w = self.crop_size
        img8 = np.rint(np.asarray(sample["crop_image"],
                                  np.float32)).astype(np.uint8)
        gt = np.asarray(sample["crop_gt"], np.float32)
        if gt.ndim == 3:
            gt = gt[..., 0]
        bits = np.packbits(gt.reshape(-1) > 0.5)
        bbox = np.asarray(sample["bbox"], np.int64)
        im_size = raw["meta"]["im_size"] if "meta" in raw \
            else raw["image"].shape[:2]
        if self.eval_protocol:
            fh, fw = (int(v) for v in im_size)
            if fh * fw > self.max_im_size[0] * self.max_im_size[1]:
                raise ValueError(
                    f"source image {fh}x{fw} exceeds the eval cache's "
                    f"max_im_size {self.max_im_size}; raise max_im_size "
                    "(row bytes scale with it)")
            for key, src in (("fullgt.u8", raw["gt"]),
                             ("fullvoid.u8", raw.get("void_pixels"))):
                row = np.zeros(self._npack_full, np.uint8)
                if src is not None:
                    packed = np.packbits(
                        np.asarray(src).reshape(-1) > 0.5)
                    row[:packed.size] = packed
                self._maps[key][index] = row
        self._maps["images.u8"][index] = img8
        self._maps["masks.u8"][index] = bits
        self._maps["bboxes.i64"][index] = bbox
        self._maps["sizes.i32"][index] = im_size
        self._maps["valid.u8"][index] = 1
        return img8, bits, bbox, tuple(int(v) for v in im_size)

    def __getitem__(self, index: int,
                    rng: np.random.Generator | None = None) -> dict:
        index = int(index)
        h, w = self.crop_size
        if self._maps["valid.u8"][index]:
            img8 = np.asarray(self._maps["images.u8"][index])
            bits = np.asarray(self._maps["masks.u8"][index])
            bbox = np.asarray(self._maps["bboxes.i64"][index]).copy()
            im_size = tuple(int(v) for v in self._maps["sizes.i32"][index])
            if not (img8.any() and bits.any()
                    and bbox.any()
                    and bbox[2] >= bbox[0] and bbox[3] >= bbox[1]
                    and im_size[0] > 0 and im_size[1] > 0
                    # eval rows: full-res gt always has object pixels
                    # (area filter); fullvoid may legitimately be empty
                    and (not self.eval_protocol
                         or self._maps["fullgt.u8"][index].any())):
                # Torn write from a crashed filler: the valid byte landed
                # but a row is still zeros — and each array lives in its own
                # file whose dirty pages persist independently, so ANY row
                # (image, mask, bbox, size) can be the torn one.  A real
                # sample always has object pixels (area filter), a non-black
                # crop, a non-degenerate bbox, and a positive source size;
                # refill (idempotent).  bbox coords are INCLUSIVE
                # (helpers.get_bbox): a thin object at relax=0 legitimately
                # has x_max == x_min, so extent is checked with >= and the
                # all-zeros torn row is caught by .any().
                img8, bits, bbox, im_size = self._fill(index)
        else:
            img8, bits, bbox, im_size = self._fill(index)
        gt = np.unpackbits(bits, count=h * w).reshape(h, w)
        if self.uint8_arrays:
            # .copy(), NOT a view: img8 may alias the writable (r+) memmap
            # row — an in-place mutation downstream would silently corrupt
            # the on-disk cache forever (gt is already fresh via unpackbits)
            sample = {"crop_image": img8.copy(), "crop_gt": gt}
        else:
            sample = {"crop_image": img8.astype(np.float32),
                      "crop_gt": gt.astype(np.float32)}
        sample["meta"] = self._meta(index, im_size)
        if self.post_transform is not None:
            sample = self.post_transform(sample, rng)
        # bbox joins AFTER the random stage: flip/rotate iterate every array
        # key and would mangle a 4-vector of coordinates (in the uncached
        # pipeline the crop — and hence bbox — comes after them).
        sample["bbox"] = bbox
        if self.eval_protocol:
            # host-side metric keys (never shipped): full-res masks from
            # the packed rows.  uint8 0/1 — np_jaccard bools them and the
            # paste-back only thresholds, so the cheap dtype is exact.
            fh, fw = im_size
            for key, src in (("gt", "fullgt.u8"),
                             ("void_pixels", "fullvoid.u8")):
                sample[key] = np.unpackbits(
                    np.asarray(self._maps[src][index]),
                    count=fh * fw).reshape(fh, fw)
        return sample

    def _meta(self, index: int, im_size: tuple[int, int]) -> dict:
        """Rebuild the sample's ``meta`` without touching the image bytes.

        A ``CombinedDataset`` wrapper (the sbd_root merge) is unwrapped to
        the constituent that owns the sample, so the meta schema stays
        identical to the uncached pipeline's (image/object/category/
        im_size) regardless of nesting."""
        ds, local = self.dataset, index
        while hasattr(ds, "datasets") and hasattr(ds, "index"):
            di, local = ds.index[local]
            ds = ds.datasets[di]
        meta = {"image": ds.sample_image_id(local), "im_size": im_size}
        obj_list = getattr(ds, "obj_list", None)
        if obj_list is not None:
            im_ii, obj_ii = obj_list[local]
            meta["object"] = str(obj_ii)
            meta["category"] = ds.obj_dict[ds.im_ids[im_ii]][obj_ii]
        return meta

    def __str__(self) -> str:
        kind = "PreparedEval" if self.eval_protocol else "Prepared"
        return (f"{kind}({self.dataset},crop={self.crop_size},"
                f"relax={self.relax},fp={self.fingerprint})")


class PreparedSemanticDataset(_PreparedCacheBase):
    """Prepared-sample cache for the semantic pipeline.

    The semantic task's deterministic front is smaller than the instance
    task's — decode → fixed resize (no mask-dependent crop) — but on a weak
    host decode still dominates.  Cached per sample: the resized image as
    uint8 and the class-id mask as uint8 (ids 0..20 plus in-band 255 void —
    exact by construction).  Flip / scale-rotate run per epoch downstream
    on the resized arrays, i.e. post-resize rather than the uncached
    pipeline's pre-resize order (the same semantics shift the instance
    cache documents; the warp's uint8 cast and nearest-gt rule are
    unchanged).
    """

    def __init__(self, dataset, cache_dir: str, crop_size=(513, 513),
                 post_transform=None, uint8_arrays: bool = False,
                 keep_fullres: bool = False, max_im_size=(512, 512)):
        if getattr(dataset, "transform", None) is not None:
            raise ValueError(
                "PreparedSemanticDataset wraps the *untransformed* dataset "
                "(construct it with transform=None)")
        self.dataset = dataset
        self.crop_size = tuple(int(v) for v in crop_size)
        self.post_transform = post_transform
        self.uint8_arrays = bool(uint8_arrays)
        #: eval_full_res protocol (data.val_prepared): additionally cache
        #: the NATIVE-resolution class-id mask (uint8 ids + in-band 255
        #: void — exact) in padded rows, emitted as ``gt_full`` so the
        #: evaluator scores mIoU at each image's original size without
        #: re-decoding the label PNG every epoch
        self.keep_fullres = bool(keep_fullres)
        self.max_im_size = tuple(int(v) for v in max_im_size)
        self._stage1 = T.Compose([
            T.FixedResize(resolutions={"image": self.crop_size,
                                       "gt": self.crop_size},
                          flagvals={"image": None, "gt": 0}),
            T.ClampRange(("image",)),
        ])
        # relax/zero_pad/fused have no semantic analogue; pinned values
        # keep the fingerprint function shared with the instance cache
        self.fingerprint = cache_fingerprint(
            dataset, self.crop_size, relax=0, zero_pad=False,
            fused_crop_resize=False)
        suffix = "-fullres" if self.keep_fullres else ""
        self.cache_dir = os.path.join(cache_dir, self.fingerprint + suffix)
        self._open_or_create()

    def _layout(self, n, h, w):
        layout = [
            ("images.u8", (n, h, w, 3), np.uint8),
            ("gts.u8", (n, h, w), np.uint8),
            ("sizes.i32", (n, 2), np.int32),
            ("valid.u8", (n,), np.uint8),
        ]
        if self.keep_fullres:
            mh, mw = self.max_im_size
            layout.append(("gtfull.u8", (n, mh * mw), np.uint8))
        return layout

    def _open_or_create(self) -> None:
        h, w = self.crop_size
        meta = {"format": _FORMAT_VERSION, "fingerprint": self.fingerprint,
                "n": len(self.dataset), "crop_size": [h, w],
                "kind": "semantic"}
        if self.keep_fullres:
            meta["fullres"] = True
            meta["max_im_size"] = list(self.max_im_size)
        self._maps = _open_maps(
            self.cache_dir, meta,
            self._layout(len(self.dataset), h, w))

    def _fill(self, index: int):
        raw = self.dataset.__getitem__(index)
        sample = self._stage1(dict(raw), None)
        img8 = np.rint(np.asarray(sample["image"],
                                  np.float32)).astype(np.uint8)
        gt8 = np.rint(np.asarray(sample["gt"], np.float32)).astype(np.uint8)
        im_size = raw["meta"]["im_size"] if "meta" in raw \
            else raw["image"].shape[:2]
        if self.keep_fullres:
            fh, fw = (int(v) for v in im_size)
            if fh * fw > self.max_im_size[0] * self.max_im_size[1]:
                raise ValueError(
                    f"source image {fh}x{fw} exceeds the fullres cache's "
                    f"max_im_size {self.max_im_size}; raise "
                    "data.val_max_im_size (row bytes scale with it)")
            row = np.zeros(self.max_im_size[0] * self.max_im_size[1],
                           np.uint8)
            full = np.rint(np.asarray(raw["gt"], np.float32)
                           ).astype(np.uint8).reshape(-1)
            row[:full.size] = full
            self._maps["gtfull.u8"][index] = row
        self._maps["images.u8"][index] = img8
        self._maps["gts.u8"][index] = gt8
        self._maps["sizes.i32"][index] = im_size
        self._maps["valid.u8"][index] = 1
        return img8, gt8, tuple(int(v) for v in im_size)

    def __getitem__(self, index: int,
                    rng: np.random.Generator | None = None) -> dict:
        index = int(index)
        if self._maps["valid.u8"][index]:
            img8 = np.asarray(self._maps["images.u8"][index])
            gt8 = np.asarray(self._maps["gts.u8"][index])
            im_size = tuple(int(v) for v in self._maps["sizes.i32"][index])
            if not (img8.any() and gt8.any()
                    and im_size[0] > 0 and im_size[1] > 0
                    # fullres rows: a VOC-style semantic mask is never
                    # all-background (objects + 255 void boundary)
                    and (not self.keep_fullres
                         or self._maps["gtfull.u8"][index].any())):
                # torn write from a crashed filler: pages persist in
                # arbitrary order per file, so ANY row (image, gt, size) can
                # be zeros while valid=1 — a real photo is never all-black,
                # a VOC segmentation mask never all-background (objects +
                # 255 void boundary), and a source size is positive; refill
                # (idempotent) rather than serve silent wrong labels
                img8, gt8, im_size = self._fill(index)
        else:
            img8, gt8, im_size = self._fill(index)
        if self.uint8_arrays:
            # copies, not views of the writable memmap rows (see the
            # instance cache): downstream in-place math must never be able
            # to corrupt the on-disk cache
            sample = {"image": img8.copy(), "gt": gt8.copy()}
        else:
            sample = {"image": img8.astype(np.float32),
                      "gt": gt8.astype(np.float32)}
        sample["meta"] = {"image": self.dataset.sample_image_id(index),
                          "im_size": im_size}
        if self.post_transform is not None:
            sample = self.post_transform(sample, rng)
        if self.keep_fullres:
            fh, fw = im_size
            # ragged host-side metric key (never shipped); uint8 ids
            # exact.  .copy(), not a view: the slice shares the writable
            # r+ memmap buffer and a consumer's in-place edit (e.g. a void
            # remap) would silently rewrite the cached labels on disk.
            sample["gt_full"] = np.asarray(
                self._maps["gtfull.u8"][index][:fh * fw]
            ).reshape(fh, fw).copy()
        return sample

    def __str__(self) -> str:
        kind = "PreparedSemanticFullres" if self.keep_fullres \
            else "PreparedSemantic"
        return (f"{kind}({self.dataset},crop={self.crop_size},"
                f"fp={self.fingerprint})")
