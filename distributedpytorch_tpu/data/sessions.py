"""Session-log data plane: serve clicks read back as training records.

The flywheel's storage leg (ROADMAP item 5).  Every serve session already
*is* a labeled example — the request path computed the relax-padded crop,
the click points, and an accepted mask — and the serve-side sink
(``serve/session_log.py``) appends them in the packed-record idiom of
``data/packed.py`` (FFCV, arXiv 2306.12517): pre-decoded blobs behind a
fixed-dtype index with per-record crc32, ``meta.json`` written LAST,
atomically.  This module owns the FORMAT (the sink imports its constants
from here) and the read side:

* :class:`SessionLogDataset` replays a log directory into training
  batches.  ``mode="replay"`` re-synthesizes the guidance channel from
  the stored clicks through the SAME seam the live serve path uses
  (``data/guidance.py:crop_point_guidance``), so a replayed batch is
  bit-identical to what the serving pipeline fed the model — pinned in
  ``tests/test_flywheel.py``.  ``mode="sample"`` emits the VOC instance
  sample contract (``{'image','gt','void_pixels','meta'}``) so the log
  composes with ``CombinedDataset`` + the standard transform stack for
  mixed VOC+session fine-tunes.
* ``seek(i)`` / ``record_index(i)`` / ``quarantine=(...)`` speak the
  packed accessor contract, so ``resolve_packed`` resolves through this
  dataset and the sentinel's quarantine ledger names the EXACT session
  records a poisoned fit rolled back over.
* crash safety is meta-bounded: readers trust ``meta.json``'s counts
  only, so bin/idx bytes past the last committed flush (a sink crash
  mid-append) are invisible — no meta, no log.

Importable pre-jax (numpy + stdlib only), like ``data/packed.py``.
"""

from __future__ import annotations

import json
import os
import zlib

import numpy as np

from ..chaos import sites as chaos_sites
from .packed import BIN_NAME, INDEX_NAME, META_NAME, PackedRecordError, \
    PackFormatError

#: bump when the session record layout / replay semantics change
SESSION_FORMAT_VERSION = 1

#: the meta.json "kind" that marks a directory as a session log — the
#: dispatch key ``dptpu-pack --verify`` uses to pick this reader over
#: ``PackedDataset``
SESSION_KIND = "sessions"

#: one fixed-size row per accepted example — the O(1)-seek surface.
#: ``points`` are the FULL-IMAGE xy clicks exactly as submitted (float64:
#: the dtype ``prepare_input`` casts to, so replay feeds the guidance
#: seam byte-identical inputs); ``bbox`` is the relax-padded crop box
#: those clicks established; ``digest`` is the submit thread's content
#: digest (``serve/sessions.py:image_digest``; the sink's crc fallback
#: for stateless requests); ``dedup`` is the sink's (digest, points)
#: dedup key; ``warm`` flags refinement clicks that reused a cached
#: crop.
SESSION_INDEX_DTYPE = np.dtype([
    ("blob_offset", np.int64),
    ("blob_len", np.int64),
    ("height", np.int32),       # crop rows (== log resolution)
    ("width", np.int32),        # crop cols
    ("shape_h", np.int32),      # full-image rows (paste-back shape)
    ("shape_w", np.int32),
    ("bbox", np.int64, (4,)),
    ("points", np.float64, (4, 2)),
    ("digest", np.uint32),
    ("dedup", np.uint64),
    ("gen_id", np.int32),
    ("warm", np.uint8),
    ("blob_crc32", np.uint32),
])


def blob_bytes(height: int, width: int) -> int:
    """Byte length of one record's blob: the float32 (H, W, 3) crop +
    the uint8 (H, W) mask, concatenated."""
    return height * width * 3 * 4 + height * width


def encode_blob(crop: np.ndarray, mask: np.ndarray) -> bytes:
    """One record's blob payload.  ``crop`` is the resized float32
    (H, W, 3) crop exactly as the serve path built it (``concat``'s RGB
    channels); ``mask`` is the accepted uint8 (H, W) binary mask."""
    crop = np.ascontiguousarray(crop, np.float32)
    mask = np.ascontiguousarray(mask, np.uint8)
    if crop.ndim != 3 or crop.shape[2] != 3 or mask.shape != crop.shape[:2]:
        raise ValueError(
            f"session blob wants (H, W, 3) crop + (H, W) mask, got "
            f"{crop.shape} / {mask.shape}")
    return crop.tobytes() + mask.tobytes()


def dedup_key(digest: int, points: np.ndarray) -> int:
    """uint64 content key of one (image, clicks) example: the image
    digest in the high 32 bits, a crc32 of the float64 click bytes in
    the low — two clicks on the same image dedup iff they are the same
    clicks."""
    pts = np.ascontiguousarray(np.asarray(points, np.float64))
    return ((int(digest) & 0xFFFFFFFF) << 32) | \
        (zlib.crc32(pts.tobytes()) & 0xFFFFFFFF)


def session_meta(*, resolution, guidance: str, alpha: float, relax: int,
                 zero_pad: bool, n_records: int, bin_bytes: int,
                 index_crc32: int) -> dict:
    """The meta.json body — one constructor so the sink and tests cannot
    drift on the schema.  ``resolution``/``guidance``/``alpha`` pin the
    synthesis parameters replay must reuse; ``relax``/``zero_pad`` ride
    along so a fine-tune can mirror the serving crop geometry."""
    h, w = resolution
    return {
        "format": SESSION_FORMAT_VERSION,
        "kind": SESSION_KIND,
        "resolution": [int(h), int(w)],
        "guidance": str(guidance),
        "alpha": float(alpha),
        "relax": int(relax),
        "zero_pad": bool(zero_pad),
        "n_records": int(n_records),
        "bin_bytes": int(bin_bytes),
        "index_crc32": int(index_crc32),
    }


def write_meta(path: str, meta: dict) -> None:
    """Atomic meta.json commit — tmp + ``os.replace``, the packed-plane
    rule: a crash mid-write reads as the PREVIOUS meta (or no log),
    never a torn verdict."""
    meta_path = os.path.join(path, META_NAME)
    tmp = meta_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, meta_path)


def is_session_log(path: str) -> bool:
    """True when ``path`` holds a session log (meta kind dispatch; False
    on missing/torn meta — the caller's format error paths own those)."""
    try:
        with open(os.path.join(path, META_NAME)) as f:
            return json.load(f).get("kind") == SESSION_KIND
    except (OSError, ValueError):
        return False


def corrupt_record(path: str, record: int, offset: int = 0) -> int:
    """Flip one byte of session ``record``'s blob ON DISK — the
    deterministic stand-in for bit rot (same contract as
    ``packed.corrupt_record``; ``--verify`` must then flag the record).
    Returns the absolute file offset flipped."""
    with open(os.path.join(path, META_NAME)) as f:
        meta = json.load(f)
    with open(os.path.join(path, INDEX_NAME), "rb") as f:
        raw = f.read(int(meta["n_records"]) * SESSION_INDEX_DTYPE.itemsize)
    index = np.frombuffer(raw, SESSION_INDEX_DTYPE)
    if not 0 <= record < len(index):
        raise IndexError(f"record {record} out of range [0, {len(index)})")
    row = index[record]
    at = int(row["blob_offset"]) + (int(offset) % int(row["blob_len"]))
    with open(os.path.join(path, BIN_NAME), "r+b") as f:
        f.seek(at)
        b = f.read(1)
        f.seek(at)
        f.write(bytes([b[0] ^ 0xFF]))
    return at


class SessionLogDataset:
    """Memory-mapped reader over a ``serve/session_log.py`` directory —
    a random-access source for the ``DataLoader``/transform stack.

    * ``mode="replay"`` (the flywheel's incremental-fit mode): each item
      is the EXACT network input the serve path synthesized —
      ``{'concat': (H, W, 4) f32, 'crop_gt': (H, W, 1) f32, 'meta'}`` —
      with the guidance channel re-synthesized from the stored clicks
      through ``data/guidance.py:crop_point_guidance``, the same call
      ``prepare_input``/``prepare_guidance`` make.  No transform runs
      (the crop IS the augmentation-free serving view).
    * ``mode="sample"`` emits the VOC instance sample contract
      (``{'image','gt','void_pixels','meta'}`` at crop geometry) and
      runs ``transform`` over it — the mixed VOC+session fine-tune
      source ``CombinedDataset`` composes.
    * every record read is crc32-verified (a torn/bit-flipped record is
      a typed :class:`PackedRecordError`, never a silent wrong sample);
      ``quarantine=(record, ...)`` drops named records from the epoch;
      ``seek``/``record_index`` speak the packed accessor contract, so
      ``resolve_packed`` and the sentinel's ledger resolve through this
      dataset unchanged.
    """

    def __init__(self, path: str, transform=None, mode: str = "replay",
                 quarantine=()):
        if mode not in ("replay", "sample"):
            raise ValueError(f"mode must be 'replay' or 'sample', "
                             f"got {mode!r}")
        if mode == "replay" and transform is not None:
            raise ValueError(
                "replay mode feeds the serving pipeline's exact inputs — "
                "a transform would break the bit-identity contract; use "
                "mode='sample' for augmented fine-tunes")
        self.path = path
        self.mode = mode
        self.transform = transform
        meta_path = os.path.join(path, META_NAME)
        if not os.path.isfile(meta_path):
            raise PackFormatError(
                f"no session log at {path} ({META_NAME} missing) — enable "
                "the sink with dptpu-serve --session-log")
        try:
            with open(meta_path) as f:
                self.meta = json.load(f)
        except ValueError as e:
            raise PackFormatError(
                f"{path}/{META_NAME} is unreadable ({e}) — torn or "
                "partially copied session log") from e
        if self.meta.get("kind") != SESSION_KIND:
            raise PackFormatError(
                f"{path} is a {self.meta.get('kind')!r} pack, not a "
                f"session log — open it with PackedDataset")
        if self.meta.get("format") != SESSION_FORMAT_VERSION:
            raise PackFormatError(
                f"{path} has session-log format {self.meta.get('format')}; "
                f"this reader speaks {SESSION_FORMAT_VERSION}")
        self.resolution = tuple(int(x) for x in self.meta["resolution"])
        self.guidance = str(self.meta["guidance"])
        self.alpha = float(self.meta["alpha"])
        n = int(self.meta["n_records"])
        # meta-bounded reads: the sink appends bin/idx first and commits
        # meta LAST, so bytes past meta's counts are an uncommitted tail
        # (crash mid-append) — sliced off here, never trusted
        with open(os.path.join(path, INDEX_NAME), "rb") as f:
            raw = f.read(n * SESSION_INDEX_DTYPE.itemsize)
        if len(raw) != n * SESSION_INDEX_DTYPE.itemsize:
            raise PackFormatError(
                f"{path}/{INDEX_NAME} holds fewer rows than meta's "
                f"{n} — torn log")
        if (zlib.crc32(raw) & 0xFFFFFFFF) != int(self.meta["index_crc32"]):
            raise PackFormatError(
                f"{path}/{INDEX_NAME} fails its checksum — the index is "
                f"torn")
        self._index = np.frombuffer(raw, SESSION_INDEX_DTYPE)
        if os.path.getsize(os.path.join(path, BIN_NAME)) \
                < int(self.meta["bin_bytes"]):
            raise PackFormatError(
                f"{path}/{BIN_NAME} is shorter than meta's "
                f"{self.meta['bin_bytes']} bytes — truncated log")
        q = sorted({int(i) for i in quarantine})
        bad = [i for i in q if not 0 <= i < n]
        if bad:
            raise ValueError(
                f"session_quarantine indices {bad} out of range [0, {n}) "
                f"for {path}")
        self.quarantine = tuple(q)
        self._live = (np.setdiff1d(np.arange(n), np.asarray(q, np.int64))
                      if q else np.arange(n))
        self._open_bin()

    def _open_bin(self) -> None:
        bin_path = os.path.join(self.path, BIN_NAME)
        # a just-created sink commits an EMPTY log ("sink on, no examples
        # yet"); mmap refuses zero-byte files, and there is nothing to map
        if os.path.getsize(bin_path) == 0:
            self._bin = np.empty(0, np.uint8)
            return
        self._bin = np.memmap(bin_path, mode="r", dtype=np.uint8)

    # mmap handles don't pickle; the files are the shared state (the
    # packed idiom — grain process workers reopen)
    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("_bin")
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._open_bin()

    # ------------------------------------------------------- protocol
    def __len__(self) -> int:
        return len(self._live)

    def record_index(self, index: int) -> int:
        """RAW record id behind dataset position ``index`` (positions
        shift when a quarantine drops records; record ids never do)."""
        return int(self._live[index])

    def sample_image_id(self, index: int) -> str:
        """Per-record synthetic image id — the CombinedDataset
        exclusion/dedup key.  The ``session-`` prefix can never collide
        with a VOC/SBD id, so mixed fine-tunes are exclusion-safe."""
        rec = self.record_index(index)
        row = self._index[rec]
        return f"session-{int(row['digest']):08x}-{rec}"

    def seek(self, index: int, read: bool = False) -> dict:
        """O(1) record lookup for dataset position ``index`` — the
        packed accessor contract (``record``/``image_id``/``object``
        keys the sentinel's quarantine ledger resolves through), plus
        the session fields (``points``/``bbox``/``shape``/``gen_id``).
        ``read=True`` adds the verified payload (``image``: the float32
        crop, ``mask``: the uint8 accepted mask)."""
        rec = self.record_index(index)
        row = self._index[rec]
        out = {
            "record": rec,
            "image_id": f"session-{int(row['digest']):08x}-{rec}",
            "object": "0",
            "category": None,
            "im_size": (int(row["height"]), int(row["width"])),
            "points": np.array(row["points"]),
            "bbox": tuple(int(x) for x in row["bbox"]),
            "shape": (int(row["shape_h"]), int(row["shape_w"])),
            "gen_id": int(row["gen_id"]),
            "warm": bool(row["warm"]),
        }
        if read:
            crop, mask = self._read_blob(rec)
            out["image"] = crop.copy()
            out["mask"] = mask.copy()
        return out

    def _read_blob(self, rec: int) -> tuple[np.ndarray, np.ndarray]:
        """The verified read: one mmap view, the chaos seam, the crc32
        gate, then zero-copy views (consumers copy before mutating —
        the ``data/packed.py`` reading discipline)."""
        row = self._index[rec]
        off, ln = int(row["blob_offset"]), int(row["blob_len"])
        if off < 0 or off + ln > self._bin.size:
            raise PackedRecordError(
                rec, self.path,
                f"blob extent [{off}, {off + ln}) past the "
                f"{self._bin.size}-byte bin file")
        buf = self._bin[off:off + ln]
        buf = chaos_sites.fire("data/packed_read", payload=buf,
                               index=rec, path=self.path)
        if (zlib.crc32(buf) & 0xFFFFFFFF) != int(row["blob_crc32"]):
            raise PackedRecordError(rec, self.path, "checksum mismatch")
        h, w = int(row["height"]), int(row["width"])
        crop = buf[:h * w * 12].view(np.float32).reshape(h, w, 3)
        mask = buf[h * w * 12:].reshape(h, w)
        return crop, mask

    def __getitem__(self, index: int,
                    rng: np.random.Generator | None = None) -> dict:
        rec = self.record_index(int(index))
        row = self._index[rec]
        crop, mask = self._read_blob(rec)
        h, w = int(row["height"]), int(row["width"])
        meta = {
            "image": f"session-{int(row['digest']):08x}-{rec}",
            "object": "0",
            "category": 0,
            "im_size": (h, w),
        }
        if self.mode == "replay":
            # the live serve path's exact arithmetic (predict.py
            # prepare_input tail), through the shared guidance seam —
            # bit-identity is by construction, pinned by test
            heat = _crop_point_guidance(
                np.array(row["points"]),
                tuple(int(x) for x in row["bbox"]),
                (h, w), self.alpha, self.guidance)
            concat = np.concatenate(
                [np.clip(crop, 0.0, 255.0), heat[..., None]], axis=-1)
            return {"concat": concat.astype(np.float32),
                    "crop_gt": mask.astype(np.float32)[..., None],
                    "meta": meta}
        sample = {"image": crop.astype(np.float32),
                  "gt": mask.astype(np.float32),
                  "void_pixels": np.zeros((h, w), np.float32),
                  "meta": meta}
        if self.transform is not None:
            sample = self.transform(sample, rng)
        return sample

    def verify(self) -> list[int]:
        """Re-checksum EVERY record (quarantined included); returns the
        raw indices that fail — the ``dptpu-pack --verify`` engine,
        session flavor."""
        bad = []
        for rec in range(len(self._index)):
            try:
                self._read_blob(rec)
            except PackedRecordError:
                bad.append(rec)
        return bad

    def __str__(self) -> str:
        m = self.meta
        return (f"SessionLog({self.path},n={m['n_records']},"
                f"res={m['resolution']},idx={int(m['index_crc32']):08x})")


def _crop_point_guidance(points, bbox, resolution, alpha, family):
    """Deferred import of the guidance seam: keeps this module's import
    cost at numpy + stdlib (the packed-plane rule) while replay still
    goes through the ONE shared synthesis path."""
    from . import guidance

    return guidance.crop_point_guidance(points, bbox, resolution,
                                        alpha=alpha, family=family)


def verify_session_log(path: str) -> list[int]:
    """Raw record indices of ``path`` that fail verification."""
    return SessionLogDataset(path).verify()
