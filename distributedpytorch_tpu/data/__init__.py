"""Data subsystem: dataset, transforms, guidance synthesis, sharded loading."""

from . import guidance, transforms
from .fake import make_fake_voc
from .pipeline import (
    DataLoader,
    build_eval_transform,
    build_train_transform,
    collate,
)
from .voc import CATEGORY_NAMES, VOCInstanceSegmentation

__all__ = [
    "CATEGORY_NAMES",
    "DataLoader",
    "VOCInstanceSegmentation",
    "build_eval_transform",
    "build_train_transform",
    "collate",
    "guidance",
    "make_fake_voc",
    "transforms",
]
