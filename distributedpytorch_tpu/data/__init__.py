"""Data subsystem: dataset, transforms, guidance synthesis, sharded loading."""

from . import guidance, transforms
from .combine import CombinedDataset
from .governor import GOVERNOR_MODES, FeedActuators, FeedGovernor, feed_block
from .fake import make_fake_sbd, make_fake_voc
from .sbd import SBDInstanceSegmentation, SBDSemanticSegmentation
from .grain_pipeline import (GrainDataLoader, HAVE_GRAIN,
                             make_grain_loader)
from .pipeline import (
    DataLoader,
    build_eval_transform,
    build_prepared_post_transform,
    build_prepared_semantic_post_transform,
    build_semantic_eval_transform,
    build_semantic_train_transform,
    build_train_transform,
    collate,
)
from .packed import (
    PackedDataset,
    PackedRecordError,
    PackFormatError,
    pack_dataset,
    pack_name,
    verify_pack,
)
from .prepared import (
    PreparedInstanceDataset,
    PreparedSemanticDataset,
    cache_fingerprint,
)
from .voc import (
    CATEGORY_NAMES,
    VOCInstanceSegmentation,
    VOCSemanticSegmentation,
    ensure_voc,
)

__all__ = [
    "CATEGORY_NAMES",
    "CombinedDataset",
    "DataLoader",
    "FeedActuators",
    "FeedGovernor",
    "GOVERNOR_MODES",
    "feed_block",
    "VOCInstanceSegmentation",
    "ensure_voc",
    "VOCSemanticSegmentation",
    "HAVE_GRAIN",
    "PackedDataset",
    "PackedRecordError",
    "PackFormatError",
    "pack_dataset",
    "pack_name",
    "verify_pack",
    "build_eval_transform",
    "build_prepared_post_transform",
    "build_prepared_semantic_post_transform",
    "PreparedInstanceDataset",
    "PreparedSemanticDataset",
    "cache_fingerprint",
    "build_semantic_eval_transform",
    "build_semantic_train_transform",
    "build_train_transform",
    "collate",
    "guidance",
    "SBDInstanceSegmentation",
    "SBDSemanticSegmentation",
    "make_fake_sbd",
    "make_fake_voc",
    "GrainDataLoader",
    "make_grain_loader",
    "transforms",
]
