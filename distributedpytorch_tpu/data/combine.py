"""Dataset combination with exclusion — the ``CombineDBs`` contract.

The reference merged extra databases (SBD) into VOC train while excluding
images present in the held-out sets: ``CombineDBs([train, sbd],
excluded=[val])`` (reference train_pascal.py:27,150-154 — a dead path there
because the ``import sbd`` was commented out, making ``use_sbd=True`` a
``NameError``; SURVEY.md §2.4 inventories the contract).  Here it is a live,
source-agnostic combinator: any datasets exposing ``__len__``,
``__getitem__(i, rng)`` and ``sample_image_id(i)`` can be concatenated, and
any sample whose image id appears in an ``excluded`` dataset is dropped —
the standard guard against train/val leakage when mixing databases that
share images.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


class CombinedDataset:
    """Concatenation of datasets minus samples whose image id occurs in any
    ``excluded`` dataset, deduplicated across constituents by image id
    (first dataset listing an image contributes its samples; later
    constituents' copies are dropped — the CombineDBs rule that keeps
    VOC-train images from also entering via their SBD copies).  Each
    constituent keeps its own transform.

    Constituents must yield the same sample schema (key set): ``collate``
    stacks by the first sample's keys, so a mixed-schema batch would either
    KeyError or silently drop keys.  The constructor probes one sample per
    dataset and rejects mismatches unless ``allow_mixed_schemas=True``
    (only sensible for unbatched / manually-batched access).
    """

    def __init__(self, datasets: Sequence, excluded: Sequence = (),
                 allow_mixed_schemas: bool = False, dedupe: bool = True):
        self.datasets = list(datasets)
        if not allow_mixed_schemas and len(self.datasets) > 1:
            probe_rng = np.random.default_rng(0)
            schemas = [
                (frozenset(ds.__getitem__(0, probe_rng).keys()) if len(ds)
                 else frozenset())
                for ds in self.datasets
            ]
            live = {s for s in schemas if s}
            if len(live) > 1:
                raise ValueError(
                    "constituent datasets yield different sample schemas "
                    f"({[sorted(s) for s in live]}); such a mix cannot be "
                    "batched — pass allow_mixed_schemas=True only for "
                    "unbatched access")
        excluded_ids: set[str] = set()
        for ds in excluded:
            excluded_ids |= {ds.sample_image_id(i) for i in range(len(ds))}
        #: flat index: (dataset position, local sample index)
        self.index: list[tuple[int, int]] = []
        # Cross-constituent dedup, first dataset wins: VOC train overlaps
        # SBD train+val on ~1300 images, and the CombineDBs contract adds
        # each image once (its objects come from whichever dataset listed
        # the image first) — without this, overlapping images train twice
        # per epoch.
        # ``dedupe=False`` keeps every copy — for merging different VIEWS of
        # the same images (e.g. instance + semantic over one VOC root).
        seen_ids: set[str] = set()  # ids from earlier constituents
        for di, ds in enumerate(self.datasets):
            ds_ids = set()
            for si in range(len(ds)):
                im_id = ds.sample_image_id(si)
                ds_ids.add(im_id)
                if im_id in excluded_ids or (dedupe and im_id in seen_ids):
                    continue
                self.index.append((di, si))
            seen_ids |= ds_ids

    def __len__(self) -> int:
        return len(self.index)

    def sample_image_id(self, index: int) -> str:
        di, si = self.index[index]
        return self.datasets[di].sample_image_id(si)

    def __getitem__(self, index: int,
                    rng: np.random.Generator | None = None) -> dict:
        di, si = self.index[index]
        return self.datasets[di].__getitem__(si, rng)

    def __str__(self) -> str:
        parts = " + ".join(str(d) for d in self.datasets)
        return f"Combined({parts}, n={len(self)})"
