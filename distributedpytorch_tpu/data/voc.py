"""Instance-level Pascal VOC 2012 dataset.

TPU-native re-design of the reference dataset (/root/reference/pascal.py,
SURVEY.md §2.2): one example per (image, object) pair — *instance-level*, not
per-image — with void-pixel handling and a one-time JSON preprocess cache of
per-object categories filtered by an area threshold.

Differences from the reference, by design:

* a plain random-access source (``__getitem__``/``__len__``) with **no torch
  dependency** — batching/sharding live in :mod:`.pipeline`;
* the dataset root is an explicit argument (the reference hid it in a
  machine-specific ``mypath`` registry, pascal.py:13,33) — config owns paths;
* the tar download/MD5 path is kept behind ``download=True`` but integrity of
  an already-extracted tree is checked structurally (directories present)
  rather than by re-hashing a 2 GB tar on every construction.
"""

from __future__ import annotations

import collections
import contextlib
import hashlib
import json
import os
import shutil
import tarfile
import tempfile
import threading
import urllib.request

import numpy as np
from PIL import Image

#: canonical VOC2012 trainval archive (reference pascal.py:21-23)
URL = "http://host.robots.ox.ac.uk/pascal/VOC/voc2012/VOCtrainval_11-May-2012.tar"
FILE = "VOCtrainval_11-May-2012.tar"
MD5 = "6cd6e144f989b92b3379bac3b3de84fd"
BASE_DIR = "VOCdevkit/VOC2012"

CATEGORY_NAMES = [
    "background",
    "aeroplane", "bicycle", "bird", "boat", "bottle",
    "bus", "car", "cat", "chair", "cow",
    "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
]

# Probed ONCE at import: os.umask() can only be read by setting it, which
# mutates process-global state — doing that per-write raced loader/build
# worker threads (a thread could briefly run with umask 0, or a cache file
# could publish with the wrong mode).  Import happens before any workers.
_UMASK = os.umask(0)
os.umask(_UMASK)


def ensure_voc(root: str, download: bool = False) -> str:
    """Ensure an extracted VOC2012 tree under ``root``; returns its path.

    With ``download=True`` and no tree present, fetches the trainval tar and
    **MD5-verifies it before extracting** — a truncated/tampered download
    must never leave a half-extracted tree that the dir-exists check would
    then trust forever.  Multi-process: call on process 0 only, then
    barrier (the Trainer does this).
    """
    if not root:
        raise ValueError(
            "data root is empty — set data.root to the directory that holds "
            "(or should receive) the VOCdevkit tree")
    voc_root = os.path.join(root, BASE_DIR)
    if os.path.isdir(voc_root):
        return voc_root
    if not download:
        raise RuntimeError(
            f"VOC tree not found under {voc_root}; pass download=True or "
            "point root at an extracted VOCdevkit.")
    os.makedirs(root, exist_ok=True)
    fpath = os.path.join(root, FILE)
    if not (os.path.isfile(fpath) and _md5(fpath) == MD5):
        urllib.request.urlretrieve(URL, fpath)
        got = _md5(fpath)
        if got != MD5:
            raise RuntimeError(
                f"downloaded {FILE} is corrupt: md5 {got} != {MD5}")
    # Extract to a scratch dir and rename the finished tree into place: an
    # interrupted extractall must never leave a partial VOC2012 that the
    # dir-exists fast path above would then trust forever.
    tmp_dir = os.path.join(root, ".voc_extract.tmp")
    shutil.rmtree(tmp_dir, ignore_errors=True)
    os.makedirs(tmp_dir)
    with tarfile.open(fpath) as tar:
        tar.extractall(tmp_dir, filter="data")
    os.makedirs(os.path.dirname(voc_root), exist_ok=True)
    os.rename(os.path.join(tmp_dir, BASE_DIR), voc_root)
    shutil.rmtree(tmp_dir, ignore_errors=True)
    return voc_root


def load_obj_cache(path: str, im_ids: list[str]) -> dict | None:
    """Read a JSON instance cache; valid iff its key set matches ``im_ids``
    exactly (reference pascal.py:154-161).  Tolerates a concurrently
    half-written file (treated as absent) — see :func:`write_obj_cache`."""
    if not os.path.isfile(path):
        return None
    try:
        with open(path) as f:
            obj = json.load(f)
    # ValueError covers JSONDecodeError AND UnicodeDecodeError (binary junk)
    except (ValueError, OSError):
        return None
    if not isinstance(obj, dict):
        return None
    return obj if sorted(obj.keys()) == sorted(im_ids) else None


def write_obj_cache(path: str, obj_dict: dict) -> None:
    """Atomic JSON cache write: temp file + rename, so concurrent builders
    (every process of a multi-host run scans on first use) can never leave
    a truncated cache for a reader to crash on — last writer wins whole."""
    # mkstemp, not a pid-suffixed name: pids collide across the hosts of a
    # multi-host run sharing the dataset root over NFS/fuse.
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".tmp.",
        dir=os.path.dirname(path) or ".")
    try:
        # mkstemp creates 0600; publish with umask-honoring permissions so
        # other users of a shared dataset root can read the cache (umask
        # cached at import — see _UMASK above).
        os.fchmod(fd, 0o666 & ~_UMASK)
        with os.fdopen(fd, "w") as f:
            json.dump(obj_dict, f, indent=1)
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise


class _DecodeCache:
    """Thread-safe LRU of decoded images keyed by image index.

    FFCV-style decode-once (PAPERS.md: FFCV; Mohan et al. data-loading
    study): JPEG/PNG decode dominates per-sample host time, and the
    instance dataset revisits the same image for every one of its objects
    plus every epoch.  Values are stored pre-float (uint8 RGB, raw mask —
    ~0.7 MB per VOC image vs ~2.8 MB as float32); callers copy-convert so
    cached arrays are never mutated.
    """

    def __init__(self, max_items: int):
        self.max_items = max_items
        self._d: collections.OrderedDict = collections.OrderedDict()
        self._lock = threading.Lock()

    def get(self, key, load):
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
                return self._d[key]
        val = load()  # decode outside the lock: loader threads overlap
        with self._lock:
            self._d[key] = val
            self._d.move_to_end(key)
            while len(self._d) > self.max_items:
                self._d.popitem(last=False)
        return val

    # Process workers (the grain loader) pickle the dataset; locks don't
    # pickle and cached bytes shouldn't ship either — each worker process
    # rebuilds an empty, independent cache.
    def __getstate__(self):
        return {"max_items": self.max_items}

    def __setstate__(self, state):
        self.__init__(state["max_items"])


class VOCInstanceSegmentation:
    """Random-access source of (image, single-object mask, void mask) samples.

    Each index addresses one *object instance*: ``obj_list[i] = (image_idx,
    object_idx)``, built from the per-image category cache and skipping
    objects filtered out by ``area_thres`` (reference pascal.py:107-116).

    ``__getitem__`` returns the reference's sample contract
    (pascal.py:122-137)::

        {'image':       float32 (H, W, 3) RGB,
         'gt':          float32 (H, W) binary mask of ONE object,
         'void_pixels': float32 (H, W) mask of 255-labelled pixels,
         'meta':        {'image', 'object', 'category', 'im_size'}}   # retname

    A ``transform`` (see :mod:`.transforms`) is applied if given; stochastic
    transforms receive the ``rng`` passed to ``__getitem__`` — the loader
    derives it from (seed, epoch, index) so every sample is reproducible.
    """

    def __init__(
        self,
        root: str,
        split="val",
        transform=None,
        download: bool = False,
        preprocess: bool = False,
        area_thres: int = 0,
        retname: bool = True,
        suppress_void_pixels: bool = True,
        default: bool = False,
        decode_cache: int = 0,
    ):
        self.root = root
        self.transform = transform
        self.area_thres = area_thres
        self.retname = retname
        self.suppress_void_pixels = suppress_void_pixels
        self.default = default
        #: decode-once LRU over ``decode_cache`` images (0 = off); see
        #: :class:`_DecodeCache`
        self._cache = _DecodeCache(decode_cache) if decode_cache > 0 else None
        self.split = sorted([split] if isinstance(split, str) else list(split))

        voc_root = os.path.join(root, BASE_DIR)
        self._image_dir = os.path.join(voc_root, "JPEGImages")
        self._mask_dir = os.path.join(voc_root, "SegmentationObject")
        self._cat_dir = os.path.join(voc_root, "SegmentationClass")
        splits_dir = os.path.join(voc_root, "ImageSets", "Segmentation")

        ensure_voc(root, download=download)

        area_suffix = f"_area_thres-{area_thres}" if area_thres else ""
        self.obj_list_file = os.path.join(
            splits_dir, "_".join(self.split) + "_instances" + area_suffix + ".txt"
        )

        self.im_ids: list[str] = []
        self.images: list[str] = []
        self.masks: list[str] = []
        self.categories: list[str] = []
        for splt in self.split:
            with open(os.path.join(splits_dir, splt + ".txt")) as f:
                ids = f.read().splitlines()
            for line in ids:
                paths = (
                    os.path.join(self._image_dir, line + ".jpg"),
                    os.path.join(self._cat_dir, line + ".png"),
                    os.path.join(self._mask_dir, line + ".png"),
                )
                for p in paths:
                    if not os.path.isfile(p):
                        raise FileNotFoundError(p)
                self.im_ids.append(line)
                self.images.append(paths[0])
                self.categories.append(paths[1])
                self.masks.append(paths[2])

        if preprocess or not self._load_obj_cache():
            self._preprocess()

        # One entry per surviving object instance.
        self.obj_list: list[tuple[int, int]] = []
        n_images_used = 0
        for ii, im_id in enumerate(self.im_ids):
            cats = self.obj_dict[im_id]
            live = [(ii, jj) for jj, cat in enumerate(cats) if cat != -1]
            self.obj_list.extend(live)
            n_images_used += bool(live)
        self.num_images = n_images_used

    # -- construction helpers ------------------------------------------------

    def _load_obj_cache(self) -> bool:
        obj = load_obj_cache(self.obj_list_file, self.im_ids)
        if obj is None:
            return False
        self.obj_dict = obj
        return True

    def _preprocess(self) -> None:
        """One-time scan: decode every instance + class PNG, area-filter each
        object, cache image id -> [category or -1, ...] as JSON (reference
        pascal.py:163-195)."""
        self.obj_dict = {}
        for ii, im_id in enumerate(self.im_ids):
            inst = np.array(Image.open(self.masks[ii]))
            ids = np.unique(inst)
            n_obj = int(ids[-2] if ids[-1] == 255 else ids[-1])
            cats = np.array(Image.open(self.categories[ii]))
            cat_ids = []
            for jj in range(n_obj):
                rows, cols = np.where(inst == jj + 1)
                if rows.size > self.area_thres:
                    cat_ids.append(int(cats[rows[0], cols[0]]))
                else:
                    cat_ids.append(-1)
            self.obj_dict[im_id] = cat_ids
        write_obj_cache(self.obj_list_file, self.obj_dict)

    # -- sample access -------------------------------------------------------

    def __len__(self) -> int:
        return len(self.obj_list)

    def sample_image_id(self, index: int) -> str:
        """Image id owning sample ``index`` (CombinedDataset exclusion key)."""
        return self.im_ids[self.obj_list[index][0]]

    def __getitem__(self, index: int, rng: np.random.Generator | None = None) -> dict:
        im_ii, obj_ii = self.obj_list[index]
        img, target, void = self._load_instance(im_ii, obj_ii)
        sample = {"image": img, "gt": target, "void_pixels": void}
        if self.retname:
            sample["meta"] = {
                "image": self.im_ids[im_ii],
                "object": str(obj_ii),
                "category": self.obj_dict[self.im_ids[im_ii]][obj_ii],
                "im_size": (img.shape[0], img.shape[1]),
            }
        if self.transform is not None:
            sample = self.transform(sample, rng)
        return sample

    def decode_raw(self, im_ii: int) -> tuple[np.ndarray, np.ndarray]:
        """The decoded pair for image ``im_ii`` — (uint8 RGB, raw
        instance mask), exactly the arrays the sample math consumes.
        Public because the packer (data/packed.py) stores these bytes
        and re-runs ``__getitem__``'s arithmetic on them, which is what
        makes packed samples bit-identical to this class's."""
        def decode():
            return (np.array(Image.open(self.images[im_ii]).convert("RGB"),
                             np.uint8),
                    np.array(Image.open(self.masks[im_ii])))

        return (self._cache.get(im_ii, decode)
                if self._cache is not None else decode())

    def _load_instance(self, im_ii: int, obj_ii: int):
        """Decode one (image, object) pair (reference pascal.py:232-263;
        the computed-but-discarded other-class masks are not reproduced)."""
        img8, inst_raw = self.decode_raw(im_ii)
        # astype COPIES, so the cached uint8 arrays are never mutated by the
        # void-suppression below or by downstream transforms.
        img = img8.astype(np.float32)
        inst = inst_raw.astype(np.float32)
        void = inst == 255
        if self.suppress_void_pixels:
            inst[void] = 0
        if self.default:
            target = inst
        else:
            target = (inst == obj_ii + 1).astype(np.float32)
        return img, target, void.astype(np.float32)

    def __str__(self) -> str:
        return f"VOC2012(split={self.split},area_thres={self.area_thres})"


class VOCSemanticSegmentation:
    """Per-image semantic VOC2012: class-id masks from ``SegmentationClass``.

    The multi-class counterpart of :class:`VOCInstanceSegmentation` for the
    DeepLabV3 semantic configs of BASELINE.md (configs 1 and 4).  The
    reference never trained this mode — its dataset is instance-level — but
    its class PNGs are read for the category cache (reference
    pascal.py:171-176), and this class exposes them directly:

        {'image': float32 (H, W, 3) RGB,
         'gt':    float32 (H, W) class ids 0..20, void pixels = 255,
         'meta':  {'image', 'im_size'}}                            # retname

    Void stays *in-band* as 255 (torchvision convention): the softmax CE loss
    masks it via ``ignore_index`` (ops.losses.softmax_xent_ignore) and the
    mIoU metric drops those pixels, so no separate void channel is needed.
    """

    def __init__(self, root: str, split="val", transform=None,
                 retname: bool = True, download: bool = False,
                 decode_cache: int = 0):
        self.root = root
        self.transform = transform
        self.retname = retname
        self.split = sorted([split] if isinstance(split, str) else list(split))
        self.nclass = len(CATEGORY_NAMES)
        self._cache = _DecodeCache(decode_cache) if decode_cache > 0 else None

        voc_root = os.path.join(root, BASE_DIR)
        image_dir = os.path.join(voc_root, "JPEGImages")
        cat_dir = os.path.join(voc_root, "SegmentationClass")
        splits_dir = os.path.join(voc_root, "ImageSets", "Segmentation")
        ensure_voc(root, download=download)

        self.im_ids: list[str] = []
        self.images: list[str] = []
        self.categories: list[str] = []
        for splt in self.split:
            with open(os.path.join(splits_dir, splt + ".txt")) as f:
                ids = f.read().splitlines()
            for line in ids:
                img = os.path.join(image_dir, line + ".jpg")
                cat = os.path.join(cat_dir, line + ".png")
                for p in (img, cat):
                    if not os.path.isfile(p):
                        raise FileNotFoundError(p)
                self.im_ids.append(line)
                self.images.append(img)
                self.categories.append(cat)

    def __len__(self) -> int:
        return len(self.im_ids)

    def sample_image_id(self, index: int) -> str:
        """Image id of sample ``index`` (CombinedDataset exclusion key)."""
        return self.im_ids[index]

    def decode_raw(self, index: int) -> tuple[np.ndarray, np.ndarray]:
        """Decoded (uint8 RGB, raw class-id mask) for image ``index`` —
        the packer's source bytes (see the instance class's
        ``decode_raw``)."""
        def decode():
            return (np.array(Image.open(self.images[index]).convert("RGB"),
                             np.uint8),
                    np.array(Image.open(self.categories[index])))

        return (self._cache.get(index, decode)
                if self._cache is not None else decode())

    def __getitem__(self, index: int,
                    rng: np.random.Generator | None = None) -> dict:
        img8, gt_raw = self.decode_raw(index)
        img = img8.astype(np.float32)  # astype copies; cache never mutated
        gt = gt_raw.astype(np.float32)
        sample = {"image": img, "gt": gt}
        if self.retname:
            sample["meta"] = {"image": self.im_ids[index],
                              "im_size": (img.shape[0], img.shape[1])}
        if self.transform is not None:
            sample = self.transform(sample, rng)
        return sample

    def __str__(self) -> str:
        return f"VOC2012Semantic(split={self.split})"


def _md5(path: str) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()
