"""SBD (Semantic Boundaries Dataset) instance-segmentation source.

The reference's dataset-merge path combined VOC with SBD via ``CombineDBs``
(reference train_pascal.py:150-154) but was dead code: ``import sbd`` stayed
commented (:29), so ``use_sbd=True`` raised ``NameError``.  This module is
the live SBD side of that contract — the same sample schema as
:class:`.voc.VOCInstanceSegmentation` (one sample per (image, object);
``{'image','gt','void_pixels','meta'}``) read from SBD's Matlab layout::

    <root>/benchmark_RELEASE/dataset/
        train.txt  val.txt
        img/<id>.jpg
        inst/<id>.mat     # GTinst struct: Segmentation (H,W ids), Categories
        cls/<id>.mat      # GTcls  struct: Segmentation (class ids) [unused]

so ``CombinedDataset([voc_train, sbd], excluded=[voc_val])`` finally works
as the reference intended (SBD training images overlap VOC val — exclusion
is load-bearing, combine.py).

scipy reads the .mat structs; like everything else in the data layer the
import is deferred so environments without scipy only pay when SBD is used.
"""

from __future__ import annotations

import os

import numpy as np
from PIL import Image

#: the tarball's internal prefix, matching the VOC BASE_DIR convention
BASE_DIR = os.path.join("benchmark_RELEASE", "dataset")


def _load_mat_struct(path: str, key: str):
    import scipy.io

    return scipy.io.loadmat(path, squeeze_me=True,
                            struct_as_record=False)[key]


class SBDInstanceSegmentation:
    """Instance-indexed SBD with the VOC sample contract.

    Constructor surface mirrors ``VOCInstanceSegmentation`` (split(s),
    area_thres, retname, suppress_void_pixels, per-sample ``rng``
    pass-through to the transform); the per-image object categories come
    from ``GTinst.Categories`` and are cached to the same JSON scheme as
    VOC's preprocess cache (reference pascal.py:154-195 semantics).
    """

    def __init__(
        self,
        root: str,
        split="train",
        transform=None,
        preprocess: bool = False,
        area_thres: int = 0,
        retname: bool = True,
        suppress_void_pixels: bool = True,
        decode_cache: int = 0,
    ):
        self.root = root
        self.transform = transform
        self.area_thres = area_thres
        self.retname = retname
        self.suppress_void_pixels = suppress_void_pixels
        from .voc import _DecodeCache
        #: decode-once LRU over (jpeg, GTinst) per image — SBD is visited
        #: once per OBJECT per epoch, same access pattern VOC caches for
        self._cache = _DecodeCache(decode_cache) if decode_cache > 0 else None
        self.split = sorted([split] if isinstance(split, str)
                            else list(split))

        base = os.path.join(root, BASE_DIR)
        self._image_dir = os.path.join(base, "img")
        self._inst_dir = os.path.join(base, "inst")

        self.im_ids: list[str] = []
        for splt in self.split:
            with open(os.path.join(base, splt + ".txt")) as f:
                ids = [l for l in f.read().splitlines() if l.strip()]
            for line in ids:
                for p in (os.path.join(self._image_dir, line + ".jpg"),
                          os.path.join(self._inst_dir, line + ".mat")):
                    if not os.path.isfile(p):
                        raise FileNotFoundError(p)
                self.im_ids.append(line)

        area_suffix = f"_area_thres-{area_thres}" if area_thres else ""
        self.obj_list_file = os.path.join(
            base, "_".join(self.split) + "_instances" + area_suffix + ".txt")
        if preprocess or not self._load_obj_cache():
            self._preprocess()

        self.obj_list: list[tuple[int, int]] = []
        for ii, im_id in enumerate(self.im_ids):
            self.obj_list.extend(
                (ii, jj) for jj, cat in enumerate(self.obj_dict[im_id])
                if cat != -1)

    def _load_obj_cache(self) -> bool:
        from .voc import load_obj_cache
        obj = load_obj_cache(self.obj_list_file, self.im_ids)
        if obj is None:
            return False
        self.obj_dict = obj
        return True

    def _preprocess(self) -> None:
        """Scan every GTinst once: object count + per-object category, with
        the VOC area filter (objects at or under ``area_thres`` px -> -1)."""
        self.obj_dict = {}
        for ii, im_id in enumerate(self.im_ids):
            gt = _load_mat_struct(
                os.path.join(self._inst_dir, im_id + ".mat"), "GTinst")
            inst = np.asarray(gt.Segmentation)
            cats = np.atleast_1d(np.asarray(gt.Categories)).astype(int)
            cat_ids = []
            for jj, cat in enumerate(cats):
                if int((inst == jj + 1).sum()) > self.area_thres:
                    cat_ids.append(int(cat))
                else:
                    cat_ids.append(-1)
            self.obj_dict[im_id] = cat_ids
        from .voc import write_obj_cache
        write_obj_cache(self.obj_list_file, self.obj_dict)

    def __len__(self) -> int:
        return len(self.obj_list)

    def sample_image_id(self, index: int) -> str:
        """Image id owning sample ``index`` — the CombinedDataset exclusion
        key (SBD ids are VOC-style ``2008_000123`` strings, so VOC-val
        exclusion matches directly)."""
        return self.im_ids[self.obj_list[index][0]]

    def decode_raw(self, im_ii: int) -> tuple[np.ndarray, np.ndarray]:
        """Decoded (uint8 RGB, raw GTinst instance mask) for image
        ``im_ii`` — the packer's source bytes (data/packed.py re-runs
        this class's sample arithmetic on them, bit-identically)."""
        im_id = self.im_ids[im_ii]

        def decode():
            img8 = np.array(Image.open(os.path.join(
                self._image_dir, im_id + ".jpg")).convert("RGB"), np.uint8)
            gt = _load_mat_struct(
                os.path.join(self._inst_dir, im_id + ".mat"), "GTinst")
            return img8, np.asarray(gt.Segmentation)

        return (self._cache.get(im_ii, decode)
                if self._cache is not None else decode())

    def __getitem__(self, index: int,
                    rng: np.random.Generator | None = None) -> dict:
        im_ii, obj_ii = self.obj_list[index]
        im_id = self.im_ids[im_ii]
        img8, inst_raw = self.decode_raw(im_ii)
        # astype COPIES — cached arrays are never mutated downstream
        img = img8.astype(np.float32)
        inst = inst_raw.astype(np.float32)
        void = inst == 255
        if self.suppress_void_pixels:
            inst = np.where(void, 0.0, inst)
        sample = {
            "image": img,
            "gt": (inst == obj_ii + 1).astype(np.float32),
            "void_pixels": void.astype(np.float32),
        }
        if self.retname:
            sample["meta"] = {
                "image": im_id,
                "object": str(obj_ii),
                "category": self.obj_dict[im_id][obj_ii],
                "im_size": (img.shape[0], img.shape[1]),
            }
        if self.transform is not None:
            sample = self.transform(sample, rng)
        return sample

    def __str__(self) -> str:
        return f"SBD(split={self.split},area_thres={self.area_thres})"


class SBDSemanticSegmentation:
    """Per-image semantic SBD: class-id masks from the ``GTcls`` structs.

    The semantic counterpart of :class:`SBDInstanceSegmentation`, with the
    :class:`.voc.VOCSemanticSegmentation` sample contract (``image``/``gt``
    class ids 0..20 with in-band 255 void, ``meta``).  Its purpose is the
    standard "train_aug" recipe for the DeepLab configs: SBD's ~10k
    annotated training images merged into VOC semantic training via
    ``CombinedDataset`` with the VOC-val overlap excluded — the semantic
    twin of the reference's instance-side ``use_sbd`` merge
    (train_pascal.py:150-154).
    """

    def __init__(self, root: str, split="train", transform=None,
                 retname: bool = True, decode_cache: int = 0):
        from .voc import CATEGORY_NAMES, _DecodeCache

        self.root = root
        self.transform = transform
        self.retname = retname
        self.nclass = len(CATEGORY_NAMES)
        self._cache = _DecodeCache(decode_cache) if decode_cache > 0 else None
        self.split = sorted([split] if isinstance(split, str)
                            else list(split))
        base = os.path.join(root, BASE_DIR)
        self._image_dir = os.path.join(base, "img")
        self._cls_dir = os.path.join(base, "cls")
        self.im_ids: list[str] = []
        #: image / label file paths (also the prepared cache's
        #: content-stamp probe — regenerated jpgs OR .mat labels must
        #: change the fingerprint)
        self.images: list[str] = []
        self.labels: list[str] = []
        for splt in self.split:
            with open(os.path.join(base, splt + ".txt")) as f:
                # .strip() filter matching SBDInstanceSegmentation: a
                # whitespace-only line must not become a phantom id
                ids = [l for l in f.read().splitlines() if l.strip()]
            for line in ids:
                img = os.path.join(self._image_dir, line + ".jpg")
                cls = os.path.join(self._cls_dir, line + ".mat")
                for p in (img, cls):
                    if not os.path.isfile(p):
                        raise FileNotFoundError(p)
                self.im_ids.append(line)
                self.images.append(img)
                self.labels.append(cls)

    def __len__(self) -> int:
        return len(self.im_ids)

    def sample_image_id(self, index: int) -> str:
        return self.im_ids[index]

    def decode_raw(self, index: int) -> tuple[np.ndarray, np.ndarray]:
        """Decoded (uint8 RGB, raw GTcls class-id mask) for image
        ``index`` — the packer's source bytes."""
        im_id = self.im_ids[index]

        def decode():
            img8 = np.array(Image.open(self.images[index]).convert("RGB"),
                            np.uint8)
            gt = _load_mat_struct(
                os.path.join(self._cls_dir, im_id + ".mat"), "GTcls")
            return img8, np.asarray(gt.Segmentation)

        return (self._cache.get(index, decode)
                if self._cache is not None else decode())

    def __getitem__(self, index: int,
                    rng: np.random.Generator | None = None) -> dict:
        im_id = self.im_ids[index]
        img8, gt_raw = self.decode_raw(index)
        img = img8.astype(np.float32)  # astype copies; cache never mutated
        sample = {"image": img, "gt": gt_raw.astype(np.float32)}
        if self.retname:
            sample["meta"] = {"image": im_id,
                              "im_size": (img.shape[0], img.shape[1])}
        if self.transform is not None:
            sample = self.transform(sample, rng)
        return sample

    def __str__(self) -> str:
        return f"SBDSemantic(split={self.split})"
