"""Host-side data-augmentation transforms over dict samples.

TPU-first re-design of the reference transform library
(/root/reference/custom_transforms.py, inventoried in SURVEY.md §2.3).  The
sample is a ``dict[str, np.ndarray]`` flowing through a ``Compose`` chain; the
stringly-typed key contract of the reference is kept on purpose (``image``,
``gt``, ``void_pixels``, ``crop_image``, ``crop_gt``, ``nellipseWithGaussians``,
``concat``, …) so a reference user finds the same pipeline vocabulary.

TPU-relevant design choices (SURVEY.md §7 hard parts a-c):

* everything here runs on **host** (numpy + OpenCV) — random geometric warps
  and mask-dependent crops are dynamic-shape control flow that would defeat
  XLA; the device only ever sees the fixed-shape output of ``FixedResize``.
* randomness is an explicit ``np.random.Generator`` passed to ``__call__`` —
  no global RNG, so per-sample seeds make the pipeline reproducible and safe
  to shard across hosts.
* the terminal transform is :class:`ToArray` (HWC float32), not a CHW
  ``ToTensor`` — NHWC is the TPU-native layout.

Keys named ``id``/``meta`` are metadata and never array-processed; ``bbox`` and
``crop_relax`` are coordinate payloads with their own rules (matching the
exemption lists at reference custom_transforms.py:108,166,482).
"""

from __future__ import annotations

from typing import Mapping, Sequence

import numpy as np

from .. import imaging
from ..utils import helpers
from . import guidance

#: sample keys that are never treated as image arrays
META_KEYS = ("id", "meta")


def _is_meta(key: str) -> bool:
    # Exact-match on purpose: the reference's substring test (`'id' in elem`,
    # custom_transforms.py:108) silently matched 'vo*id*_pixels' and skipped it
    # in ToTensor — a latent quirk we do not reproduce.
    return key in META_KEYS


def _require_rng(rng: np.random.Generator | None) -> np.random.Generator:
    return rng if rng is not None else np.random.default_rng()


class Transform:
    """Base: ``__call__(sample, rng) -> sample``.  Deterministic transforms
    ignore ``rng``."""

    def __call__(self, sample: dict, rng: np.random.Generator | None = None) -> dict:
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}()"


class Compose(Transform):
    """Chain transforms, threading one RNG through the stochastic ones."""

    def __init__(self, transforms: Sequence[Transform]):
        self.transforms = list(transforms)

    def __call__(self, sample, rng=None):
        for t in self.transforms:
            sample = t(sample, rng)
        return sample

    def __repr__(self):
        inner = ", ".join(repr(t) for t in self.transforms)
        return f"Compose([{inner}])"


# ---------------------------------------------------------------------------
# geometric transforms
# ---------------------------------------------------------------------------

class RandomHorizontalFlip(Transform):
    """p=0.5 left-right flip of every array key (reference
    custom_transforms.py:202-218)."""

    def __init__(self, p: float = 0.5):
        self.p = p

    def __call__(self, sample, rng=None):
        rng = _require_rng(rng)
        if rng.random() < self.p:
            for key, val in sample.items():
                if not _is_meta(key):
                    sample[key] = imaging.flip_h(val)
        return sample

    def __repr__(self):
        return f"RandomHorizontalFlip(p={self.p})"


def _warp_interpolation(key: str, arr: np.ndarray, semseg: bool) -> int:
    """Reference rule (custom_transforms.py:117-122): nearest for arrays whose
    values are all in {0, 1, 255} (binary / void masks), nearest for gt under
    semantic-segmentation mode, cubic otherwise."""
    if ((arr == 0) | (arr == 1) | (arr == 255)).all():
        return imaging.NEAREST
    if semseg and "gt" in key:
        return imaging.NEAREST
    return imaging.CUBIC


class ScaleNRotate(Transform):
    """Random in-plane rotation + isotropic zoom about the image center.

    Behavior-compatible with reference custom_transforms.py:76-142: tuple args
    draw uniformly from the (symmetric) range, list args pick one entry;
    ``cv2.warpAffine`` on every array key with per-key interpolation and the
    reference's uint8 cast before warping (guidance/image values live in
    [0, 255] at this point in the pipeline); ``bb_mask`` keys warp with a 255
    border (outside-bbox convention).
    """

    def __init__(self, rots=(-30, 30), scales=(0.75, 1.25), semseg: bool = False):
        if isinstance(rots, tuple) != isinstance(scales, tuple):
            raise TypeError("rots and scales must both be ranges or both be lists")
        self.rots = rots
        self.scales = scales
        self.semseg = semseg

    def _draw(self, rng: np.random.Generator) -> tuple[float, float]:
        if isinstance(self.rots, tuple):
            rot = float(rng.uniform(self.rots[0], self.rots[1]))
            sc = float(rng.uniform(self.scales[0], self.scales[1]))
        else:
            rot = float(self.rots[rng.integers(0, len(self.rots))])
            sc = float(self.scales[rng.integers(0, len(self.scales))])
        return rot, sc

    def __call__(self, sample, rng=None):
        rng = _require_rng(rng)
        rot, sc = self._draw(rng)
        for key in list(sample.keys()):
            if _is_meta(key):
                continue
            arr = sample[key]
            h, w = arr.shape[:2]
            M = imaging.rotation_matrix((w / 2, h / 2), rot, sc)
            flag = _warp_interpolation(key, arr, self.semseg)
            # Border fill: 255 for bb_mask (outside-bbox convention) AND for
            # class-id gt under semseg — warped-out regions must become void
            # (ignore_index), not background, or the CE loss would supervise
            # synthetic class-0 pixels over black image padding.
            border = 255 if ("bb_mask" in key or
                             (self.semseg and "gt" in key)) else 0
            sample[key] = imaging.warp_affine(
                arr.astype(np.uint8), M, (h, w), flag, border
            )
        return sample

    def __repr__(self):
        return f"ScaleNRotate(rots={self.rots}, scales={self.scales})"


class FixedResize(Transform):
    """Resize each key to ``resolutions[key]``; prune keys not listed.

    Behavior-compatible with reference custom_transforms.py:145-199, including
    its two load-bearing quirks (SURVEY.md §2.3):

    * a key mapped to ``None`` passes through untouched — how the val pipeline
      keeps full-resolution ``gt``/``void_pixels`` for full-image evaluation;
    * **keys absent from ``resolutions`` are deleted** — how the sample's key
      set is pruned before batching (variable-size leftovers must not reach
      the collate step).

    ``bbox``/``crop_relax``/``meta`` are exempt; ``extreme_points_coord`` is
    rescaled by the bbox→resolution ratio rather than resized.
    """

    def __init__(
        self,
        resolutions: Mapping[str, tuple[int, int] | None] | None = None,
        flagvals: Mapping[str, int] | None = None,
    ):
        self.resolutions = resolutions
        self.flagvals = flagvals
        if flagvals is not None and resolutions is not None:
            assert set(flagvals) == set(resolutions)

    def __call__(self, sample, rng=None):
        if self.resolutions is None:
            return sample
        for key in list(sample.keys()):
            exempt = "meta" in key or "bbox" in key or "crop_relax" in key
            if exempt:
                continue
            if key == "extreme_points_coord":
                if key not in self.resolutions:
                    continue
                # This repo's bbox convention is an inclusive 4-tuple
                # (x_min, y_min, x_max, y_max) from helpers.get_bbox; points
                # are (x, y) pairs, resolutions are (H, W) — scale x by the
                # width ratio and y by the height ratio.
                bbox = sample["bbox"]
                crop_wh = np.array(
                    [bbox[2] - bbox[0] + 1, bbox[3] - bbox[1] + 1], dtype=np.float32
                )
                res_h, res_w = self.resolutions[key]
                scale = np.array([res_w, res_h], dtype=np.float32) / crop_wh
                sample[key] = np.round(sample[key] * scale).astype(np.int64)
                continue
            if key not in self.resolutions:
                del sample[key]
                continue
            res = self.resolutions[key]
            if res is None:
                continue
            flag = None if self.flagvals is None else self.flagvals[key]
            val = sample[key]
            if isinstance(val, list):
                # A list of per-channel crops: resize elementwise and stack on
                # a trailing axis (reference custom_transforms.py:177-188).
                resized = [helpers.fixed_resize(v, res, flagval=flag) for v in val]
                sample[key] = np.stack(resized, axis=-1).astype(np.float32)
            else:
                sample[key] = helpers.fixed_resize(val, res, flagval=flag)
        return sample

    def __repr__(self):
        return f"FixedResize({self.resolutions})"


# ---------------------------------------------------------------------------
# mask-driven crops
# ---------------------------------------------------------------------------

def _crop_one(img, mask, relax, zero_pad):
    if mask.max() == 0:
        return np.zeros(img.shape, dtype=img.dtype)
    return helpers.crop_from_mask(img, mask, relax=relax, zero_pad=zero_pad)


def _crop_elems(sample, crop_elems, mask_elem, relax, zero_pad):
    """Shared crop loop: for each element, crop against every channel of the
    mask element; single-channel masks produce an array, multi-channel masks a
    list of crops (reference custom_transforms.py:343-371)."""
    target = sample[mask_elem]
    if target.ndim == 2:
        target = target[..., np.newaxis]
    for elem in crop_elems:
        img = sample[elem]
        if elem == mask_elem and img.ndim == 2:
            img = img[..., np.newaxis]
        crops = []
        for k in range(target.shape[-1]):
            src = img[..., k] if elem == mask_elem else img
            crops.append(_crop_one(src, target[..., k], relax, zero_pad))
        sample["crop_" + elem] = crops[0] if len(crops) == 1 else crops
    return sample


class CropFromMaskStatic(Transform):
    """Crop listed elements to the gt bbox expanded by a fixed ``relax``
    border, zero-padding beyond image borders (reference
    custom_transforms.py:329-375; the live train/val path uses relax=50,
    zero_pad=True per train_pascal.py:126,137)."""

    def __init__(self, crop_elems=("image", "gt"), mask_elem="gt", relax=0, zero_pad=False):
        self.crop_elems = crop_elems
        self.mask_elem = mask_elem
        self.relax = relax
        self.zero_pad = zero_pad

    def __call__(self, sample, rng=None):
        sample = _crop_elems(sample, self.crop_elems, self.mask_elem,
                             self.relax, self.zero_pad)
        # Record the (relaxed) crop bbox: FixedResize rescales point
        # coordinates by it, and the evaluator's crop->fullmask paste-back can
        # reuse it instead of recomputing from the full-res gt.
        mask = sample[self.mask_elem]
        if mask.ndim == 3:
            mask = mask[..., 0]
        bbox = helpers.get_bbox(mask, pad=self.relax, zero_pad=self.zero_pad)
        if bbox is None:
            # Empty mask: the crop was a full-image passthrough of zeros;
            # record the full-image box so batches keep a consistent key set.
            bbox = (0, 0, mask.shape[1] - 1, mask.shape[0] - 1)
        sample["bbox"] = np.asarray(bbox, dtype=np.int64)
        return sample

    def __repr__(self):
        return (f"CropFromMaskStatic(elems={self.crop_elems}, relax={self.relax}, "
                f"zero_pad={self.zero_pad})")


class FusedCropResize(Transform):
    """``CropFromMaskStatic`` + ``FixedResize`` in one pass.

    A pipeline-level fusion, not a reference transform: each listed element
    is resized straight from its (relaxed, zero-padded) bbox window to
    ``size`` by the native ``crop_resize`` kernel, never materializing the
    intermediate crop — the two-stage pair's biggest allocation on the hot
    path.  Output contract matches the pair: ``crop_<elem>`` keys at
    ``size``, the recorded ``bbox``, FixedResize's pruning rule (keys not
    produced/kept are deleted; ``meta``/``bbox``/``crop_relax`` exempt),
    and the same per-element interpolation rule (nearest for binary /
    255-valued windows, cubic otherwise).

    Falls back to the two-stage path when the native library is absent.
    """

    def __init__(self, crop_elems=("image", "gt"), mask_elem="gt",
                 relax=0, zero_pad=False, size=(512, 512)):
        self.crop_elems = crop_elems
        self.mask_elem = mask_elem
        self.relax = relax
        self.zero_pad = zero_pad
        self.size = tuple(size)

    def _window_flag(self, arr: np.ndarray, bbox) -> int:
        """``helpers.resize_interp_flag`` evaluated on the in-image part of
        the window (the zero padding only adds 0s, which never change
        binary-ness)."""
        win = arr[max(bbox[1], 0): bbox[3] + 1, max(bbox[0], 0): bbox[2] + 1]
        return helpers.resize_interp_flag(win)

    def _two_stage(self, sample, rng):
        return Compose([
            CropFromMaskStatic(crop_elems=self.crop_elems,
                               mask_elem=self.mask_elem,
                               relax=self.relax, zero_pad=self.zero_pad),
            FixedResize(resolutions={
                "crop_" + e: self.size for e in self.crop_elems}),
        ])(sample, rng)

    def __call__(self, sample, rng=None):
        from .. import native_ops

        if not (native_ops.enabled() and native_ops.has_crop_resize()):
            return self._two_stage(sample, rng)
        if np.asarray(sample[self.mask_elem]).ndim != 2:
            # Multi-channel mask: the pair's contract is per-channel crop
            # LISTS (custom_transforms.py:350-370) which the fused kernel
            # does not reproduce — route through the exact two-stage path.
            return self._two_stage(sample, rng)

        mask = sample[self.mask_elem]
        bbox = helpers.get_bbox(mask, pad=self.relax, zero_pad=self.zero_pad)
        for elem in self.crop_elems:
            arr = sample[elem]
            if bbox is None:  # empty mask -> zeros at the output size
                shape = self.size + arr.shape[2:]
                sample["crop_" + elem] = np.zeros(shape, np.float32)
                continue
            sample["crop_" + elem] = native_ops.crop_resize(
                arr, bbox, self.size, self._window_flag(arr, bbox))
        if bbox is None:
            bbox = (0, 0, mask.shape[1] - 1, mask.shape[0] - 1)
        sample["bbox"] = np.asarray(bbox, dtype=np.int64)
        # FixedResize's pruning rule: everything not produced goes (with
        # FixedResize's own exemptions: meta/bbox/crop_relax AND the
        # extreme_points_coord payload it rescales rather than deletes).
        produced = {"crop_" + e for e in self.crop_elems}
        for key in list(sample.keys()):
            if key in produced or "meta" in key or "bbox" in key \
                    or "crop_relax" in key or key == "extreme_points_coord":
                continue
            del sample[key]
        return sample

    def __repr__(self):
        return (f"FusedCropResize(elems={self.crop_elems}, relax={self.relax},"
                f" zero_pad={self.zero_pad}, size={self.size})")


class CropFromMask(Transform):
    """Zoom-normalizing crop: pick the relax border so the object occupies a
    target fraction of the final ``d``×``d`` crop.

    Behavior-compatible with reference custom_transforms.py:377-452: at val the
    object's long side maps to ``sqrt(0.5)·d``; at train the target is drawn
    uniformly in [``sqrt(0.45)·d``, ``sqrt(0.6)·d``]; a floor keeps tiny
    objects from being zoomed past 4% of the crop area; the chosen border is
    recorded as ``sample['crop_relax']`` for paste-back.
    """

    def __init__(self, crop_elems=("image", "gt"), mask_elem="gt", zero_pad=False,
                 d: int = 512, is_val: bool = True):
        self.crop_elems = crop_elems
        self.mask_elem = mask_elem
        self.zero_pad = zero_pad
        self.d = d
        self.is_val = is_val
        dz_val = int(np.sqrt(d * d * 0.5))
        min_object_dim = d / 5
        self.floor = ((d - dz_val) * min_object_dim) / (2 * dz_val)
        self.dz_val = dz_val
        self.dz_train_range = (int(np.sqrt(d * d * 0.45)), int(np.sqrt(d * d * 0.6)))

    def __call__(self, sample, rng=None):
        target = sample[self.mask_elem]
        if len(np.unique(target)) == 1:
            # Degenerate mask: pass every crop element through uncropped, with
            # a zero relax so the batch key-set stays consistent.
            for elem in self.crop_elems:
                sample["crop_" + elem] = sample[elem]
            sample["crop_relax"] = 0
            return sample
        if self.is_val:
            dz = float(self.dz_val)
        else:
            rng = _require_rng(rng)
            dz = float(rng.integers(self.dz_train_range[0], self.dz_train_range[1]))
        t3 = target if target.ndim == 3 else target[..., np.newaxis]
        bbox = helpers.get_bbox(t3[..., 0])
        long_side = max(bbox[2] - bbox[0], bbox[3] - bbox[1])
        long_side = max(long_side, 1)
        zoom = dz / long_side
        relax = max((self.d - long_side * zoom) / (2 * zoom), self.floor)
        relax = int(np.ceil(relax))
        sample["crop_relax"] = relax
        return _crop_elems(sample, self.crop_elems, self.mask_elem, relax, self.zero_pad)

    def __repr__(self):
        return f"CropFromMask(d={self.d}, is_val={self.is_val})"


class CreateBBMask(Transform):
    """255-outside / 0-inside bounding-box mask of ``gt`` (reference
    custom_transforms.py:67-74)."""

    def __call__(self, sample, rng=None):
        mask = sample["gt"]
        bbox = helpers.get_bbox(mask)
        out = np.full(mask.shape, 255.0, dtype=np.float32)
        if bbox is not None:
            # get_bbox max coords are inclusive.
            out[bbox[1] : bbox[3] + 1, bbox[0] : bbox[2] + 1] = 0.0
        sample["bb_mask"] = out
        return sample


# ---------------------------------------------------------------------------
# guidance-channel transforms
# ---------------------------------------------------------------------------

def _pick_points(target, pert, is_val, rng):
    if is_val:
        return guidance.extreme_points_fixed(target, pert)
    return guidance.extreme_points(target, pert, rng=_require_rng(rng))


class NEllipse(Transform):
    """Rasterize the n-ellipse through the gt's extreme points into
    ``sample['nellipse']``, scaled to [0, 255] (reference
    custom_transforms.py:9-27)."""

    def __init__(self, is_val: bool = True):
        self.is_val = is_val

    def __call__(self, sample, rng=None):
        target = sample["crop_gt"]
        if target.max() == 0:
            sample["nellipse"] = np.zeros(target.shape, dtype=target.dtype)
            return sample
        pts = _pick_points(target, 0, self.is_val, rng)
        sample["nellipse"] = guidance.nellipse_map(target.shape[:2], pts)
        return sample


class NEllipseWithGaussians(Transform):
    """The live guidance channel (reference custom_transforms.py:30-51,
    consumed at train_pascal.py:131,142): n-ellipse plus gaussian bumps at the
    extreme points, combined ``z1 + alpha·z2`` and rescaled to peak at 255."""

    def __init__(self, alpha: float = 0.6, is_val: bool = True):
        self.alpha = alpha
        self.is_val = is_val

    def __call__(self, sample, rng=None):
        target = sample["crop_gt"]
        if target.max() == 0:
            sample["nellipseWithGaussians"] = np.zeros(target.shape, dtype=target.dtype)
            return sample
        pts = _pick_points(target, 0, self.is_val, rng)
        sample["nellipseWithGaussians"] = guidance.nellipse_gaussians_map(
            target.shape[:2], pts, alpha=self.alpha)
        return sample

    def __repr__(self):
        return f"NEllipseWithGaussians(alpha={self.alpha}, is_val={self.is_val})"


class ExtremePoints(Transform):
    """DEXTR-style guidance: gaussian heatmap (sigma, max-combined) at the 4
    perturbed extreme points of ``elem`` (reference
    custom_transforms.py:221-251)."""

    def __init__(self, sigma: float = 10, pert: int = 0, elem: str = "gt",
                 is_val: bool = True):
        self.sigma = sigma
        self.pert = pert
        self.elem = elem
        self.is_val = is_val

    def __call__(self, sample, rng=None):
        target = sample[self.elem]
        if target.ndim == 3:
            raise ValueError("ExtremePoints expects a single-object 2-D mask")
        if target.max() == 0:
            sample["extreme_points"] = np.zeros(target.shape, dtype=target.dtype)
            return sample
        pts = _pick_points(target, self.pert, self.is_val, rng)
        sample["extreme_points"] = guidance.extreme_points_map(
            target.shape[:2], pts, sigma=self.sigma)
        return sample


class AddConfidenceMap(Transform):
    """Alternative guidance: skewed-axes L1L2 or multivariate-gaussian
    confidence map appended as an extra channel -> ``sample['with_hm']``
    (reference custom_transforms.py:253-298; inactive in the live driver)."""

    def __init__(self, elem="crop_image", hm_type="l1l2", tau: float = 1.0,
                 pert: int = 0, is_val: bool = True):
        assert hm_type in ("l1l2", "gaussian")
        self.elem = elem
        self.hm_type = hm_type
        self.tau = tau
        self.pert = pert
        self.is_val = is_val

    def __call__(self, sample, rng=None):
        img = sample[self.elem]
        mask = sample["crop_gt"].astype(bool)
        if len(np.unique(mask)) == 1:
            hm = np.zeros(img.shape[:2], dtype=np.float32)
        elif self.hm_type == "l1l2":
            pts = _pick_points(mask, self.pert, self.is_val, rng)
            h_map, _, _ = guidance.generate_mv_l1l2_image_skewed_axes(
                mask, extreme_points=pts, FULL_IMAGE_WEIGHTS=1, d2_THRESH=None,
                tau=self.tau,
            )
            hm = guidance.normalize_wt_map(h_map) * 255.0
        else:
            h_map = guidance.generate_mvgauss_image(mask, FULL_IMAGE_WEIGHTS=1, tau=0.5)
            hm = guidance.normalize_wt_map(h_map) * 255.0
        sample["with_hm"] = np.concatenate(
            [np.atleast_3d(img), hm[..., np.newaxis]], axis=2
        ).astype(np.float32)
        return sample


# ---------------------------------------------------------------------------
# assembly / normalization
# ---------------------------------------------------------------------------

class ConcatInputs(Transform):
    """Channel-concatenate named elements into ``sample['concat']`` — the
    model's input assembly (reference custom_transforms.py:302-326; live use:
    image(3) + guidance heatmap(1) -> 4-channel input,
    train_pascal.py:133,144)."""

    def __init__(self, elems=("image", "point")):
        self.elems = elems

    def __call__(self, sample, rng=None):
        base = sample[self.elems[0]]
        parts = [np.atleast_3d(base)]
        for elem in self.elems[1:]:
            if sample[elem].shape[:2] != base.shape[:2]:
                raise ValueError(
                    f"ConcatInputs: {elem} spatial shape {sample[elem].shape[:2]} "
                    f"!= {self.elems[0]} {base.shape[:2]}"
                )
            parts.append(np.atleast_3d(sample[elem]))
        # Single element (the device_guidance config: the map is appended on
        # device): atleast_3d is a view — skip the pointless full-array copy
        # np.concatenate would make on the hot path.
        sample["concat"] = parts[0] if len(parts) == 1 \
            else np.concatenate(parts, axis=2)
        return sample

    def __repr__(self):
        return f"ConcatInputs({self.elems})"


class ToImage(Transform):
    """Min-max rescale element(s) to [0, custom_max] (reference
    custom_transforms.py:454-473)."""

    def __init__(self, norm_elem="image", custom_max: float = 255.0):
        self.norm_elem = norm_elem if isinstance(norm_elem, tuple) else (norm_elem,)
        self.custom_max = custom_max

    def __call__(self, sample, rng=None):
        for elem in self.norm_elem:
            v = sample[elem]
            sample[elem] = self.custom_max * (v - v.min()) / (v.max() - v.min() + 1e-10)
        return sample


class Duplicate(Transform):
    """Copy sample keys (``{src: dst}``) — e.g. preserving a full-res
    ``gt`` under a new name before a resize stage consumes the original."""

    def __init__(self, mapping: Mapping[str, str]):
        self.mapping = dict(mapping)

    def __call__(self, sample, rng=None):
        for src, dst in self.mapping.items():
            if src in sample:
                sample[dst] = sample[src]
        return sample

    def __repr__(self):
        return f"Duplicate({self.mapping})"


class Rename(Transform):
    """Rename sample keys (``{old: new}``) — adapter between pipelines with
    different key contracts (e.g. the semantic pipeline's per-image
    ``image``/``gt`` onto the step contract's ``concat``/``crop_gt``)."""

    def __init__(self, mapping: Mapping[str, str]):
        self.mapping = dict(mapping)

    def __call__(self, sample, rng=None):
        for old, new in self.mapping.items():
            if old in sample:
                sample[new] = sample.pop(old)
        return sample

    def __repr__(self):
        return f"Rename({self.mapping})"


class Keep(Transform):
    """Delete every sample key except the listed ones (``meta`` always
    survives) — the terminal pruning step for hot-path pipelines, so
    ``collate`` never stacks arrays nothing downstream consumes (the
    intermediate ``crop_image``/guidance maps are a ~4x memcpy tax per
    batch once ``concat`` is assembled)."""

    def __init__(self, keys: Sequence[str]):
        self.keys = tuple(keys)

    def __call__(self, sample, rng=None):
        for key in list(sample.keys()):
            if key not in self.keys and not _is_meta(key):
                del sample[key]
        return sample

    def __repr__(self):
        return f"Keep({self.keys})"


class ClampRange(Transform):
    """Clamp named elements into ``[lo, hi]``.

    Cubic resampling overshoots value ranges near edges; in the reference
    chain that was masked by ScaleNRotate's uint8 cast
    (custom_transforms.py:124-126) upstream of the resize.  When the
    geometric stage moves on-device (``build_train_transform(geom=False)``)
    the float image reaches ``FixedResize`` unquantized, so the [0,255]
    data contract (reference train_pascal.py:188) needs this explicit
    clamp."""

    def __init__(self, elems: Sequence[str], lo: float = 0.0,
                 hi: float = 255.0):
        self.elems = tuple(elems)
        self.lo, self.hi = lo, hi

    def __call__(self, sample, rng=None):
        for k in self.elems:
            if k in sample:
                sample[k] = np.clip(sample[k], self.lo, self.hi)
        return sample

    def __repr__(self):
        return f"ClampRange({self.elems}, {self.lo}, {self.hi})"


class ToArray(Transform):
    """Terminal transform: every array key -> float32 **HWC** numpy; 2-D
    arrays get a channel axis.

    This is the TPU-native counterpart of the reference's ``ToTensor``
    (custom_transforms.py:476-503): same float32 cast and channel-axis rule,
    but the layout stays HWC (NHWC batches are what XLA/TPU convolutions
    want) instead of transposing to CHW.  ``bbox`` converts without the
    channel rule; ``crop_relax``/meta pass through.

    ``uint8_passthrough`` keeps arrays that arrive as uint8 in uint8 (the
    wire format of ``data.uint8_transfer``: 4x fewer H2D bytes; the step
    dequantizes on device) — everything else still casts to float32.
    """

    def __init__(self, uint8_passthrough: bool = False):
        self.uint8_passthrough = uint8_passthrough

    def __call__(self, sample, rng=None):
        for key, val in sample.items():
            if _is_meta(key) or "crop_relax" in key:
                continue
            if "bbox" in key:
                sample[key] = np.asarray(val)
                continue
            arr = np.asarray(val)
            if not (self.uint8_passthrough and arr.dtype == np.uint8):
                # copy=False: already-float32 arrays pass through un-copied
                arr = arr.astype(np.float32, copy=False)
            if arr.ndim == 2:
                arr = arr[:, :, np.newaxis]
            sample[key] = arr
        return sample

    def __repr__(self):
        return f"ToArray(uint8_passthrough={self.uint8_passthrough})"


class PackBits(Transform):
    """Pack binary uint8 masks to 1 bit/pixel for the wire
    (``data.packbits_masks``).

    Runs after :class:`ToArray` on the uint8 fast path: a ``(H, W, 1)``
    uint8 {0,1} mask becomes a flat ``(ceil(H*W/8),)`` uint8 array
    (``np.packbits``, big-endian bit order — the device side's unpack in
    ``parallel.step`` mirrors it with MSB-first shifts).  An 8x wire/memcpy
    cut on the mask tensor, on top of uint8_transfer's 4x: worth it when
    H2D placement — not host or chip — bounds e2e (measured reality on a
    sagging tunnel, BASELINE.md round-3 breakdown).  Collate stacks the
    packed rows to ``(B, P)``; the compiled step unpacks with fused
    elementwise bit ops.
    """

    def __init__(self, elems=("crop_gt",)):
        self.elems = elems

    def __call__(self, sample, rng=None):
        for key in self.elems:
            arr = sample.get(key)
            if arr is None:
                continue
            arr = np.asarray(arr)
            if arr.dtype != np.uint8:
                raise TypeError(
                    f"PackBits({key!r}): expected a uint8 {{0,1}} mask "
                    f"(the data.uint8_transfer wire), got {arr.dtype}")
            if arr.max(initial=0) > 1:
                # np.packbits would silently coerce any nonzero byte to
                # bit 1, losing the "gt strictly binary" contract that the
                # plain wire's debug assert enforces — fail loudly instead
                raise ValueError(
                    f"PackBits({key!r}): mask has values > 1 "
                    f"(max {arr.max()}); only binary masks pack losslessly")
            sample[key] = np.packbits(arr.ravel())
        return sample

    def __repr__(self):
        return f"PackBits({self.elems})"
