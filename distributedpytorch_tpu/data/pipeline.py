"""Input pipelines: transform composition + per-host sharded batching.

This is the framework's replacement for *both* ends of the reference's data
story:

* the transform stacks at reference train_pascal.py:123-145 (train: flip →
  scale/rotate → crop+relax → 512² resize → n-ellipse+gaussian guidance →
  concat; val: deterministic guidance and full-res gt/void passthrough for
  full-image evaluation);
* the ``DataLoader(..., num_workers=2, shuffle, drop_last)`` host parallelism
  (train_pascal.py:161-162) **and** the distributed sampler the reference only
  planned (train_pascal.py:3) — here every host reads only its
  ``process_index``-th shard of each epoch's permutation, so a multi-host TPU
  job feeds disjoint data with no coordination.

Batches are dicts of stacked NHWC float32 numpy arrays, ready for
``jax.device_put`` (or ``jax.make_array_from_process_local_data`` under a
mesh).  Decoding/augmentation runs in a thread pool — cv2/PIL release the GIL
for the heavy ops — with a bounded prefetch queue so host work overlaps device
steps.
"""

from __future__ import annotations

import concurrent.futures as cf
import queue
import threading
from typing import Iterator, Sequence

import numpy as np

from . import transforms as T

#: default guidance channel, matching the live reference pipeline
GUIDANCE_KEY = "nellipseWithGaussians"


def build_crop_stage(
    crop_size: tuple[int, int],
    relax: int,
    zero_pad: bool,
    fused: bool = False,
    clamp: bool = True,
) -> list[T.Transform]:
    """The deterministic crop front shared by the train pipeline and the
    prepared-sample cache (data.prepared_cache) — ONE definition, so the
    cached bytes can never silently diverge from the live pipeline's.

    ``fused`` collapses crop + resize into one native-kernel pass
    (transforms.FusedCropResize); ``clamp`` bounds cubic-resize overshoot
    back into the [0,255] contract (needed whenever no uint8 cast sits
    upstream — the fused kernel resizes in float32 always).
    """
    if fused:
        return [
            T.FusedCropResize(crop_elems=("image", "gt"), mask_elem="gt",
                              relax=relax, zero_pad=zero_pad,
                              size=crop_size),
            *([T.ClampRange(("crop_image",))] if clamp else []),
        ]
    return [
        T.CropFromMaskStatic(crop_elems=("image", "gt"), mask_elem="gt",
                             relax=relax, zero_pad=zero_pad),
        T.FixedResize(resolutions={"crop_image": crop_size,
                                   "crop_gt": crop_size}),
        *([T.ClampRange(("crop_image",))] if clamp else []),
    ]


def build_train_transform(
    crop_size: tuple[int, int] = (512, 512),
    relax: int = 50,
    zero_pad: bool = True,
    rots: tuple[float, float] = (-20, 20),
    scales: tuple[float, float] = (0.75, 1.25),
    alpha: float = 0.6,
    guidance: str = "nellipse_gaussians",
    flip: bool = True,
    geom: bool = True,
    fused_crop_resize: bool = False,
) -> T.Compose:
    """The training augmentation stack (reference train_pascal.py:123-134).

    ``flip=False`` drops the host-side horizontal flip — used when the
    on-device augmentation stage (ops.augment) owns flipping instead;
    ``geom=False`` likewise drops the host ScaleNRotate when the device
    stage owns rotation/scale (ops.augment.random_scale_rotate — note the
    device form rotates the fixed-size crop rather than the full image).
    ``fused_crop_resize`` collapses the crop + resize pair into one native
    kernel pass (transforms.FusedCropResize) — same output contract, no
    materialized intermediate crop.
    """
    chain: list[T.Transform] = [
        *([T.RandomHorizontalFlip()] if flip else []),
        *([T.ScaleNRotate(rots=rots, scales=scales)] if geom else []),
        # with ScaleNRotate upstream its uint8 cast already bounds values,
        # so the non-fused path only clamps when geom is off
        *build_crop_stage(crop_size, relax, zero_pad,
                          fused=fused_crop_resize,
                          clamp=fused_crop_resize or not geom),
    ]
    chain += _guidance_stage(guidance, alpha, is_val=False)
    chain.append(T.ToArray())
    return T.Compose(chain)


def build_prepared_post_transform(
    rots: tuple[float, float] = (-20, 20),
    scales: tuple[float, float] = (0.75, 1.25),
    alpha: float = 0.6,
    guidance: str = "nellipse_gaussians",
    flip: bool = True,
    geom: bool = True,
    uint8_wire: bool = False,
    packbits: bool = False,
) -> T.Compose:
    """The per-epoch random stage downstream of the prepared-sample cache
    (data.prepared_cache): the cache already holds the deterministic
    decode→crop→resize output (``crop_image``/``crop_gt``), so only the
    random transforms run here — flip, scale/rotate *on the crop* (the
    device_augment_geom semantics: the warp sees the fixed-size crop, not
    the pre-crop full image), guidance synthesis, concat.  ``flip``/``geom``
    gate the host stages exactly like :func:`build_train_transform` when the
    on-device augmentation owns them instead.

    ``uint8_wire`` (data.uint8_transfer) keeps uint8 arrays uint8 through
    ``ToArray`` — with the uint8 cache upstream, ``concat``/``crop_gt``
    ship to the device at a quarter of the float32 bytes.  The terminal
    ``Keep`` prunes everything the step doesn't consume so ``collate``
    stops memcpy'ing dead intermediates.  ``packbits``
    (data.packbits_masks) additionally ships ``crop_gt`` at 1 bit/pixel
    (see :class:`~..data.transforms.PackBits`); the compiled step unpacks.
    """
    return T.Compose([
        *([T.RandomHorizontalFlip()] if flip else []),
        *([T.ScaleNRotate(rots=rots, scales=scales)] if geom else []),
        *_guidance_stage(guidance, alpha, is_val=False),
        T.ToArray(uint8_passthrough=uint8_wire),
        T.Keep(("concat", "crop_gt")),
        *([T.PackBits(("crop_gt",))] if packbits else []),
    ])


def build_prepared_eval_post_transform(
    alpha: float = 0.6,
    guidance: str = "nellipse_gaussians",
    uint8_wire: bool = False,
    packbits: bool = False,
) -> T.Compose:
    """Per-access stage downstream of the prepared EVAL cache
    (data.val_prepared): deterministic guidance (``is_val`` semantics,
    reference train_pascal.py:135-145) + concat + array conversion.  No
    random stages and no pruning — the cache itself appends the host-side
    metric keys (full-res ``gt``/``void_pixels``, ``bbox``) afterwards.

    With ``guidance='none'`` (the device-guidance fast path) ``concat`` is
    the bare uint8 image channels and the jitted eval step synthesizes the
    4th channel on device from ``crop_gt`` (ops.guidance_device,
    ``is_val=True`` — bit-exact vs the host at pert=0).

    The terminal ``Keep`` prunes the pre-concat intermediates (crop_image,
    the guidance map) so ``collate`` stops memcpy'ing them per batch; the
    cache appends its host-side metric keys AFTER this stage, so they are
    never at risk here."""
    return T.Compose([
        *_guidance_stage(guidance, alpha, is_val=True),
        T.ToArray(uint8_passthrough=uint8_wire),
        T.Keep(("concat", "crop_gt", "meta")),
        # data.packbits_masks: the binary crop_gt is 25% of the 3-channel
        # uint8 val batch; ship it at 1 bit/pixel (the eval step unpacks)
        *([T.PackBits(("crop_gt",))] if packbits else []),
    ])


def build_prepared_semantic_eval_post_transform(
    uint8_wire: bool = False,
) -> T.Compose:
    """Downstream of the prepared semantic cache at VAL: the cache already
    holds the entire deterministic crop-res eval protocol (resize image
    cubic + gt nearest + clamp, matching build_semantic_eval_transform up
    to the cache's uint8 rounding of the image — class ids stay exact), so
    only the contract rename remains."""
    return T.Compose([
        T.Rename({"image": "concat", "gt": "crop_gt"}),
        T.ToArray(uint8_passthrough=uint8_wire),
        T.Keep(("concat", "crop_gt", "meta")),
    ])


def build_eval_transform(
    crop_size: tuple[int, int] = (512, 512),
    relax: int = 50,
    zero_pad: bool = True,
    alpha: float = 0.6,
    guidance: str = "nellipse_gaussians",
    keep_fullres: bool = True,
) -> T.Compose:
    """The validation stack (reference train_pascal.py:135-145): deterministic
    guidance; ``gt``/``void_pixels`` kept at full resolution (``None`` in the
    resize map) so the evaluator can paste predictions back and score against
    the original-size mask."""
    resolutions = {"crop_image": crop_size, "crop_gt": crop_size}
    if keep_fullres:
        resolutions.update({"gt": None, "void_pixels": None})
    chain: list[T.Transform] = [
        T.CropFromMaskStatic(crop_elems=("image", "gt"), mask_elem="gt",
                             relax=relax, zero_pad=zero_pad),
        T.FixedResize(resolutions=resolutions),
        # the val stack has no uint8 cast upstream of the cubic resize, so
        # the [0,255] input contract (reference train_pascal.py:239-241
        # asserts it in the val loop too) needs an explicit clamp
        T.ClampRange(("crop_image",)),
    ]
    chain += _guidance_stage(guidance, alpha, is_val=True)
    chain.append(T.ToArray())
    return T.Compose(chain)


def _guidance_stage(guidance: str, alpha: float, is_val: bool) -> list[T.Transform]:
    """Guidance channel family selector; 'nellipse_gaussians' is the live
    reference path, the others are its inventoried alternatives."""
    if guidance == "nellipse_gaussians":
        return [
            T.NEllipseWithGaussians(alpha=alpha, is_val=is_val),
            T.ConcatInputs(elems=("crop_image", GUIDANCE_KEY)),
        ]
    if guidance == "nellipse":
        return [
            T.NEllipse(is_val=is_val),
            T.ConcatInputs(elems=("crop_image", "nellipse")),
        ]
    if guidance == "extreme_points":
        return [
            T.ExtremePoints(sigma=10, pert=0 if is_val else 5, elem="crop_gt",
                            is_val=is_val),
            T.ConcatInputs(elems=("crop_image", "extreme_points")),
        ]
    if guidance in ("confidence_l1l2", "confidence_gaussian"):
        # The reference's commented confidence-map alternative
        # (custom_transforms.py:253-298, driver lines 132/143): the transform
        # appends the map to the image itself -> rename onto the contract.
        return [
            T.AddConfidenceMap(elem="crop_image",
                               hm_type=guidance.removeprefix("confidence_"),
                               pert=0 if is_val else 5, is_val=is_val),
            T.Rename({"with_hm": "concat"}),
        ]
    if guidance == "none":
        return [T.ConcatInputs(elems=("crop_image",))]
    raise ValueError(f"unknown guidance family: {guidance}")


def build_semantic_train_transform(
    crop_size: tuple[int, int] = (513, 513),
    rots: tuple[float, float] = (-10, 10),
    scales: tuple[float, float] = (0.5, 2.0),
    flip: bool = True,
    geom: bool = True,
) -> T.Compose:
    """Multi-class semantic pipeline (the DeepLabV3 configs of BASELINE.md):
    flip -> scale/rotate with nearest-warped class ids (``semseg=True``) ->
    fixed resize (gt nearest, 255 void preserved in-band) -> rename onto the
    step contract (``concat``/``crop_gt``).

    ``flip=False`` drops the host flip when the on-device augmentation
    stage owns it (``data.device_augment``); ``geom=False`` likewise drops
    the host ScaleNRotate for ``data.device_augment_geom``.
    """
    return T.Compose([
        *([T.RandomHorizontalFlip()] if flip else []),
        *([T.ScaleNRotate(rots=rots, scales=scales, semseg=True)]
          if geom else []),
        T.FixedResize(resolutions={"image": crop_size, "gt": crop_size},
                      flagvals={"image": None, "gt": 0}),
        # cubic resize overshoots at contrast edges; the [0,255] input
        # contract (and its debug assert) needs the explicit clamp here
        # just like the instance chains
        T.ClampRange(("image",)),
        T.Rename({"image": "concat", "gt": "crop_gt"}),
        T.ToArray(),
    ])


def build_prepared_semantic_post_transform(
    rots: tuple[float, float] = (-10, 10),
    scales: tuple[float, float] = (0.5, 2.0),
    flip: bool = True,
    geom: bool = True,
    uint8_wire: bool = False,
) -> T.Compose:
    """Per-epoch random stage downstream of the semantic prepared cache:
    flip + scale/rotate on the already-resized arrays (nearest-warped class
    ids, 255-void border), renamed onto the step contract.  Mirrors
    :func:`build_prepared_post_transform` for the semantic task."""
    return T.Compose([
        *([T.RandomHorizontalFlip()] if flip else []),
        *([T.ScaleNRotate(rots=rots, scales=scales, semseg=True)]
          if geom else []),
        T.Rename({"image": "concat", "gt": "crop_gt"}),
        T.ToArray(uint8_passthrough=uint8_wire),
        T.Keep(("concat", "crop_gt")),
    ])


def build_semantic_eval_transform(
    crop_size: tuple[int, int] = (513, 513),
    keep_fullres: bool = False,
) -> T.Compose:
    """Deterministic semantic eval: fixed resize only (gt nearest so class
    ids and 255-void stay exact), renamed onto the step contract.

    ``keep_fullres`` preserves the ORIGINAL-resolution gt as ``gt_full``
    (ragged, host-side) so the evaluator can score mIoU at each image's
    native size — the standard DeepLab protocol — instead of at the
    resized crop (the instance pipeline keeps full-res gt the same way,
    reference train_pascal.py:138)."""
    res: dict = {"image": crop_size, "gt": crop_size}
    flags: dict = {"image": None, "gt": 0}
    chain: list[T.Transform] = []
    if keep_fullres:
        chain.append(T.Duplicate({"gt": "gt_full"}))
        res["gt_full"] = None   # passthrough, survives the pruning rule
        flags["gt_full"] = 0
    chain += [
        T.FixedResize(resolutions=res, flagvals=flags),
        T.ClampRange(("image",)),  # cubic-overshoot clamp, as in train
        T.Rename({"image": "concat", "gt": "crop_gt"}),
        T.ToArray(),
    ]
    return T.Compose(chain)


# ---------------------------------------------------------------------------
# batching / sharding
# ---------------------------------------------------------------------------

#: keys that stay python lists in a batch (metadata; exact match — a substring
#: test would wrongly catch 'vo*id*_pixels', see transforms._is_meta)
_NO_STACK_KEYS = ("meta", "id", "crop_relax")


def sample_rng(seed: int, epoch: int, index: int) -> np.random.Generator:
    """THE per-sample RNG policy: ``default_rng((seed, epoch, index))``.

    Single source of truth — both this module's ``DataLoader`` and the
    grain loader derive sample randomness here, which is what makes their
    samples bit-identical regardless of worker/host count."""
    return np.random.default_rng((seed, epoch, int(index)))


def collate(samples: Sequence[dict]) -> dict:
    """Stack a list of dict samples into a dict batch.

    Fixed-shape keys stack on a new leading batch axis; ragged keys (full-res
    ``gt``/``void_pixels`` at val, whose size varies per image) and metadata
    stay as lists — they are consumed host-side by the evaluator, never
    shipped to the device.
    """
    out: dict = {}
    for key in samples[0]:
        vals = [s[key] for s in samples]
        if key in _NO_STACK_KEYS:
            out[key] = vals
            continue
        shapes = {np.asarray(v).shape for v in vals}
        if len(shapes) == 1:
            out[key] = np.stack([np.asarray(v) for v in vals])
        else:
            out[key] = vals
    return out


class DataLoader:
    """Sharded, shuffling, prefetching batch iterator over a random-access
    dataset.

    One instance per host: with ``num_shards = jax.process_count()`` and
    ``shard_index = jax.process_index()``, each host walks only its slice of
    the epoch permutation — the "distributed loader sampler" item of the
    reference's DDP checklist (train_pascal.py:3), done the JAX way.

    Every sample's RNG is ``default_rng((seed, epoch, index))``; shuffling is
    ``default_rng((seed, epoch))`` over the global index set — identical data
    order regardless of worker count or host count.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        shuffle: bool = False,
        drop_last: bool = False,
        seed: int = 0,
        num_workers: int = 2,
        shard_index: int = 0,
        num_shards: int = 1,
        prefetch: int = 2,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.num_workers = max(0, num_workers)
        self.shard_index = shard_index
        self.num_shards = num_shards
        self.prefetch = prefetch
        self.epoch = 0
        self.start_batch = 0

    def set_epoch(self, epoch: int, start_batch: int = 0) -> None:
        """Position the loader; ``start_batch`` skips that many batches of
        the epoch's (deterministic) order — the exact-mid-epoch-resume hook
        (a resumed run continues where the preempted one stopped instead of
        replaying the epoch).  ``__len__`` still reports the full epoch so
        schedules and resume math are unaffected."""
        self.epoch = epoch
        self.start_batch = start_batch

    def _epoch_indices(self, epoch: int | None = None) -> np.ndarray:
        epoch = self.epoch if epoch is None else int(epoch)
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            np.random.default_rng((self.seed, epoch)).shuffle(order)
        if self.num_shards > 1:
            # Pad the permutation (wrap-around) to a multiple of num_shards so
            # every sample lands in some shard and all shards are equal-length
            # — uneven shards would desynchronize collective step counts, and
            # truncation would silently drop the tail from evaluation.
            per_shard = -(-n // self.num_shards)
            total = per_shard * self.num_shards
            if total > n:
                order = np.concatenate([order, order[: total - n]])
            order = order[self.shard_index * per_shard : (self.shard_index + 1) * per_shard]
        return order

    def _num_batches(self, n_indices: int) -> int:
        if self.drop_last:
            return n_indices // self.batch_size
        return (n_indices + self.batch_size - 1) // self.batch_size

    def batch_sample_indices(self, batch_index: int,
                             epoch: int | None = None) -> np.ndarray:
        """Dataset indices of batch ``batch_index`` in ``epoch``'s
        deterministic order (the current epoch when None) — the O(1)
        batch -> samples resolution the sentinel's quarantine ledger and
        the packed-source ``seek`` integration use.  Indexes the FULL
        epoch order: ``start_batch`` offsets never shift it, so a batch
        index quarantined mid-run names the same samples on replay.
        Pure function of ``epoch`` — never mutates loader state, so it
        is safe while a prefetch producer is mid-epoch."""
        order = self._epoch_indices(epoch)
        lo = int(batch_index) * self.batch_size
        return order[lo:lo + self.batch_size]

    def __len__(self) -> int:
        return self._num_batches(len(self._epoch_indices()))

    def _load_one(self, index: int) -> dict:
        rng = sample_rng(self.seed, self.epoch, index)
        return self.dataset.__getitem__(int(index), rng=rng)

    def __iter__(self) -> Iterator[dict]:
        order = self._epoch_indices()
        nb = self._num_batches(len(order))
        batches = [order[i * self.batch_size : (i + 1) * self.batch_size] for i in range(nb)]
        if self.start_batch:
            # index-level skip: the skipped batches cost nothing (no decode)
            batches = batches[self.start_batch:]
        if self.num_workers == 0:
            for idxs in batches:
                yield collate([self._load_one(i) for i in idxs])
            return
        yield from self._iter_prefetched(batches)

    def _iter_prefetched(self, batches: list[np.ndarray]) -> Iterator[dict]:
        # The queue itself is unbounded; the prefetch bound is enforced
        # below against the LIVE ``self.prefetch`` so the feed governor's
        # hot resize (data/governor.py rung 1) takes effect mid-epoch:
        # growing admits more batches immediately, shrinking just waits
        # for the consumer to drain below the new bound — a shrink can
        # never strand an already-full queue (queue.Queue's maxsize is
        # fixed at construction, which is exactly why it isn't used as
        # the bound here).
        out_q: queue.Queue = queue.Queue()
        sentinel = object()
        stop = threading.Event()
        # admission is condition-notified, not polled: the consumer's get
        # wakes the producer the instant a slot drains (the latency a
        # timed poll would add lands straight in input_wait); the wait
        # timeout only backstops a bound grown by the governor while the
        # consumer sits idle (no get, so no notify)
        room = threading.Condition()

        def put_bounded(item) -> bool:
            """Bounded put that gives up when the consumer is gone — an
            abandoned iterator (early break / exception in the train loop)
            must not leave the producer blocked forever at the bound."""
            with room:
                while not stop.is_set():
                    if out_q.qsize() < max(1, int(self.prefetch)):
                        # single producer: qsize only shrinks
                        # concurrently, so the bound check cannot
                        # over-admit.  out_q is UNbounded (the condvar
                        # IS the bound), so the put cannot block:
                        out_q.put(item)  # jaxrace: disable=JR004
                        return True
                    room.wait(0.1)
            return False

        def producer():
            with cf.ThreadPoolExecutor(max_workers=self.num_workers) as pool:
                try:
                    for idxs in batches:
                        if stop.is_set():
                            return
                        samples = list(pool.map(self._load_one, idxs))
                        if not put_bounded(collate(samples)):
                            return
                except BaseException as e:  # surface worker errors to consumer
                    # UNbounded put: an error must reach the consumer
                    # promptly even when the queue sits at the prefetch
                    # bound — waiting for drain here is how a producer
                    # death turns into a consumer deadlock
                    out_q.put(e)
                finally:
                    out_q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = out_q.get()
                with room:
                    room.notify()
                if item is sentinel:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
            with room:
                room.notify()
            t.join()
