"""Grain-backed input pipeline — the north-star loader.

``BASELINE.json``'s north star phrases the data story as "pascal.py and
custom_transforms.py become a Grain input pipeline".  :mod:`.pipeline`'s
``DataLoader`` is the framework's self-contained equivalent (threads +
bounded prefetch + per-host shards); this module provides the same batches
through `grain` proper, for deployments that want Grain's process-based
workers, backpressure and checkpointable iterators:

* the dataset (any random-access source from :mod:`.voc` / :mod:`.combine`)
  is wrapped as a ``grain.RandomAccessDataSource``;
* the transform chain runs inside a ``grain.MapTransform`` with the same
  explicit per-sample RNG policy as ``DataLoader`` (``default_rng((seed,
  epoch, index))`` — reproducible regardless of worker count);
* sharding uses ``grain.ShardOptions(shard_index, shard_count)`` — the
  per-host split the reference's DDP checklist called a "distributed
  sampler" (reference train_pascal.py:3);
* batches come out as the same dict-of-stacked-arrays ``collate`` produces,
  so ``parallel.shard_batch`` and the evaluator consume either loader
  interchangeably *single-host*.  Differences to know: multi-host sharding
  drops the tail remainder for equal shard lengths (``DataLoader``
  wrap-pads instead, so prefer it for multi-host *eval* where every sample
  must be scored); shuffle orders differ between the two loaders; and with
  ``num_workers > 0`` grain batches inside each worker over its every-Nth
  record slice, so batch *composition* differs from ``num_workers=0`` (and
  ``drop_last`` drops one remainder per worker).  Exact batch parity with
  ``DataLoader`` holds for ``shuffle=False, num_workers=0``; per-sample
  contents are bit-identical in every configuration.

The transform is attached to the *loader*, not the dataset: pass a
transform-free dataset here.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

try:
    import grain.python as grain
    HAVE_GRAIN = True
except ImportError:  # pragma: no cover - grain is optional
    grain = None
    HAVE_GRAIN = False

from .pipeline import collate, sample_rng


class _Source:
    """Random-access view of a dataset, transform applied per record with
    the (seed, epoch, index)-derived RNG."""

    def __init__(self, dataset, transform, seed: int, epoch: int = 0):
        if transform is not None and getattr(dataset, "transform", None):
            raise ValueError(
                "dataset already has a transform; pass a transform-free "
                "dataset to make_grain_loader (it would be applied twice)")
        self.dataset = dataset
        self.transform = transform
        self.seed = seed
        self.epoch = epoch

    def __len__(self) -> int:
        return len(self.dataset)

    def __getitem__(self, index: int) -> dict:
        rng = sample_rng(self.seed, self.epoch, index)
        sample = self.dataset.__getitem__(int(index), rng=rng)
        if self.transform is not None:
            sample = self.transform(sample, rng)
        return sample


class _CollateBatches:
    """Grain legacy-Operation batching through our own :func:`collate` —
    unlike ``grain.Batch`` (tree-map ``np.stack``) it handles the sample
    dicts' ragged entries (per-image full-res ``gt``, ``meta`` dicts) by
    keeping them as lists, so the *eval* pipeline works too."""

    def __init__(self, batch_size: int, drop_remainder: bool):
        self.batch_size = batch_size
        self.drop_remainder = drop_remainder

    def __call__(self, records):
        buf, meta = [], None
        for record in records:
            buf.append(record.data)
            meta = record.metadata
            if len(buf) == self.batch_size:
                yield grain.Record(metadata=meta, data=collate(buf))
                buf, meta = [], None
        if buf and not self.drop_remainder:
            yield grain.Record(metadata=meta, data=collate(buf))


def make_grain_loader(
    dataset,
    batch_size: int,
    transform=None,
    shuffle: bool = False,
    drop_last: bool = False,
    seed: int = 0,
    epoch: int = 0,
    num_workers: int = 0,
    shard_index: int = 0,
    num_shards: int = 1,
):
    """A ``grain.DataLoader`` yielding the same dict batches as
    ``pipeline.DataLoader``.

    ``num_workers=0`` runs in-process (deterministic, test-friendly);
    ``> 0`` uses Grain's child processes.  Re-create the loader per epoch
    (or use distinct ``epoch`` values) to reproduce ``DataLoader``'s
    epoch-keyed sample RNG.
    """
    if not HAVE_GRAIN:
        raise ImportError("grain is not installed; use data.DataLoader")
    if num_workers > 0 and drop_last:
        import warnings
        warnings.warn(
            "grain batches inside each worker: drop_last discards up to "
            "num_workers*(batch_size-1) samples per epoch (vs batch_size-1 "
            "at num_workers=0)", stacklevel=2)
    source = _Source(dataset, transform, seed, epoch)
    # Mix (seed, epoch) collision-free — naive seed+epoch would give
    # (7, epoch 1) and (8, epoch 0) identical shuffles.
    shuffle_seed = int(np.random.SeedSequence([seed, epoch])
                       .generate_state(1)[0]) & 0x7FFFFFFF
    sampler = grain.IndexSampler(
        num_records=len(source),
        shuffle=shuffle,
        seed=shuffle_seed,
        shard_options=grain.ShardOptions(
            shard_index=shard_index, shard_count=num_shards,
            drop_remainder=num_shards > 1),
        num_epochs=1,
    )
    return grain.DataLoader(
        data_source=source,
        sampler=sampler,
        operations=[_CollateBatches(batch_size, drop_remainder=drop_last)],
        worker_count=num_workers,
    )


class GrainDataLoader:
    """Drop-in replacement for :class:`pipeline.DataLoader` backed by grain
    (same ``set_epoch`` / ``__len__`` / ``__iter__`` surface, same dict
    batches), selected in the trainer with ``data.loader=grain``.

    Epoch semantics match ``DataLoader``'s RNG policy: each ``__iter__``
    builds a fresh grain loader keyed on the current epoch, so shuffle
    order and per-sample augmentation RNG both reproduce.
    """

    def __init__(self, dataset, batch_size: int, *, transform=None,
                 shuffle: bool = False, drop_last: bool = False,
                 seed: int = 0, num_workers: int = 0, num_shards: int = 1,
                 shard_index: int = 0):
        if not HAVE_GRAIN:  # fail at construction, not at first iteration
            raise ImportError("grain is not installed; use data.DataLoader "
                              "(data.loader=threads)")
        self.dataset = dataset
        self.batch_size = batch_size
        self.transform = transform
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.num_workers = num_workers
        self.num_shards = num_shards
        self.shard_index = shard_index
        self._epoch = 0
        self._start_batch = 0

    def set_epoch(self, epoch: int, start_batch: int = 0) -> None:
        """Position the loader; ``start_batch`` skips that many batches —
        the exact-mid-epoch-resume hook (pipeline.DataLoader surface).
        Grain owns the record order internally, so the skip is an islice
        over produced batches (the skipped ones are still decoded; resume
        is rare enough that correctness beats cleverness here)."""
        self._epoch = int(epoch)
        self._start_batch = int(start_batch)

    def __len__(self) -> int:
        n = len(self.dataset)
        if self.num_shards > 1:  # ShardOptions(drop_remainder=True)
            n = n // self.num_shards
        if self.num_workers > 0:
            # grain batches inside each worker over its round-robin record
            # slice, so each worker drops (or pads) its own remainder — the
            # batch count is the sum over per-worker slice lengths.
            w = self.num_workers
            counts = [n // w + (1 if i < n % w else 0) for i in range(w)]
        else:
            counts = [n]
        if self.drop_last:
            return sum(c // self.batch_size for c in counts)
        return sum(-(-c // self.batch_size) for c in counts if c)

    def __iter__(self):
        it = iter(make_grain_loader(
            self.dataset, self.batch_size, transform=self.transform,
            shuffle=self.shuffle, drop_last=self.drop_last, seed=self.seed,
            epoch=self._epoch, num_workers=self.num_workers,
            shard_index=self.shard_index, num_shards=self.num_shards))
        if self._start_batch:
            import itertools
            return itertools.islice(it, self._start_batch, None)
        return it
