"""Packed pre-decoded dataset: decode the filesystem tree ONCE, mmap forever.

The profiling literature keeps re-finding the same per-sample host bill:
JPEG/PNG decode plus a filesystem walk dominate input time (Mohan et al.,
arXiv 2005.02130), and FFCV's answer (arXiv 2306.12517) is to pay it once
— pre-decode into fixed-layout records behind an index and memory-map them
ever after.  This module is that answer for the VOC/SBD sources:

* ``dptpu-pack`` (:func:`main`) walks a dataset once and writes a pack
  directory::

      <pack>/voc-instance-train/
          records.bin   # concatenated per-image blobs: decoded uint8 RGB
                        # + the raw instance/class mask, one blob per
                        # image (instance records of the same image SHARE
                        # the blob — no duplicated pixels)
          records.idx   # one fixed-size row per record: blob extent,
                        # shape, mask dtype, image id ref, object index,
                        # category, the 4 deterministic extreme points,
                        # and the blob's crc32 — O(1) random access
          meta.json     # identity (dataset/kind/splits/area_thres),
                        # im_ids, the index crc32 and bin byte count.
                        # Written LAST, atomically: no meta = no pack,
                        # so a crashed packer can never be half-trusted.

* :class:`PackedDataset` reads it back as a drop-in source for the
  existing ``DataLoader``/transform stack: ``__getitem__(i, rng)`` re-runs
  the EXACT arithmetic of the filesystem classes (``voc.py``/``sbd.py``)
  on the stored bytes, so samples are bit-identical to the fs pipeline by
  construction — pinned in ``tests/test_packed.py``.  Every read verifies
  the record's crc32; a torn or bit-flipped record raises a typed
  :class:`PackedRecordError` naming the record index — never a silent
  wrong sample.  ``quarantine=(i, ...)`` drops named records from the
  epoch (the ops move after ``dptpu-pack --verify`` flags them).

* ``seek(i)`` is the O(1) record accessor the governor's echo/skip/replay
  arithmetic and the sentinel's quarantine-by-batch-index resolve
  through: record identity (image id, object, category, extreme points)
  straight from the index row, the verified pixel payload on demand
  (``read=True``).

Host sharding rides the existing loader contract: the epoch permutation
is ``default_rng((seed, epoch))`` over the GLOBAL index — identical on
every host by construction, the consensus-free determinism idiom — and
each process walks only its contiguous slice of it
(``DataLoader._epoch_indices``).  The mmap makes that sharding physical:
a host only page-faults the records its slice touches, so a pod never
duplicates I/O.

This module is importable pre-jax (numpy + stdlib only) and is the ONE
prepared format going forward: ``data/prepared.py``'s cache is the
legacy form (``data.prepared_cache`` configs get a loud migration
pointer), and the prepared wrappers compose over a packed source when a
crop-stage cache is still wanted on top.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import zlib

import numpy as np

from ..chaos import sites as chaos_sites

#: bump when the record layout / reconstruction semantics change
FORMAT_VERSION = 1

META_NAME = "meta.json"
INDEX_NAME = "records.idx"
BIN_NAME = "records.bin"

KINDS = ("instance", "semantic")

#: one fixed-size row per record — the O(1)-seek surface.  ``mask_dtype``
#: is the numpy dtype str of the stored raw mask (VOC PNG masks are
#: uint8; SBD .mat structs vary), so reconstruction is exact whatever the
#: source stored.  ``extreme_points`` are the deterministic (pert=0)
#: extreme points of the record's object mask, in the (x, y) order of
#: ``guidance.extreme_points_fixed`` — instance metadata rides the
#: record, O(1)-reachable without touching the pixel payload.
INDEX_DTYPE = np.dtype([
    ("blob_offset", np.int64),
    ("blob_len", np.int64),
    ("height", np.int32),
    ("width", np.int32),
    ("mask_dtype", "S8"),
    ("image_idx", np.int32),     # -> meta["im_ids"]
    ("object_idx", np.int32),    # instance object ordinal; -1 semantic
    ("category", np.int32),      # instance category id; -1 semantic
    ("extreme_points", np.int32, (4, 2)),
    ("blob_crc32", np.uint32),
])


class PackFormatError(RuntimeError):
    """The pack directory is missing, torn at the pack level (index crc,
    truncated bin) or describes a different layout than requested."""


class PackedRecordError(RuntimeError):
    """One record's bytes failed verification (checksum mismatch or a
    blob extent past the bin file) — the typed never-a-silent-wrong-
    sample error.  ``index`` is the RAW record index (the id
    ``dptpu-pack --verify`` reports and ``data.pack_quarantine``
    takes)."""

    def __init__(self, index: int, path: str, reason: str):
        self.index = int(index)
        self.path = path
        super().__init__(
            f"packed record {int(index)} of {path} is unreadable "
            f"({reason}) — the pack is torn/bit-rotted at this record; "
            f"re-pack with dptpu-pack (or, for the TRAIN pack only, "
            f"quarantine it: data.pack_quarantine=[{int(index)}]) after "
            f"`dptpu-pack --verify {path}`")


def pack_name(dataset: str, kind: str, splits) -> str:
    """Canonical pack-directory name for (dataset, kind, splits) — the
    resolution contract between ``dptpu-pack`` and the trainer."""
    parts = sorted([splits] if isinstance(splits, str) else list(splits))
    return f"{dataset}-{kind}-{'_'.join(parts)}"


def pack_dir_path(pack_root: str, dataset: str, kind: str, splits) -> str:
    return os.path.join(pack_root, pack_name(dataset, kind, splits))


def pack_command(root: str, out: str, dataset: str, kind: str, splits,
                 area_thres: int | None = None) -> str:
    """The exact ``dptpu-pack`` invocation that builds one pack — the one
    source of truth for the governor's rung-0 recommendation and every
    missing-pack error message."""
    parts = sorted([splits] if isinstance(splits, str) else list(splits))
    cmd = (f"dptpu-pack --root {root or '<data-root>'} --dataset {dataset} "
           f"--task {kind} --splits {','.join(parts)}")
    if kind == "instance" and area_thres is not None:
        cmd += f" --area-thres {int(area_thres)}"
    return cmd + f" --out {out or '<pack-dir>'}"


def pack_commands_for_config(cfg, root: str | None = None) -> list[str]:
    """Every pack the trainer would open under ``data.source=packed`` for
    this config (duck-typed: any object with ``.task``/``.data``).  The
    governor's ``pack_recommendation`` and the trainer's missing-pack
    errors both name exactly these."""
    d = cfg.data
    root = root if root is not None else d.root
    out = d.pack_path
    area = d.area_thres if cfg.task == "instance" else None
    cmds = [pack_command(root, out, "voc", cfg.task, [d.train_split], area),
            pack_command(root, out, "voc", cfg.task, [d.val_split], area)]
    if d.sbd_root:
        cmds.append(pack_command(d.sbd_root, out, "sbd", cfg.task,
                                 ["train", "val"], area))
    return cmds


# --------------------------------------------------------------- writing

def _dataset_kind(dataset) -> str:
    return "instance" if hasattr(dataset, "obj_list") else "semantic"


def _extreme_points_of(mask: np.ndarray) -> np.ndarray:
    """Deterministic (pert=0) extreme points of one object mask, (4, 2)
    int32 in the (x, y) order of ``guidance.extreme_points_fixed``."""
    from . import guidance

    if not mask.any():
        return np.zeros((4, 2), np.int32)
    return np.asarray(guidance.extreme_points_fixed(mask, pert=0),
                      np.int32)


def pack_dataset(dataset, out_dir: str, *, dataset_name: str,
                 splits, area_thres: int | None = None,
                 progress: bool = False) -> dict:
    """Walk ``dataset`` once and write the pack at ``out_dir``; returns
    the pack meta.  ``dataset`` must be one of the raw filesystem
    sources (``voc.py``/``sbd.py`` classes — anything exposing
    ``decode_raw``/``im_ids`` and, for the instance kind,
    ``obj_list``/``obj_dict``) constructed with ``transform=None``: the
    pack stores the PRE-transform decoded bytes, so any transform stack
    runs downstream of the reader exactly as it does off the
    filesystem."""
    if getattr(dataset, "transform", None) is not None:
        raise ValueError(
            "pack_dataset walks the *untransformed* dataset (construct it "
            "with transform=None); transforms run downstream of the "
            "PackedDataset reader, never inside the pack")
    if getattr(dataset, "default", False):
        # VOCInstanceSegmentation(default=True) yields the full instance
        # map as gt; the packed reader always reconstructs the binary
        # per-object mask — packing that source would silently break the
        # bit-identical contract, so refuse loudly instead
        raise ValueError(
            "pack_dataset supports the standard per-object sample "
            "contract only; construct the dataset with default=False")
    if not hasattr(dataset, "decode_raw"):
        raise TypeError(
            f"{type(dataset).__name__} exposes no decode_raw(...) — only "
            "the raw voc.py/sbd.py sources can be packed (wrappers like "
            "CombinedDataset are combined at READ time from per-source "
            "packs)")
    kind = _dataset_kind(dataset)
    im_ids = list(dataset.im_ids)
    if kind == "instance":
        records = [(int(im_ii), int(obj_ii))
                   for im_ii, obj_ii in dataset.obj_list]
    else:
        records = [(i, -1) for i in range(len(dataset))]
    # records grouped by owning image: decode each image EXACTLY once —
    # blob write and the per-object extreme points come off one pass
    recs_by_image: dict[int, list[tuple[int, int]]] = {}
    for i, (im_ii, obj_ii) in enumerate(records):
        recs_by_image.setdefault(im_ii, []).append((i, obj_ii))
    image_indices = sorted(recs_by_image)

    os.makedirs(out_dir, exist_ok=True)
    meta_path = os.path.join(out_dir, META_NAME)
    # stale meta is removed FIRST: a pack is only trusted once meta.json
    # lands (atomically, last) — a crash mid-rewrite leaves no pack, not
    # an old meta over new bytes
    if os.path.exists(meta_path):
        os.remove(meta_path)

    index = np.zeros(len(records), INDEX_DTYPE)
    with open(os.path.join(out_dir, BIN_NAME), "wb") as f:
        offset = 0
        for k, im_ii in enumerate(image_indices):
            img8, mask = dataset.decode_raw(im_ii)
            img8 = np.ascontiguousarray(img8, np.uint8)
            mask = np.ascontiguousarray(mask)
            if img8.ndim != 3 or img8.shape[2] != 3 \
                    or mask.shape != img8.shape[:2]:
                raise ValueError(
                    f"decode_raw({im_ii}) returned image {img8.shape} / "
                    f"mask {mask.shape}; want (H, W, 3) uint8 + (H, W)")
            payload = img8.tobytes() + mask.tobytes()
            crc = zlib.crc32(payload) & 0xFFFFFFFF
            f.write(payload)
            for i, obj_ii in recs_by_image[im_ii]:
                row = index[i]
                row["blob_offset"], row["blob_len"] = offset, len(payload)
                row["height"], row["width"] = img8.shape[:2]
                row["mask_dtype"] = mask.dtype.str.encode()
                row["image_idx"] = im_ii
                row["object_idx"] = obj_ii
                row["blob_crc32"] = crc
                if kind == "instance":
                    row["category"] = int(
                        dataset.obj_dict[im_ids[im_ii]][obj_ii])
                    row["extreme_points"] = _extreme_points_of(
                        mask == obj_ii + 1)
                else:
                    row["category"] = -1
            offset += len(payload)
            if progress and (k + 1) % 200 == 0:
                print(f"packed {k + 1}/{len(image_indices)} images",
                      file=sys.stderr, flush=True)
        bin_bytes = offset
    index_bytes = index.tobytes()
    with open(os.path.join(out_dir, INDEX_NAME), "wb") as f:
        f.write(index_bytes)

    meta = {
        "format": FORMAT_VERSION,
        "kind": kind,
        "dataset": dataset_name,
        "splits": sorted([splits] if isinstance(splits, str)
                         else list(splits)),
        "source": str(dataset),
        "n_records": len(records),
        "n_images": len(image_indices),
        "area_thres": (int(area_thres) if area_thres is not None
                       else getattr(dataset, "area_thres", None)),
        "im_ids": im_ids,
        "index_crc32": zlib.crc32(index_bytes) & 0xFFFFFFFF,
        "bin_bytes": bin_bytes,
    }
    tmp = meta_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, meta_path)
    return meta


def corrupt_record(path: str, record: int, offset: int = 0) -> int:
    """Flip one byte of ``record``'s blob ON DISK — the deterministic
    stand-in for bit rot / a torn pack write (the chaos ``torn_pack``
    scenario's tear; ``--verify`` must then flag every record sharing
    the blob).  Returns the absolute file offset flipped."""
    with open(os.path.join(path, META_NAME)) as f:
        meta = json.load(f)
    with open(os.path.join(path, INDEX_NAME), "rb") as f:
        index = np.frombuffer(f.read(), INDEX_DTYPE)
    if not 0 <= record < meta["n_records"]:
        raise IndexError(f"record {record} out of range "
                         f"[0, {meta['n_records']})")
    row = index[record]
    at = int(row["blob_offset"]) + (int(offset) % int(row["blob_len"]))
    with open(os.path.join(path, BIN_NAME), "r+b") as f:
        f.seek(at)
        b = f.read(1)
        f.seek(at)
        f.write(bytes([b[0] ^ 0xFF]))
    return at


# --------------------------------------------------------------- reading

class PackedDataset:
    """Memory-mapped reader over a ``dptpu-pack`` directory — a drop-in
    random-access source for the ``DataLoader``/transform stack with the
    exact sample contract of the filesystem classes it replaces
    (``{'image', 'gt', 'void_pixels'?, 'meta'}``), bit-identical by
    construction (the reconstruction re-runs ``voc.py``/``sbd.py``'s
    arithmetic on the stored bytes).

    * every record read is crc32-verified; failure is a typed
      :class:`PackedRecordError` naming the record index;
    * ``quarantine``: RAW record indices dropped from the epoch (the
      recovery move for records ``--verify`` flagged);
    * ``seek(i)``: O(1) record identity off the index row —
      ``read=True`` adds the verified pixel payload;
    * pickles by path (grain process workers reopen the mmap).
    """

    def __init__(self, path: str, transform=None, quarantine=(),
                 retname: bool = True, suppress_void_pixels: bool = True,
                 expect_kind: str | None = None):
        self.path = path
        self.transform = transform
        self.retname = retname
        self.suppress_void_pixels = suppress_void_pixels
        meta_path = os.path.join(path, META_NAME)
        if not os.path.isfile(meta_path):
            raise PackFormatError(
                f"no pack at {path} ({META_NAME} missing) — build one "
                "with dptpu-pack")
        try:
            with open(meta_path) as f:
                self.meta = json.load(f)
        # ValueError covers JSONDecodeError AND UnicodeDecodeError: a
        # torn/partially-copied meta.json must surface as the typed
        # pack-level error (so --verify sweeps and the trainer's
        # build-it-once guidance keep working), never a raw traceback
        except ValueError as e:
            raise PackFormatError(
                f"{path}/{META_NAME} is unreadable ({e}) — torn or "
                "partially copied pack; re-pack with dptpu-pack") from e
        if self.meta.get("format") != FORMAT_VERSION:
            raise PackFormatError(
                f"{path} has pack format {self.meta.get('format')}; this "
                f"reader speaks {FORMAT_VERSION} — re-pack with the "
                "current dptpu-pack")
        self.kind = self.meta.get("kind")
        if self.kind not in KINDS:
            raise PackFormatError(f"{path} has unknown kind {self.kind!r}")
        if expect_kind is not None and self.kind != expect_kind:
            raise PackFormatError(
                f"{path} is a {self.kind!r} pack but this run needs "
                f"{expect_kind!r} — pack the matching task")
        with open(os.path.join(path, INDEX_NAME), "rb") as f:
            raw = f.read()
        if (zlib.crc32(raw) & 0xFFFFFFFF) != int(self.meta["index_crc32"]):
            raise PackFormatError(
                f"{path}/{INDEX_NAME} fails its checksum — the index is "
                "torn; re-pack with dptpu-pack")
        self._index = np.frombuffer(raw, INDEX_DTYPE)
        if len(self._index) != int(self.meta["n_records"]):
            raise PackFormatError(
                f"{path} index holds {len(self._index)} rows but meta "
                f"says {self.meta['n_records']}")
        bin_path = os.path.join(path, BIN_NAME)
        actual = os.path.getsize(bin_path)
        if actual != int(self.meta["bin_bytes"]):
            raise PackFormatError(
                f"{path}/{BIN_NAME} is {actual} bytes but meta says "
                f"{self.meta['bin_bytes']} — truncated/overgrown pack; "
                "re-pack with dptpu-pack")
        self._im_ids = list(self.meta["im_ids"])
        n = len(self._index)
        q = sorted({int(i) for i in quarantine})
        bad = [i for i in q if not 0 <= i < n]
        if bad:
            raise ValueError(
                f"pack_quarantine indices {bad} out of range [0, {n}) "
                f"for {path}")
        self.quarantine = tuple(q)
        self._live = (np.setdiff1d(np.arange(n), np.asarray(q, np.int64))
                      if q else np.arange(n))
        self._open_bin()

    def _open_bin(self) -> None:
        self._bin = np.memmap(os.path.join(self.path, BIN_NAME),
                              mode="r", dtype=np.uint8)

    # mmap handles don't pickle; the files are the shared state (the
    # prepared-cache idiom — grain process workers reopen)
    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("_bin")
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._open_bin()

    # ------------------------------------------------------- protocol
    def __len__(self) -> int:
        return len(self._live)

    def record_index(self, index: int) -> int:
        """RAW record id behind dataset position ``index`` (positions
        shift when a quarantine drops records; record ids never do)."""
        return int(self._live[index])

    def sample_image_id(self, index: int) -> str:
        """Image id owning sample ``index`` — the CombinedDataset
        exclusion/dedup key, straight off the index row (no blob
        read)."""
        row = self._index[self.record_index(index)]
        return self._im_ids[int(row["image_idx"])]

    def seek(self, index: int, read: bool = False) -> dict:
        """O(1) record lookup for dataset position ``index``: identity
        fields (``record``, ``image_id``, ``object``, ``category``,
        ``im_size``, ``extreme_points``) from the index row alone; with
        ``read=True`` the verified pixel payload joins as ``image``
        (uint8 RGB) and ``mask`` (the raw stored mask).  This is the
        accessor the sentinel's quarantine ledger and the governor's
        replay arithmetic resolve batch indices through — no sequential
        re-iteration, no decode."""
        rec = self.record_index(index)
        row = self._index[rec]
        out = {
            "record": rec,
            "image_id": self._im_ids[int(row["image_idx"])],
            "object": (str(int(row["object_idx"]))
                       if self.kind == "instance" else None),
            "category": (int(row["category"])
                         if self.kind == "instance" else None),
            "im_size": (int(row["height"]), int(row["width"])),
            "extreme_points": np.array(row["extreme_points"]),
        }
        if read:
            img8, mask = self._read_blob(rec)
            # copies: seek hands records to introspection/ledger code
            # that must never hold (or try to write) mmap views
            out["image"] = img8.copy()
            out["mask"] = mask.copy()
        return out

    def _read_blob(self, rec: int) -> tuple[np.ndarray, np.ndarray]:
        """The verified read of record ``rec``'s pixel payload: one copy
        out of the mmap, the chaos ``data/packed_read`` seam, the crc32
        gate, then zero-copy views into the private buffer."""
        row = self._index[rec]
        off, ln = int(row["blob_offset"]), int(row["blob_len"])
        if off < 0 or off + ln > self._bin.size:
            raise PackedRecordError(
                rec, self.path,
                f"blob extent [{off}, {off + ln}) past the "
                f"{self._bin.size}-byte bin file")
        # ZERO-COPY view of the mmap (read-only: mode="r"): the crc
        # below runs over the page cache directly, and every consumer
        # of the returned views copies before mutating (__getitem__'s
        # astype, seek's explicit copies) — the decode this read
        # replaces costs ~8x the checksum, and an extra memcpy here
        # would hand a third of that win back
        buf = self._bin[off:off + ln]
        # chaos seam: a bitflip fault here models bit rot / a torn read
        # — the crc gate below must catch it, typed, never silent (the
        # fault flips a PRIVATE copy; the pack bytes are never touched)
        buf = chaos_sites.fire("data/packed_read", payload=buf,
                               index=rec, path=self.path)
        if (zlib.crc32(buf) & 0xFFFFFFFF) != int(row["blob_crc32"]):
            raise PackedRecordError(rec, self.path, "checksum mismatch")
        h, w = int(row["height"]), int(row["width"])
        img8 = buf[:h * w * 3].reshape(h, w, 3)
        mask = buf[h * w * 3:].view(
            np.dtype(row["mask_dtype"].decode())).reshape(h, w)
        return img8, mask

    def __getitem__(self, index: int,
                    rng: np.random.Generator | None = None) -> dict:
        rec = self.record_index(int(index))
        row = self._index[rec]
        img8, mask = self._read_blob(rec)
        # the EXACT sample arithmetic of the filesystem classes, re-run
        # on the stored bytes (voc.py:_load_instance / sbd.py sample
        # math) — bitwise parity is by construction, pinned by test
        img = img8.astype(np.float32)
        if self.kind == "instance":
            inst = mask.astype(np.float32)
            void = inst == 255
            if self.suppress_void_pixels:
                inst[void] = 0
            obj_ii = int(row["object_idx"])
            sample = {"image": img,
                      "gt": (inst == obj_ii + 1).astype(np.float32),
                      "void_pixels": void.astype(np.float32)}
            if self.retname:
                sample["meta"] = {
                    "image": self._im_ids[int(row["image_idx"])],
                    "object": str(obj_ii),
                    "category": int(row["category"]),
                    "im_size": (img.shape[0], img.shape[1]),
                }
        else:
            sample = {"image": img, "gt": mask.astype(np.float32)}
            if self.retname:
                sample["meta"] = {
                    "image": self._im_ids[int(row["image_idx"])],
                    "im_size": (img.shape[0], img.shape[1]),
                }
        if self.transform is not None:
            sample = self.transform(sample, rng)
        return sample

    def verify(self) -> list[int]:
        """Re-checksum EVERY record (quarantined included); returns the
        raw indices that fail — the ``dptpu-pack --verify`` engine."""
        bad = []
        for rec in range(len(self._index)):
            try:
                self._read_blob(rec)
            except PackedRecordError:
                bad.append(rec)
        return bad

    def __str__(self) -> str:
        m = self.meta
        return (f"Packed({m['dataset']}-{m['kind']}-"
                f"{'_'.join(m['splits'])},n={m['n_records']},"
                f"idx={int(m['index_crc32']):08x})")


def verify_pack(path: str) -> list[int]:
    """Raw record indices of ``path`` that fail verification."""
    return PackedDataset(path).verify()


def resolve_packed(dataset, index: int):
    """Unwrap the loader-facing wrappers (CombinedDataset, the prepared
    caches) around ``dataset`` to the packed-idiom reader owning sample
    ``index``; returns ``(packed, local_index)`` or ``None`` when the
    chain bottoms out on a non-packed source.  The terminal test is the
    ACCESSOR CONTRACT (``seek`` + ``record_index``), not a class: the
    session-log reader (``data/sessions.py``) speaks it too, so the
    sentinel's quarantine ledger names exact session records the same
    way it names pack records.  The trainer resolves quarantined batch
    indices through this + ``seek``."""
    ds, local = dataset, int(index)
    for _ in range(16):  # wrappers never nest deeper; bounds a cycle
        if isinstance(ds, PackedDataset) or (
                callable(getattr(ds, "seek", None))
                and callable(getattr(ds, "record_index", None))):
            return ds, local
        if hasattr(ds, "datasets") and hasattr(ds, "index"):
            di, local = ds.index[local]
            ds = ds.datasets[di]
            continue
        inner = getattr(ds, "dataset", None)
        if inner is not None:
            ds = inner
            continue
        return None
    return None


# ------------------------------------------------------------------ CLI

def _build_source(args):
    """The raw dataset the CLI packs (imports deferred: sbd needs scipy,
    neither path needs jax)."""
    splits = [s for s in args.splits.split(",") if s]
    if args.dataset == "voc":
        from .voc import VOCInstanceSegmentation, VOCSemanticSegmentation

        if args.task == "instance":
            ds = VOCInstanceSegmentation(
                args.root, split=splits, preprocess=True,
                area_thres=args.area_thres)
        else:
            ds = VOCSemanticSegmentation(args.root, split=splits)
    else:
        from .sbd import SBDInstanceSegmentation, SBDSemanticSegmentation

        if args.task == "instance":
            ds = SBDInstanceSegmentation(
                args.root, split=splits, preprocess=True,
                area_thres=args.area_thres)
        else:
            ds = SBDSemanticSegmentation(args.root, split=splits)
    return ds, splits


def _verify_cli(path: str) -> int:
    """``--verify``: re-checksum one pack dir, or every pack under a
    root; non-zero on ANY mismatch, naming the bad record indices.
    Session-log directories (``serve/session_log.py``; meta kind
    'sessions') verify through their own reader with the same rc/remedy
    conventions — one CLI audits both pack flavors."""
    if os.path.isfile(os.path.join(path, META_NAME)):
        targets = [path]
    else:
        if not os.path.isdir(path):
            # the mistyped-path case is the common one (every torn-pack
            # error message points here) — a clean verdict, no traceback
            print(f"dptpu-pack --verify: no such path {path}",
                  file=sys.stderr)
            return 2
        targets = sorted(
            os.path.join(path, d) for d in os.listdir(path)
            if os.path.isfile(os.path.join(path, d, META_NAME)))
        if not targets:
            print(f"dptpu-pack --verify: no pack ({META_NAME}) under "
                  f"{path}", file=sys.stderr)
            return 2
    rc = 0
    for t in targets:
        from .sessions import SessionLogDataset, is_session_log

        session = is_session_log(t)
        try:
            ds = SessionLogDataset(t) if session else PackedDataset(t)
            bad = ds.verify()
        except (PackFormatError, OSError) as e:
            print(f"{t}: UNREADABLE ({e})", file=sys.stderr)
            rc = 1
            continue
        if bad:
            remedy = (f"quarantine them: data.session_quarantine={bad} "
                      f"(dptpu-flywheel quarantines them itself)"
                      if session else
                      f"re-pack (or, for the TRAIN pack only, quarantine "
                      f"them: data.pack_quarantine={bad})")
            print(f"{t}: {len(bad)} bad record(s): {bad} — {remedy}",
                  file=sys.stderr)
            rc = 1
        else:
            print(f"{t}: ok ({ds.meta['n_records']} records)")
    return rc


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="dptpu-pack",
        description="pack a VOC/SBD dataset into pre-decoded, "
                    "checksummed, memory-mapped records (the "
                    "data.source=packed input plane; see docs/DESIGN.md "
                    "'Packed data plane')")
    parser.add_argument("--root", help="dataset root (the VOCdevkit / "
                                       "benchmark_RELEASE parent)")
    parser.add_argument("--out", help="pack root; the pack lands at "
                                      "<out>/<dataset>-<task>-<splits>")
    parser.add_argument("--dataset", choices=("voc", "sbd"),
                        default="voc")
    parser.add_argument("--task", choices=KINDS, default="instance")
    parser.add_argument("--splits", default="train",
                        help="comma-separated; ONE pack over their "
                             "union (sbd merge packs train,val)")
    parser.add_argument("--area-thres", type=int, default=500,
                        help="instance area filter — MUST match the "
                             "run's data.area_thres (default mirrors "
                             "the config default)")
    parser.add_argument("--verify", metavar="PATH",
                        help="re-checksum every record of a pack (or "
                             "every pack under a root) and exit "
                             "non-zero on any mismatch")
    args = parser.parse_args(argv)
    if args.verify:
        return _verify_cli(args.verify)
    if not args.root or not args.out:
        parser.error("--root and --out are required (or use --verify)")
    ds, splits = _build_source(args)
    out_dir = pack_dir_path(args.out, args.dataset, args.task, splits)
    meta = pack_dataset(ds, out_dir, dataset_name=args.dataset,
                        splits=splits,
                        area_thres=(args.area_thres
                                    if args.task == "instance" else None),
                        progress=True)
    print(json.dumps({
        "pack": out_dir, "records": meta["n_records"],
        "images": meta["n_images"],
        "bytes": meta["bin_bytes"],
        "train_with": (f"data.source=packed data.pack_path={args.out}"),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
