"""Checkpointing: Orbax-backed, full-state, async, with best/latest policies.

The reference's checkpoint story (SURVEY.md §3.5) was whole-model
``state_dict`` saves only: a best-on-metric save (train_pascal.py:301-304), a
broken every-100-epoch snapshot (``modelName`` undefined, :229-230), a
hardcoded warm-start load (:103), and resume scaffolding whose actual load
was commented out (:93-102) — optimizer/RNG/epoch state were never persisted,
so a crash lost them.  Here a checkpoint is the complete ``TrainState``
(params, BN stats, optimizer state, RNG, step) plus the epoch and metric
history; resume is exact.

Run-dir management reproduces the reference's ``run_<N>`` auto-increment
(train_pascal.py:73-82).  Saves are async (Orbax writes in a background
thread while the next epoch trains) and, multi-host, coordinated so only one
logical save happens — the "save if master process" item of the reference's
DDP checklist (train_pascal.py:4), done the JAX way (every process
participates in the barrier; Orbax writes each shard once).
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import re

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp

from ..chaos import sites as chaos_sites
from ..parallel import TrainState
from ..telemetry import events as events_lib
from ..telemetry import get_accountant, span


def atomic_write_json(path: str, obj) -> None:
    """Write ``obj`` as JSON such that ``path`` is either the old content
    or the complete new content — never a torn intermediate: temp file in
    the same directory, flush+fsync, ``os.replace``, then fsync the
    directory so the rename itself survives a crash.  The write-side half
    of the torn-checkpoint story (the read side is
    :meth:`CheckpointManager.restore`'s fallback)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dir_fd = os.open(d, os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)


def param_digest(tree) -> str:
    """Order-stable sha256 over a param tree's raw bytes — the
    byte-identical restored-vs-saved equality check that works across
    processes (chaos invariants; ``CheckpointConfig.digest`` stamps it
    into each save's meta).  Forces a full host readback of the tree —
    call it on state that is about to be serialized anyway."""
    import numpy as np

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(tree):
        h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return h.hexdigest()


def next_run_index(work_dir: str) -> int:
    """1 + the highest existing ``run_<N>`` under ``work_dir`` (0 if none)."""
    runs = glob.glob(os.path.join(work_dir, "run_*"))
    ids = [int(m.group(1)) for r in runs
           if (m := re.search(r"run_(\d+)$", r))]
    return max(ids) + 1 if ids else 0


def next_run_dir(work_dir: str, resume_run: int | None = None) -> str:
    """``work_dir/run_<N>`` with N = 1 + max existing (or the pinned resume
    run — the reference pinned ``run_0`` when resuming, train_pascal.py:78).

    Multi-process: every process must use the SAME run dir (Orbax's
    multihost save coordinates on one path, and on a shared filesystem the
    auto-increment would race), so process 0 picks the index and broadcasts
    it.  Requires ``jax.distributed`` to be initialized first — true by the
    time a multi-host ``Trainer`` constructs.
    """
    if resume_run is not None:
        nxt = resume_run
    elif jax.process_count() > 1:
        import jax.numpy as jnp
        from jax.experimental import multihost_utils
        local = next_run_index(work_dir) if jax.process_index() == 0 else 0
        nxt = int(multihost_utils.broadcast_one_to_all(jnp.int32(local)))
    else:
        nxt = next_run_index(work_dir)
    path = os.path.join(work_dir, f"run_{nxt}")
    os.makedirs(path, exist_ok=True)
    return path


class CheckpointManager:
    """Latest-k rolling checkpoints + a separately-retained best-on-metric
    checkpoint, both full ``TrainState``.

    ``metric`` follows the reference's gate: threshold-max mean Jaccard, save
    when it beats the best seen (train_pascal.py:298-304).
    """

    def __init__(self, directory: str, keep_latest: int = 3,
                 best_metric_init: float = 0.0, async_save: bool = True,
                 digest: bool = False,
                 static_meta: dict | None = None):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.best_metric = best_metric_init
        options = ocp.CheckpointManagerOptions(
            max_to_keep=keep_latest,
            enable_async_checkpointing=async_save,
            best_fn=None,
        )
        self._mgr = ocp.CheckpointManager(
            os.path.join(self.directory, "latest"), options=options)
        best_options = ocp.CheckpointManagerOptions(
            max_to_keep=1, enable_async_checkpointing=async_save)
        self._best = ocp.CheckpointManager(
            os.path.join(self.directory, "best"), options=best_options)
        self._async_save = async_save
        #: checkpoint.digest: stamp each save's meta with
        #: ``param_digest(state.params)`` so byte-identical restore is
        #: checkable across process deaths (costs a param readback/save)
        self._digest = digest
        #: steps :meth:`restore` skipped as unreadable (torn files) on the
        #: way to the one it returned — the chaos runner's invariant hook
        self.last_restore_fallback: list[int] = []
        #: keys merged into EVERY save's meta (the trainer stamps its
        #: resolved parallel plan here, so any restore can tell whether
        #: it is crossing plans); per-save ``extra`` wins on collision
        self._static_meta = dict(static_meta or {})

    #: commit ledger sidecar (written via :func:`atomic_write_json`):
    #: records which steps had fully LANDED saves, so a restore failure
    #: can say "torn after commit" vs "save never finished"
    _LEDGER = "COMMITTED.json"

    def _write_ledger(self) -> None:
        """Refresh the commit ledger from the managers' landed steps.
        Called after sync saves and at :meth:`wait` (async saves are only
        committed once their background write finishes).  Process 0 only:
        multi-host training shares ONE checkpoint directory, and N
        processes racing the same tmp-and-replace would tear the very
        ledger that exists to diagnose torn writes."""
        if jax.process_index() != 0:
            return
        latest = sorted(int(s) for s in self._mgr.all_steps())
        atomic_write_json(
            os.path.join(self.directory, self._LEDGER),
            {"latest": latest,
             "best": sorted(int(s) for s in self._best.all_steps())})
        # flight recorder: the commit anchor — the rollback target set /
        # supervisor progress signal the timeline stitches generations on
        events_lib.emit("checkpoint", "commit",
                        step=(latest[-1] if latest else None),
                        payload={"committed_steps": len(latest)})

    def committed_steps(self, best: bool = False) -> set[int]:
        """Steps the ledger records as fully landed in the requested
        slot (empty when the ledger predates this manager or was never
        written)."""
        try:
            with open(os.path.join(self.directory, self._LEDGER)) as f:
                return set(json.load(f).get(
                    "best" if best else "latest", ()))
        except (OSError, ValueError):
            return set()

    def save(self, step: int, state: TrainState, metric: float | None = None,
             extra: dict | None = None) -> bool:
        """Save a rolling checkpoint; if ``metric`` improves on the best seen,
        also save to the best slot.  Returns True when a new best was saved.

        ``best_metric`` is updated *before* the meta is written, so the
        checkpoint always records the post-save gate — resuming from it can
        never re-admit a worse model as "best"."""
        is_best = metric is not None and metric > self.best_metric
        if is_best:
            self.best_metric = float(metric)
        payload = {"state": ocp.args.StandardSave(state)}
        meta = dict(self._static_meta)
        meta.update({"step": int(step), "best_metric": self.best_metric})
        if self._digest:
            meta["param_digest"] = param_digest(state.params)
        if metric is not None:
            meta["metric"] = float(metric)
        if extra:
            meta.update(extra)
        payload["meta"] = ocp.args.JsonSave(meta)
        # goodput: async saves charge only the enqueue here; the Orbax
        # write itself lands in wait()'s checkpoint bucket
        with get_accountant().account("checkpoint"), span("checkpoint/save"):
            if self._async_save:
                # Refresh the ledger from the saves that have LANDED so
                # far, BEFORE enqueueing this one: Orbax serializes async
                # saves (a new save waits out the previous), so at entry
                # every earlier step in all_steps() is fully committed.
                # Without this the ledger only appears at wait() — i.e.
                # never in a process that crashes mid-run, starving both
                # the supervisor's progress signal (train/supervise.py)
                # and the sentinel's committed-rollback targets.
                self._write_ledger()
            self._mgr.save(step, args=ocp.args.Composite(**payload))
            if is_best:
                self._best.save(step, args=ocp.args.Composite(**payload))
            events_lib.emit(
                "checkpoint", "save", step=int(step),
                epoch=(int(meta["epoch"]) if "epoch" in meta else None),
                payload={"best": is_best, "async": self._async_save,
                         "preempted": bool(meta.get("preempted"))})
            if not self._async_save:
                # sync saves have landed; async ones commit at wait()
                self._write_ledger()
        # chaos seam: the truncation fault tears this step's files (the
        # torn-write / post-commit-corruption scenario the restore
        # fallback exists for).  Sync saves only — an async save's step
        # dir is still a tmp name here, so firing would raise (no file
        # under the final path) or tear a file mid-write, neither of
        # which is the documented scenario.
        if not self._async_save:
            chaos_sites.fire("checkpoint/save", step=int(step),
                             path=os.path.join(self.directory, "latest",
                                               str(int(step))))
        return is_best

    def restore(self, state: TrainState, step: int | None = None,
                best: bool = False) -> tuple[TrainState, dict]:
        """Restore ``(state, meta)``; ``state`` is the abstract target whose
        shapes/shardings the restored arrays adopt (so a checkpoint written on
        one mesh restores onto another — the multi-host resume path).

        Torn-file fallback: with no pinned ``step``, an unreadable newest
        checkpoint (truncated array file, interrupted write, post-commit
        corruption) is SKIPPED — loudly — and the next older step is
        tried, so one torn file costs an epoch of progress instead of the
        whole run.  The skipped steps land in ``last_restore_fallback``.
        A caller-pinned ``step`` never falls back (they asked for that
        exact checkpoint)."""
        mgr = self._best if best else self._mgr
        pinned = step is not None
        candidates = [step] if pinned else \
            sorted((int(s) for s in mgr.all_steps()), reverse=True)
        if not candidates:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        self.last_restore_fallback = []
        committed = None
        restored = None
        with get_accountant().account("checkpoint"), \
                span("checkpoint/restore"), \
                chaos_sites.inject("checkpoint/restore"):
            for i, s in enumerate(candidates):
                try:
                    restored = mgr.restore(
                        s,
                        args=ocp.args.Composite(
                            state=ocp.args.StandardRestore(state),
                            meta=ocp.args.JsonRestore(),
                        ),
                    )
                    break
                except Exception as e:
                    if pinned or i == len(candidates) - 1:
                        raise
                    if committed is None:
                        committed = self.committed_steps(best=best)
                    diagnosis = ("torn after commit" if s in committed
                                 else "save may not have finished")
                    print(f"warning: checkpoint step {s} is unreadable "
                          f"({type(e).__name__}: {e}; {diagnosis}) — "
                          f"falling back to step {candidates[i + 1]}",
                          flush=True)
                    self.last_restore_fallback.append(int(s))
            # DONATION SAFETY: re-buffer every restored array.  The train
            # step donates its state argument, and donating
            # Orbax-restored buffers corrupts the heap on XLA CPU
            # (deterministic segfault at the first resumed dispatch — the
            # crash that forced tests/test_preemption.py's subprocess
            # isolation).  One copy pass (~ms, transiently 2x state in
            # memory) buys donation-safe, framework-owned buffers on
            # every backend.  OUTSIDE the fallback try/except above: a
            # copy failure (OOM, non-addressable multi-host array) is its
            # own error and must never masquerade as a torn checkpoint.
            fresh = jax.tree.map(
                lambda x: jnp.copy(x) if isinstance(x, jax.Array)
                else x, restored["state"])
            meta = restored["meta"]
            events_lib.emit(
                "checkpoint", "restore",
                step=(int(meta.get("step"))
                      if meta.get("step") is not None else None),
                payload={"best": best,
                         "fallback_steps": list(self.last_restore_fallback)})
            self._announce_topology_crossing(meta)
            return fresh, meta

    @staticmethod
    def _announce_topology_crossing(meta) -> None:
        """A checkpoint whose saved plan names a DIFFERENT topology than
        the one restoring it is crossing a membership change — say so
        loudly at the restore itself, so every consumer (Trainer resume,
        Predictor.from_run, ad-hoc tooling) gets the announcement even
        when it never compares plans.  The arrays are safe either way
        (StandardRestore adopts the target layout); the loudness is the
        contract — an elastic restore must never be silent."""
        from ..parallel.plan import topology_fingerprint

        saved = ((meta or {}).get("plan") or {}).get("topology")
        if not saved:
            return  # pre-fingerprint meta: nothing to compare
        live = topology_fingerprint()
        if saved != live:
            # flight recorder: the topology crossing (every host — each
            # process's restore crossed it)
            events_lib.emit("checkpoint", "topology_crossing",
                            payload={"saved": saved, "live": live})
            if jax.process_index() == 0:
                print(f"checkpoint: restoring across a topology change "
                      f"({saved} -> {live}) — arrays reshard into the "
                      "target state's layout", flush=True)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        """Steps present in the rolling (latest) slot, ascending."""
        return sorted(int(s) for s in self._mgr.all_steps())

    def wait(self) -> None:
        """Block until async saves land (call before process exit)."""
        with get_accountant().account("checkpoint"), span("checkpoint/wait"):
            self._mgr.wait_until_finished()
            self._best.wait_until_finished()
            if self._async_save:
                self._write_ledger()

    def close(self) -> None:
        self.wait()
        self._mgr.close()
        self._best.close()


def latest_checkpoint_dir(work_dir: str,
                          exclude_run: str | None = None) -> str | None:
    """The ``checkpoints`` dir of the highest-numbered ``run_<N>`` that has
    a restorable step — the target of ``resume=auto`` (restart-and-continue
    without knowing the run index; the reference hardcoded ``run_0``,
    train_pascal.py:78-79).  ``exclude_run`` skips the caller's own
    freshly-created run dir (whose manager metadata makes the directory
    non-empty before any step is saved).  None when no run qualifies."""
    def scan() -> int:
        runs = glob.glob(os.path.join(work_dir, "run_*"))
        indexed = sorted(
            (int(m.group(1)), r) for r in runs
            if (m := re.search(r"run_(\d+)$", r)))
        skip = os.path.abspath(exclude_run) if exclude_run else None
        for idx, run in reversed(indexed):
            if skip and os.path.abspath(run) == skip:
                continue
            # a restorable run has a numeric step dir in its "latest" slot
            # (CheckpointManager layout: checkpoints/latest/<step>)
            latest = os.path.join(run, "checkpoints", "latest")
            if os.path.isdir(latest) and any(
                    d.isdigit() for d in os.listdir(latest)):
                return idx
        return -1

    if jax.process_count() > 1:
        # Same race as next_run_dir: filesystem views can differ across
        # hosts (attribute caching, concurrent saves) and divergent resume
        # sources would deadlock the first collective — process 0 decides.
        import jax.numpy as jnp
        from jax.experimental import multihost_utils
        local = scan() if jax.process_index() == 0 else -1
        idx = int(multihost_utils.broadcast_one_to_all(jnp.int32(local)))
    else:
        idx = scan()
    if idx < 0:
        return None
    return os.path.join(work_dir, f"run_{idx}", "checkpoints")
