"""Checkpointing: Orbax-backed, full-state, async, with best/latest policies.

The reference's checkpoint story (SURVEY.md §3.5) was whole-model
``state_dict`` saves only: a best-on-metric save (train_pascal.py:301-304), a
broken every-100-epoch snapshot (``modelName`` undefined, :229-230), a
hardcoded warm-start load (:103), and resume scaffolding whose actual load
was commented out (:93-102) — optimizer/RNG/epoch state were never persisted,
so a crash lost them.  Here a checkpoint is the complete ``TrainState``
(params, BN stats, optimizer state, RNG, step) plus the epoch and metric
history; resume is exact.

Run-dir management reproduces the reference's ``run_<N>`` auto-increment
(train_pascal.py:73-82).  Saves are async (Orbax writes in a background
thread while the next epoch trains) and, multi-host, coordinated so only one
logical save happens — the "save if master process" item of the reference's
DDP checklist (train_pascal.py:4), done the JAX way (every process
participates in the barrier; Orbax writes each shard once).
"""

from __future__ import annotations

import glob
import os
import re

import jax
import orbax.checkpoint as ocp

from ..parallel import TrainState
from ..telemetry import get_accountant, span


def next_run_index(work_dir: str) -> int:
    """1 + the highest existing ``run_<N>`` under ``work_dir`` (0 if none)."""
    runs = glob.glob(os.path.join(work_dir, "run_*"))
    ids = [int(m.group(1)) for r in runs
           if (m := re.search(r"run_(\d+)$", r))]
    return max(ids) + 1 if ids else 0


def next_run_dir(work_dir: str, resume_run: int | None = None) -> str:
    """``work_dir/run_<N>`` with N = 1 + max existing (or the pinned resume
    run — the reference pinned ``run_0`` when resuming, train_pascal.py:78).

    Multi-process: every process must use the SAME run dir (Orbax's
    multihost save coordinates on one path, and on a shared filesystem the
    auto-increment would race), so process 0 picks the index and broadcasts
    it.  Requires ``jax.distributed`` to be initialized first — true by the
    time a multi-host ``Trainer`` constructs.
    """
    if resume_run is not None:
        nxt = resume_run
    elif jax.process_count() > 1:
        import jax.numpy as jnp
        from jax.experimental import multihost_utils
        local = next_run_index(work_dir) if jax.process_index() == 0 else 0
        nxt = int(multihost_utils.broadcast_one_to_all(jnp.int32(local)))
    else:
        nxt = next_run_index(work_dir)
    path = os.path.join(work_dir, f"run_{nxt}")
    os.makedirs(path, exist_ok=True)
    return path


class CheckpointManager:
    """Latest-k rolling checkpoints + a separately-retained best-on-metric
    checkpoint, both full ``TrainState``.

    ``metric`` follows the reference's gate: threshold-max mean Jaccard, save
    when it beats the best seen (train_pascal.py:298-304).
    """

    def __init__(self, directory: str, keep_latest: int = 3,
                 best_metric_init: float = 0.0, async_save: bool = True):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.best_metric = best_metric_init
        options = ocp.CheckpointManagerOptions(
            max_to_keep=keep_latest,
            enable_async_checkpointing=async_save,
            best_fn=None,
        )
        self._mgr = ocp.CheckpointManager(
            os.path.join(self.directory, "latest"), options=options)
        best_options = ocp.CheckpointManagerOptions(
            max_to_keep=1, enable_async_checkpointing=async_save)
        self._best = ocp.CheckpointManager(
            os.path.join(self.directory, "best"), options=best_options)

    def save(self, step: int, state: TrainState, metric: float | None = None,
             extra: dict | None = None) -> bool:
        """Save a rolling checkpoint; if ``metric`` improves on the best seen,
        also save to the best slot.  Returns True when a new best was saved.

        ``best_metric`` is updated *before* the meta is written, so the
        checkpoint always records the post-save gate — resuming from it can
        never re-admit a worse model as "best"."""
        is_best = metric is not None and metric > self.best_metric
        if is_best:
            self.best_metric = float(metric)
        payload = {"state": ocp.args.StandardSave(state)}
        meta = {"step": int(step), "best_metric": self.best_metric}
        if metric is not None:
            meta["metric"] = float(metric)
        if extra:
            meta.update(extra)
        payload["meta"] = ocp.args.JsonSave(meta)
        # goodput: async saves charge only the enqueue here; the Orbax
        # write itself lands in wait()'s checkpoint bucket
        with get_accountant().account("checkpoint"), span("checkpoint/save"):
            self._mgr.save(step, args=ocp.args.Composite(**payload))
            if is_best:
                self._best.save(step, args=ocp.args.Composite(**payload))
        return is_best

    def restore(self, state: TrainState, step: int | None = None,
                best: bool = False) -> tuple[TrainState, dict]:
        """Restore ``(state, meta)``; ``state`` is the abstract target whose
        shapes/shardings the restored arrays adopt (so a checkpoint written on
        one mesh restores onto another — the multi-host resume path)."""
        mgr = self._best if best else self._mgr
        if step is None:
            step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        with get_accountant().account("checkpoint"), \
                span("checkpoint/restore"):
            restored = mgr.restore(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(state),
                    meta=ocp.args.JsonRestore(),
                ),
            )
        return restored["state"], restored["meta"]

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def wait(self) -> None:
        """Block until async saves land (call before process exit)."""
        with get_accountant().account("checkpoint"), span("checkpoint/wait"):
            self._mgr.wait_until_finished()
            self._best.wait_until_finished()

    def close(self) -> None:
        self.wait()
        self._mgr.close()
        self._best.close()


def latest_checkpoint_dir(work_dir: str,
                          exclude_run: str | None = None) -> str | None:
    """The ``checkpoints`` dir of the highest-numbered ``run_<N>`` that has
    a restorable step — the target of ``resume=auto`` (restart-and-continue
    without knowing the run index; the reference hardcoded ``run_0``,
    train_pascal.py:78-79).  ``exclude_run`` skips the caller's own
    freshly-created run dir (whose manager metadata makes the directory
    non-empty before any step is saved).  None when no run qualifies."""
    def scan() -> int:
        runs = glob.glob(os.path.join(work_dir, "run_*"))
        indexed = sorted(
            (int(m.group(1)), r) for r in runs
            if (m := re.search(r"run_(\d+)$", r)))
        skip = os.path.abspath(exclude_run) if exclude_run else None
        for idx, run in reversed(indexed):
            if skip and os.path.abspath(run) == skip:
                continue
            # a restorable run has a numeric step dir in its "latest" slot
            # (CheckpointManager layout: checkpoints/latest/<step>)
            latest = os.path.join(run, "checkpoints", "latest")
            if os.path.isdir(latest) and any(
                    d.isdigit() for d in os.listdir(latest)):
                return idx
        return -1

    if jax.process_count() > 1:
        # Same race as next_run_dir: filesystem views can differ across
        # hosts (attribute caching, concurrent saves) and divergent resume
        # sources would deadlock the first collective — process 0 decides.
        import jax.numpy as jnp
        from jax.experimental import multihost_utils
        local = scan() if jax.process_index() == 0 else -1
        idx = int(multihost_utils.broadcast_one_to_all(jnp.int32(local)))
    else:
        idx = scan()
    if idx < 0:
        return None
    return os.path.join(work_dir, f"run_{idx}", "checkpoints")
