"""The Trainer: end-to-end experiment driver.

This is the framework's replacement for the whole of the reference's
module-level script (train_pascal.py:41-309) — device setup, run-dir
management, model/optimizer/loss construction, the epoch loop with per-epoch
validation, best-checkpoint gating, metric logging and timing — rebuilt as a
class over the TPU-native subsystems:

* one ``jax.sharding.Mesh`` instead of ``nn.DataParallel`` (reference :92);
* one jitted train step (forward+loss+backward+update, grad-accum inside)
  instead of the eager per-batch body (:185-226);
* per-host sharded loaders instead of the planned distributed sampler (:3);
* Orbax full-state checkpoints instead of bare ``state_dict`` saves
  (:229-230, :301-304), with exact resume (params, optimizer, RNG, epoch,
  best-metric — all the state the reference lost on restart);
* process-0-gated logging (the "save if master process" checklist item, :4).

The default config reproduces the reference's experiment: DANet-ResNet101,
4-channel 512² crops, SGD(5e-8, 0.9, 5e-4), batch 16, val every epoch with
threshold-max Jaccard gating best saves.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..data import (
    DataLoader,
    VOCInstanceSegmentation,
    VOCSemanticSegmentation,
    build_eval_transform,
    build_semantic_eval_transform,
    build_semantic_train_transform,
    build_train_transform,
    make_fake_voc,
)
from ..data.governor import GOVERNOR_MODES, FeedActuators, FeedGovernor
from ..chaos import sites as chaos_sites
from ..models import build_model
from ..parallel import (
    DATA_AXIS,
    DEVICE_KEYS,
    WIRE_KEY,
    create_train_state,
    make_eval_step,
    make_train_step,
    pack_wire,
    prefetch_to_device,
)
from ..parallel import plan as plan_lib
from ..telemetry import TraceCapture, get_accountant, mfu_estimate
from ..telemetry import events as events_lib
from ..telemetry import set_enabled as telemetry_set_enabled
from ..utils.helpers import generate_param_report
from ..utils.profiling import device_memory_stats
from ..chaos.policies import CircuitBreaker, CircuitOpenError
from . import config as config_lib
from .checkpoint import (
    CheckpointManager,
    atomic_write_json,
    latest_checkpoint_dir,
    next_run_dir,
)
from .evaluate import (
    batch_debug_asserts,
    evaluate,
    evaluate_semantic,
    semantic_batch_debug_asserts,
)
from .logging import (
    MetricWriter,
    MultiWriter,
    make_val_panels,
    make_writer,
)
from .optim import make_optimizer
from .precision import precision_policy
from .preemption import PreemptionGuard
from .sentinel import StepSentinel


class _RollbackBudgetTick(Exception):
    """Internal: one rollback counted against the CircuitBreaker budget
    (raised inside the breaker so the rollback books as a failure, caught
    immediately by the handler)."""


class _DivergenceDetected(RuntimeError):
    """Internal control flow: the sentinel returned ``diverged`` inside
    ``train_epoch``; ``fit`` catches this and runs rollback-and-replay.
    Escapes only when no sentinel rollback is possible (budget spent /
    no checkpoint), converted to a loud ``FloatingPointError``."""

    def __init__(self, epoch: int, step_start: int, step_end: int,
                 batch_indices: list[int], losses: list, report):
        self.epoch = epoch
        self.step_start = step_start      # global steps, inclusive window
        self.step_end = step_end
        self.batch_indices = batch_indices
        self.losses = losses              # observed losses in the window
        self.report = report              # the SentinelReport that tripped
        super().__init__(
            f"sentinel verdict 'diverged' at step {report.step} "
            f"({report.reason}: {report.value}) — window "
            f"[{step_start}, {step_end}] of epoch {epoch}, "
            f"{len(batch_indices)} batch(es) to quarantine")


class _TrainerFeedActuators(FeedActuators):
    """The feed governor's knobs, bound to a live trainer (see
    data/governor.py): prefetch depths resize hot (both prefetchers read
    their bound live), the device-path flip and echo factor apply at
    epoch boundaries only — the governor owns that discipline."""

    def __init__(self, trainer: "Trainer"):
        self._t = trainer

    def get_prefetch(self) -> tuple[int, int]:
        return self._t._host_prefetch, self._t._device_prefetch

    def set_prefetch(self, host: int, device: int) -> None:
        t = self._t
        t._host_prefetch = int(host)
        t._device_prefetch = int(device)
        if hasattr(t.train_loader, "prefetch"):  # grain has no live bound
            t.train_loader.prefetch = int(host)

    def flip_available(self) -> tuple[bool, str]:
        return self._t._feed_flip_available()

    def flip_device_path(self) -> None:
        self._t._flip_device_path()

    def get_echo(self) -> int:
        return self._t._echo

    def base_echo(self) -> int:
        return self._t.cfg.data.echo

    def can_set_echo(self) -> tuple[bool, str]:
        if self._t.cfg.data.steps_per_dispatch > 1:
            return False, ("data.steps_per_dispatch > 1 packs distinct "
                           "batches per dispatch — mutually exclusive "
                           "with echo")
        return True, ""

    def set_echo(self, factor: int) -> None:
        # takes effect at the next epoch (train_epoch reads it at entry);
        # schedules were sized for the BASE echo, so a governor-armed
        # factor shortens the poly/cosine horizon rather than extending
        # it — constant LR (the default) is unaffected
        self._t._echo = max(1, int(factor))

    def pack_status(self) -> tuple[bool, str | None]:
        return self._t._pack_status()


class Trainer:
    """Build once, ``fit()`` to train, ``validate()`` to eval.

    All construction is lazy-free and explicit so tests can reach into any
    piece (``trainer.state``, ``trainer.mesh``, ``trainer.train_step`` …).
    """

    def __init__(self, cfg: config_lib.Config,
                 writers: MetricWriter | None = None):
        self.cfg = cfg
        self.is_main = jax.process_index() == 0

        # --- run dir (reference run_<N> scheme, train_pascal.py:73-82)
        self.run_dir = next_run_dir(cfg.work_dir)
        # --- flight recorder (telemetry/events.py): every host opens its
        # own run_dir/events/<host>.<pid>.jsonl; the run_<N> index is the
        # process generation the timeline merger stitches on.  cfg.telemetry
        # off = never configured = every emit() is one list check.
        self._events = (events_lib.configure(self.run_dir)
                        if cfg.telemetry else None)
        if writers is not None:
            self.writer = writers
        elif self.is_main:
            self.writer = MultiWriter(*[
                make_writer(name, self.run_dir,
                            experiment_name=cfg.experiment_name,
                            comet_project=cfg.comet_project or None,
                            comet_workspace=cfg.comet_workspace or None)
                for name in cfg.log_writers])
        else:
            self.writer = MetricWriter()  # no-op on non-main hosts

        if cfg.task == "instance" and cfg.model.nclass != 1:
            # The instance protocol is binary by construction (sigmoid
            # prediction pasted back per object, reference
            # train_pascal.py:262,283-291); a multi-channel head would fail
            # opaquely inside the evaluator's paste-back.
            raise ValueError(
                f"task='instance' requires model.nclass=1 (binary sigmoid "
                f"head), got {cfg.model.nclass}; use task='semantic' for "
                "multi-class")
        if cfg.data.echo < 1:
            raise ValueError(f"data.echo must be >= 1, got {cfg.data.echo}")
        if cfg.data.source not in ("fs", "packed"):
            raise ValueError(
                f"data.source must be 'fs' or 'packed', got "
                f"{cfg.data.source!r}")
        if cfg.data.source == "packed" and not cfg.data.pack_path:
            raise ValueError(
                "data.source=packed needs data.pack_path — the pack root "
                "dptpu-pack --out wrote (pack once, mmap forever; see "
                "docs/QUICKSTART.md 'Packing a dataset')")
        if cfg.data.pack_quarantine and cfg.data.source != "packed":
            raise ValueError(
                "data.pack_quarantine names records of a pack — it needs "
                "data.source=packed")
        if cfg.data.prepared_cache and cfg.data.source != "packed" \
                and self.is_main:
            # migration pointer (loud, once): the packed data plane is
            # the ONE prepared format going forward — it pre-decodes the
            # whole source, shards reads by host and gives the governor/
            # sentinel O(1) seek; the prepared crop cache still works
            # but is legacy.  prepared OVER a packed source is the
            # blessed composition — no note for runs already packed.
            from ..data.packed import pack_commands_for_config
            print(
                "note: data.prepared_cache is the LEGACY prepared format "
                "— the packed data plane (data/packed.py) supersedes it: "
                "pack once with `"
                + " && ".join(pack_commands_for_config(cfg))
                + "` and set data.source=packed data.pack_path=<out>",
                file=sys.stderr, flush=True)
        if cfg.data.governor not in GOVERNOR_MODES:
            raise ValueError(
                f"data.governor must be one of {GOVERNOR_MODES}, got "
                f"{cfg.data.governor!r}")
        if cfg.data.max_echo < 1:
            raise ValueError(
                f"data.max_echo must be >= 1, got {cfg.data.max_echo}")
        if cfg.data.governor == "auto" and not cfg.telemetry:
            # auto is multi-host safe since the consensus primitive
            # (parallel/consensus.py): every ladder input routes through
            # replicated_decision, so hosts can never disagree about the
            # echo factor — the old single-process-only restriction is
            # lifted
            raise ValueError(
                "data.governor=auto needs telemetry=true: the goodput "
                "accountant's input_wait attribution IS the stall "
                "signal the governor acts on")
        if cfg.data.steps_per_dispatch < 1:
            raise ValueError(f"data.steps_per_dispatch must be >= 1, got "
                             f"{cfg.data.steps_per_dispatch}")
        if cfg.data.steps_per_dispatch > 1 and cfg.data.echo > 1:
            raise ValueError(
                "data.steps_per_dispatch and data.echo both repeat steps "
                "per host batch in incompatible ways — pick one (echo "
                "re-steps the SAME batch; steps_per_dispatch packs "
                "DISTINCT batches into one dispatch)")
        if (cfg.eval_tta_scales or cfg.eval_tta_flip) \
                and cfg.task != "semantic":
            raise ValueError(
                "eval_tta_scales/eval_tta_flip apply to the semantic task "
                "only (the instance protocol is the reference's fixed "
                "threshold sweep)")
        if cfg.eval_full_res and cfg.task != "semantic":
            raise ValueError(
                "eval_full_res applies to the semantic task only (the "
                "instance protocol already scores at full resolution via "
                "crop2fullmask paste-back)")

        # --- parallel plan (parallel/plan.py): the declarative strategy
        # -> validated mesh + composed sharding layout.  With
        # parallel.strategy unset the legacy mesh.* knobs still derive a
        # plan, so EVERY run carries one — recorded in fit_summary.json,
        # every checkpoint's meta (the cross-plan restore discriminator)
        # and the bench record's plan block.  strategy=auto walks the
        # mesh-shape ladder with the memory model; the resolution is
        # printed so the run's layout is never a mystery.
        self.plan = plan_lib.plan_from_config(
            cfg, memory_inputs=(self._plan_memory_inputs
                                if cfg.parallel.strategy == "auto"
                                else None))
        if self.is_main and cfg.parallel.strategy == "auto":
            print(f"parallel.strategy=auto resolved to "
                  f"{self.plan.describe()}", flush=True)
        self.mesh = self.plan.make_mesh()

        # --- live feed knobs (data/governor.py): the governor's
        # actuation surface.  Config values seed them; the governor (auto
        # mode) may move them — prefetch depths hot (both prefetchers
        # read their bound live), echo at epoch boundaries only.
        self._host_prefetch = cfg.data.prefetch
        self._device_prefetch = cfg.data.device_prefetch
        self._echo = cfg.data.echo
        #: set when the governor's epoch-boundary flip moved augmentation
        #: (+ guidance) on device mid-run
        self._feed_flipped = False

        # --- data
        root = cfg.data.root
        if cfg.data.fake:
            root = root or os.path.join(self.run_dir, "fake_voc")
            if not os.path.exists(os.path.join(root, "VOCdevkit")):
                make_fake_voc(root, n_images=8, size=(96, 128), n_val=3,
                              seed=cfg.seed)
        elif cfg.data.download:
            # Fetch once, on process 0 only — N processes racing a 2 GB
            # urlretrieve/extract into a shared root corrupts the tree.
            # Process 0's failure is caught and broadcast (the broadcast IS
            # the barrier), so the other processes fail fast instead of
            # hanging on a barrier process 0 never reaches.
            from ..data.voc import ensure_voc
            err = ""
            if self.is_main:
                try:
                    ensure_voc(root, download=True)
                except Exception as e:  # re-raised below, on every process
                    err = f"{type(e).__name__}: {e}"
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils
                failed = int(multihost_utils.broadcast_one_to_all(
                    jnp.int32(bool(err))))
                if failed and not err:
                    err = "see process 0 logs"
            if err:
                raise RuntimeError(f"VOC download failed on process 0 "
                                   f"({err})")
        #: the resolved dataset root (fake fixtures land under the run
        #: dir) — the governor's pack_recommendation names it
        self._data_root = root
        if cfg.data.packbits_masks and not (
                cfg.data.uint8_transfer and cfg.task == "instance"):
            raise ValueError(
                "data.packbits_masks packs the BINARY instance mask for "
                "the uint8 wire — it requires task=instance (semantic gt "
                "is class ids, not bits) and data.uint8_transfer (the "
                "packed row rides the uint8 fast path)")
        if cfg.data.coalesce_wire and not cfg.data.uint8_transfer:
            raise ValueError(
                "data.coalesce_wire concatenates the batch's uint8 leaves "
                "into one wire buffer — it requires data.uint8_transfer "
                "(float leaves would need a bitcast wire this deliberately "
                "avoids); enable uint8_transfer + prepared_cache")
        if cfg.data.uint8_transfer and not cfg.data.prepared_cache:
            raise ValueError(
                "data.uint8_transfer needs data.prepared_cache: only the "
                "prepared pipeline is uint8-exact end-to-end (the plain "
                "pipeline's cubic resize leaves fractional float values "
                "that quantization would silently alter)")
        if cfg.data.uint8_transfer and cfg.task == "instance" \
                and not (cfg.data.device_guidance
                         or cfg.data.guidance == "none"):
            raise ValueError(
                "data.uint8_transfer with HOST-side guidance is a no-op on "
                "the dominant tensor: concatenating the float guidance map "
                "promotes 'concat' back to float32, so the advertised 4x "
                "wire saving never happens — set data.device_guidance=true "
                "(the map is synthesized on device from the uint8 crop_gt) "
                "or data.guidance=none")
        if cfg.data.device_guidance:
            from ..ops.guidance_device import FAMILIES as _DEV_FAM
            if cfg.task != "instance":
                raise ValueError("data.device_guidance applies to the "
                                 "instance task only (semantic has no "
                                 "guidance channel)")
            if cfg.data.guidance not in _DEV_FAM:
                raise ValueError(
                    f"data.device_guidance supports {_DEV_FAM}, not "
                    f"{cfg.data.guidance!r}")
        if cfg.val_overlap and jax.process_count() > 1:
            raise ValueError(
                "val_overlap is single-process only: the val thread and "
                "the train loop would issue cross-host collectives in "
                "unsynchronized order (a distributed deadlock), so "
                "multi-host runs must validate serially")
        #: in-flight overlapped validation (val_overlap): set by
        #: _launch_overlapped_val, consumed by _join_overlapped_val
        self._pending_val = None
        #: set by the instance branch when the prepared val wire ships
        #: 3-channel batches and the eval step owns guidance synthesis
        self._val_device_guidance = False
        #: set by the instance branch when the prepared val wire ships the
        #: packed 1-bit crop_gt (the eval step unpacks)
        self._val_packbits = False
        if cfg.task == "instance":
            prepared = bool(cfg.data.prepared_cache)
            # Prepared cache owns the deterministic crop stage itself; the
            # wrapped dataset must stay untransformed.
            train_tf = None if prepared else build_train_transform(
                crop_size=cfg.data.crop_size, relax=cfg.data.relax,
                zero_pad=cfg.data.zero_pad, rots=cfg.data.rots,
                scales=cfg.data.scales, alpha=cfg.data.guidance_alpha,
                # device guidance: host delivers bare image channels as
                # 'concat'; the fused stage appends the map from crop_gt
                guidance=("none" if cfg.data.device_guidance
                          else cfg.data.guidance),
                flip=not cfg.data.device_augment,
                geom=not (cfg.data.device_augment
                          and cfg.data.device_augment_geom),
                fused_crop_resize=cfg.data.fused_crop_resize)
            #: val fast path (data.val_prepared): eval is deterministic end
            #: to end, so the whole per-epoch val front caches — decode,
            #: crop, resize, full-res metric masks; with device_guidance
            #: the wire also drops to 3-channel uint8 and the jitted eval
            #: step appends the guidance channel (is_val semantics).
            val_prep = prepared and cfg.data.val_prepared
            self._val_device_guidance = val_prep and cfg.data.device_guidance
            self._val_packbits = val_prep and cfg.data.packbits_masks
            val_tf = None if val_prep else build_eval_transform(
                crop_size=cfg.data.crop_size, relax=cfg.data.relax,
                zero_pad=cfg.data.zero_pad, alpha=cfg.data.guidance_alpha,
                guidance=cfg.data.guidance)
            if cfg.data.source == "packed":
                # pre-decoded mmap records (data/packed.py): no dataset
                # walk, no per-sample decode — samples bit-identical to
                # the fs classes by construction
                self.train_set = self._open_pack(
                    "voc", [cfg.data.train_split], train_tf,
                    quarantine=cfg.data.pack_quarantine)
                self.val_set = self._open_pack(
                    "voc", [cfg.data.val_split], val_tf)
            else:
                # download (if requested) already happened above,
                # gated+barriered
                self.train_set = VOCInstanceSegmentation(
                    root, split=cfg.data.train_split, transform=train_tf,
                    preprocess=True, area_thres=cfg.data.area_thres,
                    decode_cache=cfg.data.decode_cache)
                self.val_set = VOCInstanceSegmentation(
                    root, split=cfg.data.val_split, transform=val_tf,
                    preprocess=True, area_thres=cfg.data.area_thres,
                    decode_cache=cfg.data.decode_cache)
            if val_prep:
                from ..data import PreparedInstanceDataset
                from ..data.pipeline import build_prepared_eval_post_transform
                self.val_set = PreparedInstanceDataset(
                    self.val_set, cfg.data.prepared_cache,
                    crop_size=cfg.data.crop_size, relax=cfg.data.relax,
                    zero_pad=cfg.data.zero_pad,
                    fused_crop_resize=cfg.data.fused_crop_resize,
                    uint8_arrays=cfg.data.uint8_transfer,
                    eval_protocol=True,
                    max_im_size=cfg.data.val_max_im_size,
                    post_transform=build_prepared_eval_post_transform(
                        alpha=cfg.data.guidance_alpha,
                        guidance=("none" if cfg.data.device_guidance
                                  else cfg.data.guidance),
                        uint8_wire=cfg.data.uint8_transfer,
                        packbits=cfg.data.packbits_masks))
            if cfg.data.sbd_root:
                # the reference's use_sbd recipe (train_pascal.py:150-154),
                # live: merge SBD train+val, drop its VOC-val overlap
                from ..data import CombinedDataset, SBDInstanceSegmentation
                if cfg.data.source == "packed":
                    sbd = self._open_pack("sbd", ["train", "val"],
                                          train_tf)
                else:
                    sbd = SBDInstanceSegmentation(
                        cfg.data.sbd_root, split=["train", "val"],
                        transform=train_tf,
                        preprocess=True,  # same always-rebuild as VOC
                        area_thres=cfg.data.area_thres,
                        decode_cache=cfg.data.decode_cache)
                self.train_set = CombinedDataset(
                    [self.train_set, sbd], excluded=[self.val_set])
            if cfg.data.session_log:
                # flywheel: serve session logs as training data
                # (data/sessions.py).  session_only replays the EXACT
                # serving inputs (the continuous mode's incremental
                # fits); otherwise the log joins the VOC(+SBD) mix as a
                # sampled source under the standard transform stack.
                if prepared:
                    raise ValueError(
                        "data.session_log does not compose with "
                        "data.prepared_cache — the session log already "
                        "IS a pre-decoded, pre-cropped source; drop one "
                        "of the two")
                from ..data import CombinedDataset
                from ..data.sessions import SessionLogDataset
                if cfg.data.session_only:
                    sessions = SessionLogDataset(
                        cfg.data.session_log, mode="replay",
                        quarantine=cfg.data.session_quarantine)
                    if tuple(sessions.resolution) != \
                            tuple(cfg.data.crop_size):
                        raise ValueError(
                            f"session log {cfg.data.session_log} was "
                            f"captured at resolution "
                            f"{sessions.resolution} but this run trains "
                            f"at data.crop_size={cfg.data.crop_size} — "
                            "replay feeds the serving inputs verbatim, "
                            "so the two must match")
                    self.train_set = sessions
                else:
                    sessions = SessionLogDataset(
                        cfg.data.session_log, mode="sample",
                        transform=train_tf,
                        quarantine=cfg.data.session_quarantine)
                    self.train_set = CombinedDataset(
                        [self.train_set, sessions],
                        excluded=[self.val_set])
            elif cfg.data.session_only:
                raise ValueError(
                    "data.session_only requires data.session_log")
            if prepared:
                from ..data import (
                    PreparedInstanceDataset,
                    build_prepared_post_transform,
                )
                self.train_set = PreparedInstanceDataset(
                    self.train_set, cfg.data.prepared_cache,
                    crop_size=cfg.data.crop_size, relax=cfg.data.relax,
                    zero_pad=cfg.data.zero_pad,
                    fused_crop_resize=cfg.data.fused_crop_resize,
                    uint8_arrays=cfg.data.uint8_transfer,
                    post_transform=build_prepared_post_transform(
                        rots=cfg.data.rots, scales=cfg.data.scales,
                        alpha=cfg.data.guidance_alpha,
                        guidance=("none" if cfg.data.device_guidance
                                  else cfg.data.guidance),
                        flip=not cfg.data.device_augment,
                        geom=not (cfg.data.device_augment
                                  and cfg.data.device_augment_geom),
                        uint8_wire=cfg.data.uint8_transfer,
                        packbits=cfg.data.packbits_masks))
        elif cfg.task == "semantic":
            prepared = bool(cfg.data.prepared_cache)
            sem_train_tf = None if prepared else \
                build_semantic_train_transform(
                    crop_size=cfg.data.crop_size, rots=cfg.data.rots,
                    scales=cfg.data.scales,
                    flip=not cfg.data.device_augment,
                    geom=not (cfg.data.device_augment
                              and cfg.data.device_augment_geom))
            if cfg.data.source == "packed":
                self.train_set = self._open_pack(
                    "voc", [cfg.data.train_split], sem_train_tf,
                    quarantine=cfg.data.pack_quarantine)
            else:
                self.train_set = VOCSemanticSegmentation(
                    root, split=cfg.data.train_split,
                    transform=sem_train_tf,
                    decode_cache=cfg.data.decode_cache)
            # Val has no decode cache (one sample per image, scanned
            # sequentially — an LRU smaller than the split gets zero hits).
            # Built before the SBD merge so the merge can exclude its
            # overlap (SBD train covers most of VOC val — the standard
            # "train_aug" recipe needs the exclusion).
            #
            # val fast path (data.val_prepared): the semantic val front
            # (decode → resize → clamp) is deterministic and identical to
            # the prepared cache's stage1, so serve val from a prepared
            # cache too — with uint8_transfer the 25 MB f32 val batches
            # (the measured 1 img/s semantic-val wire, BASELINE.md ‡)
            # drop to uint8.  The full-res protocol composes: its
            # native-resolution gt caches as padded uint8 id rows,
            # emitted ragged as ``gt_full``.
            sem_val_prep = prepared and cfg.data.val_prepared
            sem_val_tf = None if sem_val_prep else \
                build_semantic_eval_transform(
                    crop_size=cfg.data.crop_size,
                    keep_fullres=cfg.eval_full_res)
            if cfg.data.source == "packed":
                self.val_set = self._open_pack(
                    "voc", [cfg.data.val_split], sem_val_tf)
            else:
                self.val_set = VOCSemanticSegmentation(
                    root, split=cfg.data.val_split,
                    transform=sem_val_tf)
            if sem_val_prep:
                from ..data.pipeline import (
                    build_prepared_semantic_eval_post_transform,
                )
                from ..data.prepared import PreparedSemanticDataset
                self.val_set = PreparedSemanticDataset(
                    self.val_set, cfg.data.prepared_cache,
                    crop_size=cfg.data.crop_size,
                    uint8_arrays=cfg.data.uint8_transfer,
                    keep_fullres=cfg.eval_full_res,
                    max_im_size=cfg.data.val_max_im_size,
                    post_transform=(
                        build_prepared_semantic_eval_post_transform(
                            uint8_wire=cfg.data.uint8_transfer)))
            if cfg.data.sbd_root:
                from ..data import CombinedDataset
                from ..data.sbd import SBDSemanticSegmentation
                if cfg.data.source == "packed":
                    sbd = self._open_pack("sbd", ["train", "val"],
                                          sem_train_tf)
                else:
                    sbd = SBDSemanticSegmentation(
                        cfg.data.sbd_root, split=["train", "val"],
                        transform=sem_train_tf,
                        decode_cache=cfg.data.decode_cache)
                self.train_set = CombinedDataset(
                    [self.train_set, sbd], excluded=[self.val_set])
            if prepared:
                from ..data.pipeline import (
                    build_prepared_semantic_post_transform,
                )
                from ..data.prepared import PreparedSemanticDataset
                self.train_set = PreparedSemanticDataset(
                    self.train_set, cfg.data.prepared_cache,
                    crop_size=cfg.data.crop_size,
                    uint8_arrays=cfg.data.uint8_transfer,
                    post_transform=build_prepared_semantic_post_transform(
                        rots=cfg.data.rots, scales=cfg.data.scales,
                        flip=not cfg.data.device_augment,
                        geom=not (cfg.data.device_augment
                                  and cfg.data.device_augment_geom),
                        uint8_wire=cfg.data.uint8_transfer))
        else:
            raise ValueError(
                f"unknown task: {cfg.task!r} (instance | semantic)")
        # Batch sizes are GLOBAL (the reference's trainBatch=16 spans its 4
        # GPUs; BASELINE speaks of global batches); each host's loader feeds
        # its 1/process_count share, which shard_batch assembles into the
        # global array.  The global batch must divide cleanly over BOTH the
        # process count and the mesh data axis (and accum micro-batches) —
        # catching it here beats an opaque uneven-sharding error at step 1.
        n_proc = jax.process_count()
        data_axis = self.mesh.devices.shape[0]
        tb = cfg.data.train_batch
        if tb % n_proc:
            raise ValueError(f"global train batch {tb} not divisible by "
                             f"{n_proc} processes")
        if tb % (data_axis * cfg.optim.accum_steps):
            raise ValueError(
                f"global train batch {tb} not divisible by data axis "
                f"{data_axis} x accum_steps {cfg.optim.accum_steps}")
        vb_host = max(1, -(-cfg.data.val_batch // n_proc))  # ceil, >= 1
        if self.is_main and vb_host * n_proc != cfg.data.val_batch:
            print(f"note: global val batch rounded "
                  f"{cfg.data.val_batch} -> {vb_host * n_proc} "
                  f"({vb_host}/host x {n_proc} hosts)", flush=True)
        if cfg.data.loader == "grain":
            # Grain train loader (process workers, checkpointable iterators);
            # eval stays on the thread loader, which wrap-pads the final
            # batch so every sample is scored (grain's multi-host sharding
            # drops remainders instead — fine for training, wrong for eval).
            from ..data import GrainDataLoader
            self.train_loader = GrainDataLoader(
                self.train_set, tb // n_proc, shuffle=True, drop_last=True,
                seed=cfg.seed, num_workers=cfg.data.num_workers,
                num_shards=n_proc, shard_index=jax.process_index())
        elif cfg.data.loader == "threads":
            self.train_loader = DataLoader(
                self.train_set, tb // n_proc, shuffle=True,
                drop_last=True, seed=cfg.seed,
                num_workers=cfg.data.num_workers,
                prefetch=cfg.data.prefetch,
                num_shards=n_proc, shard_index=jax.process_index())
        else:
            raise ValueError(f"unknown data.loader: {cfg.data.loader!r} "
                             "(threads | grain)")
        self.val_loader = DataLoader(
            self.val_set, vb_host, shuffle=False, drop_last=False,
            seed=cfg.seed, num_workers=cfg.data.num_workers,
            prefetch=cfg.data.prefetch,
            num_shards=n_proc, shard_index=jax.process_index())
        # drop_last swallows a sub-batch-size dataset whole; training
        # would silently run zero steps per epoch (NaN epoch loss).  The
        # emptiness decision is laundered through the consensus
        # primitive: shards round unevenly, and one host raising here
        # alone would leave the rest hanging at the first collective —
        # if ANY host's shard is empty, every host raises in lockstep.
        from ..parallel.consensus import replicated_decision
        min_batches = int(replicated_decision(
            len(self.train_loader), reduce="min",
            label="trainer/train_loader_len"))
        if min_batches == 0:
            raise ValueError(
                f"train loader is empty: dataset has {len(self.train_set)} "
                f"samples globally (~{len(self.train_set) // n_proc} on "
                f"this host's shard) but the per-host batch is "
                f"{tb // n_proc} with drop_last — lower data.train_batch or "
                "enlarge the dataset")

        # --- model / optimizer / state
        # train.precision (train/precision.py): the bf16 policy owns the
        # model's compute dtype (master params stay f32 via flax's
        # param_dtype default); train.reduce_buckets runs the step's
        # fwd/bwd per-device inside shard_map, so BN batch stats must
        # reduce explicitly — the model is built cross-replica.
        self.precision = precision_policy(cfg.train.precision)
        if cfg.train.reduce_buckets:
            # the planner owns compatibility: buckets compose with the
            # dp family incl. ZeRO-1 (plan.BUCKET_COMPATIBLE — the
            # sharded optimizer update lives outside the shard_map
            # region), never with TP or a live model axis
            if self.plan.strategy not in plan_lib.BUCKET_COMPATIBLE:
                raise plan_lib.reduce_buckets_conflict(self.plan.strategy)
            if self.plan.model > 1 or cfg.model.pam_impl == "ring":
                raise plan_lib.PlanError(
                    "train.reduce_buckets needs a data-only mesh "
                    "(model axis 1) and a non-ring PAM — its shard_map "
                    "region owns the data axis; nearest supported: "
                    "parallel.strategy=dp (or dp_zero1)")
        self.model = build_model(
            name=cfg.model.name, nclass=cfg.model.nclass,
            backbone=cfg.model.backbone, output_stride=cfg.model.output_stride,
            dtype=(self.precision.compute_dtype if self.precision
                   else cfg.model.dtype),
            bn_fp32_stats=cfg.model.bn_fp32_stats,
            bn_cross_replica_axis=(DATA_AXIS if cfg.train.reduce_buckets
                                   else None),
            pam_block_size=cfg.model.pam_block_size,
            attention_impl=cfg.model.attention_impl,
            pam_impl=cfg.model.pam_impl,
            pam_score_dtype=cfg.model.pam_score_dtype,
            # ring PAM shards the spatial tokens over this mesh's model axis
            pam_sp_mesh=(self.mesh if cfg.model.pam_impl == "ring" else None),
            remat=cfg.model.remat,
            remat_policy=cfg.model.remat_policy or None,
            moe_experts=cfg.model.moe_experts,
            moe_hidden=cfg.model.moe_hidden, moe_k=cfg.model.moe_k,
            moe_capacity_factor=cfg.model.moe_capacity_factor,
            aux_head=cfg.model.aux_head,
            encnet_codes=cfg.model.encnet_codes,
            ccnet_recurrence=cfg.model.ccnet_recurrence,
            guidance_inject=cfg.model.guidance_inject)
        steps_per_epoch = len(self.train_loader)  # > 0: guarded above
        # Each loaded batch is stepped data.echo times, so schedules (poly
        # decay, warmup fractions) must span echo x the loader length or
        # they exhaust early and clamp the LR.
        total_steps = steps_per_epoch * cfg.epochs * cfg.data.echo
        self.tx, self.schedule = make_optimizer(cfg.optim, total_steps)
        h, w = cfg.data.crop_size
        with self.mesh:
            self.state = create_train_state(
                jax.random.PRNGKey(cfg.seed), self.model, self.tx,
                (1, h, w, cfg.model.in_channels), mesh=self.mesh,
                shard_params=self.plan.shard_params,
                shard_opt_state=self.plan.shard_opt_state)
        loss_type = ("multi_softmax" if cfg.task == "semantic"
                     else "multi_sigmoid")
        # The plan's TP / ZeRO-1 layouts flow from the created state
        # into the compiled steps (live shardings — exactly what
        # create_train_state placed); the plan owns the threading rule.
        st_sh = self.plan.state_shardings(self.state, self.mesh)
        augment = self._build_device_stage(cfg.data.device_augment,
                                           cfg.data.device_guidance)
        # --- self-healing sentinel (train/sentinel.py; see fit()): built
        # before the steps because monitor_grads changes their outputs
        sc = cfg.sentinel
        self._sentinel = StepSentinel(
            ema_beta=sc.ema_beta, suspect_factor=sc.suspect_factor,
            diverged_factor=sc.diverged_factor,
            warmup_steps=sc.warmup_steps, grad_factor=sc.grad_factor,
            update_ratio_max=sc.update_ratio_max,
            telemetry=cfg.telemetry) if sc.enabled else None
        #: rollback budget — THE CircuitBreaker (chaos/policies.py):
        #: each rollback books a failure, each cleanly completed epoch a
        #: success, so only max_rollbacks CONSECUTIVE rollbacks open it
        #: (and the run then fails loudly instead of looping)
        self._rollback_breaker = CircuitBreaker(
            failure_threshold=max(1, sc.max_rollbacks)) \
            if sc.enabled else None
        #: epoch -> loader batch indices quarantined by past rollbacks
        #: (skipped on replay); the JSONL ledger under the run dir is the
        #: durable record, this index is the live skip set
        self._quarantine: dict[int, set[int]] = {}
        #: loader batch index actually dispatched for each epoch-step of
        #: the CURRENT epoch (quarantine skips make `start + i` wrong)
        self._epoch_batch_order: list[int] = []
        self.sentinel_rollbacks = 0
        self.sentinel_quarantined_steps = 0
        self._rollback_seconds: list[float] = []
        step_kwargs = dict(
            loss_weights=cfg.model.loss_weights,
            accum_steps=cfg.optim.accum_steps, mesh=self.mesh,
            loss_type=loss_type, state_shardings=st_sh, augment=augment,
            aux_loss_weight=(cfg.model.moe_aux_weight
                             if cfg.model.moe_experts else 0.0),
            loss_scale=cfg.optim.loss_scale,
            packbits_masks=cfg.data.packbits_masks,
            sentinel_metrics=sc.enabled and sc.monitor_grads,
            precision=self.precision,
            reduce_buckets=cfg.train.reduce_buckets)
        self._step_kwargs = step_kwargs
        self.train_step, self.multi_train_step = self._build_steps()
        #: data.coalesce_wire: the wire-consuming twins of the two programs
        #: above, built lazily at the first train batch — the wire layout
        #: (per-key byte extents) is data-shaped, and deriving it from the
        #: real batch instead of re-deriving shape math from config keeps
        #: one source of truth.  ``_step_kwargs`` is kept for that build.
        self._wire_spec: tuple | None = None
        self._wire_step = None
        self._wire_multi_step = None
        # --- telemetry: goodput program-identity + MFU inputs + on-demand
        # trace.  _programs_seen keys the compile-vs-step goodput split
        # (the FIRST dispatch of each compiled program pays trace+XLA and
        # is attributed to 'compile'); the trace trigger arms from SIGUSR2
        # during fit() and writes bounded XPlane captures under the run dir.
        self._programs_seen: set[str] = set()
        self._prod_steps = 0
        self._flops_per_step: float | None = None
        self._flops_source: str | None = None
        self._trace = TraceCapture(
            os.path.join(self.run_dir, "trace_on_demand")) \
            if (cfg.telemetry and self.is_main) else None
        # --- input-feed governor (data/governor.py): closes the loop
        # from the measured input_wait fraction to the pipeline knobs.
        # `observe` builds on the main process only (secondary hosts
        # would just write nothing); multi-host `auto` builds on EVERY
        # process — its actuations (the echo factor above all) must land
        # identically everywhere, which is exactly what routing the
        # ladder inputs through replicated_decision (consensus=True)
        # guarantees.  The JSONL ledger stays main-only either way.
        # Needs telemetry: the goodput snapshot deltas ARE its signal.
        # _feed_last holds the previous tick's snapshot.
        from ..telemetry.goodput import FeedWindow
        gov_auto = cfg.data.governor == "auto"
        gov_multi = gov_auto and jax.process_count() > 1
        self._governor = FeedGovernor(
            cfg.data.governor, cfg.data.governor_target,
            _TrainerFeedActuators(self), max_echo=cfg.data.max_echo,
            window=FeedWindow(cfg.data.governor_window),
            jsonl_path=(os.path.join(self.run_dir, "governor.jsonl")
                        if self.is_main else None),
            # auto ALWAYS routes through the consensus primitive —
            # single-process the gather is [value] and the reduce is an
            # identity (no communication), so the multi-host semantics
            # are the only semantics and never rot untested
            consensus=gov_auto,
            telemetry=True) \
            if (cfg.data.governor != "off" and cfg.telemetry
                and (self.is_main or gov_multi)) else None
        self._feed_last: dict | None = None
        eval_preprocess = None
        if self._val_device_guidance:
            # prepared val ships bare image channels; append the guidance
            # channel on device with the DETERMINISTIC val semantics
            # (extreme_points_fixed — bit-exact vs the host at pert=0).
            # The rng argument is never consumed at is_val.
            from ..ops.guidance_device import make_device_guidance
            gstage = make_device_guidance(
                family=cfg.data.guidance, alpha=cfg.data.guidance_alpha,
                is_val=True)
            fixed_key = jax.random.PRNGKey(0)

            def eval_preprocess(b, _g=gstage, _k=fixed_key):
                return _g(b, _k)
        self.eval_step = make_eval_step(
            self.model, loss_weights=cfg.model.loss_weights, mesh=self.mesh,
            loss_type=loss_type, state_shardings=st_sh,
            preprocess=eval_preprocess,
            packbits_masks=self._val_packbits)

        # --- checkpointing
        self.ckpt = CheckpointManager(
            os.path.join(self.run_dir, "checkpoints"),
            keep_latest=cfg.checkpoint.keep_latest,
            best_metric_init=cfg.checkpoint.best_metric_init,
            async_save=cfg.checkpoint.async_save,
            digest=cfg.checkpoint.digest,
            # every save's meta names the plan that laid the state out —
            # the cross-plan restore discriminator (chaos
            # plan_mismatch_restore asserts it)
            static_meta={"plan": self.plan.block()})
        self.start_epoch = 0
        self._resume_start_batch = 0  # exact mid-epoch resume offset
        #: steps the resume restore SKIPPED as unreadable (torn files) on
        #: the way to the one it used — surfaced for ops/chaos assertions
        self.resume_fallback_steps: list[int] = []
        #: the restored checkpoint's meta dict (empty when not resumed) —
        #: the chaos runner's digest-continuity invariants read it
        self.resume_meta: dict = {}
        #: True when the resume restored ACROSS a plan (or topology)
        #: crossing — the elastic chaos scenario's "every restore
        #: announced the crossing" evidence bit
        self.resume_plan_crossing = False
        if cfg.checkpoint.warm_start:
            self._warm_start(cfg.checkpoint.warm_start,
                             cfg.checkpoint.warm_start_partial)
        if cfg.resume == "auto":
            # Continue from the newest prior run with checkpoints (the
            # reference's pinned-run_0 resume, without knowing the index).
            src = latest_checkpoint_dir(cfg.work_dir,
                                        exclude_run=self.run_dir)
            if src is None:
                if self.is_main:
                    print("resume=auto: no prior checkpoints under "
                          f"{cfg.work_dir}; starting fresh", flush=True)
            else:
                self._resume(src)
        elif cfg.resume:
            self._resume(cfg.resume)

        # --- param report (reference generate_param_report, :169)
        if self.is_main:
            flat = config_lib.flatten(cfg)
            flat["n_params"] = self.n_params
            flat["n_devices"] = self.mesh.devices.size
            # the RESOLVED plan (config.json only records the request —
            # under strategy=auto the two differ)
            flat["resolved_plan"] = self.plan.describe()
            flat["train_set"] = str(self.train_set)
            flat["val_set"] = str(self.val_set)
            generate_param_report(
                os.path.join(self.run_dir, f"{cfg.experiment_name}.txt"), flat)
            config_lib.to_json(cfg, os.path.join(self.run_dir, "config.json"))
            self.writer.hparams(flat)

    @property
    def n_params(self) -> int:
        """Trainable parameter count (the reference printed this at startup,
        train_pascal.py:105)."""
        return sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(self.state.params))

    # ---------------------------------------------------- packed source
    def _open_pack(self, dataset_name: str, splits, transform,
                   quarantine=()):
        """Open one ``dptpu-pack`` directory under ``data.pack_path`` as
        this run's source for (dataset, task, splits).  A missing or
        mismatched pack fails LOUDLY with the exact ``dptpu-pack``
        invocation that builds it — the operator's move, named."""
        from ..data.packed import (
            PackedDataset,
            PackFormatError,
            pack_command,
            pack_dir_path,
        )

        cfg = self.cfg
        path = pack_dir_path(cfg.data.pack_path, dataset_name, cfg.task,
                             splits)
        root = (cfg.data.sbd_root if dataset_name == "sbd"
                else self._data_root)
        cmd = pack_command(root, cfg.data.pack_path, dataset_name,
                           cfg.task, splits,
                           cfg.data.area_thres if cfg.task == "instance"
                           else None)
        try:
            ds = PackedDataset(path, transform=transform,
                               quarantine=quarantine,
                               expect_kind=cfg.task)
        except (OSError, PackFormatError) as e:
            raise ValueError(
                f"data.source=packed but no readable "
                f"{dataset_name}/{cfg.task} pack at {path} "
                f"({type(e).__name__}: {e}) — build it once: `{cmd}`"
            ) from e
        if cfg.task == "instance" \
                and ds.meta.get("area_thres") != cfg.data.area_thres:
            raise ValueError(
                f"pack {path} was built with area_thres="
                f"{ds.meta.get('area_thres')} but this run wants "
                f"data.area_thres={cfg.data.area_thres} — its instance "
                f"list differs; re-pack: `{cmd}`")
        return ds

    def _pack_status(self) -> tuple[bool, str | None]:
        """The governor's rung-0 input (data/governor.py): is this run
        already feeding from a pack, and if not, the exact CLI that
        removes the stall at its source."""
        cfg = self.cfg
        if cfg.data.source == "packed":
            return True, None
        from ..data.packed import pack_commands_for_config
        cmds = pack_commands_for_config(cfg, root=self._data_root)
        return False, (
            "rung 0 — cheaper than tuning around the stall is deleting "
            "it: pre-decode the dataset once and train from the mmap "
            "(data.source=packed data.pack_path=<out>): `"
            + " && ".join(cmds) + "`")

    def _plan_memory_inputs(self) -> tuple:
        """``strategy=auto``'s memory-model inputs: a shape-only
        ``TrainState`` template of THIS config's model/optimizer (via
        ``jax.eval_shape`` — no weights initialized, no mesh needed:
        state shapes are layout-independent) and the global train
        batch's byte count.  Built from the config alone, before the
        mesh exists — the plan decides the mesh."""
        cfg = self.cfg
        h, w = cfg.data.crop_size
        in_ch = cfg.model.in_channels
        model = build_model(
            name=cfg.model.name, nclass=cfg.model.nclass,
            backbone=cfg.model.backbone,
            output_stride=cfg.model.output_stride,
            dtype=(precision_policy(cfg.train.precision).compute_dtype
                   if precision_policy(cfg.train.precision)
                   else cfg.model.dtype),
            moe_experts=cfg.model.moe_experts,
            moe_hidden=cfg.model.moe_hidden, moe_k=cfg.model.moe_k,
            moe_capacity_factor=cfg.model.moe_capacity_factor,
            aux_head=cfg.model.aux_head,
            encnet_codes=cfg.model.encnet_codes,
            ccnet_recurrence=cfg.model.ccnet_recurrence,
            guidance_inject=cfg.model.guidance_inject)
        tx, _ = make_optimizer(cfg.optim, 100)  # shapes don't see steps
        state_struct = jax.eval_shape(
            lambda: create_train_state(
                jax.random.PRNGKey(0), model, tx, (1, h, w, in_ch)))
        # device-bound train tensors, f32 on device (the uint8 wire
        # dequantizes inside the step): concat + crop_gt (+void)
        batch_bytes = cfg.data.train_batch * h * w * (in_ch + 2) * 4
        return state_struct, batch_bytes

    def _warm_start(self, path: str, partial: bool) -> None:
        """Import model weights from a torch ``.pth`` state_dict — the
        reference's unconditional warm start (train_pascal.py:103) as a
        config knob.  Only params/batch-stats are imported (the reference
        never persisted optimizer state, SURVEY.md §3.5); step/opt-state/RNG
        stay fresh.  Use ``resume`` for full-state Orbax restarts."""
        from ..utils.torch_interop import (
            inflate_stem_channels,
            is_torchvision_resnet,
            load_torch_file,
            torch_state_dict_to_params,
            torchvision_resnet_depth,
            torchvision_resnet_rename,
        )

        sd = load_torch_file(path)
        rename = None
        if is_torchvision_resnet(sd):
            # An ImageNet-pretrained torchvision backbone (the reference's
            # model lineage): bridge the naming, widen the RGB stem to this
            # model's input channels, and import partially (the seg head
            # isn't in a classification checkpoint).
            bb = self.cfg.model.backbone
            if not bb.startswith("resnet"):
                raise ValueError(
                    f"{path} looks like a torchvision ResNet checkpoint "
                    f"but model.backbone={bb!r}")
            depth = torchvision_resnet_depth(sd)
            if depth != int(bb[len("resnet"):]):
                # a partial import would silently leave most of the deeper
                # net at fresh init — refuse instead
                raise ValueError(
                    f"{path} is a torchvision resnet{depth} checkpoint "
                    f"but model.backbone={bb!r}")
            sd = inflate_stem_channels(sd, self.cfg.model.in_channels)
            rename = torchvision_resnet_rename(depth)
            partial = True
            if self.is_main:
                print(f"warm start: torchvision ResNet naming detected in "
                      f"{path}; importing as pretrained backbone",
                      flush=True)
        # Shape/dtype-only templates: the live state may be sharded across
        # processes, and describing shapes must not gather it to host.
        as_struct = lambda t: jax.tree.map(  # noqa: E731
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), t)
        params, stats = torch_state_dict_to_params(
            sd, as_struct(self.state.params), as_struct(self.state.batch_stats),
            rename=rename, allow_missing=partial, allow_unused=partial)
        if rename is not None:
            # Torchvision mode forces partial (the seg head isn't in a
            # classification checkpoint), but the BACKBONE must import
            # completely — width variants (wide_resnet, resnext) share a
            # plain resnet's layer counts and would otherwise fall through
            # the shape-mismatch path leaf by leaf, leaving a silently
            # half-pretrained backbone.
            from flax.traverse_util import flatten_dict
            missing = [
                ".".join(p)
                for tree in (params.get("backbone", {}),
                             stats.get("backbone", {}))
                for p, v in flatten_dict(tree).items()
                if isinstance(v, jax.ShapeDtypeStruct)
            ]
            if missing:
                raise ValueError(
                    f"torchvision import left {len(missing)} backbone "
                    f"leaves at fresh init (e.g. backbone.{missing[0]}): "
                    f"tensor shapes in {path} do not match a plain "
                    f"resnet{torchvision_resnet_depth(sd)} (wide_resnet / "
                    "resnext variants are not supported)")

        imported = [0, 0]  # [loaded from checkpoint, kept template]

        def place(new, old):
            if isinstance(new, jax.ShapeDtypeStruct):
                imported[1] += 1
                return old  # leaf absent from the checkpoint (partial)
            imported[0] += 1
            # numpy -> sharded device array in one hop, preserving the
            # leaf's existing mesh placement (replicated or TP-sharded).
            # DONATION SAFETY (the checkpoint.restore lesson): on CPU,
            # device_put of a host numpy array can be ZERO-COPY — the
            # jax.Array aliases the numpy buffer — and the first train
            # step DONATES these leaves, handing XLA memory that the
            # import pipeline still references.  That intermittently
            # surfaced as a non-finite first loss from a clean batch and
            # correct imported weights (timing-dependent: whether the
            # put aliases depends on allocator state).  jnp.copy
            # re-buffers into XLA-owned memory, donation-safe on every
            # backend — one extra copy, paid once at warm start.
            return jnp.copy(jax.device_put(np.asarray(new), old.sharding))

        self.state = self.state.replace(
            params=jax.tree.map(place, params, self.state.params),
            batch_stats=jax.tree.map(place, stats, self.state.batch_stats))
        if imported[0] == 0:
            # Every leaf fell through allow_missing: a key-naming mismatch,
            # not a warm start.  Silently training from fresh init is the
            # masking torch_interop's two separate flags exist to prevent.
            raise ValueError(
                f"warm start from {path} imported 0 of "
                f"{imported[1]} leaves — checkpoint keys do not match this "
                "model; check the architecture/naming")
        if self.is_main:
            print(f"warm-started {imported[0]} leaves from {path} "
                  f"({imported[1]} kept from fresh init)", flush=True)

    def _resume(self, source: str) -> None:
        mgr = CheckpointManager(source) if os.path.abspath(source) != \
            os.path.abspath(os.path.join(self.run_dir, "checkpoints")) \
            else self.ckpt
        self.state, meta = mgr.restore(self.state)
        self.resume_meta = dict(meta)
        saved_plan = meta.get("plan")
        n_dev = self.mesh.devices.size
        if plan_lib.plans_differ(saved_plan, self.plan.block(), n_dev):
            # Cross-plan restore: StandardRestore adopts the TARGET
            # state's shardings, so the arrays land resharded into this
            # plan's layout (and restore's re-buffer pass keeps them
            # donation-safe) — announce it loudly; a silent layout
            # change under a resumed run is how garbage gets loaded.
            # plans_differ also sees TOPOLOGY crossings the layout
            # can't (a data=None dp plan normalizes equal on any
            # device count) — the elastic shrink/grow path.
            self.resume_plan_crossing = True
            if self.is_main:
                saved_topo = (saved_plan or {}).get("topology")
                topo = (f" across a topology change ({saved_topo} -> "
                        f"{self.plan.topology})"
                        if saved_topo and saved_topo != self.plan.topology
                        else "")
                print("cross-plan restore: checkpoint was saved under "
                      f"plan {saved_plan} and is resharding into "
                      f"{self.plan.block()} (strategy "
                      f"{saved_plan.get('strategy')} -> "
                      f"{self.plan.strategy}){topo}", flush=True)
        self.resume_fallback_steps = list(mgr.last_restore_fallback)
        self.start_epoch = int(meta.get("epoch", 0)) + 1
        self.ckpt.best_metric = float(
            meta.get("best_metric", self.ckpt.best_metric))
        interrupted = meta.get("interrupted_epoch")
        if interrupted is not None and self.cfg.checkpoint.exact_resume:
            # Exact mid-epoch resume: the preempt save recorded how many
            # steps of the interrupted epoch already trained; the epoch's
            # batch order is deterministic given (seed, epoch), so continue
            # at that batch instead of replaying the epoch.  A batch
            # interrupted mid-echo replays its echoes (rounded down).
            # The recorded offset indexes THE batch order it was written
            # under; anything that changes that order (host count, batch
            # size, seed) or the steps-per-batch accounting (echo) makes it
            # meaningless.  Replaying the epoch is the layout-safe fallback
            # (batches repeat, none skipped).
            now = {"num_shards": jax.process_count(),
                   "echo": self.cfg.data.echo,
                   "train_batch": self.cfg.data.train_batch,
                   "seed": self.cfg.seed}
            stale = {k: (meta.get(k, v), v) for k, v in now.items()
                     if int(meta.get(k, v)) != v}
            if stale:
                if self.is_main:
                    diffs = ", ".join(f"{k}: {a} -> {b}"
                                      for k, (a, b) in stale.items())
                    print(f"exact_resume: data-order config changed "
                          f"({diffs}) — replaying the interrupted epoch "
                          "instead", flush=True)
            else:
                done = int(meta.get("epoch_steps_done", 0)) \
                    // max(1, self.cfg.data.echo)
                # A stop landing exactly on the epoch's last step still
                # needs the epoch-end bookkeeping (validation, best gate,
                # checkpoint) the preempt skipped — replay the final batch
                # so the epoch completes through the normal path.
                done = min(done, len(self.train_loader) - 1)
                self.start_epoch = int(interrupted)
                self._resume_start_batch = done
        if self.is_main:
            at = f"epoch {self.start_epoch}"
            if self._resume_start_batch:
                at += f" batch {self._resume_start_batch}"
            print(f"resumed from {source} at {at} "
                  f"(best={self.ckpt.best_metric:.4f})", flush=True)

    # ------------------------------------------------------------------ train
    def _build_steps(self, wire_spec: tuple | None = None):
        """The (single-step, K-step-or-None) compiled train programs from
        the one stored ``_step_kwargs`` — the only constructor for both the
        plain and the wire-consuming (data.coalesce_wire) twins, so the two
        families cannot drift as kwargs grow.  The K-step program exists
        iff data.steps_per_dispatch > 1; epoch-tail remainders always run
        through the single-step one."""
        k = self.cfg.data.steps_per_dispatch
        single = make_train_step(self.model, self.tx, wire_spec=wire_spec,
                                 **self._step_kwargs)
        multi = (make_train_step(self.model, self.tx, steps_per_call=k,
                                 wire_spec=wire_spec, **self._step_kwargs)
                 if k > 1 else None)
        return single, multi

    def _pack_wire_transform(self, batch: dict) -> dict:
        """data.coalesce_wire stage for the prefetcher's placement thread:
        pack the batch into the one-buffer wire, and on the FIRST batch
        derive the spec + build the wire-consuming step programs.  Runs on
        the worker so the full-batch memcpy stays off the dispatch thread;
        the attribute writes are published to the dispatch loop by the
        placement future's ``result()`` (completion happens-before the
        first wire batch is yielded)."""
        batch, spec = pack_wire(batch, DEVICE_KEYS)
        if self._wire_spec is None:
            self._wire_spec = spec
            self._wire_step, self._wire_multi_step = self._build_steps(spec)
        elif spec != self._wire_spec:
            raise RuntimeError(
                f"data.coalesce_wire: batch layout changed mid-training "
                f"({spec} vs {self._wire_spec}) — the train loader must "
                "produce fixed-shape batches (drop_last + fixed crop)")
        return batch

    def _note_step_cost(self, fn, args, steps_per_call: int) -> None:
        """One-shot model-FLOPs/step estimate for MFU — XLA's own
        ``cost_analysis`` of the exact compiled program (the executable is
        cache-shared with the running step, so this re-traces but never
        re-compiles), falling back to a parameter-proportional floor
        (fwd+bwd ~ 3 param passes x 2 FLOPs/MAC x batch) on backends whose
        cost model is unavailable.  The source is recorded so a fallback
        estimate can never masquerade as a measured count."""
        if self._flops_per_step is not None or not self.cfg.telemetry:
            return
        from ..telemetry.goodput import xla_step_cost
        flops = xla_step_cost(fn, *args)["flops"]
        if flops and flops > 0:  # guard negative cost-model sentinels
            flops /= max(1, steps_per_call)
            self._flops_source = "xla_cost_analysis"
        else:
            flops = 6.0 * self.n_params * self.cfg.data.train_batch
            self._flops_source = "param_estimate"
        self._flops_per_step = flops

    def _report_goodput(self, history: dict | None = None) -> None:
        """Fit-end goodput breakdown + MFU estimate: into the writer stack
        (=> metrics.jsonl / console / comet), the registry gauges (=> the
        serve front's /metrics when co-hosted) and ``history``."""
        if not self.cfg.telemetry:
            return
        rep = get_accountant().report()
        if history is not None:
            history["goodput"] = rep
        scalars = {f"goodput/{b}_s": round(v, 4)
                   for b, v in rep["buckets"].items()}
        scalars["goodput/total_s"] = round(rep["total_s"], 4)
        scalars["goodput/productive_frac"] = round(rep["goodput"], 4)
        if self._flops_per_step and self._prod_steps:
            step_time = rep["buckets"]["step"] / self._prod_steps
            if step_time > 0:
                est = mfu_estimate(
                    self._flops_per_step / self.mesh.devices.size,
                    step_time, device_kind=None)
                est["flops_source"] = self._flops_source
                if history is not None:
                    history["mfu"] = est
                scalars["mfu"] = round(est["mfu"], 6)
                scalars["mfu/flops_per_step"] = self._flops_per_step
                scalars["mfu/peak_flops_per_device"] = \
                    est["peak_flops_per_device"]
        if self.is_main:
            self.writer.scalars(scalars, int(self.state.step))

    # ------------------------------------------------------- feed governor
    def _feed_tick(self, epoch: int, step: int) -> None:
        """Log-cadence governor observation: difference the goodput
        snapshot against the previous tick's and push the delta into the
        stall window.  Only step/compile/input_wait move between ticks of
        the train loop (eval/checkpoint book their own buckets), so the
        fraction is a pure feed signal.  Pure perf_counter bookkeeping —
        no host sync enters the loop."""
        snap = get_accountant().snapshot()
        last = self._feed_last
        self._feed_last = snap
        if last is None:
            return
        busy = (snap["step"] - last["step"]) \
            + (snap["compile"] - last["compile"])
        wait = snap["input_wait"] - last["input_wait"]
        if busy + wait <= 0 and not self._governor.consensus:
            # zero-delta local tick: nothing to learn — but under
            # consensus the tick still runs (its allgather is a
            # collective every host must join at this cadence; the
            # governor drops the empty sample itself, and FeedWindow
            # still drops negative deltas from accountant resets)
            return
        self._governor.tick(busy, wait, step=step, epoch=epoch)

    def _feed_flip_available(self) -> tuple[bool, str]:
        """Eligibility of the governor's rung-2 flip: move augmentation
        (and, instance task, guidance synthesis — the expensive host
        stage) on device at an epoch boundary.  Ineligible configs get
        the reason as a RECOMMENDATION naming the config keys — the
        governor logs it instead of acting."""
        cfg = self.cfg
        already = cfg.data.device_augment and (
            cfg.task == "semantic" or cfg.data.device_guidance
            or cfg.data.guidance == "none")
        if already or self._feed_flipped:
            return False, "on-device augmentation + guidance already active"
        if cfg.data.coalesce_wire:
            # unreachable today (coalesce_wire validation requires the
            # prepared cache below) but load-bearing if that chain ever
            # loosens: the dispatch loop runs the wire-built steps, and
            # a flip-changed batch layout is refused mid-training
            return False, (
                "coalesce_wire packed the wire layout from the current "
                "host pipeline — set data.device_augment/"
                "data.device_guidance in the config instead")
        if cfg.data.prepared_cache:
            return False, (
                "prepared cache owns the pipeline front — set "
                "data.device_augment/data.device_guidance (and consider "
                "data.uint8_transfer) in the config instead")
        if cfg.data.loader != "threads":
            return False, (
                "grain loader builds its pipeline up front — set "
                "data.device_augment/data.device_guidance in the config")
        if cfg.task == "instance" and cfg.data.guidance != "none" \
                and not cfg.data.device_guidance:
            from ..ops.guidance_device import FAMILIES as _DEV_FAM
            if cfg.data.guidance not in _DEV_FAM:
                return False, (
                    f"guidance family {cfg.data.guidance!r} has no device "
                    f"implementation (supported: {_DEV_FAM}) — "
                    "data.prepared_cache is the remaining lever")
        what = "flip augmentation"
        if cfg.task == "instance" and cfg.data.guidance != "none":
            what += " + guidance synthesis"
        return True, (f"move {what} on device "
                      "(data.device_augment=true"
                      + (", data.device_guidance=true"
                         if cfg.task == "instance"
                         and cfg.data.guidance != "none" else "") + ")")

    def _build_device_stage(self, device_augment: bool,
                            device_guidance: bool):
        """The fused on-device augmentation (+ guidance synthesis) stage
        for the compiled step, or None when both are off.  The ONE
        constructor shared by the config path (build time) and the
        governor's rung-2 flip — a config-enabled run and a
        governor-flipped run must train through the identical stage."""
        if not (device_augment or device_guidance):
            return None
        cfg = self.cfg
        from ..ops.augment import make_device_augment

        guidance_fn = None
        if device_guidance:  # instance task only (validated at build)
            from ..ops.guidance_device import make_device_guidance
            guidance_fn = make_device_guidance(
                family=cfg.data.guidance, alpha=cfg.data.guidance_alpha)
        return make_device_augment(  # host flip (+geom) disabled
            hflip=device_augment,
            scale_rotate=device_augment and cfg.data.device_augment_geom,
            rots=cfg.data.rots, scales=cfg.data.scales,
            semantic=cfg.task == "semantic",
            guidance_fn=guidance_fn)

    def _flip_device_path(self) -> None:
        """Apply the rung-2 flip (epoch boundary — the recompile-safe
        seam): rebuild the host transform stacks with the flip/guidance
        stages dropped, install the fused on-device stage, and rebuild
        the compiled steps.  The next dispatch re-traces and books under
        'compile' (the program keys are cleared below).  Val is
        untouched: it keeps the deterministic host path it was built
        with."""
        ok, reason = self._feed_flip_available()
        if not ok:
            raise RuntimeError(f"device-path flip not available: {reason}")
        cfg = self.cfg
        dev_guidance = (cfg.task == "instance"
                        and cfg.data.guidance != "none")
        if cfg.task == "instance":
            new_tf = build_train_transform(
                crop_size=cfg.data.crop_size, relax=cfg.data.relax,
                zero_pad=cfg.data.zero_pad, rots=cfg.data.rots,
                scales=cfg.data.scales, alpha=cfg.data.guidance_alpha,
                guidance="none" if dev_guidance else cfg.data.guidance,
                flip=False, geom=not cfg.data.device_augment_geom,
                fused_crop_resize=cfg.data.fused_crop_resize)
        else:
            new_tf = build_semantic_train_transform(
                crop_size=cfg.data.crop_size, rots=cfg.data.rots,
                scales=cfg.data.scales, flip=False,
                geom=not cfg.data.device_augment_geom)

        def set_transform(ds):
            subs = getattr(ds, "datasets", None)
            if subs is not None:  # CombinedDataset: per-constituent
                for s in subs:
                    set_transform(s)
            elif hasattr(ds, "transform"):
                ds.transform = new_tf

        set_transform(self.train_set)
        self._step_kwargs["augment"] = self._build_device_stage(
            True, dev_guidance)
        self.train_step, self.multi_train_step = self._build_steps()
        # the rebuilt programs' first dispatch is a fresh trace+XLA —
        # re-book it as 'compile', not a mysteriously slow 'step'
        self._programs_seen.discard("plain1")
        self._programs_seen.discard("plainK")
        self._feed_flipped = True
        if self.is_main:
            print(f"governor: flipped augmentation"
                  f"{' + guidance' if dev_guidance else ''} on device "
                  "(host stages dropped; steps rebuilt)", flush=True)

    # ------------------------------------------------------------ IR audit
    def audit_programs(self, train_batch=None, val_batch=None) -> dict:
        """``{name: (fn, example_args)}`` for the EXACT jitted programs
        this trainer dispatches — the hook jaxaudit (analysis.ir) traces.
        Args are ShapeDtypeStruct templates: tracing never executes, and
        a struct can never be consumed by the step's donation.

        ``train_batch`` / ``val_batch``: one host batch from the real
        loaders, for configs whose wire format (uint8_transfer,
        packbits_masks, coalesce_wire, device_guidance) a config-derived
        synthesis cannot reproduce; the plain f32 wire synthesizes
        itself.  Under data.coalesce_wire the WIRE-consuming twins are
        audited (they are what the loop dispatches): the caller's real
        batch is packed through the prefetcher's own transform, which
        derives/validates the wire spec and builds the twins if no batch
        has yet.  The K-step program (data.steps_per_dispatch) is
        included when configured."""
        from ..analysis.ir import struct_of

        cfg = self.cfg
        h, w = cfg.data.crop_size
        sds = jax.ShapeDtypeStruct
        if train_batch is None:
            if cfg.data.uint8_transfer or cfg.data.packbits_masks \
                    or cfg.data.coalesce_wire:
                raise ValueError(
                    "this config ships a non-f32 wire "
                    "(uint8_transfer/packbits/coalesce) — pass one real "
                    "host batch from the train loader as train_batch")
            train_batch = {
                "concat": sds((cfg.data.train_batch, h, w,
                               cfg.model.in_channels), jnp.float32),
                "crop_gt": sds((cfg.data.train_batch, h, w),
                               jnp.float32),
            }
        if val_batch is None and not (self._val_device_guidance
                                      or self._val_packbits):
            # the shape the eval loop actually dispatches: the per-host
            # val share, padded to the device multiple exactly as
            # evaluate() does (pad_to_multiple + shard_batch) — NOT the
            # train batch, which eval never sees
            n_proc = jax.process_count()
            n_dev = self.mesh.devices.size
            vb_host = max(1, -(-cfg.data.val_batch // n_proc))
            vb = -(-vb_host // n_dev) * n_dev * n_proc
            val_batch = {
                "concat": sds((vb, h, w, cfg.model.in_channels),
                              jnp.float32),
                "crop_gt": sds((vb, h, w), jnp.float32),
            }
        state_s = struct_of(self.state)
        if cfg.data.coalesce_wire:
            # the dispatched programs are the wire-consuming twins —
            # packing the caller's real host batch through the same
            # transform the prefetcher uses derives (or validates) the
            # wire spec and builds the twins if the first batch hasn't
            batch_s = struct_of(self._pack_wire_transform(
                dict(train_batch)))
            train_fn, multi_fn = self._wire_step, self._wire_multi_step
        else:
            train_fn, multi_fn = self.train_step, self.multi_train_step
            batch_s = struct_of(dict(train_batch))
        programs = {"train_step": (train_fn, (state_s, batch_s))}
        if multi_fn is not None:
            k = cfg.data.steps_per_dispatch
            programs["multi_train_step"] = (
                multi_fn, (state_s,) + (batch_s,) * k)
        if val_batch is not None:
            programs["eval_step"] = (self.eval_step,
                                     (state_s, struct_of(dict(val_batch))))
        return programs

    def audit(self, check: bool = False, contracts_dir: str | None = None,
              **batches) -> dict:
        """Run jaxaudit over :meth:`audit_programs`; returns
        ``{name: report}``.  With ``check``, each report additionally
        carries ``contract_drift`` (the drift lines against the
        checked-in contracts — empty means clean).

        Under ``train.precision`` the JA002 pass audits against the
        policy's declared accumulation points (``ja002_allow``) — the
        strict default would flag the policy's own f32 islands (master-
        grad accumulation, BN stats, the loss) on every report."""
        from ..analysis import contracts as contracts_lib
        from ..analysis import ir as ir_lib

        audit_kwargs = {}
        if getattr(self, "precision", None) is not None:
            audit_kwargs["f32_allow"] = self.precision.ja002_allow()
        if self.cfg.train.reduce_buckets:
            audit_kwargs["overlap_expected"] = True
        with self.mesh:
            reports = ir_lib.audit_many(self.audit_programs(**batches),
                                        **audit_kwargs)
        if check:
            for rep in reports.values():
                rep["contract_drift"] = contracts_lib.check_report(
                    rep, contracts_dir)
        return reports

    def train_epoch(self, epoch: int,
                    guard: PreemptionGuard | None = None,
                    start_batch: int = 0,
                    abort_check=None) -> float:
        """One epoch; returns mean train loss (the reference printed the
        running loss once per epoch, train_pascal.py:207-212).

        ``guard``: stop-consensus checked every ``preempt_check_every``
        steps, so all hosts leave the loop at the same step.
        ``start_batch``: skip the first batches of the epoch's deterministic
        order — the exact-resume continuation of a preempted epoch (the
        returned mean covers only the batches actually trained)."""
        cfg = self.cfg
        self.train_loader.set_epoch(epoch, start_batch=start_batch)
        losses = []
        #: per-dispatch (grad_norm, update_ratio) outputs, aligned with
        #: ``losses`` (sentinel.monitor_grads only; else stays empty)
        aux_outs = []
        monitor = bool(self._step_kwargs.get("sentinel_metrics"))
        self._epoch_batch_order = []
        t0 = time.perf_counter()
        acct = get_accountant()
        # Track the step as a python int (start + i): reading
        # ``self.state.step`` every iteration would block on the device and
        # serialize host data-prep against device compute.
        step0 = int(self.state.step)

        def host_batches():
            # quarantine (sentinel rollback-and-replay): loader indices a
            # past rollback blamed for divergence are skipped on replay;
            # the order list maps each dispatched step back to its loader
            # index so a LATER divergence in this epoch quarantines the
            # right batches even after skips.
            qset = self._quarantine.get(epoch)
            for i, batch in enumerate(self.train_loader):
                idx = start_batch + i
                if qset and idx in qset:
                    continue
                if cfg.debug_asserts:
                    if cfg.task == "instance":
                        batch_debug_asserts(
                            batch, packed_masks=cfg.data.packbits_masks)
                    else:
                        semantic_batch_debug_asserts(batch, cfg.model.nclass)
                self._epoch_batch_order.append(idx)
                yield batch

        # the echo factor in effect for THIS epoch: the config's base, or
        # the governor's armed factor (changed at epoch boundaries only,
        # so it is stable across the epoch's accounting below)
        echo = self._echo

        def echoed(it):
            # Data echoing (config.py: data.echo): repeat each already-placed
            # device batch — zero extra host decode or H2D traffic per echo;
            # the step's advancing RNG gives each echo fresh on-device
            # augmentation when enabled.
            for b in it:
                for _ in range(echo):
                    yield b

        def waited(it):
            # input-wait measured at the batch-fetch boundary: host time
            # blocked on the prefetcher IS the data-pipeline stall signal
            # (the silently-dominant cost FFCV / arxiv 2005.02130 document)
            # — a first-class goodput bucket instead of invisible idle.
            # Pure perf_counter bookkeeping: no host sync enters the loop.
            it = iter(it)
            while True:
                with acct.account("input_wait"):
                    try:
                        b = next(it)
                    except StopIteration:
                        return
                    # chaos seam: injected latency here IS input stall
                    # (books under input_wait); payload poisoning tears
                    # the batch the step is about to consume
                    b = chaos_sites.fire("trainer/batch_fetch", payload=b)
                yield b

        def dispatches(placed):
            """(n_steps, losses) per compiled call: K-step chunks through
            the multi-step program (data.steps_per_dispatch), the epoch
            tail (and the k=1 config) through the single-step one.  The
            wire-consuming twins substitute under data.coalesce_wire —
            read per call, not hoisted: they are built lazily by
            ``host_batches`` while the prefetcher pulls ahead."""
            def dispatch(fn, key, n, args):
                """One compiled call, goodput-attributed: the first
                dispatch of each program pays trace+XLA and books under
                'compile'; repeats are productive 'step' time.  The trace
                trigger ticks BEFORE the call so an armed capture starts
                on (not after) the step it was requested for."""
                if self._trace is not None:
                    self._trace.tick(n)
                first = key not in self._programs_seen
                with acct.account("compile" if first else "step"):
                    self.state, out = fn(self.state, *args)
                if first:
                    self._programs_seen.add(key)
                    # the cost-analysis re-trace books as compile too —
                    # it is trace time, and idle must stay unexplained
                    # time only
                    with acct.account("compile"):
                        self._note_step_cost(fn, (self.state, *args), n)
                else:
                    self._prod_steps += n
                # chaos seam, between dispatches: sigterm here is a
                # preemption landing mid-epoch (through the real guard),
                # nan poisons the LOSS the loop observes (the divergence-
                # detection driver — the state itself trained on real
                # data and stays finite)
                return chaos_sites.fire("trainer/train_step", payload=out)

            def one_step(b):
                if cfg.data.coalesce_wire:
                    return dispatch(self._wire_step, "wire1", 1, (b,))
                return dispatch(self.train_step, "plain1", 1, (b,))

            if cfg.data.steps_per_dispatch <= 1:
                for b in placed:
                    yield 1, one_step(b)
                return
            import itertools
            k = cfg.data.steps_per_dispatch
            it = iter(placed)
            while True:
                chunk = list(itertools.islice(it, k))
                if not chunk:
                    return
                if len(chunk) == k:
                    if cfg.data.coalesce_wire:
                        lv = dispatch(self._wire_multi_step, "wireK", k,
                                      chunk)
                    else:
                        lv = dispatch(self.multi_train_step, "plainK", k,
                                      chunk)
                    yield k, lv
                else:
                    for b in chunk:
                        yield 1, one_step(b)

        steps_done = 0
        interrupted = False
        with self.mesh:
            # Async H2D overlap: up to device_prefetch batches are already
            # placed (sharded) while the current step computes.
            batches = prefetch_to_device(
                host_batches(), self.mesh,
                # a multi-step dispatch consumes K placed batches at once;
                # a window smaller than K would stall the chip on placement
                # at every chunk boundary.  Read live (callable) so the
                # governor's hot resize applies mid-epoch.
                size=lambda: max(self._device_prefetch,
                                 cfg.data.steps_per_dispatch),
                keys=(WIRE_KEY,) if cfg.data.coalesce_wire
                else DEVICE_KEYS,
                transform=(self._pack_wire_transform
                           if cfg.data.coalesce_wire else None))
            if echo > 1:
                batches = echoed(batches)
            batches = waited(batches)
            # cadence comes from the guard itself (a caller-provided guard
            # may carry its own check_every)
            check = guard.check_every if guard is not None else 1
            for n_steps, out in dispatches(batches):
                if monitor:  # step emits (loss, (grad_norm, ratio))
                    loss, aux = out
                    aux_outs.append(aux)
                else:
                    loss = out
                losses.append(loss)  # device scalar or (K,); sync deferred
                steps_done += n_steps
                step = step0 + steps_done
                # Boundary-crossing test, not a bare modulo: with K-step
                # dispatches the step sequence is K-strided and could skip
                # every `step % check == 0` point for a whole epoch.  All
                # processes see identical (step, n_steps), so the consensus
                # cadence stays synchronized.
                if guard is not None and \
                        (step // check) != ((step - n_steps) // check) and \
                        guard.should_stop():
                    interrupted = True
                    break
                crossed = (step // cfg.log_every_steps) \
                    != ((step - n_steps) // cfg.log_every_steps)
                if crossed and abort_check is not None:
                    # val_overlap: a failure on the val thread (e.g. the
                    # non-finite watchdog) must abort training NOW, not a
                    # full epoch later at the join
                    abort_check()
                if crossed:
                    # The log-cadence sync runs on EVERY process, not just
                    # main: the watchdog below must raise on all hosts
                    # together (loss is replicated, so they all see the
                    # same value) — a main-only raise would leave the other
                    # processes blocked forever at their next collective.
                    # Goodput: this sync pays the deferred device compute
                    # of the steps dispatched since the last crossing —
                    # productive step time (the epoch-end bulk-readback
                    # convention), not idle.  The feed window's busy
                    # delta depends on it: unbooked, a fully-overlapped
                    # feed would read as a ~1.0 stall fraction.
                    with acct.account("step"):
                        loss_vec = np.atleast_1d(jax.device_get(loss))
                    if self._sentinel is not None:
                        # sentinel absorbs the isfinite watchdog: judge
                        # the latest dispatch against the current EMA
                        # (update=False — the epoch-end sweep owns EMA
                        # advancement, in strict step order) and hand a
                        # diverged verdict to fit's rollback path
                        g_vec = r_vec = None
                        if monitor:
                            a = np.atleast_2d(
                                np.asarray(jax.device_get(aux_outs[-1])))
                            g_vec, r_vec = a[:, 0], a[:, 1]
                        rep = self._sentinel.observe(
                            step - n_steps + 1, loss_vec, grad_norms=g_vec,
                            update_ratios=r_vec, update=False)
                        if rep.diverged:
                            raise self._divergence(
                                epoch, step0, rep, step, loss_vec)
                    if self._governor is not None:
                        # feed-governor tick (data/governor.py): one
                        # goodput-snapshot delta into the stall window,
                        # rung-1 prefetch resize may hot-apply.  Rides
                        # the cadence the loop already pays — no extra
                        # host sync.
                        self._feed_tick(epoch, step)
                    if self._sentinel is None and cfg.debug_asserts and \
                            not np.all(np.isfinite(loss_vec)):
                        # bf16 watchdog: surface divergence at the log
                        # cadence instead of training garbage for the rest
                        # of the epoch (see also the epoch-end sweep below).
                        # The whole (K,) dispatch vector is checked, not
                        # just one element — a mid-dispatch blowup must not
                        # slip past the cadence check.
                        off = int(np.flatnonzero(
                            ~np.isfinite(loss_vec))[0])
                        raise FloatingPointError(
                            f"non-finite train loss {loss_vec[off]} at "
                            f"step {step - n_steps + 1 + off} (epoch "
                            f"{epoch}) — divergence; lower optim.lr, "
                            "enable optim.grad_clip_norm, or set "
                            "optim.loss_scale for bf16 underflow")
                    if self.is_main:
                        # Attribute each logged loss to the step that
                        # crossed a cadence boundary, indexing that step's
                        # own element of the (K,) dispatch vector —
                        # loss_vec[-1] at `step` would skew the train/loss
                        # curve by up to K-1 steps.  A single dispatch can
                        # cross SEVERAL boundaries (K > log_every_steps):
                        # every multiple of the cadence inside
                        # (step - n_steps, step] gets its own point.  For
                        # K=1 this is exactly one (loss_vec[0], step).
                        L = cfg.log_every_steps
                        bstep = ((step - n_steps) // L + 1) * L
                        while bstep <= step:
                            loss_now = float(
                                loss_vec[bstep - (step - n_steps) - 1])
                            self.writer.scalars(
                                {"train/loss": loss_now,
                                 "train/lr": float(self.schedule(bstep)),
                                 "train/epoch": epoch}, bstep)
                            bstep += L
        # One bulk readback, not one float() per step: each scalar fetch is a
        # full host<->device round trip (~70ms through a tunneled chip — per-
        # step syncs would dwarf the epoch itself).  Entries are scalars
        # (one per step) or (K,) vectors (one per multi-step dispatch).
        # Goodput: this wait IS the deferred device compute of the epoch's
        # steps landing — productive time, not idle.
        if losses:
            with acct.account("step"):
                fetched, fetched_aux = jax.device_get((losses, aux_outs))
            loss_arr = np.concatenate([np.atleast_1d(x) for x in fetched])
        else:
            loss_arr = np.array([np.nan])
        if self._sentinel is not None and losses:
            # THE EMA-updating sentinel pass: the full epoch's losses in
            # strict step order (free — the bulk readback above already
            # landed them).  Mid-epoch cadence checks judged against a
            # per-epoch-stale EMA; this is where it advances.
            g_arr = r_arr = None
            if monitor and fetched_aux:
                aux_arr = np.concatenate(
                    [np.atleast_2d(np.asarray(x)) for x in fetched_aux])
                g_arr, r_arr = aux_arr[:, 0], aux_arr[:, 1]
            rep = self._sentinel.observe(
                step0 + 1, loss_arr, grad_norms=g_arr,
                update_ratios=r_arr, update=True)
            if rep.diverged:
                raise self._divergence(epoch, step0, rep,
                                       step0 + loss_arr.size, loss_arr)
        bad = np.flatnonzero(~np.isfinite(loss_arr))
        if bad.size and losses and self._sentinel is None:
            # Epoch-end non-finite sweep (free: the losses are already on
            # host).  Always logged; fatal under debug_asserts.  With the
            # sentinel enabled this legacy response is absorbed: a
            # non-finite loss is a 'diverged' verdict handled above.
            msg = (f"{bad.size}/{loss_arr.size} non-finite train losses this "
                   f"epoch (first at epoch step {int(bad[0])}) — divergence "
                   "or bf16 underflow; lower optim.lr, enable "
                   "optim.grad_clip_norm, or set optim.loss_scale")
            if cfg.debug_asserts:
                raise FloatingPointError(msg)
            if self.is_main:
                print(f"warning: {msg}", flush=True)
                self.writer.scalars(
                    {"train/nonfinite_steps": int(bad.size)},
                    int(self.state.step))
        mean_loss = float(np.mean(loss_arr)) if losses else float("nan")
        dt = time.perf_counter() - t0
        if not losses and self._quarantine.get(epoch):
            # every batch of the epoch is quarantined: nothing trained,
            # nothing to log — the caller's loop moves on
            return float("nan")
        # Distinct images ingested — echoed repeats of a batch are not fresh
        # data; reporting them would make any echo setting look like a win.
        # `echo` is this epoch's LIVE factor (governor-armed included).
        n_imgs = steps_done * cfg.data.train_batch / echo
        # An interrupted epoch logs no completed-epoch summary: its partial
        # mean would skew per-epoch curves, and the replayed epoch will log
        # the real one.
        if self.is_main and not interrupted:
            scalars = {"train/epoch_loss": mean_loss,
                       "train/imgs_per_sec": n_imgs / dt if dt > 0 else 0.0,
                       "train/epoch_seconds": dt, "train/epoch": epoch}
            if start_batch:
                scalars["train/resumed_at_batch"] = start_batch
            peak = device_memory_stats()["peak_bytes_in_use"]
            if peak:  # backends without stats (CPU) report zero
                scalars["train/peak_hbm_gb"] = round(peak / 2**30, 3)
            self.writer.scalars(scalars, int(self.state.step))
        return mean_loss

    # ------------------------------------------------- sentinel rollback
    def _divergence(self, epoch: int, step0: int, report, end_step: int,
                    observed) -> _DivergenceDetected:
        """Build the rollback request for a ``diverged`` verdict: the
        quarantine window runs from the verdict's step through the end of
        the observed vector (later steps in the same dispatch trained on
        a state the bad step already poisoned), mapped back to loader
        batch indices via this epoch's dispatch order."""
        first = end_step - len(observed) + 1
        w0 = int(report.step)
        window = [float(x) for x in observed[w0 - first:]]
        # the LIVE echo factor (governor-armed included): each loader
        # batch produced that many steps this epoch, so the step->batch
        # index mapping must divide by it — and the quarantine skip then
        # drops ALL echoes of a poisoned batch on replay (host_batches
        # skips the index before the echo stage re-expands it)
        echo = max(1, self._echo)
        order = self._epoch_batch_order
        idxs = sorted({
            order[j] for s in range(w0, end_step + 1)
            if 0 <= (j := (s - step0 - 1) // echo) < len(order)})
        return _DivergenceDetected(epoch, w0, end_step, idxs, window,
                                   report)

    def _budget_tick(self) -> None:
        raise _RollbackBudgetTick()

    def _last_committed_step(self) -> int | None:
        """Newest checkpoint step the commit ledger vouches for (rollback
        must never target a possibly-torn write; a torn restore target
        would turn one bad batch into a dead run).  With no ledger yet
        (a pre-ledger directory) the manager's newest step is trusted."""
        committed = self.ckpt.committed_steps()
        for s in sorted((int(s) for s in self.ckpt.all_steps()),
                        reverse=True):
            if not committed or s in committed:
                return s
        return None

    def _handle_divergence(self, d: _DivergenceDetected,
                           history: dict) -> int:
        """Rollback-and-replay: budget-check, quarantine the bad window,
        restore the last COMMITTED checkpoint in-process, and return the
        epoch to resume from.  Runs identically on every host (all inputs
        are replicated values or collective ops), so multi-host rollback
        needs no extra consensus."""
        cfg = self.cfg
        # budget FIRST: a run that diverges after every rollback must
        # fail loudly, not loop.  Each rollback books one failure on the
        # breaker; a cleanly completed epoch (fit loop) books a success.
        try:
            self._rollback_breaker.call(self._budget_tick)
        except CircuitOpenError:
            raise FloatingPointError(
                f"sentinel: rollback budget exhausted "
                f"({cfg.sentinel.max_rollbacks} consecutive rollbacks "
                f"without a cleanly completed epoch) — still diverging: "
                f"{d}") from d
        except _RollbackBudgetTick:
            pass
        self._discard_overlapped_val()
        t0 = time.perf_counter()
        self.ckpt.wait()  # land in-flight async saves + refresh the ledger
        target = self._last_committed_step()
        if target is None:
            # fit() saves a step-0 checkpoint when the sentinel is armed,
            # so this means checkpointing itself is broken — surface it
            raise FloatingPointError(
                f"sentinel: diverged with NO committed checkpoint to roll "
                f"back to ({d})") from d
        self.state, meta = self.ckpt.restore(self.state, step=target)
        dt = time.perf_counter() - t0
        self._rollback_seconds.append(dt)
        self.sentinel_rollbacks += 1
        self.sentinel_quarantined_steps += len(d.batch_indices)
        self._quarantine.setdefault(d.epoch, set()).update(d.batch_indices)
        self._sentinel.reset()  # spike verdicts re-warm on the replay
        self._book_rollback(d, target, dt)
        resume_epoch = int(meta.get("epoch", -1)) + 1
        # flight recorder: the replay anchor closing the
        # divergence -> rollback -> replay episode
        events_lib.emit("sentinel", "replay", step=int(self.state.step),
                        epoch=resume_epoch,
                        payload={"rolled_back_to_step": int(target)})
        # completed-epoch history about to be replayed is dropped — the
        # replay logs the real entries (same rule as preempt resume).
        # val entries carry their epoch stamp, so a rollback past a
        # validated epoch (e.g. its best-save was the torn write) cannot
        # leave duplicate val records after the replay re-validates.
        del history["train_loss"][max(0, resume_epoch - self.start_epoch):]
        history["val"] = [m for m in history["val"]
                          if m.get("epoch", -1) < resume_epoch]
        self._resume_start_batch = 0
        if self.is_main:
            print(f"sentinel: diverged at step {d.report.step} "
                  f"({d.report.reason}) — rolled back to committed step "
                  f"{target} in {dt:.2f}s, quarantined batches "
                  f"{d.batch_indices} of epoch {d.epoch}, resuming at "
                  f"epoch {resume_epoch} (rollback "
                  f"{self.sentinel_rollbacks}/"
                  f"{cfg.sentinel.max_rollbacks})", flush=True)
        return resume_epoch

    def _quarantine_records(self, d: _DivergenceDetected) -> list | None:
        """Resolve the quarantined loader batch indices to the exact
        packed records through ``PackedDataset.seek`` — O(1) per sample
        off the pack's index rows.  The batch -> sample mapping is the
        epoch's deterministic order (``DataLoader.batch_sample_indices``);
        None when the train source is not packed (or the loader can't
        map), in which case batch indices remain the ledger's only
        name."""
        from ..data.packed import resolve_packed

        mapper = getattr(self.train_loader, "batch_sample_indices", None)
        if mapper is None or resolve_packed(self.train_set, 0) is None:
            return None
        out = []
        for bi in sorted(d.batch_indices):
            entries = []
            for si in mapper(int(bi), epoch=d.epoch):
                hit = resolve_packed(self.train_set, int(si))
                if hit is None:  # mixed sources: stay honest, omit all
                    return None
                ds, local = hit
                m = ds.seek(local)
                entries.append({"record": m["record"],
                                "image": m["image_id"],
                                "object": m["object"]})
            out.append({"batch_index": int(bi), "records": entries})
        return out

    def _book_rollback(self, d: _DivergenceDetected, target: int,
                       seconds: float) -> None:
        """Durable + telemetry record of one rollback: a quarantine.jsonl
        line (the ledger ops reads back), registry counters, and writer
        scalars."""
        if self.is_main:
            rec = {"epoch": d.epoch, "step_start": d.step_start,
                   "step_end": d.step_end,
                   "batch_indices": list(d.batch_indices),
                   # packed source: the quarantined batches resolved to
                   # the EXACT records via PackedDataset.seek (O(1) off
                   # the index rows — no re-iteration, no decode); null
                   # on fs sources, where batch indices are the only
                   # stable name
                   "records": self._quarantine_records(d),
                   # JSON has no NaN/Inf: non-finite observed losses are
                   # null (the same rule JsonlWriter applies)
                   "losses": [x if np.isfinite(x) else None
                              for x in d.losses],
                   "reason": d.report.reason,
                   "rollback_to_step": int(target),
                   "restore_seconds": round(seconds, 3)}
            with open(os.path.join(self.run_dir, "quarantine.jsonl"),
                      "a") as f:
                f.write(json.dumps(rec) + "\n")
            self.writer.scalars(
                {"train/sentinel_rollbacks": self.sentinel_rollbacks,
                 "train/sentinel_quarantined_steps":
                     self.sentinel_quarantined_steps,
                 "train/sentinel_rollback_to_step": int(target)},
                d.step_end)
        # flight recorder: the rollback itself (every host; quarantine.jsonl
        # above stays the main-only authoritative ledger)
        events_lib.emit(
            "sentinel", "rollback", step=d.step_end, epoch=d.epoch,
            payload={"reason": d.report.reason,
                     "rollback_to_step": int(target),
                     "restore_seconds": round(seconds, 3),
                     "batch_indices": sorted(int(b)
                                             for b in d.batch_indices)})
        if self.cfg.telemetry:
            from ..telemetry import get_registry
            from ..telemetry.registry import is_enabled

            if is_enabled():
                reg = get_registry()
                reg.counter(
                    "train_sentinel_rollbacks_total",
                    "Sentinel-triggered in-process rollbacks").inc()
                reg.counter(
                    "train_sentinel_quarantined_steps_total",
                    "Steps quarantined by sentinel rollbacks"
                ).inc(len(d.batch_indices))
                reg.histogram(
                    "train_sentinel_recovery_seconds",
                    "Rollback restore time (divergence -> resumed state)"
                ).observe(seconds)

    # ------------------------------------------------------------------- eval
    def _eval_metrics(self, state, epoch: int | None = None
                      ) -> tuple[dict, dict | None]:
        """The device/host evaluation half of :meth:`validate` — no writer
        or checkpoint side effects, so it is safe to run on the val-overlap
        thread against a snapshot ``state``."""
        # goodput: validation wall-clock books under 'eval' (per-thread
        # stacks keep the val-overlap thread's books separate)
        with get_accountant().account("eval"):
            return self._eval_metrics_inner(state, epoch)

    def _eval_metrics_inner(self, state, epoch: int | None = None
                            ) -> tuple[dict, dict | None]:
        self.val_loader.set_epoch(0)
        with self.mesh:
            if self.cfg.task == "semantic":
                metrics = evaluate_semantic(
                    self.eval_step, state, self.val_loader,
                    nclass=self.cfg.model.nclass, mesh=self.mesh,
                    tta_scales=self.cfg.eval_tta_scales,
                    tta_flip=self.cfg.eval_tta_flip,
                    debug_asserts=self.cfg.debug_asserts,
                    bf16_probs=self.cfg.eval_bf16_probs,
                    device_fullres=(
                        tuple(self.cfg.data.val_max_im_size)
                        if self.cfg.eval_device_fullres else None))
            else:
                metrics = evaluate(
                    self.eval_step, state, self.val_loader,
                    thresholds=self.cfg.eval_thresholds,
                    relax=self.cfg.data.relax,
                    zero_pad=self.cfg.data.zero_pad, mesh=self.mesh,
                    debug_asserts=self.cfg.debug_asserts,
                    packed_masks=self._val_packbits,
                    bf16_readback=self.cfg.eval_bf16_probs)
        first = metrics.pop("_first_batch", None)
        if self.cfg.debug_asserts and not np.isfinite(metrics["loss"]):
            # Watchdog, val side: a 1-step epoch's train loss is computed
            # BEFORE the diverging update, so the val loss can be the first
            # place non-finite values surface.
            raise FloatingPointError(
                f"non-finite val loss {metrics['loss']} at epoch {epoch} — "
                "divergence; lower optim.lr, enable optim.grad_clip_norm, "
                "or set optim.loss_scale for bf16 underflow")
        return metrics, first

    def validate(self, epoch: int | None = None, log_panels: bool = True,
                 state=None) -> dict:
        state = self.state if state is None else state
        metrics, first = self._eval_metrics(state, epoch)
        self._log_val(metrics, first, epoch, int(state.step),
                      log_panels=log_panels)
        return metrics

    def _log_val(self, metrics: dict, first: dict | None,
                 epoch: int | None, step: int,
                 log_panels: bool = True) -> None:
        """Writer half of validation — main thread only."""
        if self.is_main:
            flat = {"val/loss": metrics["loss"],
                    "val/jaccard": metrics["jaccard"]}
            if "best_threshold" in metrics:
                flat["val/best_threshold"] = metrics["best_threshold"]
            for th, v in metrics.get("jaccard_per_threshold", {}).items():
                flat[f"val/jaccard@{th}"] = v
            if "miou" in metrics:
                flat["val/miou"] = metrics["miou"]
                flat["val/pixel_acc"] = metrics["pixel_acc"]
            if epoch is not None:
                flat["val/epoch"] = epoch
            self.writer.scalars(flat, step)
            if log_panels and first is not None:
                try:
                    fig = make_val_panels(first)
                    self.writer.figure("val_panels", fig, step)
                    import matplotlib.pyplot as plt
                    plt.close(fig)
                except Exception:
                    pass  # visualization must never kill training

    # ----------------------------------------------------- val overlap
    def _launch_overlapped_val(self, epoch: int, step: int) -> None:
        """Start validation of the CURRENT state on a thread (val_overlap):
        the next train epoch proceeds while eval forwards interleave on the
        device and the paste-back runs beside the loader.

        The snapshot must be a device-side COPY, not a reference: the
        train step donates its state argument, so the next epoch's first
        step would delete the original buffers while the val thread (and
        the deferred best-save) still read them.  One extra full state in
        HBM until the join; the copy itself is a single pass of HBM
        bandwidth (~ms).  All writer/checkpoint side effects happen at
        :meth:`_join_overlapped_val` on the main thread."""
        import threading

        with self.mesh:
            state = jax.tree.map(
                lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x,
                self.state)
        box: dict = {}

        def run() -> None:
            try:
                box["result"] = self._eval_metrics(state, epoch)
            except BaseException as e:  # re-raised at join
                box["error"] = e

        t = threading.Thread(target=run, name=f"val-overlap-{epoch}",
                             daemon=True)
        t.start()
        self._pending_val = (epoch, step, state, t, box)

    def _poll_overlapped_val_error(self) -> None:
        """Fail fast if the in-flight overlapped validation already died
        (called at the train loop's log cadence): without this, a val-side
        divergence watchdog would only surface at the join, a full train
        epoch after the fact."""
        pending = self._pending_val
        if pending is not None and "error" in pending[4]:
            self._join_overlapped_val(None)  # immediate join; raises

    def _join_overlapped_val(self, history: dict | None,
                             finish: bool = True) -> None:
        """Wait for the in-flight overlapped validation (if any) and apply
        its deferred epoch-end bookkeeping via :meth:`_finish_val`.
        ``finish=False`` waits only (benchmarks timing the schedule must
        not fold checkpoint/panel costs into the measurement)."""
        pending = self._pending_val
        if pending is None:
            return
        self._pending_val = None
        epoch, step, state, thread, box = pending
        thread.join()
        if "error" in box:
            raise box["error"]
        if finish:
            metrics, first = box["result"]
            self._finish_val(metrics, first, epoch, step, state, history)

    def _discard_overlapped_val(self) -> None:
        """Abandon the in-flight overlapped validation: join the thread
        (it reads a valid snapshot; letting it run unsupervised would race
        a later validate() on the shared val loader and pin the extra HBM
        state) and drop its result.  For unwind paths only — a primary
        exception is already propagating, so the box's own error (if any)
        is intentionally swallowed."""
        pending = self._pending_val
        if pending is None:
            return
        self._pending_val = None
        pending[3].join()

    def _finish_val(self, metrics: dict, first: dict | None, epoch: int,
                    step: int, state, history: dict | None) -> None:
        """THE epoch-end validation bookkeeping — one owner for both the
        serial and overlapped schedules (logging, history, best-gated
        checkpoint of ``state`` at ``step``)."""
        self._log_val(metrics, first, epoch, step)
        if history is not None:
            # epoch-stamped: a sentinel rollback must be able to drop the
            # entries of epochs it is about to replay (see
            # _handle_divergence) without positional guesswork
            history["val"].append(dict(metrics, epoch=epoch))
        is_best = self.ckpt.save(step, state, metric=metrics["jaccard"],
                                 extra={"epoch": epoch})
        if is_best and self.is_main:
            self.writer.scalars(
                {"val/new_best_jaccard": metrics["jaccard"],
                 "val/epoch": epoch}, step)

    # -------------------------------------------------------------------- fit
    def fit(self, guard: PreemptionGuard | None = None) -> dict:
        """The full loop (reference train_pascal.py:180-308): train each
        epoch; validate every ``eval_every``; snapshot every
        ``snapshot_every``; save best on threshold-max Jaccard improvement.

        Preemption: unless disabled (``checkpoint.save_on_preempt=false``),
        SIGTERM/SIGINT triggers a consensus stop, one final full-state
        checkpoint, and a clean return — ``history["preempted"]`` marks it.
        The save records the epoch position (``epoch_steps_done``); with
        ``checkpoint.exact_resume`` (default) the resumed run continues the
        interrupted epoch at exactly that batch — no batch trains twice and
        none are skipped (the epoch's order is deterministic given
        (seed, epoch)).  Exactness is at batch granularity: a stop landing
        mid-echo (``data.echo > 1``) replays that batch's echoes, a stop on
        the epoch's last step replays the final batch (so epoch-end
        validation/best-gating still run), and a resume whose data-order
        config changed (process count, train batch, seed, echo) replays the
        whole epoch — the recorded offset indexes an order that no longer
        exists.  ``exact_resume=false`` replays the epoch from its start
        unconditionally (batches repeat, none skipped).  Pass your own
        entered ``guard`` to drive stops programmatically (e.g. a
        wall-clock watchdog calling ``trip()``)."""
        cfg = self.cfg
        history = {"train_loss": [], "val": []}
        if cfg.profile_epoch is not None and self.is_main and not \
                (self.start_epoch <= cfg.profile_epoch < cfg.epochs):
            print(f"warning: profile_epoch={cfg.profile_epoch} outside the "
                  f"epoch range [{self.start_epoch}, {cfg.epochs}) — no "
                  "trace will be written", flush=True)
        # goodput books cover exactly this fit; the on-demand trace trigger
        # (SIGUSR2 -> bounded XPlane capture under run_dir/trace_on_demand)
        # is armed for its duration.  set_enabled gates EVERY optional
        # instrumentation path (spans, preemption publishing) process-wide,
        # so telemetry=false is the true zero-instrumentation baseline.
        telemetry_set_enabled(cfg.telemetry)
        get_accountant().reset(enabled=cfg.telemetry)
        # flight recorder: the generation's opening anchor — the timeline
        # merger bounds every generation by this fit_start/fit_end pair
        # (an unpaired fit_start IS the crash evidence)
        events_lib.emit(
            "trainer", "fit_start", step=int(self.state.step),
            epoch=self.start_epoch,
            payload={"epochs": cfg.epochs,
                     "resumed": bool(self.resume_meta),
                     "plan_crossing": bool(self.resume_plan_crossing)})
        # chaos: arm an env-named fault plan (DPTPU_CHAOS_PLAN) for this
        # fit; with the env unset and nothing armed this is one getenv.
        chaos_sites.maybe_arm_from_env()
        self._prod_steps = 0
        # the accountant's books were just zeroed: a snapshot from a
        # previous fit would difference negative (FeedWindow drops
        # negatives, but a fresh fit starts a fresh window)
        self._feed_last = None
        with contextlib.ExitStack() as stack:
            if self._trace is not None:
                stack.callback(self._trace.close)
                stack.callback(self._trace.install_signal())
            if guard is None and cfg.checkpoint.save_on_preempt:
                guard = stack.enter_context(PreemptionGuard(
                    check_every=cfg.checkpoint.preempt_check_every))
            # an exception unwinding past the loop (train-side watchdog,
            # Ctrl-C without a guard) must not strand the val-overlap
            # thread: it would race a later validate() on the shared val
            # loader and pin the snapshot's HBM.  Normal completion joins
            # with full bookkeeping below, making this a no-op.
            stack.callback(self._discard_overlapped_val)
            if self._sentinel is not None and \
                    self._last_committed_step() is None:
                # the sentinel's rollback target must EXIST before the
                # first divergence can strike: a fresh run commits its
                # initial state (step 0, or the resumed step) up front, so
                # an epoch-0 divergence rolls back to init instead of
                # failing with nothing to restore
                self.ckpt.save(int(self.state.step), self.state,
                               extra={"epoch": self.start_epoch - 1})
                self.ckpt.wait()
            epoch = self.start_epoch
            while epoch < cfg.epochs:
                t0 = time.perf_counter()
                sb = self._resume_start_batch  # only the run's first epoch
                self._resume_start_batch = 0
                estep0 = int(self.state.step)
                if cfg.profile_epoch == epoch and self.is_main:
                    # On-demand op-level device trace (SURVEY §5.1: the
                    # reference had only wall-clock prints).  One epoch,
                    # written under the run dir for tensorboard/xprof.
                    from ..utils.profiling import trace
                    ctx = trace(os.path.join(self.run_dir, "profile"))
                else:
                    ctx = contextlib.nullcontext()
                try:
                    with ctx:
                        epoch_loss = self.train_epoch(
                            epoch, guard=guard, start_batch=sb,
                            abort_check=(self._poll_overlapped_val_error
                                         if cfg.val_overlap else None))
                except _DivergenceDetected as d:
                    # rollback-and-replay: restore the last committed
                    # checkpoint, quarantine the bad window, re-enter the
                    # loop at the restored epoch (budget-bounded — the
                    # handler raises when the CircuitBreaker is open)
                    epoch = self._handle_divergence(d, history)
                    continue
                # the previous epoch's overlapped validation ran during
                # this train epoch; land its bookkeeping (best save, logs)
                # before this epoch's own epoch-end work
                self._join_overlapped_val(history)
                step = int(self.state.step)
                if guard is not None and guard.should_stop():
                    # The partial epoch is not appended to history; the
                    # resumed run continues it at the recorded batch
                    # (checkpoint.exact_resume) or replays it in full.
                    history["preempted"] = True
                    # shield(): signals delivered during the final save and
                    # flush are absorbed (no escalation), so a scheduler's
                    # follow-up SIGTERM cannot kill the very checkpoint this
                    # stop exists to land.
                    with guard.shield():
                        if self.ckpt.latest_step() != step:
                            self.ckpt.save(
                                step, self.state,
                                extra={"epoch": epoch - 1,
                                       "interrupted_epoch": epoch,
                                       # epoch position in steps, counting
                                       # what an earlier partial run of this
                                       # same epoch already consumed
                                       "epoch_steps_done":
                                           sb * self._echo
                                           + (step - estep0),
                                       # the batch order's identity; a
                                       # change in any of these makes the
                                       # offset stale -> _resume falls back
                                       # to replay.  The LIVE echo: a
                                       # governor-armed factor differs from
                                       # the resumed config's base, so the
                                       # resume safely replays the epoch.
                                       "num_shards": jax.process_count(),
                                       "echo": self._echo,
                                       "train_batch": cfg.data.train_batch,
                                       "seed": cfg.seed,
                                       "preempted": True})
                        self.ckpt.wait()
                    if self.is_main:
                        self.writer.scalars(
                            {"preempted_at_epoch": epoch}, step)
                    break
                history["train_loss"].append(epoch_loss)
                if self._governor is not None:
                    # the recompile-safe seam: device-path flip / echo
                    # arm / hysteresis disarm land BETWEEN epochs, before
                    # validation (val books its own goodput bucket, so it
                    # never pollutes the stall window either way)
                    self._governor.epoch_boundary(epoch=epoch, step=step)
                if self._rollback_breaker is not None:
                    # a cleanly completed epoch closes the rollback
                    # breaker: the budget bounds CONSECUTIVE rollbacks,
                    # not lifetime ones (config.sentinel.max_rollbacks)
                    self._rollback_breaker.call(lambda: None)
                extra = {"epoch": epoch}
                if cfg.eval_every and (epoch + 1) % cfg.eval_every == 0:
                    if cfg.val_overlap:
                        # validate concurrently with the NEXT train epoch
                        # (joined after it); the last epoch's launch is
                        # joined right after the loop
                        self._launch_overlapped_val(epoch, step)
                    else:
                        metrics, first = self._eval_metrics(self.state,
                                                            epoch)
                        self._finish_val(metrics, first, epoch, step,
                                         self.state, history)
                elif cfg.checkpoint.snapshot_every and \
                        (epoch + 1) % cfg.checkpoint.snapshot_every == 0:
                    self.ckpt.save(step, self.state, extra=extra)
                if self.is_main:
                    self.writer.scalars(
                        {"epoch": epoch,
                         "epoch_total_seconds": time.perf_counter() - t0},
                        step)
                epoch += 1
            # Flush inside the stack (and shielded): the graceful-stop
            # handlers must stay installed, and escalation deferred, until
            # the last async save has committed.
            with guard.shield() if guard is not None else contextlib.nullcontext():
                # the final epoch's overlapped validation has no train
                # epoch to hide behind; land it before the last save wait
                self._join_overlapped_val(history)
                self.ckpt.wait()
            # after the last save has landed, so its wait is in the books
            self._report_goodput(history)
            # recovery block (the bench/report schema, train/sentinel.py):
            # populated when the sentinel ran, None when it was off — the
            # key itself is always present
            if self._sentinel is not None:
                from ..utils.profiling import percentile
                from .sentinel import make_recovery_block
                history["recovery"] = make_recovery_block(
                    rollbacks=self.sentinel_rollbacks,
                    quarantined_steps=self.sentinel_quarantined_steps,
                    # supervisor_restarts stays None here — a supervisor
                    # concept; dptpu-supervise folds its own count into
                    # the summaries it aggregates
                    recovery_p50_s=(
                        round(percentile(self._rollback_seconds, 50), 3)
                        if self._rollback_seconds else None))
            else:
                history["recovery"] = None
            # feed block (data/governor.py): the governor's summary —
            # windowed stall fraction, effective echo, the action tally.
            # Key always present; None when the governor is off (the
            # recovery-block convention).
            history["feed"] = (self._governor.summary_block()
                               if self._governor is not None else None)
            if self.is_main:
                # fit_summary.json: the one file a SUPERVISOR (or operator)
                # can classify an exited run by without Orbax — written
                # atomically so a crash mid-write reads as "no summary"
                # (= crashed), never as a torn verdict
                atomic_write_json(
                    os.path.join(self.run_dir, "fit_summary.json"),
                    {"preempted": bool(history.get("preempted")),
                     "completed": not history.get("preempted"),
                     "final_step": int(self.state.step),
                     "start_epoch": self.start_epoch,
                     "epochs": cfg.epochs,
                     "epochs_recorded": len(history["train_loss"]),
                     "recovery": history["recovery"],
                     "feed": history["feed"],
                     # the resolved plan this run actually trained under
                     # (under strategy=auto, the ladder's pick)
                     "plan": self.plan.block()})
            gp = history.get("goodput") or {}
            events_lib.emit(
                "trainer", "fit_end", step=int(self.state.step),
                payload={"preempted": bool(history.get("preempted")),
                         "epochs_recorded": len(history["train_loss"]),
                         "rollbacks": self.sentinel_rollbacks,
                         # the goodput breakdown rides the closing anchor
                         # so the doctor's wall-clock sinks need no
                         # writer-specific metrics file
                         "goodput": {
                             "total_s": gp.get("total_s"),
                             "buckets": gp.get("buckets"),
                             "productive_frac": gp.get("goodput")}})
            self.writer.flush()
        return history

    def close(self) -> None:
        if self._trace is not None:
            self._trace.close()
        self.ckpt.close()
        self.writer.close()
        # restores any outer event log (a flywheel's, when the fit ran
        # in-process) as the current sink
        events_lib.release(self._events)
        self._events = None
