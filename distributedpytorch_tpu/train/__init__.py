"""Training subsystem: config, optimizer, checkpointing, evaluation, trainer."""

from .checkpoint import (CheckpointManager, latest_checkpoint_dir,
                         next_run_dir)
from .config import (
    CheckpointConfig,
    Config,
    DataConfig,
    MeshConfig,
    ModelConfig,
    OptimConfig,
    SentinelConfig,
    TrainConfig,
    apply_overrides,
    flatten,
    from_json,
    to_json,
)
from .evaluate import batch_debug_asserts, evaluate, evaluate_semantic
from .logging import (
    CometWriter,
    ConsoleWriter,
    JsonlWriter,
    MetricWriter,
    MultiWriter,
    TensorBoardWriter,
    make_val_panels,
    make_writer,
)
from .optim import make_optimizer, make_param_labeler, make_schedule
from .precision import Policy, precision_block, precision_policy
from .preemption import PreemptionGuard
from .sentinel import StepSentinel, recovery_block
from .trainer import Trainer

__all__ = [
    "CheckpointConfig",
    "CheckpointManager",
    "Config",
    "CometWriter",
    "ConsoleWriter",
    "DataConfig",
    "JsonlWriter",
    "MeshConfig",
    "MetricWriter",
    "ModelConfig",
    "MultiWriter",
    "OptimConfig",
    "Policy",
    "PreemptionGuard",
    "SentinelConfig",
    "StepSentinel",
    "TensorBoardWriter",
    "TrainConfig",
    "Trainer",
    "precision_block",
    "precision_policy",
    "recovery_block",
    "apply_overrides",
    "batch_debug_asserts",
    "evaluate",
    "evaluate_semantic",
    "flatten",
    "from_json",
    "make_optimizer",
    "make_param_labeler",
    "make_schedule",
    "make_val_panels",
    "make_writer",
    "latest_checkpoint_dir",
    "next_run_dir",
    "to_json",
]
