"""Continuous (flywheel) training: session logs -> short fits -> canaried swap.

The production loop's last edge.  ``dptpu-serve --session-log`` appends
every accepted interaction to a crash-safe packed log (serve/session_log);
this module watches that log and closes the loop:

1. **Watch.**  ``poll()`` reads the log's committed ``meta.json`` (stdlib
   json — the supervisor never touches jax before deciding there is work)
   and does nothing until ``min_new_records`` NEW examples have landed
   since the last consumed high-water mark.
2. **Verify + quarantine.**  A ``verify_session_log`` sweep runs first;
   torn records go straight into the persistent quarantine
   (``flywheel_state.json``) and are excluded from every future fit.
3. **Fit, guarded.**  A short incremental fit replays the log through the
   training pipeline (``data.session_log`` + ``data.session_only``, so
   replayed batches are bit-identical to what was served) with the step
   sentinel armed.  Any record the sentinel quarantines
   (``quarantine.jsonl`` names exact session record ids — packed seek,
   no archaeology) joins the persistent quarantine.
4. **Hold or commit.**  A fit that ROLLED BACK never swaps — whatever
   poisoned it is now quarantined, and the next cycle refits clean.  A
   clean fit must beat the last committed val metric by
   ``min_improvement``; otherwise it is held.
5. **Canary, then promote.**  On commit with a live service, the new
   params enter :meth:`InferenceService.swap` as a canary
   (``promote_after=promote_probes``); the flywheel drives probe clicks
   replayed from the log's own crops.  Clean probes auto-promote; a
   single non-finite output rolls back instantly and the fleet keeps
   serving the old generation — the session never sees the bad params.

``dptpu-flywheel`` runs this loop standalone (committing checkpoints for
an out-of-process serving fleet to pick up); compose it with the crash
supervisor as ``dptpu-supervise -- dptpu-flywheel ...`` for the
production posture.  ``FLYWHEEL_KEYS`` / :func:`flywheel_block` mirror
sentinel's recovery-block convention so bench records always carry the
block (null when the flywheel is off).
"""

from __future__ import annotations

import argparse
import json
import os
import time

#: the flywheel block's schema — bench records carry exactly these keys
#: (all-null when continuous mode is off), mirroring sentinel.RECOVERY_KEYS
FLYWHEEL_KEYS = ("examples_logged", "fits_run", "swaps_promoted",
                 "swaps_rolled_back", "fits_held", "quarantined_records")


def make_flywheel_block(*, examples_logged: int, fits_run: int,
                        swaps_promoted: int, swaps_rolled_back: int,
                        fits_held: int, quarantined_records: int) -> dict:
    """Construct a populated flywheel block — the ONE place the schema's
    keys are written (:meth:`Flywheel.report` builds through this;
    :func:`flywheel_block` re-projects it for bench records, so the two
    surfaces cannot drift)."""
    out = dict.fromkeys(FLYWHEEL_KEYS)
    out.update(examples_logged=examples_logged, fits_run=fits_run,
               swaps_promoted=swaps_promoted,
               swaps_rolled_back=swaps_rolled_back, fits_held=fits_held,
               quarantined_records=quarantined_records)
    return out


def flywheel_block(report: dict | None = None) -> dict:
    """The ``flywheel`` block for bench records: populated from a
    :meth:`Flywheel.report` when one exists, all-null otherwise (the
    keys are ALWAYS present — regression tooling filters on them)."""
    out = {k: None for k in FLYWHEEL_KEYS}
    if report:
        out.update({k: report.get(k) for k in FLYWHEEL_KEYS})
    return out


def _read_meta(log_dir: str) -> dict | None:
    """The log's committed meta (None when absent/unreadable) — readers
    trust ONLY meta counts, so an in-progress append is invisible here."""
    try:
        with open(os.path.join(log_dir, "meta.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _read_fit_quarantine(run_dir: str) -> list[int]:
    """Session record ids the sentinel quarantined during a fit: the
    trainer's ``quarantine.jsonl`` names each batch's packed records as
    ``{"record": <raw index>, ...}`` — exactly the ids
    ``data.session_quarantine`` takes."""
    ids: set[int] = set()
    try:
        with open(os.path.join(run_dir, "quarantine.jsonl")) as f:
            for line in f:
                if not line.strip():
                    continue
                rec = json.loads(line)
                for batch in rec.get("records") or []:
                    for r in batch.get("records") or []:
                        if r.get("record") is not None:
                            ids.add(int(r["record"]))
    except (OSError, ValueError):
        pass
    return sorted(ids)


def _default_fit_runner(cfg) -> dict:
    """One in-process guarded fit; returns the evidence poll() decides
    on.  Injectable (``fit_runner=``) so tests drive the policy without
    paying for real training."""
    from .trainer import Trainer

    tr = Trainer(cfg)
    try:
        history = tr.fit()
    finally:
        tr.close()
    vals = [v.get("jaccard") for v in history.get("val") or []
            if v.get("jaccard") is not None]
    rec = history.get("recovery") or {}
    return {"run_dir": tr.run_dir,
            "metric": max(vals) if vals else None,
            "rollbacks": int(rec.get("rollbacks") or 0),
            "quarantined": _read_fit_quarantine(tr.run_dir)}


class Flywheel:
    """The supervisor driving continuous mode (see the module docstring
    for the loop).  ``service=None`` is the standalone posture: commits
    are checkpoints on disk, not hot swaps."""

    def __init__(self, log_dir: str, base_cfg, work_dir: str,
                 service=None, *, min_new_records: int = 8,
                 fit_epochs: int = 1, min_improvement: float = 0.0,
                 canary_fraction: float = 1.0, promote_probes: int = 3,
                 fit_runner=None):
        self.log_dir = log_dir
        self.base_cfg = base_cfg
        self.work_dir = work_dir
        self.service = service
        self.min_new_records = int(min_new_records)
        self.fit_epochs = int(fit_epochs)
        self.min_improvement = float(min_improvement)
        self.canary_fraction = float(canary_fraction)
        self.promote_probes = int(promote_probes)
        self._fit_runner = fit_runner or _default_fit_runner
        os.makedirs(work_dir, exist_ok=True)
        self._state_path = os.path.join(work_dir, "flywheel_state.json")
        self._ledger_path = os.path.join(work_dir, "flywheel.jsonl")
        # durable state: survives supervisor restarts (dptpu-supervise
        # respawning dptpu-flywheel resumes the same high-water mark)
        self._state = {"consumed_records": 0, "quarantine": [],
                       "best_metric": None, "committed_run": None,
                       "cycles": 0, "fits_run": 0, "fits_held": 0,
                       "swaps_promoted": 0, "swaps_rolled_back": 0,
                       "examples_logged": 0}
        try:
            with open(self._state_path) as f:
                self._state.update(json.load(f))
        except (OSError, ValueError):
            pass

    # ------------------------------------------------------------ state

    def _save_state(self) -> None:
        from .checkpoint import atomic_write_json

        atomic_write_json(self._state_path, self._state)

    def _record(self, entry: dict) -> None:
        self._state["cycles"] += 1
        self._save_state()
        with open(self._ledger_path, "a") as f:
            f.write(json.dumps(entry) + "\n")
        # flight recorder mirror (telemetry/events.py): flywheel.jsonl
        # above stays the authoritative cycle ledger
        from ..telemetry import events as events_lib

        events_lib.emit("flywheel", entry.get("action") or "cycle",
                        payload=dict(entry,
                                     cycle=int(self._state["cycles"])))

    @property
    def quarantine(self) -> list[int]:
        return list(self._state["quarantine"])

    def _quarantine_add(self, ids) -> list[int]:
        fresh = sorted(set(int(i) for i in ids)
                       - set(self._state["quarantine"]))
        if fresh:
            self._state["quarantine"] = sorted(
                set(self._state["quarantine"]) | set(fresh))
        return fresh

    def report(self) -> dict:
        """The populated flywheel block (bench's ``flywheel`` schema)."""
        s = self._state
        return make_flywheel_block(
            examples_logged=int(s["examples_logged"]),
            fits_run=int(s["fits_run"]),
            swaps_promoted=int(s["swaps_promoted"]),
            swaps_rolled_back=int(s["swaps_rolled_back"]),
            fits_held=int(s["fits_held"]),
            quarantined_records=len(s["quarantine"]))

    # ------------------------------------------------------------- cycle

    def poll(self) -> dict:
        """One cycle: watch -> verify -> fit -> hold/commit -> canary.
        Returns the cycle record (also appended to ``flywheel.jsonl``)."""
        meta = _read_meta(self.log_dir)
        if meta is None:
            entry = {"action": "idle", "reason": "no_log"}
            self._record(entry)
            return entry
        n = int(meta.get("n_records", 0))
        self._state["examples_logged"] = n
        new = n - int(self._state["consumed_records"])
        if new < self.min_new_records:
            entry = {"action": "idle", "reason": "insufficient_new_records",
                     "new_records": new, "need": self.min_new_records}
            self._record(entry)
            return entry

        # verify sweep: torn records quarantine BEFORE the fit ever
        # touches them (same packed-idiom crc gate dptpu-pack --verify runs)
        from ..data.sessions import verify_session_log

        torn = self._quarantine_add(verify_session_log(self.log_dir))

        entry: dict = {"new_records": new, "torn_quarantined": torn}
        fit = self._run_fit()
        # the data is consumed either way: a held fit's poison is now
        # quarantined, so refitting the SAME window again cannot help
        self._state["consumed_records"] = n
        entry["fit"] = {k: fit.get(k) for k in
                        ("run_dir", "metric", "rollbacks", "error")}
        if fit.get("error"):
            self._state["fits_held"] += 1
            entry.update(action="held", reason="fit_failed")
            self._record(entry)
            return entry
        self._state["fits_run"] += 1
        fresh = self._quarantine_add(fit.get("quarantined") or [])
        entry["sentinel_quarantined"] = fresh

        # POLICY: a fit the sentinel rolled back NEVER swaps — committed
        # val metrics from a poisoned run are not evidence
        if int(fit.get("rollbacks") or 0) > 0:
            self._state["fits_held"] += 1
            entry.update(action="held", reason="sentinel_rollback",
                         rollbacks=int(fit["rollbacks"]))
            self._record(entry)
            return entry

        metric, best = fit.get("metric"), self._state["best_metric"]
        if metric is None:
            self._state["fits_held"] += 1
            entry.update(action="held", reason="no_val_metric")
            self._record(entry)
            return entry
        if best is not None and metric < best + self.min_improvement:
            self._state["fits_held"] += 1
            entry.update(action="held", reason="no_improvement",
                         metric=metric, best_metric=best)
            self._record(entry)
            return entry

        outcome = "committed"
        if self.service is not None:
            outcome = self._canary_swap(fit["run_dir"])
        if outcome == "rolled_back":
            # the canary refuted the val metric — do not commit it
            self._state["swaps_rolled_back"] += 1
            entry.update(action="rolled_back", metric=metric,
                         run_dir=fit["run_dir"])
            self._record(entry)
            return entry
        self._state["best_metric"] = metric
        self._state["committed_run"] = fit["run_dir"]
        if outcome == "promoted":
            self._state["swaps_promoted"] += 1
        entry.update(action=outcome, metric=metric,
                     run_dir=fit["run_dir"])
        self._record(entry)
        return entry

    # -------------------------------------------------------------- fit

    def _fit_cfg(self, tag: str):
        from .config import apply_overrides

        return apply_overrides(self.base_cfg, {
            "data.session_log": self.log_dir,
            "data.session_only": True,
            "data.session_quarantine": list(self._state["quarantine"]),
            # guard training: the sentinel is what makes a poisoned log
            # a quarantine event instead of a poisoned checkpoint
            "sentinel.enabled": True,
            "epochs": self.fit_epochs,
            # the improvement gate needs the last epoch's val metric
            "eval_every": self.fit_epochs,
            "work_dir": os.path.join(self.work_dir, "fits", tag),
        })

    def _run_fit(self) -> dict:
        tag = f"fit_{self._state['cycles']:04d}"
        try:
            return self._fit_runner(self._fit_cfg(tag))
        except Exception as e:  # noqa: BLE001 — held, never a crashed loop
            return {"error": f"{type(e).__name__}: {e}"}

    # ------------------------------------------------------------ canary

    def _probe_inputs(self, k: int):
        """Probe click k, replayed from the log's own crops: the crop is
        the image, the clicks are the logged points in crop space — real
        traffic's distribution, no synthetic fixtures."""
        import numpy as np

        from ..data.guidance import scale_points_to_crop
        from ..data.sessions import SessionLogDataset

        ds = SessionLogDataset(self.log_dir,
                               quarantine=self._state["quarantine"])
        if len(ds) == 0:
            return None
        rec = ds.seek(k % len(ds), read=True)
        image = np.clip(rec["image"], 0.0, 255.0).astype(np.uint8)
        pts = scale_points_to_crop(rec["points"], rec["bbox"],
                                   image.shape[:2])
        return image, pts

    def _canary_swap(self, run_dir: str) -> str:
        """Swap ``run_dir``'s best checkpoint in as a canary, drive the
        probes, and report ``promoted`` | ``rolled_back``."""
        import numpy as np

        from ..predict import load_run
        from ..serve.swap import load_swap_predictor

        svc = self.service
        _cfg, _model, state = load_run(run_dir)
        pred = load_swap_predictor(svc.predictor, state.params,
                                   state.batch_stats)
        before = svc.health()["swap"]["swaps"]
        gen = svc.swap(pred, label=os.path.basename(run_dir.rstrip("/")),
                       canary_fraction=self.canary_fraction,
                       promote_after=self.promote_probes)
        for k in range(self.promote_probes):
            probe = self._probe_inputs(k)
            if probe is None:
                break
            image, pts = probe
            try:
                svc.predict(image, pts, timeout=120,
                            session_id=f"flywheel-probe-{gen}-{k}")
            except Exception:  # noqa: BLE001 — the pool's observe decides
                pass
            if svc.health()["swap"]["canary"] is None:
                break  # decided early (rollback, or auto-promote)
        after = svc.health()["swap"]
        if after["swaps"]["rolled_back"] > before["rolled_back"]:
            return "rolled_back"
        if after["canary"] is not None:
            # probes ran clean but fell short of promote_after (short
            # log) — the evidence is all ok, finish the promotion
            svc.promote()
        return "promoted"


# ---------------------------------------------------------------- CLI

def main(argv=None) -> int:
    """``dptpu-flywheel``: watch a session log, run guarded incremental
    fits, commit improvements.  Standalone it commits checkpoints (the
    serving fleet swaps them in on its own cadence); under
    ``dptpu-supervise -- dptpu-flywheel ...`` it is crash-restartable
    (state resumes from ``flywheel_state.json``)."""
    ap = argparse.ArgumentParser(
        prog="dptpu-flywheel",
        description="continuous training from serve session logs")
    ap.add_argument("--log", required=True,
                    help="session log directory (dptpu-serve --session-log)")
    ap.add_argument("--work-dir", required=True,
                    help="flywheel state + fit run dirs")
    ap.add_argument("--config", default=None,
                    help="base training config JSON (default: defaults)")
    ap.add_argument("--override", action="append", default=[],
                    metavar="KEY=VALUE", help="dotted config overrides")
    ap.add_argument("--interval", type=float, default=30.0,
                    help="seconds between polls")
    ap.add_argument("--max-cycles", type=int, default=0,
                    help="stop after N polls (0 = forever)")
    ap.add_argument("--min-new-records", type=int, default=8)
    ap.add_argument("--fit-epochs", type=int, default=1)
    ap.add_argument("--min-improvement", type=float, default=0.0)
    args = ap.parse_args(argv)

    from .config import Config, apply_overrides, from_json

    cfg = from_json(args.config) if args.config else Config()
    if args.override:
        cfg = apply_overrides(cfg, list(args.override))
    # flight recorder: the flywheel's cycle events (and the pool's swap
    # events it drives) land under the work dir; each in-process fit
    # pushes its own run_<N> log for the fit's duration
    from ..telemetry import events as events_lib

    events_lib.configure(args.work_dir)
    fw = Flywheel(args.log, cfg, args.work_dir,
                  min_new_records=args.min_new_records,
                  fit_epochs=args.fit_epochs,
                  min_improvement=args.min_improvement)
    cycle = 0
    while True:
        entry = fw.poll()
        print(json.dumps({"cycle": cycle, **entry}), flush=True)
        cycle += 1
        if args.max_cycles and cycle >= args.max_cycles:
            break
        time.sleep(args.interval)
    print(json.dumps({"flywheel": fw.report()}), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
