"""Failure detection / graceful preemption.

SURVEY.md §5.3: the reference had no failure handling at all — no
try/except around training, no signal handling; a mid-run SIGTERM (or a
cluster preemption) lost the optimizer state entirely because it was never
checkpointed (reference train_pascal.py:301-304 saved bare ``state_dict``
only).  Here a termination signal lands one final full-state checkpoint
(params, optimizer, RNG, epoch, best-metric) and the next run resumes
exactly where it stopped.

TPU-shaped detail: under multi-host SPMD every process must leave the train
loop at the SAME step, or the processes still inside it hang on collectives
that the departed ones never join.  The stop decision is therefore taken by
consensus — each process contributes its local signal flag through a tiny
allgather at a fixed step cadence, and all processes act on the OR of the
flags.  (A signal delivered to one host stops the whole job cleanly.)
"""

from __future__ import annotations

import contextlib
import signal
import threading


class PreemptionGuard:
    """Installs termination-signal handlers; exposes a consensus stop flag.

    Usage::

        with PreemptionGuard() as guard:
            for step, batch in enumerate(loader):
                ...
                if guard.should_stop(step):
                    break   # every process breaks at the same step
        if guard.triggered:
            ckpt.save(...)

    ``trip()`` sets the flag programmatically — the hook for tests and for
    higher-level schedulers (e.g. a time-budget watchdog) to request the
    same graceful stop a signal would.
    """

    def __init__(self, signals: tuple[int, ...] = (signal.SIGTERM,
                                                   signal.SIGINT),
                 check_every: int = 32):
        self._signals = tuple(signals)
        self._prev: dict[int, object] = {}
        self._flag = threading.Event()
        self._shield_depth = 0
        self.check_every = max(1, int(check_every))
        #: termination signals delivered to this process (handler-side
        #: count; mirrored into the telemetry registry by should_stop)
        self.signals_received = 0
        self._signals_reported = 0

    # ------------------------------------------------------------ handlers
    def __enter__(self) -> "PreemptionGuard":
        for s in self._signals:
            try:
                self._prev[s] = signal.signal(s, self._handle)
            except ValueError:
                # signal.signal only works in the main thread; a guard used
                # from a worker thread still functions via trip().
                pass
        return self

    def __exit__(self, *exc) -> bool:
        for s, prev in self._prev.items():
            # getsignal() reports None for handlers installed from C code;
            # the closest restorable disposition is the default one.
            signal.signal(s, signal.SIG_DFL if prev is None else prev)
        self._prev.clear()
        return False

    def _handle(self, signum, frame) -> None:
        # plain attribute increment only: a handler interrupting arbitrary
        # bytecode must never touch a lock (the registry's counters do);
        # should_stop() mirrors this into telemetry from a normal context
        self.signals_received += 1
        if self._flag.is_set():
            if self._shield_depth > 0:
                # Inside a shield() block (the final checkpoint flush):
                # stay graceful — dying here would lose the very write the
                # graceful stop exists to land.
                return
            # Second delivery: the user (or scheduler) means it.  Restore the
            # previous disposition and re-deliver, so a double Ctrl-C raises
            # KeyboardInterrupt as usual and a second SIGTERM terminates —
            # the run is never uninterruptible.
            prev = self._prev.pop(signum, signal.SIG_DFL)
            if prev is None:  # prior handler came from C code; see __exit__
                prev = signal.SIG_DFL
            if callable(prev):
                signal.signal(signum, prev)
                prev(signum, frame)
            else:
                signal.signal(signum, prev)
                signal.raise_signal(signum)
            return
        self._flag.set()

    @contextlib.contextmanager
    def shield(self):
        """Critical section: while active, further signal deliveries never
        escalate — they are absorbed so an in-flight final checkpoint write
        completes.  Use around the post-stop flush only; keep it short."""
        self._shield_depth += 1
        try:
            yield
        finally:
            self._shield_depth -= 1

    # ---------------------------------------------------------------- state
    def trip(self) -> None:
        """Request a graceful stop (same effect as receiving a signal)."""
        self._flag.set()

    @property
    def triggered(self) -> bool:
        """This process's local flag (signal received or ``trip()`` called)."""
        return self._flag.is_set()

    def should_stop(self, step: int | None = None) -> bool:
        """Cluster-wide stop decision, evaluated every ``check_every`` steps.

        With ``step`` given, non-cadence steps return False without any
        communication; cadence steps reach consensus.  With ``step=None``
        (epoch boundaries), consensus is always evaluated.  All processes
        must call this at the same points — that is what makes the returned
        decision identical everywhere.
        """
        if step is not None and step % self.check_every != 0:
            return False
        self._publish_telemetry()
        import jax

        if jax.process_count() == 1:
            return self.triggered
        import numpy as np
        from jax.experimental import multihost_utils
        from ..telemetry import span

        # the consensus allgather is a host sync on the step-loop cadence:
        # named in the device trace so its cost is attributable, not folded
        # into whatever op happens to be adjacent
        with span("preempt/consensus"):
            flags = multihost_utils.process_allgather(
                np.asarray(self.triggered, np.int32))
        return bool(np.any(flags))

    def _publish_telemetry(self) -> None:
        """Mirror handler-side signal counts into the registry (normal
        thread context — the handler itself must stay lock-free)."""
        from ..telemetry import get_registry
        from ..telemetry.registry import is_enabled

        if not is_enabled():
            return

        seen = self.signals_received
        if seen > self._signals_reported:
            get_registry().counter(
                "preemption_signals_total",
                "termination signals delivered to this process"
            ).inc(seen - self._signals_reported)
            self._signals_reported = seen
            # flight recorder: first sight of the signal(s), from normal
            # thread context (the handler itself stays lock-free) — the
            # preempt -> resume episode's opening anchor
            from ..telemetry import events as events_lib

            events_lib.emit("preemption", "preempt",
                            payload={"signals_received": seen})
        get_registry().gauge(
            "preemption_stop_pending",
            "1 while a graceful stop is requested but not yet taken"
        ).set(float(self.triggered))
