"""Experiment configuration.

The reference had no config system: hyperparameters were module-level
constants stuffed into an ``OrderedDict p`` (reference train_pascal.py:44-82),
dataset roots hid in a machine-specific ``mypath`` module (pascal.py:13,33),
checkpoint filenames were hardcoded (train_pascal.py:103,304) and a Comet API
key was committed in source (train_pascal.py:41).  Here the whole experiment
is one nested dataclass tree, JSON-serializable both ways, with dotted-path
CLI overrides — and no secrets in code (anything secret comes from the
environment).

Defaults reproduce the reference's hyperparameter point
(train_pascal.py:50-71): 100 epochs, train batch 16, val batch 1, 4-channel
512² input, SGD lr=5e-8 / momentum 0.9 / wd 5e-4, constant LR (the poly
scheduler existed but was commented out, train_pascal.py:34,164 — it is a
first-class option here), eval every epoch, threshold sweep {0.3, 0.5, 0.8}.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any


@dataclass
class DataConfig:
    source: str = "fs"                  # fs | packed: where samples come
                                        # from.  'fs' decodes JPEG/PNG
                                        # per sample off the dataset
                                        # tree; 'packed' memory-maps the
                                        # pre-decoded, checksummed
                                        # records dptpu-pack wrote
                                        # (data/packed.py — no per-
                                        # sample decode, O(1) seek, the
                                        # governor's rung 0).  Samples
                                        # are bit-identical either way.
    pack_path: str = ""                 # source=packed: the pack ROOT
                                        # dptpu-pack --out wrote; the
                                        # trainer opens
                                        # <pack_path>/<dataset>-<task>-
                                        # <splits> per source
    pack_quarantine: tuple[int, ...] = ()
                                        # source=packed: RAW record
                                        # indices dropped from the TRAIN
                                        # pack's epoch (the recovery
                                        # move for records `dptpu-pack
                                        # --verify` flagged as torn)
    session_log: str = ""               # flywheel: a serve session-log
                                        # directory (serve/session_log)
                                        # mixed into training via
                                        # data/sessions.SessionLogDataset
    session_only: bool = False          # flywheel: train on the session
                                        # log ALONE in replay mode (the
                                        # exact serving inputs, no
                                        # augmentation) — the continuous
                                        # mode's incremental fits
    session_quarantine: tuple[int, ...] = ()
                                        # RAW session record ids dropped
                                        # from the log's epoch (poisoned
                                        # examples the sentinel ledger /
                                        # dptpu-pack --verify named)
    root: str = ""                      # dataset root (was: the mypath module)
    sbd_root: str = ""                  # set: merge SBD into training via
                                        # CombinedDataset, excluding the
                                        # VOC-val overlap.  Instance task:
                                        # the reference's use_sbd recipe
                                        # (train_pascal.py:150-154).
                                        # Semantic task: the standard
                                        # "train_aug" recipe (~10k extra
                                        # images for the DeepLab configs).
    fake: bool = False                  # synth fixture instead of real VOC
    download: bool = False              # fetch + MD5-verify VOC if absent
    train_split: str = "train"
    val_split: str = "val"
    area_thres: int = 500               # instance area filter (pascal.py:36)
    crop_size: tuple[int, int] = (512, 512)
    relax: int = 50                     # bbox relax px (train_pascal.py:127)
    zero_pad: bool = True
    rots: tuple[float, float] = (-20.0, 20.0)
    scales: tuple[float, float] = (0.75, 1.25)
    guidance: str = "nellipse_gaussians"
    guidance_alpha: float = 0.6         # z1 + alpha*z2 (custom_transforms.py:45)
    train_batch: int = 16
    val_batch: int = 1
    loader: str = "threads"             # threads | grain (train loader;
                                        # eval always uses threads, which
                                        # wrap-pads so every sample scores)
    num_workers: int = 2                # loader threads (train_pascal.py:161)
    prefetch: int = 2                   # host-side decoded-batch buffer
    device_prefetch: int = 2            # batches placed on-device ahead
    device_augment: bool = False        # flip on-device (fused into step)
    device_augment_geom: bool = False   # rotation/scale on-device too (the
                                        # device form warps the fixed crop,
                                        # not the pre-crop full image)
    device_guidance: bool = False       # synthesize the guidance channel
                                        # on-device from crop_gt (the most
                                        # expensive host transform; instance
                                        # task, all five guidance families)
    fused_crop_resize: bool = False     # crop+resize as ONE native-kernel
                                        # pass (no materialized crop).
                                        # Wins on the cv2-free native
                                        # imaging backend (+26%); with cv2
                                        # present its SIMD resize is still
                                        # faster — leave off (BASELINE.md)
    prepared_cache: str = ""            # dir for the prepared-sample disk
                                        # cache (FFCV-style): the train
                                        # pipeline's deterministic front
                                        # (instance: decode→crop→resize;
                                        # semantic: decode→resize) is
                                        # computed once per sample and
                                        # mmap-read ever after; flip/rotate/
                                        # guidance stay per-epoch random,
                                        # post-crop.  Keyed by a config
                                        # fingerprint — changing crop knobs
                                        # rebuilds.  ~0.75 MB/sample @512².
    uint8_transfer: bool = False        # ship train batches to the device
                                        # as uint8 (4x fewer H2D bytes and
                                        # host memcpys; the compiled step
                                        # dequantizes on device).  Requires
                                        # prepared_cache (whose arrays are
                                        # uint8-exact by construction).
    packbits_masks: bool = False        # ship the binary train mask at
                                        # 1 bit/pixel (np.packbits on the
                                        # wire, fused bit-ops unpack inside
                                        # the step) — ~22% fewer wire bytes
                                        # on top of uint8_transfer; pays
                                        # when H2D placement bounds e2e
                                        # (BASELINE.md round-3 breakdown).
                                        # Instance task + uint8_transfer
                                        # only.
    coalesce_wire: bool = False         # pack the train batch's device-
                                        # bound uint8 leaves into ONE
                                        # (B, bytes) buffer per batch: one
                                        # H2D transfer instead of one per
                                        # key, so per-RPC link latency is
                                        # paid once (tunneled/remoted
                                        # devices flap 5→160 ms per RPC on
                                        # minute timescales — BASELINE.md
                                        # round-4 wire study; on local PCIe
                                        # this is neutral).  The compiled
                                        # step slices the leaves back out
                                        # (static offsets, fused by XLA).
                                        # Requires uint8_transfer; composes
                                        # with packbits_masks (the packed
                                        # row rides the same buffer).
    val_prepared: bool = True           # when prepared_cache is set, serve
                                        # the crop-res VAL protocol from a
                                        # prepared cache too (eval is fully
                                        # deterministic, so the WHOLE
                                        # per-epoch decode→crop→resize(→
                                        # guidance) front caches; instance
                                        # mode also caches full-res gt/void
                                        # as packed bits for the paste-back
                                        # metric).  With uint8_transfer the
                                        # val wire ships uint8 as well.
                                        # SEMANTICS: the cached val image
                                        # is uint8-rounded (same <=0.5/255
                                        # perturbation the train cache
                                        # makes; masks/bboxes bit-exact),
                                        # so val metrics move ~1e-3 vs the
                                        # plain path — set false for
                                        # bit-exact protocol comparisons.
                                        # The semantic full-res protocol
                                        # (eval_full_res) composes: its
                                        # native-res gt caches as padded
                                        # uint8 id rows (gt_full).
    val_max_im_size: tuple[int, int] = (512, 512)
                                        # eval-cache budget for native-res
                                        # mask rows (instance packed
                                        # gt/void bits AND the semantic
                                        # eval_full_res gt_full ids):
                                        # raise for datasets with images
                                        # larger than VOC's 500px sides
                                        # (changing it rebuilds the val
                                        # cache)
    decode_cache: int = 0               # decode-once LRU over this many
                                        # images (FFCV-style; instance mode
                                        # revisits an image once per object
                                        # per epoch).  ~0.7 MB/image host
                                        # RAM; 0 = off.
    steps_per_dispatch: int = 1         # >1: scan this many optimizer
                                        # steps inside ONE compiled call
                                        # (each over its own batch) —
                                        # per-step dispatch overhead drops
                                        # K-fold, the lever when the host's
                                        # dispatch path (not data prep) is
                                        # the bound.  Epoch-tail batches
                                        # run through the single-step
                                        # program.  Mutually exclusive with
                                        # echo>1.
    echo: int = 1                       # data echoing (Choi et al. 2019,
                                        # arXiv:1907.05550): step each loaded
                                        # batch this many times — recovers
                                        # throughput when the host input
                                        # pipeline, not the chip, is the
                                        # bottleneck.  With device_augment
                                        # each echo draws fresh augmentation
                                        # randomness.
    governor: str = "observe"           # input-feed governor
                                        # (data/governor.py): off |
                                        # observe (default: the ladder's
                                        # decisions are logged to
                                        # run_dir/governor.jsonl and the
                                        # registry, nothing is actuated)
                                        # | auto (decisions applied: hot
                                        # prefetch resize, epoch-boundary
                                        # device-path flip, auto-armed
                                        # echo with hysteresis disarm).
                                        # Multi-host auto routes every
                                        # ladder input through the
                                        # consensus primitive (stall =
                                        # max across hosts, parallel/
                                        # consensus.py), so all hosts
                                        # take identical decisions.
    governor_target: float = 0.1        # windowed input-stall fraction
                                        # the governor keeps the feed
                                        # under (and the bench feed
                                        # gate's threshold)
    governor_window: int = 16           # stall-window size in ticks
                                        # (log-cadence samples); smaller
                                        # reacts faster, larger resists
                                        # transients
    max_echo: int = 4                   # clamp for the governor's auto-
                                        # armed echo factor
                                        # (ceil(1/(1-stall)) capped here;
                                        # a manually-set data.echo is
                                        # never clamped)


@dataclass
class ModelConfig:
    name: str = "danet"                 # danet | deeplabv3 | deeplabv3plus
                                        # | fcn | pspnet | encnet
    nclass: int = 1                     # binary/sigmoid head (DANet(1, ...))
    backbone: str = "resnet101"
    output_stride: int | None = None
    in_channels: int = 4                # RGB + guidance heatmap
    remat_policy: str = ""              # with model.remat: a jax.
                                        # checkpoint_policies name (e.g.
                                        # dots_saveable — keep conv/matmul
                                        # outputs, recompute elementwise/BN
                                        # chains) instead of full recompute
    bn_fp32_stats: bool = True          # False: BN batch stats in the
                                        # compute dtype (bf16) instead of
                                        # flax's f32 promotion — the A/B
                                        # for the convert+reduce chains the
                                        # op profiles blame for the b16
                                        # regression (BASELINE.md)
    dtype: str = "float32"              # 'bfloat16' = BASELINE config 3
    loss_weights: tuple[float, ...] | None = None
    pam_block_size: int | None = None   # blocked position-attention
    attention_impl: str = "auto"        # BOTH DANet attention branches at
                                        # once: auto (flash Pallas kernels
                                        # for bf16 compute on TPU — the
                                        # mixed-precision hot path — XLA
                                        # einsum otherwise, per the f32
                                        # crossover sweep) | xla (einsum
                                        # everywhere, the reference-parity
                                        # form) | flash (force the Pallas
                                        # kernels; interpret-mode off-TPU).
                                        # pam_impl below overrides the
                                        # position branch when set.
    pam_impl: str = ""                  # position-branch override of
                                        # attention_impl: auto | einsum |
                                        # flash (pallas) | ring (sequence-
                                        # parallel PAM over the mesh's
                                        # model axis).  "" = inherit
                                        # attention_impl.  auto = flash for
                                        # bf16-TPU; otherwise einsum while
                                        # the N^2 scores fit HBM, flash
                                        # beyond (memory feasibility)
    pam_score_dtype: str | None = None  # einsum PAM only: dtype the N x N
                                        # score matrix materializes in.
                                        # 'bfloat16' halves the dominant
                                        # non-MXU HBM round trip of the
                                        # flagship step (BASELINE.md
                                        # roofline); softmax arithmetic and
                                        # einsum accumulation stay f32.
                                        # Measured round 3: +2.5% (b8) /
                                        # +5.7% (b16) step rate, accuracy
                                        # curve tracks f32 within epoch
                                        # noise (conv run d) — recommended
                                        # on; default stays f32 for bit-
                                        # parity with the reference.
                                        # None = f32 (exact reference-like
                                        # scores)
    quantization: str = ""              # SERVE-side post-training weight
                                        # quantization (serve/quantize):
                                        # 'int8' = per-channel symmetric
                                        # int8 kernels, dequant-at-use in
                                        # the jitted forward, JA002-
                                        # audited against QuantPolicy's
                                        # declared dequant points.
                                        # Training always runs
                                        # full-precision; dptpu-serve and
                                        # dptpu-aot read this knob (their
                                        # --quantize flag overrides).
                                        # "" = serve the checkpoint as
                                        # trained
    remat: bool = False                 # rematerialize backbone blocks
    moe_experts: int = 0                # >0: MoE FFN in the DANet head
    moe_hidden: int | None = None       # expert MLP width (default: channels)
    moe_k: int = 1                      # top-k routing (1 = Switch)
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01        # load-balancing aux-loss weight
    aux_head: bool = False              # DeepLabV3/FCN/PSPNet/EncNet:
                                        # auxiliary FCN head on c3 (second
                                        # output; weight it via
                                        # loss_weights, e.g. [1.0,0.4])
    encnet_codes: int = 32              # EncNet: context-encoding codebook
                                        # size (the SE branch's codewords)
    ccnet_recurrence: int = 2           # CCNet: weight-shared criss-cross
                                        # steps (R=2 = full-image receptive
                                        # field through one hop)
    guidance_inject: str = "stem"       # DANet: where the click-guidance
                                        # channel enters — 'stem'
                                        # (reference parity: backbone sees
                                        # the 4-channel concat) or 'head'
                                        # (backbone sees RGB only; the
                                        # guidance joins at the head via a
                                        # zero-init 1x1 projection), which
                                        # makes the backbone encoding
                                        # reusable across a session's
                                        # refinement clicks
                                        # (serve/sessions.py)


@dataclass
class TrainConfig:
    """Raw step-speed levers (train/precision.py + parallel/step.py):
    the ROADMAP item-4 trio, each off by default for reference parity."""
    precision: str = "float32"          # float32 | bfloat16: 'bfloat16' is
                                        # the mixed-precision policy (bf16
                                        # compute, f32 master params/
                                        # optimizer/loss — train/precision
                                        # .py) threaded through the model
                                        # build and the compiled steps;
                                        # overrides model.dtype.  jaxaudit
                                        # JA002 audits the bf16 step
                                        # against the policy's declared
                                        # accumulation points.
    reduce_buckets: int = 0             # >0: data-parallel gradients are
                                        # all-reduced in this many reverse-
                                        # topological buckets (explicit
                                        # shard_map psums) instead of the
                                        # compiler's fused end-of-backward
                                        # reduce — head-param buckets
                                        # become schedulable as soon as the
                                        # early backward produces them, so
                                        # their reduce overlaps the
                                        # remaining backbone backward (the
                                        # arxiv 1711.00705 bucketed-
                                        # overlap recipe; async -start
                                        # forms contract-pinned on TPU).
                                        # Pure data parallel only (no TP/
                                        # ring PAM); loss/BN take DDP
                                        # semantics (per-shard loss
                                        # normalization averaged across
                                        # shards, cross-replica BN stats).
                                        # 0 = GSPMD-implicit (reference-
                                        # parity numerics).


@dataclass
class OptimConfig:
    name: str = "sgd"                   # sgd (reference parity,
                                        # train_pascal.py:118) | adamw
                                        # (decoupled weight decay; its two
                                        # moment buffers are where
                                        # mesh.shard_opt_state pays most)
    lr: float = 5e-8
    momentum: float = 0.9
    weight_decay: float = 5e-4
    adam_b1: float = 0.9                # adamw only
    adam_b2: float = 0.999
    adam_eps: float = 1e-8
    schedule: str = "constant"          # constant | poly | cosine
    poly_power: float = 0.9
    warmup_steps: int = 0
    accum_steps: int = 1                # the reference's nAveGrad knob
    loss_scale: float = 1.0             # static loss scaling for bf16
                                        # regimes: loss is scaled before the
                                        # backward pass and gradients
                                        # unscaled after, guarding tiny
                                        # gradients against bf16/f32
                                        # underflow at aggressive LRs.  The
                                        # reported loss is unscaled.  1.0 =
                                        # off (the flagship's bf16 runs are
                                        # stable without it, BASELINE.md).
    grad_clip_norm: float | None = None
    freeze: tuple[str, ...] = ()        # param-path prefixes to freeze
    lr_mult: dict[str, float] | None = None  # per-prefix LR multipliers


@dataclass
class ParallelConfig:
    """The declarative sharding strategy (parallel/plan.py): one knob
    that resolves to a validated mesh + composed state layout.  Leave
    ``strategy`` unset to keep driving the low-level ``mesh.*`` knobs —
    the planner then derives the plan FROM them, so every run carries
    one either way."""
    strategy: str = ""                  # "" = derive from mesh.* |
                                        # dp | dp_tp | dp_zero1 |
                                        # dp_tp_zero1 | auto (walk the
                                        # mesh-shape ladder with the
                                        # memory model, smallest model
                                        # axis that fits per-chip HBM)
    data: int | None = None             # explicit data-axis size
                                        # (None = all devices not
                                        # claimed by model, per slice)
    model: int = 0                      # explicit model-axis size
                                        # (0 = derive: 1 for the dp
                                        # family, 2 for the tp family)
    hbm_budget_gb: float = 0.0          # auto only: per-chip HBM budget
                                        # override (0 = detect from the
                                        # backend's bytes_limit, 16 GiB
                                        # fallback on backends without
                                        # memory stats)


@dataclass
class MeshConfig:
    data: int | None = None             # None = all devices (per slice
                                        # when slices > 1)
    model: int = 1                      # tensor-parallel axis size
    slices: int = 1                     # DCN factor of the data axis:
                                        # >1 = hierarchical DP over a
                                        # multi-slice topology
                                        # (make_hybrid_mesh)
    process_is_granule: bool | None = None
                                        # DCN granule choice for slices>1:
                                        # None = auto (device slice_index
                                        # when it matches, else hosts);
                                        # true forces host granules
    shard_params: bool = False          # TP: shard kernels over `model`
    shard_opt_state: bool = False       # ZeRO-1: shard optimizer state
                                        # over `data` (1/N optimizer
                                        # memory per device for one
                                        # param-sized all-gather per step)


@dataclass
class CheckpointConfig:
    keep_latest: int = 3
    snapshot_every: int = 100           # epoch snapshots (train_pascal.py:56)
    best_metric_init: float = 0.0       # reference pinned 0.913 (…:177)
    warm_start: str | None = None       # .pth to import weights from (the
                                        # reference's unconditional torch
                                        # warm start, train_pascal.py:103)
    warm_start_partial: bool = False    # tolerate missing/unused keys
    async_save: bool = True
    save_on_preempt: bool = True        # SIGTERM -> final full-state save
    preempt_check_every: int = 32       # stop-consensus cadence (steps)
    exact_resume: bool = True           # continue a preempted epoch at the
                                        # batch it stopped (no batch trains
                                        # twice); false = replay the epoch
                                        # from its start (batches repeat,
                                        # none skipped)
    digest: bool = False                # stamp each save's meta with a
                                        # sha256 over the param bytes —
                                        # the byte-identical-restore
                                        # invariant becomes checkable
                                        # across process deaths (the
                                        # chaos crash_loop scenario's
                                        # hook).  Costs one full param
                                        # readback per save; off by
                                        # default.


@dataclass
class SentinelConfig:
    """Self-healing training (train/sentinel.py): detection thresholds
    and the rollback budget.  Off by default — the trainer's legacy
    responses (log-and-continue / debug_asserts abort) stay pinned."""
    enabled: bool = False               # verdicts + rollback-and-replay
    ema_beta: float = 0.9               # loss-EMA smoothing
    suspect_factor: float = 3.0         # loss > f x EMA -> suspect
    diverged_factor: float = 10.0       # loss > f x EMA -> diverged
    warmup_steps: int = 8               # EMA updates before spike
                                        # verdicts arm (non-finite always
                                        # armed)
    monitor_grads: bool = False         # train step also emits
                                        # (grad_norm, update/param ratio)
                                        # — a second (2,) output on the
                                        # compiled program, so contracts
                                        # of sentinel-monitored programs
                                        # differ from the canonical ones
    grad_factor: float = 10.0           # grad_norm > f x EMA -> suspect
    update_ratio_max: float | None = None
                                        # ||update||/||param|| above this
                                        # -> diverged (None = off)
    max_rollbacks: int = 2              # rollback budget: consecutive
                                        # rollbacks without a cleanly
                                        # completed epoch in between
                                        # before the run fails loudly
                                        # (chaos CircuitBreaker)


@dataclass
class Config:
    task: str = "instance"              # instance (reference) | semantic
    data: DataConfig = field(default_factory=DataConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    optim: OptimConfig = field(default_factory=OptimConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    sentinel: SentinelConfig = field(default_factory=SentinelConfig)
    epochs: int = 100
    eval_every: int = 1                 # nTestInterval (train_pascal.py:62)
    val_overlap: bool = False           # run each validation on a thread
                                        # CONCURRENTLY with the next train
                                        # epoch (eval forwards interleave
                                        # on device; paste-back runs beside
                                        # the loader) — hides the val epoch
                                        # behind training wall-clock.
                                        # Best-save/logging land when the
                                        # next train epoch finishes — so a
                                        # HARD crash (no SIGTERM) during
                                        # that epoch loses one more epoch
                                        # than serial mode would (the
                                        # deferred checkpoint never
                                        # landed).  Costs one extra full
                                        # state in HBM while in flight;
                                        # single-process only (two threads
                                        # issuing collectives could
                                        # deadlock across hosts).
    eval_thresholds: tuple[float, ...] = (0.3, 0.5, 0.8)
    eval_tta_scales: tuple[float, ...] = ()  # semantic TTA: average softmax
                                        # probs over these input scales
                                        # (1.0 = the base pass)
    eval_tta_flip: bool = False         # semantic TTA: also average the
                                        # horizontal flip
    eval_full_res: bool = False         # semantic: score mIoU at each
                                        # image's ORIGINAL resolution
                                        # (probabilities bilinearly resized
                                        # back per sample — the standard
                                        # DeepLab protocol) instead of at
                                        # the resized eval crop
    eval_bf16_probs: bool = True        # semantic full-res/TTA: read the
                                        # softmax volumes back in bfloat16
                                        # — halves the dominant D2H cost
                                        # (~22 MB/image f32 at 513², the
                                        # measured bound of the full-res
                                        # protocol on a slow wire); argmax-
                                        # after-resize is tie-epsilon
                                        # sensitive only (tested).  Also
                                        # halves the INSTANCE val logit
                                        # readback (boundary-pixel rounding
                                        # at the thresholds; tested).
                                        # false restores exact f32
                                        # readback everywhere.
    eval_device_fullres: bool = True    # semantic full-res (non-TTA): do
                                        # the per-sample native-res resize
                                        # + argmax ON DEVICE (separable
                                        # weight-matmul warp, ops/warp.py)
                                        # and ship only the uint8 class
                                        # map — 21x fewer wire bytes and
                                        # no per-image host resize (the
                                        # 1.5 imgs/s r4 bound).  Applies
                                        # when every image in the batch
                                        # fits data.val_max_im_size and
                                        # the run is single-process;
                                        # false restores the host resize
                                        # path (bit-exact legacy).
    seed: int = 0
    work_dir: str = "runs"              # run_<N> dirs created under this
    resume: str | None = None           # checkpoint dir to resume from, or
                                        # 'auto' = newest prior run under
                                        # work_dir with a saved step
    debug_asserts: bool = False         # data-contract checks (…:188-190)
    log_every_steps: int = 50
    experiment_name: str = "experiment"
    log_writers: tuple[str, ...] = ("console", "jsonl")
                                        # console | jsonl | tensorboard |
                                        # comet (key from COMET_API_KEY)
    comet_project: str = ""             # reference used 'Attention' (:41)
    comet_workspace: str = ""
    profile_epoch: int | None = None    # XPlane-trace this epoch (0-based)
    telemetry: bool = True              # goodput/MFU accounting + the
                                        # SIGUSR2 on-demand trace trigger
                                        # (telemetry/); false = every
                                        # account() is a no-op (the <=2%
                                        # overhead contract's baseline)


def _to_jsonable(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: _to_jsonable(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, tuple):
        return list(obj)
    return obj


def _from_dict(cls, d: dict):
    # f.type is a *string* under `from __future__ import annotations`;
    # resolve real types once so nested dataclasses recurse properly.
    import typing
    hints = typing.get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in d:
            continue
        v = d[f.name]
        ftype = hints.get(f.name, f.type)
        if isinstance(ftype, type) and dataclasses.is_dataclass(ftype) \
                and isinstance(v, dict):
            v = _from_dict(ftype, v)
        elif f.name in ("crop_size", "rots", "scales", "loss_weights",
                        "eval_thresholds", "eval_tta_scales",
                        "freeze", "val_max_im_size", "pack_quarantine",
                        "session_quarantine") and isinstance(v, list):
            v = tuple(v)
        kwargs[f.name] = v
    return cls(**kwargs)


_SUBCONFIGS = {"data": DataConfig, "model": ModelConfig,
               "train": TrainConfig, "optim": OptimConfig,
               "parallel": ParallelConfig, "mesh": MeshConfig,
               "checkpoint": CheckpointConfig,
               "sentinel": SentinelConfig}


def to_json(cfg: Config, path: str | None = None) -> str:
    s = json.dumps(_to_jsonable(cfg), indent=2)
    if path:
        with open(path, "w") as f:
            f.write(s + "\n")
    return s


def from_json(source: str) -> Config:
    """Parse a JSON string or (if it names an existing file) a JSON file."""
    import os
    if os.path.exists(source):
        with open(source) as f:
            source = f.read()
    d = json.loads(source)
    kwargs = {}
    for k, v in d.items():
        if k in _SUBCONFIGS:
            kwargs[k] = _from_dict(_SUBCONFIGS[k], v)
        else:
            kwargs[k] = v
    base = Config()
    for f in dataclasses.fields(Config):
        if f.name not in kwargs:
            kwargs[f.name] = getattr(base, f.name)
        elif f.name in ("eval_thresholds", "eval_tta_scales",
                        "log_writers") \
                and isinstance(kwargs[f.name], list):
            kwargs[f.name] = tuple(kwargs[f.name])
    return Config(**kwargs)


def apply_overrides(cfg: Config, overrides: dict[str, Any] | list[str]) -> Config:
    """Dotted-path overrides: ``{"optim.lr": 1e-3}`` or ``["optim.lr=1e-3"]``.

    String values are JSON-decoded when possible so CLI args round-trip to
    numbers/bools/lists.
    """
    if isinstance(overrides, list):
        parsed = {}
        for item in overrides:
            k, _, v = item.partition("=")
            parsed[k.strip()] = v.strip()
        overrides = parsed
    cfg = dataclasses.replace(cfg)  # shallow copy of the root
    for path, value in overrides.items():
        if isinstance(value, str):
            try:
                value = json.loads(value)
            except (ValueError, TypeError):
                pass
        *parents, leaf = path.split(".")
        node = cfg
        trail = []
        for p in parents:
            trail.append((node, p))
            node = getattr(node, p)
        if not any(f.name == leaf for f in dataclasses.fields(node)):
            raise KeyError(f"unknown config field: {path}")
        if isinstance(getattr(node, leaf), tuple) and isinstance(value, list):
            value = tuple(value)
        new_leaf = dataclasses.replace(node, **{leaf: value})
        for parent, name in reversed(trail):
            new_leaf = dataclasses.replace(parent, **{name: new_leaf})
        cfg = new_leaf
    return cfg


def flatten(cfg: Config) -> dict[str, Any]:
    """Flat ``section.field -> value`` view — feeds the param report
    (the reference's ``generate_param_report``, train_pascal.py:169)."""
    out: dict[str, Any] = {}

    def walk(prefix: str, obj: Any):
        if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
            for f in dataclasses.fields(obj):
                walk(f"{prefix}{f.name}.", getattr(obj, f.name))
        else:
            out[prefix[:-1]] = obj

    walk("", cfg)
    return out
