"""Step-health sentinel: typed verdicts over the training loss stream.

The reference could not survive a bad step at all (SURVEY §0/§5.3: a NaN
loss trained garbage until someone looked at the curves), and until this
module the trainer had exactly two responses — log-and-continue
(``train/nonfinite_steps``) or abort hard (the ``debug_asserts``
FloatingPointError watchdogs).  The sentinel is the third response's
detection half: it watches the loss values the trainer ALREADY reads
back on the host (the log-cadence readback and the epoch-end bulk
fetch — no new host syncs, no reads inside compiled programs) and turns
them into typed verdicts:

* ``healthy``  — finite, within the spike envelope;
* ``suspect``  — finite but > ``suspect_factor`` x the loss EMA (or a
  grad-norm spike, when the optional monitor is on): logged and
  counted, training continues;
* ``diverged`` — non-finite, > ``diverged_factor`` x the EMA, or an
  update/param-norm ratio above ``update_ratio_max``: the trainer's
  rollback-and-replay path fires (see Trainer._handle_divergence).

Detection is deterministic on replicated values: every host reads the
same loss, computes the same EMA, and reaches the same verdict at the
same step — which is what lets multi-host rollback happen without any
extra consensus traffic.

Two observation passes, by design: the log-cadence pass judges the
latest dispatch against the CURRENT EMA without updating it
(``update=False``), and the epoch-end sweep — the one place the full
ordered loss stream exists on host — is the single EMA-updating pass.
The EMA therefore advances in strict step order and no deduplication
bookkeeping is needed.

Metrics (process registry, ``telemetry`` config gate): verdict counts
as ``train_sentinel_verdicts_total{verdict}``, the EMA as the
``train_sentinel_loss_ema`` gauge; the trainer books
``train_sentinel_rollbacks_total`` / ``train_sentinel_quarantined_steps_total``
and rollback restore times into ``train_sentinel_recovery_seconds``.
"""

from __future__ import annotations

import math

HEALTHY = "healthy"
SUSPECT = "suspect"
DIVERGED = "diverged"


class SentinelReport:
    """One observation pass's outcome: the worst verdict, plus where the
    first ``diverged`` step sits (the rollback window's right edge)."""

    __slots__ = ("verdict", "step", "value", "reason")

    def __init__(self, verdict: str = HEALTHY, step: int | None = None,
                 value: float | None = None, reason: str = ""):
        self.verdict = verdict
        self.step = step          # first diverged/suspect global step
        self.value = value        # the observed value that tripped it
        self.reason = reason      # nonfinite_loss | loss_spike | ...

    @property
    def diverged(self) -> bool:
        return self.verdict == DIVERGED

    def __repr__(self) -> str:  # quarantine records / error messages
        return (f"SentinelReport({self.verdict}, step={self.step}, "
                f"value={self.value}, reason={self.reason!r})")


class StepSentinel:
    """Loss-EMA spike + non-finite detection (and an optional grad-norm /
    update-ratio monitor) over host-side loss readbacks.

    ``warmup_steps`` observations must update the EMA before spike
    verdicts arm — the first steps of a fresh run legitimately fall fast
    and a factor-of-N test against a 1-sample EMA would false-trip.
    Non-finite detection is always armed, warmup included.
    """

    def __init__(self, *, ema_beta: float = 0.9,
                 suspect_factor: float = 3.0,
                 diverged_factor: float = 10.0,
                 warmup_steps: int = 8,
                 grad_factor: float = 10.0,
                 update_ratio_max: float | None = None,
                 telemetry: bool = True):
        if not 0.0 < ema_beta < 1.0:
            raise ValueError(f"ema_beta must be in (0, 1), got {ema_beta}")
        if suspect_factor > diverged_factor:
            raise ValueError(
                f"suspect_factor {suspect_factor} > diverged_factor "
                f"{diverged_factor} — suspect must trip first")
        self.ema_beta = float(ema_beta)
        self.suspect_factor = float(suspect_factor)
        self.diverged_factor = float(diverged_factor)
        self.warmup_steps = int(warmup_steps)
        self.grad_factor = float(grad_factor)
        self.update_ratio_max = update_ratio_max
        self._telemetry = telemetry
        self.ema: float | None = None
        self.grad_ema: float | None = None
        self.n_observed = 0

    # ------------------------------------------------------------ observing
    def observe(self, first_step: int, losses, grad_norms=None,
                update_ratios=None, update: bool = True) -> SentinelReport:
        """Judge ``losses[i]`` as global step ``first_step + i``; returns
        the WORST verdict (first ``diverged`` wins — its step bounds the
        quarantine window).  ``update=False`` judges against the current
        EMA without advancing it (the log-cadence pass)."""
        report = SentinelReport()
        for i, loss in enumerate(losses):
            step = first_step + i
            loss = float(loss)
            gnorm = (None if grad_norms is None
                     else float(grad_norms[i]))
            ratio = (None if update_ratios is None
                     else float(update_ratios[i]))
            verdict, reason, value = self._judge(loss, gnorm, ratio)
            if update and math.isfinite(loss) and verdict != DIVERGED:
                # a diverged loss must not drag the EMA to its own scale
                # (or to NaN) — the envelope keeps describing health
                self.ema = loss if self.ema is None else \
                    self.ema_beta * self.ema + (1 - self.ema_beta) * loss
                if gnorm is not None and math.isfinite(gnorm):
                    self.grad_ema = gnorm if self.grad_ema is None else \
                        (self.ema_beta * self.grad_ema
                         + (1 - self.ema_beta) * gnorm)
                self.n_observed += 1
            if update:
                self._book(verdict)
            if verdict == DIVERGED:
                report.verdict = DIVERGED
                report.step, report.value, report.reason = step, value, reason
                if not update:
                    self._book(DIVERGED)  # raised before any update pass
                break
            if verdict == SUSPECT and report.verdict == HEALTHY:
                report.verdict = SUSPECT
                report.step, report.value, report.reason = step, value, reason
        if update:
            self._gauge()
        return report

    def _judge(self, loss: float, gnorm, ratio):
        if not math.isfinite(loss):
            return DIVERGED, "nonfinite_loss", loss
        if gnorm is not None and not math.isfinite(gnorm):
            return DIVERGED, "nonfinite_grad_norm", gnorm
        if ratio is not None and not math.isfinite(ratio):
            return DIVERGED, "nonfinite_update_ratio", ratio
        if self.update_ratio_max is not None and ratio is not None \
                and ratio > self.update_ratio_max:
            # one update rewriting a macroscopic fraction of the weights
            # IS divergence even while the loss still looks plausible
            return DIVERGED, "update_ratio", ratio
        armed = self.n_observed >= self.warmup_steps
        if armed and self.ema is not None and self.ema > 0 \
                and loss > self.diverged_factor * self.ema:
            return DIVERGED, "loss_spike", loss
        if armed and self.ema is not None and self.ema > 0 \
                and loss > self.suspect_factor * self.ema:
            return SUSPECT, "loss_spike", loss
        if armed and gnorm is not None and self.grad_ema is not None \
                and self.grad_ema > 0 and gnorm > self.grad_factor \
                * self.grad_ema:
            return SUSPECT, "grad_norm_spike", gnorm
        return HEALTHY, "", loss

    # ------------------------------------------------------------- rollback
    def reset(self) -> None:
        """Post-rollback re-arm: the EMA (a description of healthy loss
        scale) survives, but spike verdicts re-warm so the replayed
        window's recovery transient cannot immediately re-trip."""
        self.n_observed = 0

    # ------------------------------------------------------------ telemetry
    def _book(self, verdict: str) -> None:
        if not self._telemetry:
            return
        from ..telemetry import get_registry
        from ..telemetry.registry import is_enabled

        if not is_enabled():
            return
        get_registry().counter(
            "train_sentinel_verdicts_total",
            "Step-health sentinel verdicts (train/sentinel.py)",
            labels={"verdict": verdict}).inc()

    def _gauge(self) -> None:
        if not self._telemetry or self.ema is None:
            return
        from ..telemetry import get_registry
        from ..telemetry.registry import is_enabled

        if not is_enabled():
            return
        get_registry().gauge(
            "train_sentinel_loss_ema",
            "EMA of the observed train loss").set(self.ema)


#: the bench/report schema for self-healing outcomes — keys ALWAYS
#: present (the PR 4 convention), every value null when the sentinel
#: never ran
RECOVERY_KEYS = ("rollbacks", "quarantined_steps", "supervisor_restarts",
                 "recovery_p50_s")


def make_recovery_block(*, rollbacks: int, quarantined_steps: int,
                        recovery_p50_s: float | None,
                        supervisor_restarts: int | None = None) -> dict:
    """Construct a populated recovery block — the ONE place the schema's
    keys are written (``Trainer.fit`` builds its history/fit-summary
    block through this; ``recovery_block`` below re-projects it for
    bench records), so the two surfaces cannot drift."""
    out = dict.fromkeys(RECOVERY_KEYS)
    out.update(rollbacks=rollbacks, quarantined_steps=quarantined_steps,
               supervisor_restarts=supervisor_restarts,
               recovery_p50_s=recovery_p50_s)
    return out


def recovery_block(history: dict | None = None) -> dict:
    """The ``recovery`` block for bench records / fit summaries: populated
    from a ``Trainer.fit`` history when it carries one, all-null
    otherwise (sentinel off, or a bench loop that never ran ``fit``)."""
    rec = (history or {}).get("recovery") if history else None
    out = {k: None for k in RECOVERY_KEYS}
    if rec:
        out.update({k: rec.get(k) for k in RECOVERY_KEYS})
    return out
