"""Optimizer and LR-schedule factory.

The reference's optimizer point: ``SGD(lr=5e-8, momentum=0.9, wd=5e-4)``
(train_pascal.py:118) with a poly LR scheduler imported but commented out so
the run used a constant LR (train_pascal.py:34,164).  Both are first-class
here; poly decay is the classic segmentation schedule
``lr * (1 - step/total)^power`` the reference's ``LR_Scheduler('poly', …)``
implemented externally.

Parameter groups: the reference experimented with freezing the backbone and
with per-param-group LRs — both left as commented code (backbone
``requires_grad=False`` loop, train_pascal.py:87-89; pretrained-vs-head LR
groups, :90-91).  Here they are config knobs: ``freeze`` pins named subtrees
(their updates are zeroed, momentum state carries nothing), ``lr_mult``
scales the whole update of a named subtree — torch param-group semantics,
expressed as an ``optax.multi_transform`` over path-prefix labels.

Weight decay note: torch SGD's ``weight_decay`` is L2-added-to-grad *before*
momentum; ``optax.sgd`` has no wd, so we compose ``add_decayed_weights``
ahead of the momentum trace to match torch semantics exactly.

``optim.name=adamw`` swaps the update rule for decoupled-decay AdamW
(optax.adamw) under the same schedules and param-group machinery; its two
moment buffers are where ``mesh.shard_opt_state`` (ZeRO-1) pays most.
"""

from __future__ import annotations

import jax
import optax

from .config import OptimConfig


def make_schedule(cfg: OptimConfig, total_steps: int) -> optax.Schedule:
    if cfg.schedule == "constant":
        sched = optax.constant_schedule(cfg.lr)
    elif cfg.schedule == "poly":
        # transition_begin stays 0: when joined behind a warmup phase,
        # join_schedules already offsets the step count by the boundary.
        sched = optax.polynomial_schedule(
            init_value=cfg.lr, end_value=0.0, power=cfg.poly_power,
            transition_steps=max(total_steps - cfg.warmup_steps, 1),
        )
    elif cfg.schedule == "cosine":
        # half-cosine decay lr -> 0 over the post-warmup steps (the other
        # standard segmentation schedule besides poly)
        sched = optax.cosine_decay_schedule(
            init_value=cfg.lr,
            decay_steps=max(total_steps - cfg.warmup_steps, 1),
        )
    else:
        raise ValueError(f"unknown schedule: {cfg.schedule!r} "
                         "(constant | poly | cosine)")
    if cfg.warmup_steps > 0:
        warm = optax.linear_schedule(0.0, cfg.lr, cfg.warmup_steps)
        sched = optax.join_schedules([warm, sched], [cfg.warmup_steps])
    return sched


def _dotted(path) -> str:
    return ".".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def _matches(dotted: str, prefix: str) -> bool:
    return dotted == prefix or dotted.startswith(prefix + ".")


def make_param_labeler(freeze: tuple[str, ...],
                       lr_mult: dict[str, float] | None):
    """``params -> label pytree`` for ``optax.multi_transform``.

    A parameter's label is ``"frozen"`` if any ``freeze`` prefix matches its
    dotted path (e.g. ``backbone`` matches ``backbone.layer1.conv.kernel``),
    else ``"mult:<prefix>"`` for the longest matching ``lr_mult`` prefix,
    else ``"base"``.

    Every prefix must match at least one parameter — a typo'd prefix that
    silently trained an intended-frozen subtree would be invisible until
    someone inspected the weights, so it raises instead.
    """

    def labeler(params):
        matched: set[str] = set()

        def label_of(path, _leaf):
            dotted = _dotted(path)
            frozen = False
            for p in freeze:
                if _matches(dotted, p):
                    matched.add(p)
                    frozen = True
            best = ""
            for p in (lr_mult or {}):
                if _matches(dotted, p):
                    matched.add(p)
                    if len(p) > len(best):
                        best = p
            if frozen:
                return "frozen"
            return f"mult:{best}" if best else "base"

        labels = jax.tree_util.tree_map_with_path(label_of, params)
        missing = (set(freeze) | set(lr_mult or {})) - matched
        if missing:
            raise ValueError(
                f"param-group prefixes matched no parameter: "
                f"{sorted(missing)}")
        return labels

    return labeler


def make_optimizer(cfg: OptimConfig, total_steps: int
                   ) -> tuple[optax.GradientTransformation, optax.Schedule]:
    """Returns ``(tx, schedule)``; the schedule is also returned separately so
    the trainer can log the current LR."""
    sched = make_schedule(cfg, total_steps)

    def base_update(mult: float = 1.0) -> optax.GradientTransformation:
        parts = []
        if cfg.name == "sgd":
            # torch SGD semantics: wd is L2-added-to-grad BEFORE momentum
            if cfg.weight_decay:
                parts.append(optax.add_decayed_weights(cfg.weight_decay))
            parts.append(optax.sgd(sched, momentum=cfg.momentum or None))
        elif cfg.name == "adamw":
            # adamw's decay is DECOUPLED (applied to params, scaled by the
            # schedule) — optax.adamw owns that semantics
            parts.append(optax.adamw(
                sched, b1=cfg.adam_b1, b2=cfg.adam_b2, eps=cfg.adam_eps,
                weight_decay=cfg.weight_decay))
        else:
            raise ValueError(
                f"unknown optimizer: {cfg.name!r} (sgd | adamw)")
        if mult != 1.0:  # torch param-group lr: scales the whole step
            parts.append(optax.scale(mult))
        return optax.chain(*parts)

    labeler = None
    if cfg.freeze or cfg.lr_mult:
        labeler = make_param_labeler(tuple(cfg.freeze), cfg.lr_mult)
        group_txs = {"base": base_update(), "frozen": optax.set_to_zero()}
        for prefix, mult in (cfg.lr_mult or {}).items():
            group_txs[f"mult:{prefix}"] = base_update(float(mult))
        tx = optax.multi_transform(group_txs, labeler)
    else:
        tx = base_update()
    if cfg.grad_clip_norm:
        pre = []
        if cfg.freeze:
            # Frozen params contribute nothing to the step, so they must not
            # contribute to the clip norm either (torch excludes
            # requires_grad=False params from clip_grad_norm_): zero their
            # grads ahead of the global-norm computation.
            def frozen_mask(tree):
                return jax.tree.map(lambda lb: lb == "frozen", labeler(tree))

            pre.append(optax.masked(optax.set_to_zero(), frozen_mask))
        # Global-norm clipping spans all (trainable) groups, so it sits
        # ahead of the per-group split.
        pre.append(optax.clip_by_global_norm(cfg.grad_clip_norm))
        tx = optax.chain(*pre, tx)
    return tx, sched
