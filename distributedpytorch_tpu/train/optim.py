"""Optimizer and LR-schedule factory.

The reference's optimizer point: ``SGD(lr=5e-8, momentum=0.9, wd=5e-4)``
(train_pascal.py:118) with a poly LR scheduler imported but commented out so
the run used a constant LR (train_pascal.py:34,164).  Both are first-class
here; poly decay is the classic segmentation schedule
``lr * (1 - step/total)^power`` the reference's ``LR_Scheduler('poly', …)``
implemented externally.

Weight decay note: torch SGD's ``weight_decay`` is L2-added-to-grad *before*
momentum; ``optax.sgd`` has no wd, so we compose ``add_decayed_weights``
ahead of the momentum trace to match torch semantics exactly.
"""

from __future__ import annotations

import optax

from .config import OptimConfig


def make_schedule(cfg: OptimConfig, total_steps: int) -> optax.Schedule:
    if cfg.schedule == "constant":
        sched = optax.constant_schedule(cfg.lr)
    elif cfg.schedule == "poly":
        # transition_begin stays 0: when joined behind a warmup phase,
        # join_schedules already offsets the step count by the boundary.
        sched = optax.polynomial_schedule(
            init_value=cfg.lr, end_value=0.0, power=cfg.poly_power,
            transition_steps=max(total_steps - cfg.warmup_steps, 1),
        )
    else:
        raise ValueError(f"unknown schedule: {cfg.schedule!r}")
    if cfg.warmup_steps > 0:
        warm = optax.linear_schedule(0.0, cfg.lr, cfg.warmup_steps)
        sched = optax.join_schedules([warm, sched], [cfg.warmup_steps])
    return sched


def make_optimizer(cfg: OptimConfig, total_steps: int
                   ) -> tuple[optax.GradientTransformation, optax.Schedule]:
    """Returns ``(tx, schedule)``; the schedule is also returned separately so
    the trainer can log the current LR."""
    sched = make_schedule(cfg, total_steps)
    parts = []
    if cfg.grad_clip_norm:
        parts.append(optax.clip_by_global_norm(cfg.grad_clip_norm))
    if cfg.weight_decay:
        parts.append(optax.add_decayed_weights(cfg.weight_decay))
    parts.append(optax.sgd(sched, momentum=cfg.momentum or None))
    return optax.chain(*parts), sched
