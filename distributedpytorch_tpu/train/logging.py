"""Metric writers and visualization panels.

The reference's observability was ad hoc (SURVEY.md §5.5): a Comet ML
experiment receiving only matplotlib figures (train_pascal.py:41,276), scalar
metrics only ``print``ed (:208-212,296-306), TensorBoard scaffolding fully
commented out (:24,113-114,221,299-300), a hyperparameter text report
(:169).  Here one small writer abstraction serves console, JSONL files and
TensorBoard uniformly; the figure panels (image+gt overlay, prediction,
position-attention map, channel-attention map — train_pascal.py:263-275) are
reproduced as a pure function over the first val batch.

The Comet writer (the reference's actual backend) IS built in — but the API
key comes exclusively from the environment (``COMET_API_KEY``, comet_ml's own
convention), never from source: the reference committed its key at :41, the
anti-pattern this module exists to avoid.  Select writers with the
``log_writers`` config knob via :func:`make_writer`.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, Mapping

import numpy as np


class MetricWriter:
    """Protocol: scalars / figures / hparams sinks."""

    def scalars(self, metrics: Mapping[str, float], step: int) -> None: ...

    def figure(self, name: str, fig, step: int) -> None: ...

    def hparams(self, params: Mapping[str, Any]) -> None: ...

    def flush(self) -> None: ...

    def close(self) -> None:
        self.flush()


class ConsoleWriter(MetricWriter):
    def __init__(self, prefix: str = ""):
        self.prefix = prefix

    def scalars(self, metrics, step):
        body = "  ".join(f"{k}={v:.6g}" if isinstance(v, float) else f"{k}={v}"
                         for k, v in metrics.items())
        print(f"{self.prefix}[step {step}] {body}", flush=True)

    def figure(self, name, fig, step):
        pass

    def hparams(self, params):
        print(self.prefix + "hyperparameters:", flush=True)
        for k, v in params.items():
            print(f"{self.prefix}  {k}: {v}", flush=True)

    def flush(self):
        pass


class JsonlWriter(MetricWriter):
    """One JSONL stream of scalar events + PNG figures on disk — greppable,
    diffable, no deps; the run directory becomes the experiment record.

    Non-finite values serialize as ``null``: ``json.dumps`` would emit
    bare ``NaN``/``Infinity`` (a Python extension no strict JSON parser
    accepts), and a diverging run is EXACTLY when the log must stay
    machine-readable.  The stream is line-buffered so a crashed run keeps
    its tail — the last lines before the crash are the diagnosis.
    """

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._f = open(os.path.join(directory, "metrics.jsonl"), "a",
                       buffering=1)

    @classmethod
    def _jsonable(cls, v):
        if isinstance(v, bool):
            return v
        if isinstance(v, (int, float, np.integer, np.floating)):
            f = float(v)
            return f if math.isfinite(f) else None
        if isinstance(v, dict):  # containers sanitize recursively, so a
            return {k: cls._jsonable(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):  # nested NaN can't crash dumps
            return [cls._jsonable(x) for x in v]
        return v

    def scalars(self, metrics, step):
        rec = {"step": int(step), "time": time.time()}
        rec.update({k: self._jsonable(v) for k, v in metrics.items()})
        try:
            line = json.dumps(rec, allow_nan=False)
        except (TypeError, ValueError):
            # a writer must never kill the run it records: stringify the
            # offending values and keep the stream valid JSONL
            line = json.dumps({k: v if isinstance(
                v, (bool, int, float, str, type(None))) else repr(v)
                for k, v in rec.items()}, allow_nan=False)
        self._f.write(line + "\n")

    def figure(self, name, fig, step):
        path = os.path.join(self.directory, f"{name}_step{step}.png")
        fig.savefig(path, dpi=100, bbox_inches="tight")

    def hparams(self, params):
        with open(os.path.join(self.directory, "hparams.json"), "w") as f:
            json.dump({k: repr(v) if not isinstance(
                v, (int, float, str, bool, type(None))) else v
                for k, v in params.items()}, f, indent=2)

    def flush(self):
        self._f.flush()

    def close(self):
        self.flush()
        self._f.close()


class TensorBoardWriter(MetricWriter):
    """TensorBoard events via torch's SummaryWriter (the scaffolding the
    reference left commented out, train_pascal.py:24,113-114) — optional, the
    import is deferred and failure degrades to a no-op."""

    def __init__(self, directory: str):
        try:
            from torch.utils.tensorboard import SummaryWriter
            self._w = SummaryWriter(directory)
        except Exception:
            self._w = None

    def scalars(self, metrics, step):
        if self._w:
            for k, v in metrics.items():
                if isinstance(v, (int, float)):
                    self._w.add_scalar(k, v, step)

    def figure(self, name, fig, step):
        if self._w:
            self._w.add_figure(name, fig, step)

    def hparams(self, params):
        if self._w:
            self._w.add_text("hparams", json.dumps(
                {k: str(v) for k, v in params.items()}, indent=2), 0)

    def flush(self):
        if self._w:
            self._w.flush()

    def close(self):
        if self._w:
            self._w.close()


class CometWriter(MetricWriter):
    """Comet ML experiment writer — the reference's logging backend
    (train_pascal.py:10,41,276), upgraded: scalars AND figures AND hparams
    (the reference uploaded only figures; its scalars were prints), and the
    API key read from ``COMET_API_KEY`` instead of source.

    Deferred import; a missing SDK or key prints one warning and degrades
    to a no-op, so ``log_writers=[...,comet]`` never kills a training run.
    """

    def __init__(self, project: str | None = None,
                 workspace: str | None = None,
                 experiment_name: str | None = None):
        from ..chaos.policies import CircuitBreaker

        self._exp = None
        #: the framework's one consecutive-failure breaker
        #: (chaos/policies): _MAX_FAILS failures in a row open it, any
        #: success resets; no half-open — on open the SDK handle is
        #: dropped, so the writer is permanently (and quietly) done.
        #: Constructed here, not lazily — the counter is part of the
        #: writer's state contract, not an accident of first error.
        self._breaker = CircuitBreaker(failure_threshold=self._MAX_FAILS)
        try:
            from comet_ml import Experiment
            if not os.environ.get("COMET_API_KEY"):
                raise RuntimeError("COMET_API_KEY is not set")
            kw: dict = {"log_code": False, "log_env_details": False}
            if project:
                kw["project_name"] = project
            if workspace:
                kw["workspace"] = workspace
            self._exp = Experiment(**kw)
            if experiment_name:
                self._exp.set_name(experiment_name)
        except Exception as e:
            print(f"CometWriter disabled: {e}", flush=True)

    #: consecutive runtime failures tolerated before giving up on the SDK
    _MAX_FAILS = 5

    @property
    def _fails(self) -> int:
        """Consecutive failures so far — kept as the writer's documented
        state surface; the count now lives in the shared breaker."""
        return self._breaker.failures

    def _guarded(self, call) -> None:
        """A live-experiment SDK/network error must degrade, not abort the
        training run (the 'never kills a run' contract of __init__).
        Transient blips are survived; only _MAX_FAILS consecutive errors
        open the breaker and disable the writer (a permanently dead
        uplink should not print per-step tracebacks forever)."""
        try:
            self._breaker.call(call)
        except Exception as e:
            if self._breaker.is_open:
                print(f"CometWriter error (disabled after "
                      f"{self._breaker.failures} consecutive failures): "
                      f"{e}", flush=True)
                self._exp = None
            else:
                print(f"CometWriter error (will retry): {e}", flush=True)

    def scalars(self, metrics, step):
        if self._exp:
            self._guarded(lambda: self._exp.log_metrics(
                {k: v for k, v in metrics.items()
                 if isinstance(v, (int, float))}, step=step))

    def figure(self, name, fig, step):
        if self._exp:  # the reference's exp.log_figure (train_pascal.py:276)
            self._guarded(lambda: self._exp.log_figure(
                figure_name=name, figure=fig, step=step))

    def hparams(self, params):
        if self._exp:
            self._guarded(lambda: self._exp.log_parameters(
                {k: str(v) for k, v in params.items()}))

    def flush(self):
        pass

    def close(self):
        if self._exp:
            self._guarded(lambda: self._exp.end())


def make_writer(name: str, run_dir: str,
                experiment_name: str | None = None,
                comet_project: str | None = None,
                comet_workspace: str | None = None) -> MetricWriter:
    """Writer factory behind the ``log_writers`` config knob."""
    if name == "console":
        return ConsoleWriter()
    if name == "jsonl":
        return JsonlWriter(run_dir)
    if name == "tensorboard":
        return TensorBoardWriter(os.path.join(run_dir, "tb"))
    if name == "comet":
        return CometWriter(project=comet_project, workspace=comet_workspace,
                           experiment_name=experiment_name)
    raise ValueError(f"unknown writer {name!r} "
                     "(console | jsonl | tensorboard | comet)")


class MultiWriter(MetricWriter):
    def __init__(self, *writers: MetricWriter):
        self.writers = [w for w in writers if w is not None]

    def scalars(self, metrics, step):
        for w in self.writers:
            w.scalars(metrics, step)

    def figure(self, name, fig, step):
        for w in self.writers:
            w.figure(name, fig, step)

    def hparams(self, params):
        for w in self.writers:
            w.hparams(params)

    def flush(self):
        for w in self.writers:
            w.flush()

    def close(self):
        for w in self.writers:
            w.close()


def make_val_panels(first_batch: dict, max_samples: int = 2):
    """The reference's first-val-batch figure (train_pascal.py:257-278):
    per sample a row of [input image + gt overlay, fused prediction,
    position-attention prediction, channel-attention prediction].

    ``first_batch`` is the ``_first_batch`` record from
    :func:`evaluate.evaluate`.  Returns a matplotlib Figure (Agg backend —
    never opens a display)."""
    import matplotlib
    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    from ..utils.helpers import overlay_mask, tens2image

    batch = first_batch["batch"]
    outputs = first_batch["outputs"]
    n = min(outputs[0].shape[0], max_samples)
    ncols = 1 + len(outputs)
    fig, axes = plt.subplots(n, ncols, figsize=(3 * ncols, 3 * n),
                             squeeze=False)
    titles = ["image+gt", "fused", "pam", "cam"]
    for i in range(n):
        # overlay_mask blends in [0, 1] (and imshow clips floats there) —
        # feed it the normalized image, not raw [0, 255] channels.
        img = np.clip(tens2image(np.asarray(batch["concat"][i]))[..., :3],
                      0, 255) / 255.0
        gt = tens2image(np.asarray(batch["crop_gt"][i]))
        axes[i][0].imshow(overlay_mask(img, gt > 0.5))
        for k, out in enumerate(outputs):
            prob = 1.0 / (1.0 + np.exp(-tens2image(out[i])))
            axes[i][1 + k].imshow(prob, vmin=0, vmax=1)
        for j, ax in enumerate(axes[i]):
            ax.set_axis_off()
            if i == 0 and j < len(titles):
                ax.set_title(titles[j] if j < len(titles) else "")
    fig.tight_layout()
    return fig
