"""Elastic pod training: detect the topology change, re-plan, restore,
continue.

Every primitive this composes is individually chaos-proven — byte-
identical cross-plan restore (``plan_mismatch_restore``: a dp8
checkpoint reshards into dp4xtp2), supervisor restarts with
``resume=auto`` (``crash_loop`` / ``preemption_storm``), zero-lost-step
preemption resume — but until this module nothing *reacted to the
topology itself changing*.  A preempted slice or a grown reservation
killed the child and the supervisor restarted it into the same (now
wrong, or gone) device set; surviving that took a human editing the
mesh config.  Elastic supervision closes the loop:

1. **Detect** — before every spawn the supervisor probes the topology
   the NEXT child will see (:func:`probe_topology`, a stdlib
   subprocess so the supervisor itself never imports jax).  A child
   exit whose post-exit probe fingerprint differs from the one it was
   launched under is classified ``topology_changed`` — a new exit
   class in the restart ledger, distinct from ``crashed``: a shrink is
   the scheduler reshaping the pod, not the run failing, so it resets
   the crash-loop fingerprint count and never naps the backoff curve
   (the give-up math must not starve a run to death for being
   preempted off a slice three times).
2. **Re-plan** — the restart carries ``parallel.strategy=auto`` (the
   supervisor's ``replan_arg``, riding ``resume_overrides`` exactly as
   the ``plan_mismatch_restore`` scenario proved end-to-end): the child
   re-resolves the mesh-shape ladder against the devices it actually
   has.  Multi-host, the resolution routes through
   :func:`~..parallel.consensus.replicated_decision` — the detected
   HBM budget reduces by min across hosts and the chosen rung is
   verified identical everywhere — so every host compiles the SAME
   plan or fails loudly, never a silent per-host mesh.
3. **Restore** — ``resume=auto`` restores the newest committed
   checkpoint THROUGH the plan crossing (Orbax adopts the target
   layout; the saved meta's plan block — now stamped with a
   :func:`~..parallel.plan.topology_fingerprint` — makes the crossing
   detectable and loudly announced even when the *layout* normalizes
   equal, e.g. dp-on-8 -> dp-on-4 with ``data=None``).
4. **Continue** — exact-resume arithmetic is device-count-independent
   (the loader shards per *process*, the global batch is config), so
   not one optimizer step is lost or duplicated across the crossing.

``dptpu-supervise --elastic`` arms all of it; the ``elastic_membership``
chaos scenario is the acceptance gate (three unattended topology
changes in one run, digest chain unbroken, every exit classified
``topology_changed``).

Deliberately importable before jax, like :mod:`supervise` — the
supervisor must outlive anything that can take a device runtime down.
"""

from __future__ import annotations

import json
import math
import os
import re
import subprocess
import sys

#: the bench/fit-summary ``elastic`` block's keys (schema-stable)
ELASTIC_KEYS = ("topology_changes", "replans", "recovery_p50_s")

#: the override an elastic restart appends so the child re-resolves its
#: plan against the live topology (CLI ``--replan-arg`` overrides)
DEFAULT_REPLAN_ARG = "parallel.strategy=auto"

_FORCED_COUNT_RE = re.compile(
    r"--xla_force_host_platform_device_count=(\d+)")

#: the jax-importing probe child (stdlib parent, heavyweight child —
#: the supervisor's process must never initialize a device runtime)
_PROBE_SRC = (
    "import json, jax\n"
    "d = jax.devices()\n"
    "print(json.dumps({'platform': d[0].platform,"
    " 'n_devices': len(d),"
    " 'process_count': jax.process_count()}))\n")


def parse_forced_device_count(env: dict) -> int | None:
    """The ``--xla_force_host_platform_device_count`` a child env pins
    (the tests'/chaos' topology knob); None when unpinned."""
    m = _FORCED_COUNT_RE.search(env.get("XLA_FLAGS", "") or "")
    return int(m.group(1)) if m else None


def force_device_count_flags(flags: str, n: int) -> str:
    """``XLA_FLAGS`` with the forced-host-device count rewritten to
    ``n`` (other flags preserved) — the write half of the flag grammar
    :func:`parse_forced_device_count` reads, kept beside it so the
    chaos runner's topology knob and the probe's fast path can never
    drift apart."""
    if _FORCED_COUNT_RE.search(flags or ""):
        return _FORCED_COUNT_RE.sub(
            f"--xla_force_host_platform_device_count={int(n)}", flags)
    return ((flags or "")
            + f" --xla_force_host_platform_device_count={int(n)}").strip()


def fingerprint(info: dict) -> str:
    """``"<platform>:<n_devices>/p<procs>"`` — the same identity
    :func:`~..parallel.plan.topology_fingerprint` stamps into plan
    blocks, computed from a probe report so the two surfaces compare."""
    return (f"{info['platform']}:{int(info['n_devices'])}"
            f"/p{int(info.get('process_count', 1))}")


def probe_topology(env: dict | None = None,
                   timeout_s: float = 180.0) -> dict:
    """What topology would a child launched with ``env`` see?  Returns
    ``{"platform", "n_devices", "process_count", "fingerprint"}``.

    Pinned CPU topologies (``JAX_PLATFORMS=cpu`` + the forced-device-
    count flag — the conftest/chaos idiom) are read straight from the
    env: deterministic and free.  Anything else pays one throwaway
    ``python -c "import jax; ..."`` subprocess (~seconds — amortized
    against a child generation's lifetime), because the device set is
    the runtime's to report, not the env's."""
    env = dict(os.environ if env is None else env)
    forced = parse_forced_device_count(env)
    if forced and env.get("JAX_PLATFORMS") == "cpu" \
            and "JAX_COORDINATOR_ADDRESS" not in env:
        info = {"platform": "cpu", "n_devices": forced,
                "process_count": 1}
        info["fingerprint"] = fingerprint(info)
        return info
    out = subprocess.run([sys.executable, "-c", _PROBE_SRC],
                         capture_output=True, text=True,
                         timeout=timeout_s, env=env)
    if out.returncode != 0:
        raise RuntimeError(
            f"topology probe exited {out.returncode}: "
            f"{out.stderr[-500:]}")
    info = json.loads(out.stdout.strip().splitlines()[-1])
    info["fingerprint"] = fingerprint(info)
    return info


def _p50(xs) -> float | None:
    """Nearest-rank median, stdlib (the supervisor may not import
    numpy)."""
    if not xs:
        return None
    s = sorted(xs)
    return round(float(s[max(0, math.ceil(0.5 * len(s)) - 1)]), 3)


def elastic_block(report: dict | None = None) -> dict | None:
    """The ``elastic`` record block for bench records / supervisor
    reports: ``None`` when the supervisor never re-planned (the plan/
    precision-block null convention — null means "the static default
    regime", so elastic-exercised records never compare against static
    history), else ``{topology_changes, replans, recovery_p50_s}``
    with every key present.

    ``report`` is a :meth:`~.supervise.Supervisor.run` report dict (or
    None for the common static case)."""
    if not report:
        return None
    changes = int((report.get("restarts") or {}).get(
        "topology_changed", 0) or 0)
    if not changes:
        return None
    events = report.get("topology_changes") or []
    return {
        "topology_changes": changes,
        "replans": sum(1 for e in events if e.get("replan")),
        "recovery_p50_s": _p50(report.get(
            "topology_recovery_seconds") or []),
    }
