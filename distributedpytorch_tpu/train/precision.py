"""Mixed-precision policy: bf16 compute, f32 master params and optimizer.

The reference trained f32 end-to-end (PyTorch defaults, reference
train_pascal.py — no AMP/GradScaler anywhere).  On TPU the MXU runs
bf16 matmuls at twice the f32 rate and halves every activation's HBM
round trip, so the flagship step leaves ~2x on the table until the whole
train path computes in bf16.  This module is the ONE place that regime
is declared:

* **compute** runs in ``bfloat16`` — the flax modules are built with
  ``dtype=bfloat16``, so convs/matmuls/attention promote their (f32)
  params down and do bf16 math;
* **master params, gradients and optimizer state stay float32** — flax's
  ``param_dtype`` default keeps params f32, so ``jax.grad`` w.r.t. them
  accumulates the bf16 backward contributions into f32 buffers and the
  optimizer update runs entirely in f32 (no precision loss across
  steps, the standard mixed-precision contract);
* **the loss and BatchNorm batch statistics accumulate in float32** —
  the loss kernels (:mod:`ops.losses`) upcast logits on entry, and flax
  BN's ``force_float32_reductions`` keeps mean/var f32
  (``model.bn_fp32_stats``).

Those three f32 islands are not accidents — they are the policy's
*declared accumulation points*, and :attr:`Policy.ja002_allow` names the
exact primitives they are allowed to run on upcast bf16 data
(:data:`POLICY_ACCUM_PRIMS`).  jaxaudit's JA002 dtype-flow check audits
the bf16 train step against THAT allowlist: zero findings means every
f32 op in the program is one the policy declared, and any new silent
upcast (a layer accidentally computing f32, an f32 copy of an
activation) is a contract failure, not a vibe.  Audits of programs
without a policy keep the strict default allowlist.

``train.precision`` is the config knob (``float32`` | ``bfloat16``);
:func:`precision_block` is the schema-stable record block bench.py
stamps into train/serve/sessions records (null when f32).
"""

from __future__ import annotations

import dataclasses
from typing import Any

#: primitives the policy's declared f32 accumulation points run on upcast
#: bf16 data, beyond the strict default allowlist (reductions + matmul/conv
#: accumulation, analysis/ir.py DEFAULT_F32_ACCUM_ALLOW).  Every entry is
#: tied to a declared island, observed on the audited bf16 train step:
#:
#: * ``add`` — the f32 master-gradient accumulation: each param's bf16
#:   backward contributions are upcast and summed into its f32 gradient
#:   (multiple use sites of one kernel -> one `add` tree per kernel);
#: * ``mul``/``square``/``sub`` — BatchNorm's f32 batch statistics
#:   (mean of x², centered variance) over upcast bf16 activations;
#: * ``abs``/``eq``/``ge``/``max``/``div`` — the loss kernels' f32
#:   arithmetic (balanced-BCE masking/normalization, softmax-CE guards)
#:   on upcast logits/targets;
#: * ``exp``/``log``/``select_n`` — the softmax-CE loss's log-sum-exp
#:   and ignore-index select on upcast logits (the semantic task's loss).
#:
#: Deliberately NOT here: activation-function transcendentals and the
#: rest of the elementwise zoo (`sin`, `tanh`, `logistic`, `rsqrt`,
#: `pow`, ...) — an activation or normalization chain silently running
#: f32 on the bf16 path still fails JA002 under the policy allowlist.
POLICY_ACCUM_PRIMS = frozenset({
    "add", "sub", "mul", "div", "square", "abs", "eq", "ge", "max",
    "exp", "log", "select_n",
})


@dataclasses.dataclass(frozen=True)
class Policy:
    """One mixed-precision regime, immutable and JSON-able.

    ``compute_dtype`` is what the model computes in (flax ``dtype``);
    ``param_dtype`` what params/grads/optimizer state live in (flax
    ``param_dtype`` — always f32 here: bf16 master weights lose ~8
    mantissa bits of every SGD update and are not worth the memory on a
    framework whose optimizer state already shards, see parallel.zero);
    ``loss_dtype`` what the loss accumulates in.
    """

    compute_dtype: str = "bfloat16"
    param_dtype: str = "float32"
    loss_dtype: str = "float32"

    def cast_to_compute(self, x: Any):
        """Cast one array (or pytree) of inputs to the compute dtype —
        the train step applies this at the model boundary so the input
        tensor's HBM traffic is halved before the first conv (which
        would otherwise do the cast itself, after the f32 read)."""
        import jax
        import jax.numpy as jnp

        dt = jnp.dtype(self.compute_dtype)
        return jax.tree.map(
            lambda v: v.astype(dt)
            if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating)
            and v.dtype != dt else v, x)

    def cast_to_loss(self, outputs):
        """Upcast model outputs to the loss dtype — the declared
        accumulation boundary between bf16 compute and f32 loss math
        (the loss kernels upcast defensively too; under this policy the
        boundary is explicit and auditable)."""
        import jax
        import jax.numpy as jnp

        dt = jnp.dtype(self.loss_dtype)
        return jax.tree.map(lambda v: v.astype(dt), outputs)

    def ja002_allow(self) -> frozenset:
        """The JA002 allowlist for programs built under this policy:
        the strict default set plus :data:`POLICY_ACCUM_PRIMS`."""
        from ..analysis.ir import DEFAULT_F32_ACCUM_ALLOW

        return DEFAULT_F32_ACCUM_ALLOW | POLICY_ACCUM_PRIMS

    def block(self) -> dict:
        """The bench-record ``precision`` block (keys stable)."""
        return {
            "compute_dtype": self.compute_dtype,
            "param_dtype": self.param_dtype,
            "loss_dtype": self.loss_dtype,
        }


def precision_policy(name: str | None) -> Policy | None:
    """``train.precision`` -> policy.  ``'float32'``/``None``/``''`` is
    the f32 end-to-end regime (no policy object: every consumer's
    ``policy is None`` branch is the exact pre-policy code path);
    ``'bfloat16'`` is bf16 compute + f32 master params/loss."""
    if not name or name == "float32":
        return None
    if name == "bfloat16":
        return Policy()
    raise ValueError(
        f"unknown train.precision: {name!r} (float32 | bfloat16)")


def precision_block(policy: Policy | None) -> dict | None:
    """The record block for bench/telemetry consumers: the policy's
    declared dtypes, or ``None`` under f32 (key always present in the
    record, the PR 4 schema-stability convention)."""
    return None if policy is None else policy.block()
