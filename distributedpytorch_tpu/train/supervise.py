"""``dptpu-supervise``: a crash-loop supervisor for training runs.

The third layer of self-healing (after the in-process sentinel and its
rollback-and-replay, train/sentinel.py): some failures kill the whole
process — OOM, a segfaulting extension, SIGKILL from a scheduler — and
no in-process machinery survives them.  The supervisor runs the training
command as a CHILD, watches how it exits, and restarts it:

* **clean**        — exit 0 and the newest run's ``fit_summary.json``
  says the schedule completed: done.
* **preempted**    — exit 0 but the summary says the run stopped on a
  termination signal (the PreemptionGuard's graceful stop): restarted
  immediately (``restart_on_preempt``), because a preemption is the
  scheduler's problem, not the run's.
* **crashed**      — non-zero exit or death by signal: restarted after
  an exponential-backoff nap (the one :class:`chaos.policies.Retry`
  schedule).
* **crash-looping** — ``crash_loop_threshold`` crashes with the SAME
  fingerprint (exit code + last stderr line) inside
  ``crash_loop_window_s``, with NO checkpoint progress between them:
  give up loudly (:class:`CrashLoopError`).  Progress resets the count —
  a run that dies every hour but advances its committed step is limping,
  not looping, and restarts are exactly what it needs.
* **topology_changed** — (elastic supervision, :mod:`train.elastic`:
  ``topology_probe`` set) the child died AND the topology the next
  child would see differs from the one it launched under: a preempted
  slice, a shrunken or grown visible-device set.  Restarted
  immediately with the ``replan_arg`` override appended
  (``parallel.strategy=auto`` — the child re-resolves its plan against
  the new devices and restores through the plan crossing).  A
  topology change is the SCHEDULER reshaping the pod, not the run
  failing, so it is distinct from ``crashed`` in the restart ledger
  and resets the crash-loop fingerprint count — a shrink must never
  count toward give-up.

Progress is read from the checkpoint commit ledger
(``run_*/checkpoints/COMMITTED.json``, plain JSON — no Orbax, no jax),
and run outcomes from ``fit_summary.json`` (written atomically by
``Trainer.fit``), so the supervisor itself stays a stdlib process that
can never be taken down by the failure it is supervising.  Deliberately
importable before jax, like ``chaos/policies.py``; telemetry booking is
lazy and best-effort.

Restart downtime (child death -> next child spawned) lands in the
``train_supervisor_recovery_seconds{reason}`` histogram and restart
counts in ``train_supervisor_restarts_total{reason}`` — the
``chaos_recovery_seconds``-shaped surface the chaos scenarios assert
against.  Every event is also appended to ``<work_dir>/supervisor.jsonl``.
"""

from __future__ import annotations

import glob
import json
import os
import re
import subprocess
import sys
import time
from typing import Callable, Sequence

from ..chaos.policies import Retry
from ..telemetry import events as events_lib
from . import elastic as elastic_lib

#: classification outcomes (the ``outcome`` field of run() reports)
CLEAN = "clean"
PREEMPTED = "preempted"
CRASHED = "crashed"
TOPOLOGY_CHANGED = "topology_changed"
CRASH_LOOP = "crash_loop"
GAVE_UP = "gave_up"


class CrashLoopError(RuntimeError):
    """The child died with the same fingerprint, without progress, too
    many times in a row; the supervisor's report rides on the exception."""

    def __init__(self, report: dict):
        self.report = report
        fp = report.get("last_fingerprint")
        super().__init__(
            f"crash loop: {report['restarts']['crashed']} crashes, "
            f"{report['crash_loop_count']} identical without progress "
            f"(fingerprint {fp!r}) — giving up")


def _scan_runs(work_dir: str) -> list[tuple[int, str]]:
    """(index, path) of every ``run_<N>`` under ``work_dir``, ascending."""
    runs = glob.glob(os.path.join(work_dir, "run_*"))
    return sorted((int(m.group(1)), r) for r in runs
                  if (m := re.search(r"run_(\d+)$", r)))


def latest_fit_summary(work_dir: str) -> dict | None:
    """The newest run's ``fit_summary.json`` (None when no run wrote
    one — e.g. the child died before finishing a fit)."""
    for _idx, run in reversed(_scan_runs(work_dir)):
        path = os.path.join(run, "fit_summary.json")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            continue
    return None


def latest_committed_step(work_dir: str) -> int | None:
    """Max step any run has durably landed — the supervisor's progress
    signal.  Two stdlib-only sources, unioned: the ``COMMITTED.json``
    ledger (written at sync saves, async-save entry, and ``wait()``) and
    the finalized numeric step dirs under ``checkpoints/latest/`` —
    Orbax writes to a tmp-suffixed name and renames on commit, so a
    purely-numeric dir IS a landed save.  The dir scan covers the child
    that enqueued exactly ONE async save and was then killed (its ledger
    refresh never saw a landed predecessor), which is precisely the
    crash the progress signal must not starve on."""
    best: int | None = None

    def take(s: int) -> None:
        nonlocal best
        best = s if best is None else max(best, s)

    for _idx, run in _scan_runs(work_dir):
        ck = os.path.join(run, "checkpoints")
        try:
            with open(os.path.join(ck, "COMMITTED.json")) as f:
                for s in json.load(f).get("latest") or []:
                    take(int(s))
        except (OSError, ValueError):
            pass
        try:
            for d in os.listdir(os.path.join(ck, "latest")):
                if d.isdigit():
                    take(int(d))
        except OSError:
            pass
    return best


class Supervisor:
    """Run ``argv`` as a child until it completes, restarting per the
    policy above.

    ``argv`` is the child command, or a callable ``attempt -> argv``
    (the chaos runner uses this to give each attempt its own spec file).
    ``resume_arg`` (e.g. ``"resume=auto"``) is appended to list-style
    commands on every RESTART — the knob that makes a plain
    ``dptpu-train`` command continue instead of starting over; callables
    own their resume handling and never get it.

    ``topology_probe`` (``env -> info dict``, see
    :func:`elastic.probe_topology`) arms ELASTIC supervision: exits
    whose probed fingerprint moved are classified
    ``topology_changed`` and restarted with ``replan_arg`` appended
    (list-style commands; callables own their overrides, and the
    report marks their re-plans as theirs).  Probe failures degrade to
    the legacy classification, loudly — never a crash of the
    supervisor itself.
    """

    def __init__(self, argv: Sequence[str] | Callable[[int], Sequence[str]],
                 *, work_dir: str,
                 max_restarts: int = 16,
                 crash_loop_threshold: int = 3,
                 crash_loop_window_s: float = 600.0,
                 restart_on_preempt: bool = True,
                 backoff: Retry | None = None,
                 resume_arg: str | None = None,
                 env: dict | None = None,
                 child_env: Callable[[int], dict | None] | None = None,
                 capture_output: bool = True,
                 telemetry: bool = True,
                 topology_probe: Callable[[dict], dict] | None = None,
                 replan_arg: str | None = None):
        if crash_loop_threshold < 1:
            raise ValueError(f"crash_loop_threshold must be >= 1, got "
                             f"{crash_loop_threshold}")
        self._argv = argv
        self.work_dir = work_dir
        self.max_restarts = int(max_restarts)
        self.crash_loop_threshold = int(crash_loop_threshold)
        self.crash_loop_window_s = float(crash_loop_window_s)
        self.restart_on_preempt = restart_on_preempt
        #: nap schedule between crash restarts — THE Retry policy's
        #: backoff curve (chaos/policies.py), not a third reimplementation
        self.backoff = backoff or Retry(base_s=1.0, cap_s=60.0)
        self.resume_arg = resume_arg
        self.env = env
        self.child_env = child_env
        self.capture_output = capture_output
        self._telemetry = telemetry
        self.topology_probe = topology_probe
        self.replan_arg = replan_arg
        #: set once a topology change has been observed: every later
        #: restart keeps the re-plan override (the new topology is the
        #: topology until it changes again)
        self._replan = False
        self.events: list[dict] = []

    # --------------------------------------------------------------- pieces
    def _argv_for(self, attempt: int) -> list[str]:
        if callable(self._argv):
            return list(self._argv(attempt))
        argv = list(self._argv)
        if attempt > 0 and self.resume_arg:
            argv.append(self.resume_arg)
        if attempt > 0 and self._replan and self.replan_arg:
            argv.append(self.replan_arg)
        return argv

    def _child_env(self, attempt: int) -> dict:
        """The exact env attempt ``attempt`` would run under — one
        builder shared by :meth:`_spawn` and the topology probe, so the
        probe can never see a different device set than the child."""
        env = dict(self.env if self.env is not None else os.environ)
        if self.child_env is not None:
            extra = self.child_env(attempt)
            if extra:
                env.update(extra)
        return env

    def _probe(self, attempt: int) -> dict | None:
        """Topology info for the env of ``attempt`` (None: probing off
        or failed — failure is an event, never a supervisor death)."""
        if self.topology_probe is None:
            return None
        try:
            return self.topology_probe(self._child_env(attempt))
        except Exception as e:
            self._event("topology_probe_failed", attempt=attempt,
                        error=f"{type(e).__name__}: {e}")
            return None

    def _spawn(self, attempt: int) -> tuple[int, str]:
        """Run one child; returns ``(returncode, stderr_tail)``.

        stderr is ALWAYS tapped — the crash fingerprint (exit code +
        last stderr line) is what keeps distinct failures from
        conflating into one crash loop — but only a BOUNDED tail is
        kept: a multi-day child emitting a warning per step must not
        grow the supervisor's memory with it.  With
        ``capture_output=False`` (the CLI) every stderr line is teed
        through live; ``True`` (tests, the chaos runner) silences the
        child entirely (stdout to devnull, stderr tail only)."""
        import collections
        import threading

        proc = subprocess.Popen(
            self._argv_for(attempt),
            stdout=subprocess.DEVNULL if self.capture_output else None,
            stderr=subprocess.PIPE, text=True,
            env=self._child_env(attempt))
        tail: collections.deque = collections.deque(maxlen=40)

        def drain() -> None:
            for line in proc.stderr:
                tail.append(line)
                if not self.capture_output:
                    sys.stderr.write(line)
                    sys.stderr.flush()

        t = threading.Thread(target=drain, daemon=True)
        t.start()
        rc = proc.wait()
        t.join(timeout=10)
        proc.stderr.close()
        return rc, "".join(tail)

    @staticmethod
    def _fingerprint(rc: int, stderr_tail: str) -> str:
        """Identity of a failure: exit code (negative = signal) + the
        last non-empty stderr line.  Two OOMs look the same; an OOM and
        an assertion do not — only the former pair counts toward the
        crash-loop give-up."""
        tail = ""
        for line in reversed(stderr_tail.splitlines()):
            if line.strip():
                tail = line.strip()[-200:]
                break
        return f"rc={rc}|{tail}"

    def _event(self, kind: str, **fields) -> None:
        ev = {"event": kind, "t": round(time.time(), 3), **fields}
        self.events.append(ev)
        try:
            os.makedirs(self.work_dir, exist_ok=True)
            with open(os.path.join(self.work_dir, "supervisor.jsonl"),
                      "a") as f:
                f.write(json.dumps(ev) + "\n")
        except OSError:
            pass  # a read-only work dir must not kill supervision
        # flight recorder mirror (telemetry/events.py, stdlib — keeps
        # the supervisor pre-jax): supervisor.jsonl above stays the
        # authoritative classification ledger; the event copy is what
        # the timeline merger anchors the generation chain on.  The
        # attempt number IS the process generation.
        events_lib.emit("supervisor", kind,
                        generation=fields.get("attempt"),
                        payload=fields)

    def _book(self, reason: str, downtime_s: float | None) -> None:
        if not self._telemetry:
            return
        try:  # lazy + best-effort: the supervisor must outlive telemetry
            from ..telemetry import get_registry
            from ..telemetry.registry import is_enabled

            if not is_enabled():
                return
            get_registry().counter(
                "train_supervisor_restarts_total",
                "Supervisor child restarts (train/supervise.py)",
                labels={"reason": reason}).inc()
            if downtime_s is not None:
                get_registry().histogram(
                    "train_supervisor_recovery_seconds",
                    "Child death -> next child spawned",
                    labels={"reason": reason}).observe(downtime_s)
        except Exception:
            pass

    @staticmethod
    def _finish(report: dict) -> dict:
        """Stamp the schema-stable ``elastic`` block (null when no
        membership change conditioned this supervision) on every way
        out of :meth:`run` — return or give-up alike."""
        report["elastic"] = elastic_lib.elastic_block(report)
        return report

    # ------------------------------------------------------------------ run
    def run(self) -> dict:
        """Supervise to completion; returns the report dict.  Raises
        :class:`CrashLoopError` on give-up (report attached)."""
        # flight recorder for the supervisor's own process: its events
        # land under <work_dir>/events/ and stitch the per-run_<N>
        # generations into one chain.  Best-effort (a read-only work dir
        # degrades to counted drops), released on every way out.
        evlog = events_lib.configure(self.work_dir) \
            if self._telemetry else None
        try:
            return self._run_supervised()
        finally:
            events_lib.release(evlog)

    def _run_supervised(self) -> dict:
        restarts = {PREEMPTED: 0, CRASHED: 0, TOPOLOGY_CHANGED: 0}
        loop_count = 0
        loop_t0: float | None = None
        last_fp: str | None = None
        last_progress = latest_committed_step(self.work_dir)
        attempt = 0
        consecutive_crashes = 0
        report: dict = {"outcome": None, "attempts": 0,
                        "restarts": restarts, "crash_loop_count": 0,
                        "last_fingerprint": None,
                        "recovery_seconds": [],
                        #: elastic supervision's ledger halves: one
                        #: entry per membership change, and the
                        #: downtime of exactly those restarts (the
                        #: elastic block's recovery_p50_s source)
                        "topology_changes": [],
                        "topology_recovery_seconds": []}
        # the topology attempt 0 will launch under — the baseline every
        # exit's probe compares against (None: elastic detection off)
        topo = self._probe(0)
        topo_fp = topo.get("fingerprint") if topo else None
        while True:
            self._event("spawn", attempt=attempt,
                        argv=self._argv_for(attempt))
            rc, stderr_tail = self._spawn(attempt)
            exit_t = time.monotonic()
            attempt += 1
            report["attempts"] = attempt

            if rc == 0:
                summary = latest_fit_summary(self.work_dir)
                if summary and summary.get("preempted"):
                    if not self.restart_on_preempt:
                        # the operator opted out of restarts: report the
                        # truth — a preempted run is NOT a completed one
                        self._event("preempted_final", attempt=attempt - 1,
                                    summary=summary)
                        report["outcome"] = PREEMPTED
                        return self._finish(report)
                    outcome = PREEMPTED
                else:
                    if summary is None:
                        # exit 0 but NO fit summary under work_dir: the
                        # contract can't be checked (work-dir mismatch?
                        # a command that never runs fit?).  Restarting
                        # would loop a non-training command forever, so
                        # accept the exit — LOUDLY, because a preempted
                        # run whose summary we cannot find would
                        # otherwise be silently declared complete.
                        msg = (f"dptpu-supervise: child exited 0 but no "
                               f"run under {self.work_dir!r} has a "
                               "fit_summary.json — accepting the exit "
                               "as clean UNVERIFIED (is --work-dir the "
                               "training run's work_dir?)")
                        print(msg, file=sys.stderr)
                        self._event("clean_exit_unverified",
                                    attempt=attempt - 1, warning=msg)
                    self._event("clean_exit", attempt=attempt - 1,
                                summary=summary)
                    report["outcome"] = CLEAN
                    return self._finish(report)
            else:
                outcome = CRASHED

            # ---- elastic: did the topology move underneath the child?
            # The probe sees what the NEXT attempt would see; a moved
            # fingerprint re-classifies this exit — whatever the rc —
            # as topology_changed: restart immediately (no backoff),
            # with the re-plan override, and WITHOUT advancing the
            # crash-loop math (a shrink is the scheduler's act, and
            # counting it toward give-up would starve a run off
            # preemptible capacity — the economics this exists for).
            new_topo = self._probe(attempt)
            new_fp = new_topo.get("fingerprint") if new_topo else None
            if topo_fp is None:
                # the baseline probe failed at launch (transient): adopt
                # the first fingerprint we DO get as the baseline — a
                # permanently-None baseline would silently disable
                # elastic detection for the whole run
                topo_fp = new_fp
            elif new_fp is not None and new_fp != topo_fp:
                outcome = TOPOLOGY_CHANGED
                # callable commands own their overrides (the chaos
                # runner bakes strategy=auto into each attempt's spec);
                # list commands get replan_arg appended from now on
                replan = bool(self.replan_arg) or callable(self._argv)
                self._replan = True
                report["topology_changes"].append(
                    {"attempt": attempt - 1, "old": topo_fp,
                     "new": new_fp, "rc": rc, "replan": replan})
                self._event("topology_changed", attempt=attempt - 1,
                            rc=rc, old=topo_fp, new=new_fp,
                            replan=replan)
                topo_fp = new_fp

            # ---- give-up checks before any restart.  topology_changed
            # restarts are excluded from the budget on BOTH sides: the
            # current exit never trips the cap, and past reshapes don't
            # consume it — a long run on preemptible capacity may be
            # reshaped arbitrarily often, and each reshape is the
            # scheduler's act, not the run burning its restart budget.
            if outcome != TOPOLOGY_CHANGED and \
                    attempt - restarts[TOPOLOGY_CHANGED] \
                    > self.max_restarts:
                self._event("gave_up", reason="max_restarts",
                            attempts=attempt)
                report["outcome"] = GAVE_UP
                raise CrashLoopError(self._finish(report))
            if outcome == CRASHED:
                consecutive_crashes += 1
                fp = self._fingerprint(rc, stderr_tail)
                progress = latest_committed_step(self.work_dir)
                progressed = (progress is not None
                              and (last_progress is None
                                   or progress > last_progress))
                now = time.monotonic()
                in_window = (loop_t0 is not None
                             and now - loop_t0 <= self.crash_loop_window_s)
                if fp == last_fp and not progressed and in_window:
                    loop_count += 1
                else:
                    loop_count = 1
                    loop_t0 = now
                last_fp, last_progress = fp, progress
                report["last_fingerprint"] = fp
                report["crash_loop_count"] = loop_count
                self._event("crash", attempt=attempt - 1,
                            rc=rc, fingerprint=fp,
                            progressed=progressed,
                            stderr_tail=stderr_tail[-800:])
                if loop_count >= self.crash_loop_threshold:
                    self._event("gave_up", reason="crash_loop",
                                fingerprint=fp, count=loop_count)
                    report["outcome"] = CRASH_LOOP
                    raise CrashLoopError(self._finish(report))
                nap = self.backoff.backoff_s(consecutive_crashes)
            elif outcome == TOPOLOGY_CHANGED:
                # the pod was reshaped, not the run broken: restart at
                # once, and RESET the crash-loop bookkeeping — the old
                # fingerprint described a topology that no longer
                # exists, so identical-crash counting across the change
                # would conflate two different worlds
                consecutive_crashes = 0
                loop_count = 0
                loop_t0 = None
                last_fp = None
                nap = 0.0
            else:  # preempted: graceful, restart without backoff
                consecutive_crashes = 0
                loop_count = 0
                nap = 0.0
                self._event("preempted", attempt=attempt - 1)

            restarts[outcome] += 1
            self.backoff.sleep(nap)
            downtime = time.monotonic() - exit_t
            report["recovery_seconds"].append(round(downtime, 3))
            if outcome == TOPOLOGY_CHANGED:
                report["topology_recovery_seconds"].append(
                    round(downtime, 3))
            self._book(outcome, downtime)
            self._event("restart", attempt=attempt, reason=outcome,
                        downtime_s=round(downtime, 3))


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="dptpu-supervise",
        description="crash-loop supervisor: run a training command as a "
                    "child, restart it on crash or preemption, give up "
                    "loudly on a genuine crash loop (see docs/DESIGN.md "
                    "'Self-healing training')",
        epilog="example: dptpu-supervise --work-dir runs -- "
               "dptpu-train data.root=/data/voc epochs=100")
    parser.add_argument("--work-dir", default="runs",
                        help="the training work_dir (run_<N> dirs): where "
                             "fit summaries, checkpoint ledgers and "
                             "supervisor.jsonl live")
    parser.add_argument("--max-restarts", type=int, default=16)
    parser.add_argument("--crash-loop", type=int, default=3,
                        metavar="N",
                        help="identical no-progress crashes before giving "
                             "up (default 3)")
    parser.add_argument("--crash-loop-window", type=float, default=600.0,
                        metavar="SECONDS")
    parser.add_argument("--no-restart-on-preempt", action="store_true",
                        help="treat a graceful preemption stop as final")
    parser.add_argument("--backoff-base", type=float, default=1.0,
                        help="first crash-restart nap (doubles, capped)")
    parser.add_argument("--backoff-cap", type=float, default=60.0)
    parser.add_argument("--resume-arg", default="resume=auto",
                        help="override appended to the command on every "
                             "restart ('' disables); the default makes "
                             "dptpu-train continue from the newest "
                             "checkpoint")
    parser.add_argument("--elastic", action="store_true",
                        help="elastic supervision (train/elastic.py): "
                             "probe the topology around every child "
                             "exit; a membership change is classified "
                             "topology_changed (never a crash), "
                             "restarted immediately with --replan-arg "
                             "appended so the run re-resolves its "
                             "parallel plan and restores through the "
                             "plan crossing")
    parser.add_argument("--replan-arg",
                        default=elastic_lib.DEFAULT_REPLAN_ARG,
                        help="override appended (with --elastic) to "
                             "restarts after a topology change "
                             "(default: parallel.strategy=auto)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="the child command (prefix with -- )")
    args = parser.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("a child command is required (after --)")

    sup = Supervisor(
        command, work_dir=args.work_dir, max_restarts=args.max_restarts,
        crash_loop_threshold=args.crash_loop,
        crash_loop_window_s=args.crash_loop_window,
        restart_on_preempt=not args.no_restart_on_preempt,
        backoff=Retry(base_s=args.backoff_base, cap_s=args.backoff_cap),
        resume_arg=args.resume_arg or None,
        topology_probe=(elastic_lib.probe_topology if args.elastic
                        else None),
        replan_arg=(args.replan_arg or None) if args.elastic else None,
        capture_output=False)  # interactive: child logs stream through
    try:
        report = sup.run()
    except CrashLoopError as e:
        print(json.dumps(e.report, indent=2), file=sys.stderr)
        print(f"dptpu-supervise: {e}", file=sys.stderr)
        return 1
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
