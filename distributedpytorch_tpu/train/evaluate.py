"""Validation: threshold-swept Jaccard with full-resolution paste-back.

The reference's val loop (train_pascal.py:233-308): per sample, sigmoid the
fused output, paste the 512² crop-space prediction back into full-image
coordinates (``crop2fullmask`` with the same bbox/relax the crop used),
binarize at thresholds {0.3, 0.5, 0.8} and score IoU against the *full-res*
ground truth with void-pixel exclusion; report the per-threshold means and
gate "best" on the max.

TPU split of labour: the model forward runs batched/jitted on device (the
reference ran val through ``DataParallel`` too, :245); the paste-back is
inherently ragged (every image has its own size, :286-291) so it stays
host-side numpy per sample — overlap comes from the loader's prefetch.

The reference's ``relaxes[jj]`` latent bug (indexing a 1-element list by
batch position, safe only because ``testBatch=1``, SURVEY.md §2.1) is not
reproduced: the relax is taken from the sample's own crop metadata.
"""

from __future__ import annotations

import functools
import time
from typing import Callable, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.metrics import np_jaccard_thresholds
from ..parallel import INPUT_KEY, pad_to_multiple, shard_batch
from ..telemetry import span
from ..utils.helpers import crop2fullmask, get_bbox, tens2image


def _sigmoid(x: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-x))


def _local_rows(arr) -> np.ndarray:
    """Host-local rows of a (possibly globally-sharded) batch-dim array.

    Multi-host, the eval outputs are sharded over all processes and
    ``device_get`` of the global array would fail (not fully addressable);
    each host fetches exactly its own shard rows — which are the outputs for
    the samples its loader shard contributed, in order."""
    if isinstance(arr, jax.Array) and not arr.is_fully_addressable:
        shards = sorted(arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        return np.concatenate([np.asarray(s.data) for s in shards], axis=0)
    return np.asarray(jax.device_get(arr))


def _as_list(v, n: int) -> list:
    """Batch entry -> per-sample list (stacked array or already a list)."""
    if isinstance(v, np.ndarray) and v.ndim >= 1 and v.shape[0] == n:
        return [v[i] for i in range(n)]
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v] * n


def evaluate(
    eval_step: Callable,
    state,
    loader,
    thresholds: Sequence[float] = (0.3, 0.5, 0.8),
    relax: int = 50,
    zero_pad: bool = True,
    mesh=None,
    max_batches: int | None = None,
    debug_asserts: bool = False,
    packed_masks: bool = False,
    bf16_readback: bool = False,
) -> dict:
    """Run the full validation protocol; returns a metrics dict.

    ``loader`` yields batches with device keys (``concat``/``crop_gt``) plus
    host-side full-res ``gt``/``void_pixels`` (kept by the eval transform's
    ``None`` resolutions, reference train_pascal.py:138).

    ``debug_asserts`` re-enables the reference's per-batch data-contract
    checks in the val loop too (train_pascal.py:239-241 asserted in BOTH
    loops).
    """
    thresholds = tuple(thresholds)
    jac_sum = np.zeros(len(thresholds))
    n_samples = 0
    losses: list = []  # device scalars; ONE bulk readback at epoch end
    first_batch_vis = None
    t0 = time.perf_counter()

    n_dev = mesh.devices.size if mesh is not None else 1

    def forwarded():
        """One-batch look-ahead: dispatch batch i+1's forward BEFORE
        materializing batch i's outputs, so the per-sample host paste-back
        below overlaps the next forward's device compute (eval was
        dispatch-bound at the reference's bs=1 protocol, ~180 ms/sample
        through a tunneled chip).  ``eval_step`` is async — holding its
        un-materialized outputs costs nothing."""
        prev = None
        for bi, batch in enumerate(loader):
            if max_batches is not None and bi >= max_batches:
                break
            if debug_asserts:
                batch_debug_asserts(batch, packed_masks=packed_masks)
            device_keys = {k: v for k, v in batch.items()
                           if k in (INPUT_KEY, "crop_gt", "crop_void")}
            padded, _ = pad_to_multiple(device_keys, n_dev)
            if mesh is not None:
                padded = shard_batch(mesh, padded)
            with span("eval/dispatch"):  # async: launch cost, not compute
                outputs, loss = eval_step(state, padded)
            # deferred: float(loss) here would add a host<->device round
            # trip per val batch (~70ms each through a tunneled chip) on
            # top of the outputs fetch — the same stall train_epoch's bulk
            # readback fixed
            losses.append(loss)
            if prev is not None:
                yield prev
            prev = (batch, outputs)
        if prev is not None:
            yield prev

    for batch, outputs in forwarded():
        n = batch[INPUT_KEY].shape[0]
        # primary head only; ragged paste-back per sample on host.
        # bf16_readback (eval_bf16_probs): cast the logit volume to bf16
        # ON DEVICE before the D2H fetch — half the val readback bytes
        # (same policy the semantic full-res path uses); threshold-level
        # effects are boundary-pixel rounding only (tested).
        raw = outputs[0]
        if bf16_readback and isinstance(raw, jax.Array):
            raw = raw.astype(jnp.bfloat16)
        probs = _sigmoid(
            _local_rows(raw)[:n].astype(np.float32, copy=False))
        if first_batch_vis is None:
            vis_batch = batch
            if packed_masks:
                # panels overlay crop_gt on the image; hand them the
                # unpacked mask, not the 1-bit wire row
                h, w = np.asarray(batch[INPUT_KEY]).shape[1:3]
                gt_bits = np.asarray(batch["crop_gt"])
                vis_batch = dict(batch)
                vis_batch["crop_gt"] = np.unpackbits(
                    gt_bits, axis=-1, count=h * w).reshape(n, h, w)
            first_batch_vis = {
                "batch": vis_batch,
                "outputs": [_local_rows(o)[:n] for o in outputs],
            }
        gts = _as_list(batch["gt"], n)
        voids = _as_list(batch.get("void_pixels", [None] * n), n)
        bboxes = _as_list(batch["bbox"], n) if "bbox" in batch else [None] * n
        # the ragged host half of the protocol, named in traces so a
        # paste-back-bound eval shows up as itself, not as device idle
        with span("eval/pasteback"):
            for j in range(n):
                gt = tens2image(np.asarray(gts[j]))
                void = None if voids[j] is None \
                    else tens2image(np.asarray(voids[j]))
                if gt.max() <= 0.5:  # empty gt: pred-empty is IoU 1, else 0
                    for ti, th in enumerate(thresholds):
                        jac_sum[ti] += float(not (probs[j] > th).any())
                    n_samples += 1
                    continue
                # Prefer the bbox the crop transform recorded for this
                # sample — guaranteed to be the exact box the crop was taken
                # from; only recompute (with this function's relax/zero_pad)
                # when absent.
                if bboxes[j] is not None:
                    bbox = tuple(int(v) for v in np.asarray(bboxes[j]))
                else:
                    bbox = get_bbox(gt > 0.5, pad=relax, zero_pad=zero_pad)
                pred = tens2image(probs[j])
                full = crop2fullmask(pred, bbox, gt.shape[:2],
                                     zero_pad=zero_pad, relax=relax)
                # all thresholds in one pass (digitize + bincount) — the
                # scoring half of the host paste-back no longer scales with
                # the threshold count
                jac_sum += np_jaccard_thresholds(full, thresholds,
                                                 gt > 0.5, void)
                n_samples += 1

    loss_sum = float(np.sum(jax.device_get(losses))) if losses else 0.0
    n_batches = len(losses)
    # Multi-host: every process evaluated only its loader shard; reduce the
    # raw sums across processes so all hosts hold identical global metrics —
    # the best-checkpoint gate must not diverge (the collective best-save
    # would deadlock if some hosts skipped it).
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        packed = np.concatenate([jac_sum,
                                 [n_samples, loss_sum, n_batches]])
        summed = np.asarray(
            multihost_utils.process_allgather(packed)).sum(axis=0)
        jac_sum = summed[:len(thresholds)]
        n_samples = int(summed[-3])
        loss_sum = float(summed[-2])
        n_batches = int(summed[-1])

    jac_avg = (jac_sum / max(n_samples, 1)).tolist()
    best_i = int(np.argmax(jac_avg))
    return {
        "loss": loss_sum / max(n_batches, 1),
        "jaccard_per_threshold": dict(zip(map(str, thresholds), jac_avg)),
        "jaccard": jac_avg[best_i],          # threshold-max mean IoU
        "best_threshold": thresholds[best_i],
        "n_samples": n_samples,
        "seconds": time.perf_counter() - t0,
        "_first_batch": first_batch_vis,     # for visualization panels
    }


def batch_debug_asserts(batch: Mapping[str, np.ndarray],
                        packed_masks: bool = False) -> None:
    """The reference's per-batch data-contract asserts
    (train_pascal.py:188-190), as an opt-in debug check rather than an
    always-on hot-loop cost: guidance/image channels within [0,255] and
    non-degenerate, gt strictly binary.

    With ``packed_masks`` (data.packbits_masks) the mask rides the wire at
    1 bit/pixel — binary by construction — so the gt check becomes
    structural: the packed row must be uint8 of exactly ceil(H*W/8) bytes
    for the batch's spatial shape."""
    x = np.asarray(batch[INPUT_KEY])
    assert x.min() >= 0.0 and x.max() <= 255.0, "input outside [0,255]"
    assert len(np.unique(x[..., :3])) > 2, "degenerate RGB channels"
    gt = np.asarray(batch["crop_gt"])
    if packed_masks:
        h, w = x.shape[1:3]
        expect = (h * w + 7) // 8
        assert gt.dtype == np.uint8 and gt.shape == (x.shape[0], expect), \
            f"packed gt shape/dtype off: {gt.shape} {gt.dtype}, " \
            f"expected ({x.shape[0]}, {expect}) uint8"
        return
    uniq = np.unique(gt)
    assert np.all(np.isin(uniq, (0.0, 1.0))), f"gt not binary: {uniq[:5]}"


def semantic_batch_debug_asserts(batch: Mapping[str, np.ndarray],
                                 nclass: int,
                                 ignore_index: int = 255) -> None:
    """Semantic-task counterpart of :func:`batch_debug_asserts`: image
    channels within [0,255] and non-degenerate, gt restricted to valid
    class ids plus the in-band void value."""
    x = np.asarray(batch[INPUT_KEY])
    assert x.min() >= 0.0 and x.max() <= 255.0, "input outside [0,255]"
    assert len(np.unique(x[..., :3])) > 2, "degenerate RGB channels"
    uniq = np.unique(np.asarray(batch["crop_gt"]))
    valid = np.concatenate([np.arange(nclass), [ignore_index]])
    assert np.all(np.isin(uniq, valid)), \
        f"gt ids outside 0..{nclass - 1} u {{{ignore_index}}}: {uniq[:8]}"


@functools.partial(jax.jit, static_argnums=(2, 3))
def _batch_confusion(outputs, labels, nclass: int, ignore_index: int):
    """argmax + confusion counts, compiled once per (nclass, ignore) pair
    (module-level so repeated eval epochs reuse the jit cache)."""
    import jax.numpy as jnp

    from ..ops.metrics import confusion_matrix

    pred = jnp.argmax(outputs, axis=-1)
    if labels.ndim == pred.ndim + 1:
        labels = labels[..., 0]
    return confusion_matrix(pred, labels, nclass, ignore_index)


def evaluate_semantic(
    eval_step: Callable,
    state,
    loader,
    nclass: int,
    ignore_index: int = 255,
    mesh=None,
    max_batches: int | None = None,
    tta_scales: tuple[float, ...] = (),
    tta_flip: bool = False,
    debug_asserts: bool = False,
    bf16_probs: bool = True,
    device_fullres: tuple[int, int] | None = None,
) -> dict:
    """Multi-class semantic validation: confusion-matrix mIoU.

    The metric for the DeepLabV3 configs of BASELINE.md ("val mIoU").  The
    argmax prediction and per-batch confusion counts are computed on device
    (one bincount — no NxC transfers); the (C, C) counts accumulate on host
    and reduce across processes, so the protocol is multi-host-safe the same
    way :func:`evaluate` is.

    ``tta_scales``/``tta_flip``: the standard DeepLab test-time-augmentation
    protocol — softmax probabilities averaged over the listed input scales
    (each a fixed shape, so each costs exactly one extra compiled program),
    with ``tta_flip`` adding the horizontal flip AT EVERY scale; argmax of
    the average.  The votes are exactly scales x flips as configured (a list
    omitting 1.0 does not vote the base pass); ``loss`` always reports the
    plain single-scale pass.  Empty/false = the plain protocol, on the
    unchanged fast path (device-side argmax, no NxC transfer).

    ``bf16_probs`` (config.eval_bf16_probs): the full-res and TTA protocols
    read whole softmax volumes back to the host — 22 MB/image in f32 at
    513²/21 classes, the measured bound of the full-res loop on a slow
    wire (BASELINE.md round-3, e2e row 12).  bf16 on the wire halves that;
    probabilities are widened back to f32 on host before any resize/
    averaging arithmetic, so the only effect is one bf16 rounding of each
    probability — argmax-after-resize tie noise (tested against f32).

    ``device_fullres`` (config.eval_device_fullres; the (max_h, max_w) =
    ``data.val_max_im_size`` canvas when enabled): the non-TTA full-res
    protocol resizes per-sample to native size and argmaxes ON DEVICE
    (``ops.warp.fullres_argmax`` — a separable weight-matmul warp, no
    gathers) and ships only the uint8 class map: ~21x fewer D2H bytes
    than the bf16 probability volume and zero per-image host resizes
    (the measured 1.5 imgs/s bound of the host path, BASELINE.md r4).
    Falls back to the host path per batch when an image exceeds the
    canvas, under TTA (the averaged probabilities already live on host),
    or multi-host.
    """
    import jax.numpy as jnp

    from .. import imaging
    from ..ops.metrics import miou_from_confusion
    from ..utils.helpers import fixed_resize

    def np_confusion(pred: np.ndarray, label: np.ndarray) -> np.ndarray:
        """Host-side (C, C) confusion, rows=true cols=pred — the ragged
        full-res twin of ops.metrics.confusion_matrix."""
        valid = label != ignore_index
        idx = label[valid].astype(np.int64) * nclass \
            + pred[valid].astype(np.int64)
        return np.bincount(idx, minlength=nclass * nclass) \
            .reshape(nclass, nclass)

    def fullres_confusion(probs: np.ndarray, gts_full: list) -> np.ndarray:
        """Per-sample: bilinear-resize class probabilities to the gt's
        native size, argmax, score — the standard DeepLab protocol (metric
        at ORIGINAL resolution, not the network's crop)."""
        out = np.zeros((nclass, nclass), np.int64)
        for j, gt in enumerate(gts_full):
            gt = np.asarray(gt)
            if gt.ndim == 3:
                gt = gt[..., 0]
            p = fixed_resize(probs[j], gt.shape[:2], flagval=imaging.LINEAR)
            out += np_confusion(np.argmax(p, axis=-1), gt)
        return out

    if len(set(tta_scales)) != len(tta_scales):
        raise ValueError(f"duplicate tta_scales {tta_scales} would "
                         "double-weight votes")
    n_dev = mesh.devices.size if mesh is not None else 1
    tta = bool(tta_flip or any(s != 1.0 for s in tta_scales))
    scale_list = list(tta_scales) if tta_scales else [1.0]
    conf = np.zeros((nclass, nclass), np.int64)
    confs: list = []   # device (C,C) counts; bulk-read at epoch end
    losses: list = []  # device scalars; same deferred-sync policy
    fullres_maps: list = []  # (device uint8 class maps, native gts);
    #                          scored host-side after the bulk readback
    n_samples = 0
    t0 = time.perf_counter()
    wire_dt = jnp.bfloat16 if bf16_probs else jnp.float32

    def read_probs(dev_probs) -> np.ndarray:
        """DEVICE softmax volume -> host f32, shipping ``wire_dt`` bytes.
        The cast must run ON DEVICE, before ``_local_rows`` does the
        device_get — casting the already-fetched numpy array would pay the
        bf16 rounding for zero wire savings."""
        host = _local_rows(dev_probs.astype(wire_dt))
        return host.astype(np.float32)

    def forward_probs(inp: np.ndarray, gt: np.ndarray):
        """One padded+sharded eval pass -> (softmax probs for the n real
        rows, loss).  Softmax runs on device; one D2H transfer."""
        padded, _ = pad_to_multiple({INPUT_KEY: inp, "crop_gt": gt}, n_dev)
        if mesh is not None:
            padded = shard_batch(mesh, padded)
        outputs, loss = eval_step(state, padded)
        probs = jax.nn.softmax(
            jnp.asarray(outputs[0]).astype(jnp.float32), axis=-1)
        return read_probs(probs)[: inp.shape[0]], loss

    for bi, batch in enumerate(loader):
        if max_batches is not None and bi >= max_batches:
            break
        if debug_asserts:
            semantic_batch_debug_asserts(batch, nclass, ignore_index)
        n = batch[INPUT_KEY].shape[0]
        n_samples += n
        if not tta:
            device_keys = {k: v for k, v in batch.items()
                           if k in (INPUT_KEY, "crop_gt")}
            padded, _ = pad_to_multiple(device_keys, n_dev)
            if mesh is not None:
                padded = shard_batch(mesh, padded)
            outputs, loss = eval_step(state, padded)
            losses.append(loss)
            # Padding repeats real samples; drop them from the counts by
            # scoring only the first n rows (host-local multi-host).
            if "gt_full" in batch:  # native-resolution protocol
                gts_full = [np.asarray(g) for g in
                            _as_list(batch["gt_full"], n)]
                hw = np.array([g.shape[:2] for g in gts_full], np.int32)
                # softmax on DEVICE either way (no host-side exp/sum over
                # B*H*W*C stalling the loop)
                probs_dev = jax.nn.softmax(
                    jnp.asarray(outputs[0]).astype(jnp.float32), axis=-1)
                if (device_fullres is not None
                        and jax.process_count() == 1
                        and hw[:, 0].max() <= device_fullres[0]
                        and hw[:, 1].max() <= device_fullres[1]):
                    # resize-to-native + argmax on device; only the uint8
                    # class map crosses the wire.  Padding rows get a 1x1
                    # target — never scored.
                    from ..ops.warp import fullres_argmax
                    hw_pad = np.ones((probs_dev.shape[0], 2), np.int32)
                    hw_pad[:n] = hw
                    # deferred: the uint8 maps stay on device until the
                    # epoch-end bulk readback (same policy as losses/confs)
                    # so the next batch's forward overlaps this one's warp
                    fullres_maps.append((fullres_argmax(
                        probs_dev, jnp.asarray(hw_pad),
                        tuple(device_fullres)), gts_full))
                else:
                    conf += fullres_confusion(read_probs(probs_dev)[:n],
                                              gts_full)
            elif jax.process_count() == 1:
                # crop-res fast path, single process: argmax + bincount on
                # DEVICE from the still-resident outputs — only the (C,C)
                # counts ever cross the wire.  (The previous _local_rows
                # round trip shipped the full B·H·W·C logits volume DOWN
                # and straight back UP per batch — 2×84 MB at 513²/21
                # classes, the measured 1 img/s semantic-val bound.)
                confs.append(_batch_confusion(
                    jnp.asarray(outputs[0])[:n],
                    jnp.asarray(padded["crop_gt"])[:n],
                    nclass, ignore_index))
            else:
                # multi-host: each process scores its own shard rows; the
                # (C,C) counts are allgather-summed at the end
                out0 = _local_rows(outputs[0])[:n]
                labels = _local_rows(padded["crop_gt"])[:n]
                confs.append(_batch_confusion(
                    jnp.asarray(out0), jnp.asarray(labels), nclass,
                    ignore_index))
            continue

        inp = np.asarray(batch[INPUT_KEY])
        gt = np.asarray(batch["crop_gt"])
        h, w = inp.shape[1:3]
        # the plain pass always runs — it is THE reported loss; it votes
        # only if 1.0 is a configured scale
        base_probs, loss = forward_probs(inp, gt)
        losses.append(loss)
        probs = np.zeros_like(base_probs)
        votes = 0
        for s in scale_list:
            if s == 1.0:
                inp_s, gt_s = inp, gt
                p = base_probs
            else:
                hs, ws = max(1, round(h * s)), max(1, round(w * s))
                inp_s = np.stack([
                    fixed_resize(im, (hs, ws), flagval=imaging.LINEAR)
                    for im in inp])
                gt_s = np.stack([
                    fixed_resize(g, (hs, ws), flagval=imaging.NEAREST)
                    for g in gt])
                p_s, _ = forward_probs(inp_s, gt_s)
                p = np.stack([
                    fixed_resize(pp, (h, w), flagval=imaging.LINEAR)
                    for pp in p_s])
            probs += p
            votes += 1
            if tta_flip:
                p_f, _ = forward_probs(inp_s[:, :, ::-1], gt_s[:, :, ::-1])
                p_f = p_f[:, :, ::-1]
                if s != 1.0:
                    p_f = np.stack([
                        fixed_resize(pp, (h, w), flagval=imaging.LINEAR)
                        for pp in p_f])
                probs += p_f
                votes += 1
        avg = probs / votes
        if "gt_full" in batch:  # TTA composes with the native-res protocol
            conf += fullres_confusion(avg, _as_list(batch["gt_full"], n))
        else:
            confs.append(_batch_confusion(
                jnp.asarray(avg), jnp.asarray(gt), nclass, ignore_index))

    with span("eval/readback"):  # the epoch-end bulk D2H sync, named
        if confs:  # one bulk readback for every deferred device value
            conf += np.sum(np.asarray(jax.device_get(confs), np.int64),
                           axis=0)
        for dev_maps, gts in fullres_maps:
            maps = np.asarray(jax.device_get(dev_maps))
            for j, g in enumerate(gts):
                if g.ndim == 3:
                    g = g[..., 0]
                conf += np_confusion(maps[j, :g.shape[0], :g.shape[1]], g)
        loss_sum = float(np.sum(jax.device_get(losses))) if losses else 0.0
    n_batches = len(losses)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        gathered = multihost_utils.process_allgather(
            jnp.asarray(conf, jnp.int64))
        conf = np.asarray(gathered).sum(axis=0)
        packed = np.array([loss_sum, n_batches, n_samples])
        summed = np.asarray(
            multihost_utils.process_allgather(packed)).sum(axis=0)
        loss_sum, n_batches = float(summed[0]), int(summed[1])
        n_samples = int(summed[2])

    out = miou_from_confusion(conf)
    out.update({
        "loss": loss_sum / max(n_batches, 1),
        "jaccard": out["miou"],        # uniform best-checkpoint gate key
        "n_samples": n_samples,
        "seconds": time.perf_counter() - t0,
    })
    return out
