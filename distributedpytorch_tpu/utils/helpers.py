"""Array helpers for the segmentation data path.

TPU-native re-design of the helper contract the reference consumes from its
missing ``dataloaders.helpers`` module (inventoried in SURVEY.md §2.4 from the
call sites in /root/reference/custom_transforms.py and
/root/reference/train_pascal.py:286-291).  Everything here is host-side
numpy/cv2: bounding boxes, mask crops and paste-backs are inherently
dynamic-shape, so they stay off the accelerator; the device only ever sees
fixed-shape (H, W, C) batches.

Conventions
-----------
* images/masks are numpy arrays in HWC (or HW) layout — the TPU-preferred
  layout; there is no CHW anywhere in this framework.
* a bbox is ``(x_min, y_min, x_max, y_max)`` with **inclusive** max coords,
  x = column, y = row.
"""

from __future__ import annotations

import json
import os
from typing import Sequence

import numpy as np

from .. import imaging


# ---------------------------------------------------------------------------
# bounding boxes / crops
# ---------------------------------------------------------------------------

def get_bbox(mask: np.ndarray, points=None, pad: int = 0, zero_pad: bool = False):
    """Tight bounding box of a binary mask (or point list), optionally padded.

    Equivalent of the ``helpers.get_bbox`` contract at reference
    custom_transforms.py:70,416 and train_pascal.py:287.

    Returns ``(x_min, y_min, x_max, y_max)`` (inclusive), or ``None`` for an
    empty mask.  With ``zero_pad=True`` the padded box may extend beyond the
    image (callers zero-pad the out-of-bounds region); otherwise it is clamped
    to the image bounds.
    """
    if points is not None:
        inds = np.flipud(np.asarray(points).T)  # rows = (y, x)
    else:
        inds = np.where(mask > 0)
        if inds[0].size == 0:
            return None
    h, w = mask.shape[:2]
    if zero_pad:
        x_min_bound, y_min_bound = -np.inf, -np.inf
        x_max_bound, y_max_bound = np.inf, np.inf
    else:
        x_min_bound, y_min_bound = 0, 0
        x_max_bound, y_max_bound = w - 1, h - 1

    x_min = max(inds[1].min() - pad, x_min_bound)
    y_min = max(inds[0].min() - pad, y_min_bound)
    x_max = min(inds[1].max() + pad, x_max_bound)
    y_max = min(inds[0].max() + pad, y_max_bound)
    return int(x_min), int(y_min), int(x_max), int(y_max)


def crop_from_bbox(img: np.ndarray, bbox, zero_pad: bool = False) -> np.ndarray:
    """Crop ``img`` to ``bbox``; out-of-bounds area (zero_pad) is filled with 0."""
    bounds = (0, 0, img.shape[1] - 1, img.shape[0] - 1)
    # Valid (in-image) part of the requested box.
    bbox_valid = (
        max(bbox[0], bounds[0]),
        max(bbox[1], bounds[1]),
        min(bbox[2], bounds[2]),
        min(bbox[3], bounds[3]),
    )
    if zero_pad:
        crop_shape = (bbox[3] - bbox[1] + 1, bbox[2] - bbox[0] + 1) + img.shape[2:]
        crop = np.zeros(crop_shape, dtype=img.dtype)
        offsets = (-bbox[0], -bbox[1])
    else:
        assert bbox == bbox_valid, "out-of-bounds crop requires zero_pad=True"
        crop_shape = (
            bbox_valid[3] - bbox_valid[1] + 1,
            bbox_valid[2] - bbox_valid[0] + 1,
        ) + img.shape[2:]
        crop = np.zeros(crop_shape, dtype=img.dtype)
        offsets = (-bbox_valid[0], -bbox_valid[1])

    inds_x = (bbox_valid[0] + offsets[0], bbox_valid[2] + offsets[0])
    inds_y = (bbox_valid[1] + offsets[1], bbox_valid[3] + offsets[1])
    crop[inds_y[0] : inds_y[1] + 1, inds_x[0] : inds_x[1] + 1, ...] = img[
        bbox_valid[1] : bbox_valid[3] + 1, bbox_valid[0] : bbox_valid[2] + 1, ...
    ]
    return crop


def crop_from_mask(
    img: np.ndarray, mask: np.ndarray, relax: int = 0, zero_pad: bool = False
) -> np.ndarray:
    """Crop ``img`` to the bbox of ``mask`` expanded by ``relax`` pixels.

    Equivalent of ``helpers.crop_from_mask`` (reference
    custom_transforms.py:359,366,436,443).  If the mask resolution differs from
    the image, the mask is nearest-resized to the image first.
    """
    if mask.shape[:2] != img.shape[:2]:
        mask = imaging.resize(
            mask, (img.shape[0], img.shape[1]), imaging.NEAREST
        )
    bbox = get_bbox(mask, pad=relax, zero_pad=zero_pad)
    if bbox is None:
        return np.zeros(img.shape, dtype=img.dtype)
    return crop_from_bbox(img, bbox, zero_pad=zero_pad)


def resize_interp_flag(arr: np.ndarray) -> int:
    """The reference's value-based resize-interpolation rule: nearest for
    {0,1}- or {0,255}-valued arrays (binary / void masks), cubic otherwise.
    Single owner — ``fixed_resize`` and the fused crop+resize path both
    dispatch through it, so the two can never disagree on a mask.
    (``ScaleNRotate``'s warp rule is the reference's OTHER rule — the mixed
    {0,1,255} set — and deliberately stays separate.)"""
    if ((arr == 0) | (arr == 1)).all() or ((arr == 0) | (arr == 255)).all():
        return imaging.NEAREST
    return imaging.CUBIC


def fixed_resize(
    sample: np.ndarray, resolution, flagval: int | None = None
) -> np.ndarray:
    """Resize to ``resolution`` (int => scale shortest side, tuple => (H, W)).

    Equivalent of ``helpers.fixed_resize`` (reference
    custom_transforms.py:186-193).  Interpolation default mirrors the
    reference's convention: nearest for {0,1}/{0,255}-valued masks, cubic
    otherwise.
    """
    if flagval is None:
        flagval = resize_interp_flag(sample)

    if isinstance(resolution, int):
        tmp = [resolution, resolution]
        tmp[int(np.argmax(sample.shape[:2]))] = int(
            round(resolution * np.max(sample.shape[:2]) / np.min(sample.shape[:2]))
        )
        resolution = tuple(tmp)

    if sample.ndim == 2 or (sample.ndim == 3 and sample.shape[2] == 3):
        sample = imaging.resize(sample, tuple(resolution), flagval)
    else:
        tmp = sample
        sample = np.zeros(
            np.append(resolution, tmp.shape[2]).astype(np.int32), dtype=np.float32
        )
        for ii in range(sample.shape[2]):
            sample[:, :, ii] = imaging.resize(
                tmp[:, :, ii], tuple(resolution), flagval
            )
    return sample


def crop2fullmask(
    crop_mask: np.ndarray,
    bbox,
    im_size: tuple[int, int],
    zero_pad: bool = False,
    relax: int = 0,
    mask_relax: bool = True,
    interpolation: int = imaging.CUBIC,
) -> np.ndarray:
    """Paste a crop-space prediction back into a full-image-sized mask.

    Inverse of :func:`crop_from_mask`; equivalent of the ``crop2fullmask``
    contract at reference train_pascal.py:290.  ``bbox`` must be the
    (already relax-padded) box the crop was taken from; with ``mask_relax``
    (default) predictions inside the relax border are zeroed after paste-back,
    so only the un-padded object box contributes to the full-image mask.
    """
    if zero_pad:
        # Mask the valid region in crop coordinates.
        bounds = (0, 0, im_size[1] - 1, im_size[0] - 1)
        bbox_valid = (
            max(bbox[0], bounds[0]),
            max(bbox[1], bounds[1]),
            min(bbox[2], bounds[2]),
            min(bbox[3], bounds[3]),
        )
        offsets = (-bbox[0], -bbox[1])
    else:
        bbox_valid = bbox
        offsets = (-bbox[0], -bbox[1])

    inds = tuple(map(int, (
        bbox_valid[0] + offsets[0],
        bbox_valid[1] + offsets[1],
        bbox_valid[2] + offsets[0],
        bbox_valid[3] + offsets[1],
    )))

    crop_h = bbox[3] - bbox[1] + 1
    crop_w = bbox[2] - bbox[0] + 1
    crop_mask = imaging.resize(
        crop_mask.astype(np.float32), (crop_h, crop_w), interpolation
    )

    result = np.zeros(im_size, dtype=crop_mask.dtype)
    result[bbox_valid[1] : bbox_valid[3] + 1, bbox_valid[0] : bbox_valid[2] + 1] = (
        crop_mask[inds[1] : inds[3] + 1, inds[0] : inds[2] + 1]
    )

    if mask_relax and relax > 0:
        # Shave the relax border: keep only the un-padded object box.
        inner = (
            max(bbox[0] + relax, 0),
            max(bbox[1] + relax, 0),
            min(bbox[2] - relax, im_size[1] - 1),
            min(bbox[3] - relax, im_size[0] - 1),
        )
        keep = np.zeros(im_size, dtype=bool)
        if inner[2] >= inner[0] and inner[3] >= inner[1]:
            keep[inner[1] : inner[3] + 1, inner[0] : inner[2] + 1] = True
        result = np.where(keep, result, 0)
    return result


# ---------------------------------------------------------------------------
# tensor / layout conversion
# ---------------------------------------------------------------------------

def tens2image(tens) -> np.ndarray:
    """Array (possibly batched / channel-first) -> HW(C) numpy image.

    Equivalent of the ``tens2image`` contract at reference
    train_pascal.py:286,288.  Accepts numpy or jax arrays of shape
    (H, W), (H, W, C), (C, H, W), (1, ...) — squeezes the leading batch dim
    and moves a small leading channel dim last.
    """
    arr = np.asarray(tens)
    if arr.ndim == 4:
        assert arr.shape[0] == 1, "tens2image expects batch size 1"
        arr = arr[0]
    if arr.ndim == 3 and arr.shape[0] in (1, 3, 4) and arr.shape[0] < arr.shape[1]:
        arr = np.moveaxis(arr, 0, -1)
    if arr.ndim == 3 and arr.shape[2] == 1:
        arr = arr[:, :, 0]
    return arr


# ---------------------------------------------------------------------------
# point heatmaps
# ---------------------------------------------------------------------------

def make_gaussian(size, center, sigma: float = 10.0) -> np.ndarray:
    """2-D gaussian bump of ``size``=(H, W) centered at ``center``=(x, y)."""
    x = np.arange(0, size[1], 1, float)
    y = np.arange(0, size[0], 1, float)[:, np.newaxis]
    x0, y0 = center[0], center[1]
    return np.exp(-4 * np.log(2) * ((x - x0) ** 2 + (y - y0) ** 2) / sigma**2)


def make_gt(
    target: np.ndarray,
    labels,
    sigma: float = 10.0,
    one_mask_per_point: bool = False,
) -> np.ndarray:
    """Gaussian heatmap image from a point list.

    Equivalent of the ``helpers.make_gt`` contract at reference
    custom_transforms.py:246 (used by the ExtremePoints transform).
    """
    h, w = target.shape[:2]
    labels = np.asarray(labels)
    if labels.ndim == 1:
        labels = labels[np.newaxis]
    if one_mask_per_point:
        gt = np.zeros((h, w, labels.shape[0]), dtype=np.float32)
        for ii in range(labels.shape[0]):
            gt[:, :, ii] = make_gaussian((h, w), center=labels[ii], sigma=sigma)
    else:
        from .. import native_ops
        if native_ops.enabled():  # ~3x the numpy loop on 512^2 crops
            return native_ops.gaussian_hm(labels[:, :2], (h, w), sigma)
        gt = np.zeros((h, w), dtype=np.float32)
        for ii in range(labels.shape[0]):
            gt = np.maximum(gt, make_gaussian((h, w), center=labels[ii], sigma=sigma))
    return gt.astype(np.float32)


# ---------------------------------------------------------------------------
# visualization
# ---------------------------------------------------------------------------

def color_mask_with_alpha(
    mask: np.ndarray, color: Sequence[float] = (1.0, 0.0, 0.0), transparency: float = 0.7
) -> np.ndarray:
    """Binary mask -> RGBA overlay image (contract of ``colorMaskWithAlpha``
    at reference train_pascal.py:265)."""
    out = np.zeros(mask.shape[:2] + (4,), dtype=np.float32)
    for c in range(3):
        out[..., c] = mask * color[c]
    out[..., 3] = mask * transparency
    return out


def overlay_mask(img: np.ndarray, mask: np.ndarray, alpha: float = 0.5,
                 color: Sequence[float] = (1.0, 0.0, 0.0)) -> np.ndarray:
    """Blend a binary mask over an RGB image in [0,1] (contract of
    ``helpers.overlay_mask`` at reference pascal.py:283)."""
    img = np.asarray(img, dtype=np.float32)
    mask = np.asarray(mask, dtype=np.float32)
    out = img.copy()
    for c in range(3):
        out[..., c] = np.where(mask > 0.5, (1 - alpha) * img[..., c] + alpha * color[c], img[..., c])
    return out


# ---------------------------------------------------------------------------
# reporting
# ---------------------------------------------------------------------------

def generate_param_report(path: str, params: dict) -> None:
    """Dump a hyperparameter dict to a text file (and a JSON sidecar).

    Equivalent of the ``generate_param_report`` contract at reference
    train_pascal.py:169.
    """
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        for k, v in params.items():
            f.write(f"{k}: {v}\n")
    with open(os.path.splitext(path)[0] + ".json", "w") as f:
        json.dump({k: str(v) for k, v in params.items()}, f, indent=2)
