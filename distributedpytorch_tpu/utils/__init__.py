"""Utilities: array helpers, logging, debug checks, profiling."""

from . import helpers, profiling, torch_interop
from .profiling import (StepTimer, annotate, device_memory_stats,
                        throughput, trace)

__all__ = ["StepTimer", "annotate", "device_memory_stats", "helpers",
           "profiling", "throughput", "torch_interop", "trace"]
