"""Utilities: array helpers, logging, debug checks, profiling."""

from . import helpers, profiling, torch_interop
from .profiling import StepTimer, annotate, throughput, trace

__all__ = ["StepTimer", "annotate", "helpers", "profiling", "throughput",
           "torch_interop", "trace"]
