"""Utilities: array helpers, logging, debug checks, profiling."""

from . import helpers, profiling
from .profiling import StepTimer, annotate, throughput, trace

__all__ = ["StepTimer", "annotate", "helpers", "profiling", "throughput",
           "trace"]
