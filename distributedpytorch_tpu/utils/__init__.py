"""Utilities: array helpers, logging, debug checks, profiling."""

from . import helpers

__all__ = ["helpers"]
