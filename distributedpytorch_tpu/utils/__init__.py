"""Utilities: array helpers, logging, debug checks, profiling."""

from . import compile_watchdog, helpers, profiling, torch_interop
from .compile_watchdog import CompileWatchdog, RecompileError
from .profiling import (StepTimer, annotate, device_memory_stats,
                        throughput, trace)

__all__ = ["CompileWatchdog", "RecompileError", "StepTimer", "annotate",
           "compile_watchdog", "device_memory_stats", "helpers",
           "profiling", "throughput", "torch_interop", "trace"]
