"""PyTorch checkpoint interop: state_dict <-> flax param trees.

The reference's training always warm-started from a torch checkpoint
(``torch.load('danet_1e-7_91.3.pth')``, reference train_pascal.py:103) and
saved ``state_dict`` files its users have accumulated.  This module is the
migration path: convert between torch ``state_dict`` tensors and this
framework's ``(params, batch_stats)`` trees, handling the layout conventions
that differ:

| tensor              | torch               | flax/here            |
|---------------------|---------------------|----------------------|
| conv kernel         | (O, I, kH, kW)      | (kH, kW, I, O)       |
| linear kernel       | (out, in)           | (in, out)            |
| batchnorm scale     | ``weight``          | ``scale``            |
| batchnorm stats     | ``running_mean/var``| batch_stats mean/var |

Keys are this framework's own flattened paths (slashes -> dots), e.g.
``head.pam.query.kernel``.  Checkpoints with other naming (torchvision,
PyTorch-Encoding) are bridged with a ``rename`` callable that maps their
keys onto ours — naming is the checkpoint owner's 10-line dictionary; the
layout/transpose work (the error-prone part) lives here.

No torch import is required for the conversion itself — state_dicts are
treated as mappings of numpy-convertible arrays; :func:`load_torch_file`
wraps ``torch.load`` for actual ``.pth`` files.
"""

from __future__ import annotations

from typing import Callable, Mapping

import numpy as np
from flax.traverse_util import flatten_dict, unflatten_dict

#: flax leaf -> torch suffix.  Both conv/dense ``kernel`` and batchnorm
#: ``scale`` become torch ``weight`` — no collision, a module has one or
#: the other at a given path.
_PARAM_SUFFIX = {"kernel": "weight", "scale": "weight", "bias": "bias"}
_STATS_SUFFIX = {"mean": "running_mean", "var": "running_var"}


def _to_torch_layout(path: tuple[str, ...], arr: np.ndarray) -> np.ndarray:
    leaf = path[-1]
    if leaf == "kernel":
        if arr.ndim == 4:                       # conv HWIO -> OIHW
            return np.transpose(arr, (3, 2, 0, 1))
        if arr.ndim == 2:                       # dense (in,out) -> (out,in)
            return arr.T
    return arr


def _from_torch_layout(path: tuple[str, ...], arr: np.ndarray,
                       like: np.ndarray) -> np.ndarray:
    leaf = path[-1]
    if leaf == "kernel":
        if like.ndim == 4:
            arr = np.transpose(arr, (2, 3, 1, 0))   # OIHW -> HWIO
        elif like.ndim == 2:
            arr = arr.T
    if arr.shape != like.shape:
        raise ValueError(
            f"shape mismatch at {'.'.join(path)}: checkpoint "
            f"{arr.shape} vs model {like.shape}")
    return arr.astype(like.dtype)


def _torch_key(path: tuple[str, ...], is_stats: bool) -> str:
    *mods, leaf = path
    suffix = _STATS_SUFFIX if is_stats else _PARAM_SUFFIX
    return ".".join((*mods, suffix.get(leaf, leaf)))


def params_to_torch_state_dict(params, batch_stats=None) -> dict:
    """Export ``(params, batch_stats)`` as a torch-convention state_dict
    (numpy arrays; pass through ``torch.tensor`` to save with torch)."""
    out: dict[str, np.ndarray] = {}
    for path, arr in flatten_dict(params).items():
        out[_torch_key(path, False)] = _to_torch_layout(
            path, np.asarray(arr))
    for path, arr in flatten_dict(batch_stats or {}).items():
        out[_torch_key(path, True)] = np.asarray(arr)
    return out


def torch_state_dict_to_params(
    state_dict: Mapping[str, np.ndarray],
    params_template,
    batch_stats_template=None,
    rename: Callable[[str], str | None] | None = None,
    allow_missing: bool = False,
    allow_unused: bool = False,
):
    """Import a torch state_dict into ``(params, batch_stats)`` trees shaped
    like the templates (e.g. from ``model.init``).

    Templates only need ``.shape``/``.ndim``/``.dtype`` per leaf —
    ``jax.ShapeDtypeStruct`` trees work, so callers with sharded live states
    never have to gather arrays to host just to describe shapes.

    ``rename`` maps checkpoint keys to this framework's keys (return None to
    drop a key — classifier heads, num_batches_tracked, ...).  Two
    *independent* escape hatches (deliberately not one flag — a rename typo
    shows up as BOTH a missing leaf and an unused key, and partial warm
    starts must not mask it):

    * ``allow_missing`` — template leaves absent from the checkpoint (or
      present with a mismatched shape, e.g. a re-sized classifier head)
      keep their template values (the partial warm start);
    * ``allow_unused`` — checkpoint keys matching no template leaf are
      ignored instead of raising.
    """
    available: dict[str, np.ndarray] = {}
    for k, v in state_dict.items():
        k2 = rename(k) if rename else k
        if k2 is not None:
            available[k2] = np.asarray(v)

    used = set()

    def fill(template, is_stats: bool):
        flat = flatten_dict(template)
        out = {}
        for path, like in flat.items():
            key = _torch_key(path, is_stats)
            if key in available:
                try:
                    out[path] = _from_torch_layout(path, available[key],
                                                   like)
                    used.add(key)
                except ValueError:
                    # shape mismatch (e.g. a re-sized head): under a partial
                    # warm start keep the template leaf; the checkpoint key
                    # stays un-"used" so allow_unused still governs it.
                    if not allow_missing:
                        raise
                    out[path] = like
            elif allow_missing:
                out[path] = like
            else:
                raise KeyError(
                    f"checkpoint missing {key!r} (template leaf "
                    f"{'.'.join(path)}); pass allow_missing=True for a "
                    "partial warm start")
        return unflatten_dict(out)

    new_params = fill(params_template, False)
    new_stats = (fill(batch_stats_template, True)
                 if batch_stats_template is not None else None)
    leftovers = set(available) - used
    if leftovers and not allow_unused:
        raise KeyError(f"checkpoint keys unmatched by the model: "
                       f"{sorted(leftovers)[:8]}{'...' if len(leftovers) > 8 else ''}")
    return (new_params, new_stats) if new_stats is not None else new_params


def load_torch_file(path: str) -> dict:
    """``torch.load`` a ``.pth`` into a numpy state_dict (CPU, weights only;
    strips a ``module.`` DataParallel prefix — the reference wrapped its net
    in ``nn.DataParallel`` before saving, train_pascal.py:92,301-304)."""
    import torch

    raw = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(raw, dict) and "state_dict" in raw:
        raw = raw["state_dict"]
    out = {}
    for k, v in raw.items():
        if k.startswith("module."):
            k = k[len("module."):]
        if k.endswith("num_batches_tracked"):
            continue
        out[k] = v.detach().numpy() if hasattr(v, "detach") else np.asarray(v)
    return out


# ---------------------------------------------------------------------------
# torchvision ResNet checkpoints (ImageNet-pretrained backbones)
# ---------------------------------------------------------------------------
#
# The reference's model lineage started from an ImageNet-pretrained ResNet
# (PyTorch-Encoding's DANet builds on one; the warm-start .pth at reference
# train_pascal.py:103 descends from it, with the stem widened to 4 input
# channels).  torchvision's ResNet state_dicts are the canonical source of
# those backbones, so their naming gets a ready-made bridge here:
#
#   torchvision                      this framework
#   conv1.weight                     backbone.Conv_0.weight
#   bn1.*                            backbone.BatchNorm_0.*
#   layer{s}.{i}.conv{k}.weight      backbone.<Block>_{flat}.Conv_{k-1}.weight
#   layer{s}.{i}.bn{k}.*             backbone.<Block>_{flat}.BatchNorm_{k-1}.*
#   layer{s}.{i}.downsample.0/1.*    backbone.<Block>_{flat}.Conv_K/BatchNorm_K.*
#   fc.*                             (dropped — no classifier here)
#
# where <Block> is BottleneckBlock (50/101/152) or BasicBlock (18/34), flat
# is the global block index (our blocks number across stages), and K is the
# block's downsample slot (3 for bottleneck, 2 for basic).

def is_torchvision_resnet(state_dict: Mapping[str, np.ndarray]) -> bool:
    """Heuristic: torchvision ResNet naming, not this framework's export."""
    keys = state_dict.keys()
    return ("conv1.weight" in keys
            and any(k.startswith("layer1.0.conv") for k in keys)
            and not any("Block_" in k for k in keys))


def torchvision_resnet_rename(depth: int, prefix: str = "backbone"
                              ) -> Callable[[str], str | None]:
    """Key-rename callable for ``torch_state_dict_to_params`` importing a
    torchvision ResNet-``depth`` state_dict into the ``prefix`` submodule."""
    from ..models.resnet import BOTTLENECK_DEPTHS, RESNET_DEPTHS

    counts = RESNET_DEPTHS[depth]
    bottleneck = depth in BOTTLENECK_DEPTHS
    block = "BottleneckBlock" if bottleneck else "BasicBlock"
    down_slot = 3 if bottleneck else 2
    stage_base = [sum(counts[:s]) for s in range(len(counts))]

    def rename(key: str) -> str | None:
        parts = key.split(".")
        if parts[0] == "fc" or parts[-1] == "num_batches_tracked":
            return None
        if parts[0] == "conv1":
            return f"{prefix}.Conv_0.{parts[1]}"
        if parts[0] == "bn1":
            return f"{prefix}.BatchNorm_0.{parts[1]}"
        if parts[0].startswith("layer"):
            stage = int(parts[0][len("layer"):]) - 1
            flat = stage_base[stage] + int(parts[1])
            mod = f"{prefix}.{block}_{flat}"
            if parts[2] == "downsample":
                kind = "Conv" if parts[3] == "0" else "BatchNorm"
                return f"{mod}.{kind}_{down_slot}.{parts[4]}"
            if parts[2].startswith("conv"):
                return f"{mod}.Conv_{int(parts[2][4:]) - 1}.{parts[3]}"
            if parts[2].startswith("bn"):
                return f"{mod}.BatchNorm_{int(parts[2][2:]) - 1}.{parts[3]}"
        return key  # unknown keys surface through allow_unused

    return rename


def inflate_stem_channels(state_dict: Mapping[str, np.ndarray],
                          in_channels: int,
                          key: str = "conv1.weight") -> dict:
    """Zero-pad the stem conv's input channels (OIHW dim 1) to
    ``in_channels`` — the standard 3->4-channel inflation for adding a
    guidance channel to an RGB-pretrained backbone (the extra channel starts
    contributing zero; RGB filters are untouched).  The reference's 4-channel
    DANet stem was produced by exactly this kind of external surgery
    (SURVEY.md §2.4)."""
    out = dict(state_dict)
    w = np.asarray(out[key])
    have = w.shape[1]
    if have > in_channels:
        raise ValueError(f"stem has {have} input channels; cannot shrink "
                         f"to {in_channels}")
    if have < in_channels:
        pad = np.zeros((w.shape[0], in_channels - have) + w.shape[2:],
                       dtype=w.dtype)
        out[key] = np.concatenate([w, pad], axis=1)
    return out


def torchvision_resnet_depth(state_dict: Mapping[str, np.ndarray]) -> int:
    """Infer the ResNet depth a torchvision state_dict was saved from, by
    stage block counts + block type.  Raises on unrecognized layouts —
    importing a wrong-depth checkpoint partially would silently produce a
    half-pretrained backbone."""
    from ..models.resnet import BOTTLENECK_DEPTHS, RESNET_DEPTHS

    counts = []
    for s in (1, 2, 3, 4):
        n = 0
        while f"layer{s}.{n}.conv1.weight" in state_dict:
            n += 1
        counts.append(n)
    bottleneck = any(".conv3." in k for k in state_dict)
    for depth, c in RESNET_DEPTHS.items():
        if (tuple(c) == tuple(counts)
                and (depth in BOTTLENECK_DEPTHS) == bottleneck):
            return depth
    raise ValueError(
        f"unrecognized torchvision ResNet layout: stage counts {counts}, "
        f"{'bottleneck' if bottleneck else 'basic'} blocks")
