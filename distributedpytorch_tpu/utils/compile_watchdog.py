"""Compile watchdog: count XLA compilations, fail on steady-state recompiles.

The runtime complement of the :mod:`analysis` (jaxlint) static rules: the
linter catches recompile *hazards* in the source; this context manager
catches the recompiles that actually happen.  A steady-state train step
that recompiles (shape drift from a ragged batch, a donation mismatch, a
Python branch on a tracer) costs seconds-to-minutes of XLA work per
occurrence and is invisible in wall-clock-only logging — three rounds of
this repo's perf work (VERDICT r3) chased overheads that a compile counter
would have attributed instantly.

Built on ``jax.log_compiles()``: with it enabled, every in-memory jit-cache
miss logs ``Compiling <fn> with global shapes and types ...`` from the
lowering path — BEFORE the persistent compilation cache is consulted, so
the count is cache-state-independent (a persistent-cache hit is still a
retrace + relink the step loop should not be paying).

>>> with CompileWatchdog(match="step_fn", max_compiles=1) as wd:
...     for batch in batches:
...         state, loss = step(state, batch)
>>> wd.counts            # {"step_fn": 1}

``max_compiles`` arms the watchdog: leaving the block raises
:class:`RecompileError` if any single matching function compiled more than
that many times.  Without it the watchdog only counts.
"""

from __future__ import annotations

import logging
import re
from collections import Counter

import jax

#: the lowering-path log line both pjit and pmap emit per compilation
_COMPILE_RE = re.compile(r"Compiling ([^\s]+) with global shapes")


class RecompileError(AssertionError):
    """A watched function compiled more often than the declared budget."""


class _CountingHandler(logging.Handler):
    def __init__(self, watchdog: "CompileWatchdog"):
        super().__init__(level=logging.DEBUG)
        self._watchdog = watchdog

    def emit(self, record: logging.LogRecord) -> None:
        try:
            m = _COMPILE_RE.search(record.getMessage())
        except Exception:   # a foreign record whose args don't format
            return
        if m is not None:
            self._watchdog._record(m.group(1))


class CompileWatchdog:
    """Count XLA compilations per jitted-function name within a region.

    ``match``: substring filter on the jitted function's name — only
    matching compilations count (and only they can trip the budget), so a
    step-loop watchdog isn't tripped by unrelated one-off jits (jnp.zeros,
    metrics) compiling nearby.  ``max_compiles``: per-function budget
    enforced at block exit (a primary exception propagating out of the
    block takes precedence — the watchdog never masks it).

    ``mute_jax_logs=False`` keeps the ``jax`` logger propagating while the
    watchdog is active.  The default pause is right for a short test
    region (log_compiles' WARNING spam would flood the console), but a
    LONG-LIVED watchdog — the serve batcher holds one open for the
    service's lifetime — would otherwise silence every jax warning/error
    process-wide for as long as it runs.
    """

    def __init__(self, match: str | None = None,
                 max_compiles: int | None = None,
                 mute_jax_logs: bool = True):
        self.match = match
        self.max_compiles = max_compiles
        self.mute_jax_logs = mute_jax_logs
        self.counts: Counter[str] = Counter()
        self._handler: _CountingHandler | None = None
        self._log_ctx = None

    def _record(self, name: str) -> None:
        if self.match is None or self.match in name:
            self.counts[name] += 1

    @property
    def total(self) -> int:
        return sum(self.counts.values())

    def __enter__(self) -> "CompileWatchdog":
        self._handler = _CountingHandler(self)
        # the "Compiling ..." records come from jax._src.* child loggers;
        # one handler on the package root sees them all via propagation.
        # Propagation above "jax" is paused so log_compiles' WARNING spam
        # doesn't flood the console of every watched test.
        jax_logger = logging.getLogger("jax")
        jax_logger.addHandler(self._handler)
        self._prev_propagate = jax_logger.propagate
        if self.mute_jax_logs:
            jax_logger.propagate = False
        self._log_ctx = jax.log_compiles()
        self._log_ctx.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._log_ctx is not None:
            self._log_ctx.__exit__(exc_type, exc, tb)
            self._log_ctx = None
        if self._handler is not None:
            jax_logger = logging.getLogger("jax")
            jax_logger.removeHandler(self._handler)
            jax_logger.propagate = self._prev_propagate
            self._handler = None
        if exc_type is not None:
            return  # never mask the primary failure
        if self.max_compiles is not None:
            over = {name: n for name, n in self.counts.items()
                    if n > self.max_compiles}
            if over:
                detail = ", ".join(f"{k} x{v}" for k, v in over.items())
                raise RecompileError(
                    f"steady-state recompile: {detail} (budget "
                    f"{self.max_compiles} per function) — look for shape "
                    "drift in the batch, donation mismatches, or Python "
                    "control flow on tracers (run jaxlint)")
