"""Tracing / profiling utilities.

The reference's only performance instrumentation was wall-clock epoch timing
with ``timeit.default_timer`` printed to stdout (reference
train_pascal.py:12,181,307-308) — no profiler, no NVTX, no per-step numbers
(SURVEY.md §5.1).  TPU-native replacements:

* :func:`trace` — context manager around ``jax.profiler`` writing a
  TensorBoard-loadable XPlane trace (op-level device timeline, HBM usage,
  fusion view) for any code region;
* :class:`StepTimer` — per-step *latency* timing (block on a representative
  output, read the clock, skip warmup).  Measures launch + sync round-trip,
  which is the right number for interactive latency but NOT for throughput —
  on remote-tunneled devices ``block_until_ready`` can even be a no-op, so
  for throughput always use :func:`throughput` instead;
* :func:`annotate` — named ``TraceAnnotation`` regions that show up inside
  the device trace (host-side markers).
"""

from __future__ import annotations

import contextlib
import math
import statistics
import time

import jax


@contextlib.contextmanager
def trace(log_dir: str):
    """Profile the enclosed region into ``log_dir`` (XPlane format;
    `tensorboard --logdir` or xprof reads it)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region visible in profiler timelines."""
    return jax.profiler.TraceAnnotation(name)


def throughput(step_fn, steps: int, warmup: int = 2,
               items_per_step: int | None = None) -> dict:
    """Steady-state throughput of ``step_fn() -> outputs``.

    Dispatches all ``steps`` calls and synchronizes ONCE on the final
    output — measuring device throughput with async dispatch fully
    pipelined.  This is the right shape for benchmarks: per-step host
    syncs (``StepTimer``) measure launch+round-trip latency, which on a
    remote-tunneled device can wildly misstate device throughput in either
    direction.  Warmup steps (compile) are synchronized and excluded.

    Synchronization is ``jax.device_get`` (actual value materialization),
    NOT ``block_until_ready``: on remote-tunneled platforms the latter can
    return before the computation exists anywhere (observed: 20 un-run train
    steps "ready" in 0.000s).  Make ``step_fn`` return something whose value
    depends on everything you want timed (e.g. the loss AND a parameter
    leaf, so the optimizer update is provably complete).
    """
    out = None
    for _ in range(warmup):
        out = step_fn()
    jax.device_get(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = step_fn()
    jax.device_get(out)
    dt = time.perf_counter() - t0
    res = {"steps": steps, "total_s": dt, "mean_s": dt / steps}
    if items_per_step:
        res["items_per_sec"] = items_per_step * steps / dt
    return res


def percentile(values, q: float) -> float:
    """Nearest-rank percentile of ``values`` (q in [0, 100]).

    The latency-reporting convention: p99 is an actually-observed sample,
    never an interpolation between two samples (an interpolated tail value
    can be a latency no request ever experienced).  Shared by
    :class:`StepTimer` and the serve metrics (serve/metrics.py).
    """
    if not values:
        raise ValueError("percentile of no samples")
    if not 0.0 <= q <= 100.0:
        raise ValueError(f"q must be in [0, 100], got {q}")
    ordered = sorted(values)
    if q == 0.0:
        return ordered[0]
    rank = math.ceil(q / 100.0 * len(ordered))
    return ordered[min(len(ordered), rank) - 1]


class StepTimer:
    """Accumulates per-step wall times, async-dispatch-aware.

    >>> timer = StepTimer(warmup=2)
    >>> for batch in loader:
    ...     state, loss = step(state, batch)
    ...     timer.tick(loss)          # blocks on loss, records dt
    >>> timer.summary()               # {'mean_s': ..., 'p50_s': ..., ...}

    ``sync="device_get"`` opts into materializing the outputs instead of
    ``block_until_ready`` — the remote-tunneled-backend mode where
    ``block_until_ready`` can be a no-op (see :func:`throughput`'s
    rationale); the default stays the cheaper local-device block.
    """

    def __init__(self, warmup: int = 2, sync: str = "block"):
        if sync not in ("block", "device_get"):
            raise ValueError(f"sync must be 'block' or 'device_get', "
                             f"got {sync!r}")
        self.warmup = warmup
        self.sync = sync
        self._seen = 0
        self._last: float | None = None
        self.times: list[float] = []

    def tick(self, *outputs) -> float | None:
        """Record one step boundary; pass any step outputs to block on."""
        if outputs:
            if self.sync == "device_get":
                jax.device_get(outputs)
            else:
                jax.block_until_ready(outputs)
        now = time.perf_counter()
        dt = None
        if self._last is not None:
            self._seen += 1
            if self._seen > self.warmup:
                dt = now - self._last
                self.times.append(dt)
        self._last = now
        return dt

    def summary(self, items_per_step: int | None = None) -> dict:
        if not self.times:
            return {"steps": 0}
        out = {
            "steps": len(self.times),
            "mean_s": statistics.fmean(self.times),
            "p50_s": statistics.median(self.times),
            "p99_s": percentile(self.times, 99.0),
            "min_s": min(self.times),
            "max_s": max(self.times),
        }
        if items_per_step:
            out["items_per_sec"] = items_per_step / out["mean_s"]
        return out


def device_memory_stats(device=None) -> dict:
    """HBM usage of one device, normalized to a small stable dict.

    Returns ``{bytes_in_use, peak_bytes_in_use, bytes_limit}`` (zeros for
    backends that expose no stats, e.g. CPU) — the TPU-side answer to "does
    this config fit", which the reference left to CUDA OOMs and hand-tuned
    batch sizes (SURVEY.md §2.5 note on activation memory).
    """
    device = device or jax.devices()[0]
    stats = getattr(device, "memory_stats", lambda: None)() or {}
    return {
        "bytes_in_use": int(stats.get("bytes_in_use", 0)),
        "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
        "bytes_limit": int(stats.get("bytes_limit", 0)),
    }
