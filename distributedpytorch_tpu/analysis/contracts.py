"""Compile contracts: checked-in pins of what each hot program compiles to.

A contract is a small platform-keyed JSON file under ``tests/contracts/``
recording what :func:`ir.audit` observed for one program — collective
counts per mesh axis (jaxpr) and per HLO op (compiled), output
shapes/dtypes, donation declaration + aliasing effectiveness, baked
constant totals, an XLA FLOPs estimate, and the per-class finding
counts.  ``jaxaudit check`` re-traces the live program and fails on
drift; ``jaxaudit update`` regenerates the pins after a REVIEWED change.

Why platform-keyed (``<program>.<platform><ndevices>.json``): the same
Python builds a different program per backend and topology — GSPMD
inserts different collectives for 8 devices than for 1, donation aliases
on some backends and not others, FLOPs counts differ with fused ops.  A
single un-keyed contract would be wrong everywhere but the machine that
wrote it.  The checked-in set pins the canonical tier-1 topology (the
8-device virtual CPU mesh of tests/conftest.py); ``jaxaudit`` pins the
same topology when run standalone, so the gate is deterministic on any
dev box.  TPU contracts are generated the same way on a chip
(``JAX_PLATFORMS=tpu jaxaudit update``).

Drift semantics, per field:

* collectives / outputs / donation / finding counts — exact: one stray
  psum or a lost ``donate_argnums`` IS the regression this gate exists
  to catch;
* constant bytes — bound: growth past 5% fails (const bloat), shrinkage
  passes (an improvement should not fail CI; update the pin when you
  land it);
* FLOPs — banded at ±10%: the estimate wobbles with XLA fusion choices,
  but a silently doubled step does not hide in a 10% band.
"""

from __future__ import annotations

import json
import os
import sys

#: the canonical audited set: the trainer's two steps, two serve buckets
#: (the bucket ladder's ends), and the session-serving encode/decode
#: split at the interactive click shape (b1).  Any ``serve_forward_b<N>``
#: name is buildable on demand (``--programs serve_forward_b4``).
PROGRAM_NAMES = ("train_step", "train_step_bf16",
                 "train_step_dp_tp", "train_step_dp_zero1",
                 "train_step_dp_tp_zero1", "eval_step",
                 "serve_forward_b1", "serve_forward_b8",
                 "serve_forward_int8_b1", "serve_forward_int8_b8",
                 "encode_step", "decode_step", "decode_int8")

#: the plan-built canonical programs: ``train_step_<strategy>`` for each
#: resolvable non-trivial rung of parallel/plan.py's ladder (plain dp IS
#: ``train_step``).  Their contracts additionally pin the per-mesh-axis
#: HLO collective inventory (``collectives.hlo_axes``).
PLAN_PROGRAM_NAMES = ("train_step_dp_tp", "train_step_dp_zero1",
                      "train_step_dp_tp_zero1")

_PROGRAM_HELP = {
    "train_step": "jitted mesh train step (fwd+loss+bwd+SGD, donated)",
    "train_step_bf16": "mixed-precision (train.precision=bfloat16) train "
                       "step with bucketed overlapped gradient reduce — "
                       "JA002 audited against the policy's declared "
                       "accumulation points",
    "train_step_dp_tp": "plan dp_tp: params/momentum sharded over the "
                        "model axis — contract pins per-mesh-axis "
                        "collectives (model-axis counts nonzero)",
    "train_step_dp_zero1": "plan dp_zero1: optimizer state sharded over "
                           "data — per-mesh-axis collectives pinned",
    "train_step_dp_tp_zero1": "plan dp_tp_zero1: TP x ZeRO-1 composed "
                              "on one spec tree — per-mesh-axis "
                              "collectives pinned",
    "eval_step": "jitted mesh eval step (fwd+loss)",
    "serve_forward_b1": "serve bucket forward, batch 1",
    "serve_forward_b8": "serve bucket forward, batch 8",
    "serve_forward_int8_b1": "int8-quantized serve forward, batch 1 — "
                             "JA002 audited against the QuantPolicy "
                             "dequant allowlist; const bytes pin the "
                             "~4x int8 shrink",
    "serve_forward_int8_b8": "int8-quantized serve forward, batch 8",
    "decode_int8": "int8-quantized session decode (features + guidance "
                   "-> mask probabilities, b1)",
    "encode_step": "session serving: RGB crop -> backbone features "
                   "(guidance_inject='head', b1)",
    "decode_step": "session serving: features + guidance -> mask "
                   "probabilities (b1)",
}

#: relative FLOPs band and constant-bytes growth bound (see module doc)
FLOPS_RTOL = 0.10
CONST_BYTES_GROWTH = 0.05

#: canonical audited config: small enough that trace+compile fits the
#: tier-1 budget, mesh-sharded so the collective structure is real
_AUDIT_HW = (64, 64)
_AUDIT_CHANNELS = 4


def platform_key(platform: str | None = None,
                 n_devices: int | None = None) -> str:
    """``cpu8`` / ``tpu4`` — the contract filename key."""
    if platform is None or n_devices is None:
        import jax

        devs = jax.devices()
        platform = platform or devs[0].platform
        n_devices = n_devices or len(devs)
    return f"{platform}{n_devices}"


def default_contracts_dir() -> str:
    """``<repo>/tests/contracts`` for a source checkout (the layout this
    repo ships); installed deployments pass ``--contracts-dir``."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg), "tests", "contracts")


def contract_path(contracts_dir: str, program: str, key: str) -> str:
    return os.path.join(contracts_dir, f"{program}.{key}.json")


# ----------------------------------------------------------------- contracts

def contract_from_report(report: dict) -> dict:
    """The pinned subset of an :func:`ir.audit` report.

    A report stamped ``overlap_expected`` (the bucketed train step)
    additionally pins ``require_async_starts`` on MULTI-DEVICE TPU
    platform keys: ``check`` then demands at least one async ``-start``
    collective in the live HLO — the comm/compute-overlap regression
    gate.  Single-chip TPU keys never pin it (XLA deletes the
    singleton-group all-reduces — there is nothing to overlap).  CPU
    keys never pin it (XLA:CPU lowers every collective synchronously); there
    the overlap structure is gated by the exact psum-bucket counts in
    the jaxpr inventory instead (a step silently regressing to
    replicated zeroes them — the same failure class, caught at the
    jaxpr level)."""
    out = {
        "program": report["program"],
        "platform_key": platform_key(report["platform"],
                                     report["n_devices"]),
        "collectives": report["collectives"],
        "outputs": list(report["outputs"]),
        "donation": {
            "declared_args": report["donation"]["declared_args"],
            "effective": report["donation"]["effective"],
        },
        "constants": {
            "count": report["constants"]["count"],
            "total_bytes": report["constants"]["total_bytes"],
        },
        "flops": report["flops"],
        "finding_counts": dict(report["finding_counts"]),
    }
    if (report.get("overlap_expected") and report["platform"] == "tpu"
            and int(report.get("n_devices") or 0) > 1):
        # single-chip meshes have nothing to overlap: XLA deletes the
        # singleton-group all-reduces, so a tpu1-keyed contract pinning
        # async starts would self-drift forever — only multi-device
        # topologies can (and must) show `-start` forms
        out["require_async_starts"] = True
    return out


def diff_contract(contract: dict, report: dict) -> list[str]:
    """Human-readable drift lines; empty list == the live program still
    matches its pins."""
    drift: list[str] = []

    # "hlo_axes" is the per-mesh-axis inventory plan-built programs pin
    # (ir.mesh_axis_collective_counts): a 2-D-mesh step regressing to
    # replicated zeroes its model-axis counts and fails here.
    # "hlo_schedule" is its ordered twin (jaxguard's JG002 substrate):
    # same counts in a different issue order still deadlocks a pod.
    # Contracts that predate a level simply don't pin it and are
    # skipped — which is how new levels land additively without
    # invalidating every checked-in contract.
    for level in ("jaxpr", "hlo", "hlo_axes", "hlo_schedule"):
        want = (contract.get("collectives") or {}).get(level)
        have = (report.get("collectives") or {}).get(level)
        if want is None:
            continue
        if have is None:
            drift.append(f"collectives[{level}]: live inventory "
                         f"unavailable (contract pins {want})")
        elif want != have:
            drift.append(f"collectives[{level}]: contract {want} "
                         f"!= live {have}")

    if contract.get("require_async_starts"):
        from .ir import async_start_count

        hlo = (report.get("collectives") or {}).get("hlo")
        n_async = async_start_count(hlo)
        if n_async == 0:
            drift.append(
                "async overlap: contract requires async -start "
                "collectives (> 0) but the live HLO lowered "
                f"{'none' if hlo else 'no collectives at all'} — the "
                "bucketed reduce re-serialized (or the step regressed "
                "to replicated)")

    want_out, have_out = contract["outputs"], report["outputs"]
    if want_out != have_out:
        if len(want_out) != len(have_out):
            drift.append(f"outputs: contract has {len(want_out)}, "
                         f"live has {len(have_out)}")
        else:
            i = next(i for i, (a, b) in enumerate(zip(want_out, have_out))
                     if a != b)
            drift.append(f"outputs: #{i} contract {want_out[i]} != "
                         f"live {have_out[i]}")

    dw, dh = contract["donation"], report["donation"]
    if dw["declared_args"] != dh["declared_args"]:
        drift.append(f"donation: contract declares "
                     f"{dw['declared_args']} donated arg(s), live "
                     f"declares {dh['declared_args']}")
    if dw.get("effective") != dh.get("effective"):
        drift.append(f"donation: aliasing effective={dh.get('effective')} "
                     f"(contract pins {dw.get('effective')})")

    cw, ch = contract["constants"], report["constants"]
    if cw["count"] != ch["count"]:
        drift.append(f"constants: contract pins {cw['count']}, live has "
                     f"{ch['count']}")
    limit = cw["total_bytes"] * (1 + CONST_BYTES_GROWTH) + 1024
    if ch["total_bytes"] > limit:
        drift.append(f"constants: {ch['total_bytes']} bytes baked into "
                     f"the trace, past the pinned "
                     f"{cw['total_bytes']} (+{CONST_BYTES_GROWTH:.0%})")

    fw, fh = contract.get("flops"), report.get("flops")
    if fw:
        if not fh:
            drift.append(f"flops: live estimate unavailable (contract "
                         f"pins {fw:.3g})")
        elif abs(fh - fw) / fw > FLOPS_RTOL:
            drift.append(f"flops: live {fh:.4g} outside ±{FLOPS_RTOL:.0%} "
                         f"of pinned {fw:.4g}")

    for cls, want_n in contract["finding_counts"].items():
        have_n = report["finding_counts"].get(cls, 0)
        if have_n != want_n:
            drift.append(f"findings[{cls}]: {have_n} (contract pins "
                         f"{want_n})")
    return drift


def save_contract(contract: dict, contracts_dir: str) -> str:
    os.makedirs(contracts_dir, exist_ok=True)
    path = contract_path(contracts_dir, contract["program"],
                         contract["platform_key"])
    with open(path, "w", encoding="utf-8") as f:
        json.dump(contract, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


# ------------------------------------------------------------- schema police

#: the one declared schema every checked-in contract file must satisfy —
#: a hand-edited contract should fail HERE (loudly, naming the key),
#: not silently pass `check` because a typo'd key is never compared
_PLATFORM_KEY_RE = r"^[a-z]+\d+$"
_RLE_RE = r"^[a-z-]+(\*\d+)?$"
_PROGRAM_KEYS_REQUIRED = frozenset({
    "program", "platform_key", "collectives", "outputs", "donation",
    "constants", "flops", "finding_counts",
})
_PROGRAM_KEYS_OPTIONAL = frozenset({"require_async_starts"})
_COLLECTIVES_LEVELS = frozenset({"jaxpr", "hlo", "hlo_axes",
                                 "hlo_schedule"})
_SCHEDULE_SET_KEYS = frozenset({
    "kind", "program", "platform_key", "schedules", "divergent_pairs",
})


def _is_count_map(v, depth: int) -> bool:
    """``{str: int}`` (depth 1) or ``{str: {str: int}}`` (depth 2),
    counts non-negative."""
    if not isinstance(v, dict):
        return False
    for k, x in v.items():
        if not isinstance(k, str):
            return False
        if depth > 1:
            if not _is_count_map(x, depth - 1):
                return False
        elif not (isinstance(x, int) and not isinstance(x, bool)
                  and x >= 0):
            return False
    return True


def _is_schedule_map(v) -> bool:
    """``{axis: ["op" | "op*N", ...]}`` with the rle grammar."""
    import re as _re

    if not isinstance(v, dict):
        return False
    return all(
        isinstance(ax, str) and isinstance(seq, list)
        and all(isinstance(s, str) and _re.match(_RLE_RE, s)
                for s in seq)
        for ax, seq in v.items())


def validate_contract_file(path: str, doc: dict) -> list[str]:
    """Schema violations of one checked-in contract JSON — empty when
    the file is well-formed.  Dispatches on ``kind``: absent means a
    program contract (the :func:`contract_from_report` shape), and
    ``"schedule_set"`` the jaxguard cross-program schedule pin."""
    import re as _re

    errs: list[str] = []
    base = os.path.basename(path)
    if not isinstance(doc, dict):
        return [f"{base}: top level must be a JSON object"]
    kind = doc.get("kind")

    if kind == "threads":
        # jaxrace host-thread pin: guard map + blessed lock order.  No
        # platform_key — host concurrency is topology-independent, so
        # one pin covers every accelerator configuration.
        unknown = set(doc) - {"kind", "program", "guards", "lock_order"}
        if unknown:
            errs.append(f"{base}: unknown key(s) {sorted(unknown)}")
        if doc.get("program") != "threads":
            errs.append(f"{base}: 'program' must be 'threads'")
        elif base != "threads.json":
            errs.append(f"{base}: filename must be 'threads.json'")
        guards = doc.get("guards")
        if not isinstance(guards, dict) or not all(
                isinstance(ck, str) and isinstance(gm, dict)
                and all(isinstance(a, str) and isinstance(lk, str)
                        for a, lk in gm.items())
                for ck, gm in guards.items()):
            errs.append(f"{base}: 'guards' must be "
                        "{class_key: {attr: lock_attr}}")
        order = doc.get("lock_order")
        if not isinstance(order, list) or not all(
                isinstance(p, list) and len(p) == 2
                and all(isinstance(x, str) for x in p) and p[0] != p[1]
                for p in order):
            errs.append(f"{base}: 'lock_order' must be a list of "
                        "[first, second] distinct lock-ident pairs")
        return errs

    prog = doc.get("program")
    key = doc.get("platform_key")
    if not isinstance(prog, str) or not prog:
        errs.append(f"{base}: 'program' must be a non-empty string")
    if not (isinstance(key, str) and _re.match(_PLATFORM_KEY_RE, key)):
        errs.append(f"{base}: 'platform_key' must match "
                    f"{_PLATFORM_KEY_RE} (e.g. cpu8, tpu4), got {key!r}")
    elif isinstance(prog, str) and base != f"{prog}.{key}.json":
        errs.append(f"{base}: filename must be "
                    f"'{prog}.{key}.json' (program + platform key)")

    if kind == "schedule_set":
        unknown = set(doc) - _SCHEDULE_SET_KEYS
        if unknown:
            errs.append(f"{base}: unknown key(s) {sorted(unknown)}")
        scheds = doc.get("schedules")
        if not isinstance(scheds, dict) or not all(
                isinstance(nm, str) and _is_schedule_map(sc)
                for nm, sc in scheds.items()):
            errs.append(f"{base}: 'schedules' must be "
                        "{program: {axis: [rle ops...]}}")
        pairs = doc.get("divergent_pairs")
        if not isinstance(pairs, list) or not all(
                isinstance(p, list) and len(p) == 2
                and all(isinstance(x, str) for x in p) and p[0] != p[1]
                for p in pairs):
            errs.append(f"{base}: 'divergent_pairs' must be a list of "
                        "[program_a, program_b] pairs (distinct names)")
        return errs
    if kind is not None:
        return errs + [f"{base}: unknown contract kind {kind!r}"]

    missing = _PROGRAM_KEYS_REQUIRED - set(doc)
    if missing:
        errs.append(f"{base}: missing required key(s) {sorted(missing)}")
    unknown = set(doc) - _PROGRAM_KEYS_REQUIRED - _PROGRAM_KEYS_OPTIONAL
    if unknown:
        errs.append(f"{base}: unknown key(s) {sorted(unknown)} — a "
                    "typo'd key silently pins nothing")
    if "require_async_starts" in doc \
            and doc["require_async_starts"] is not True:
        errs.append(f"{base}: 'require_async_starts' is pin-presence "
                    "only: True or absent")

    col = doc.get("collectives")
    if isinstance(col, dict):
        bad_levels = set(col) - _COLLECTIVES_LEVELS
        if bad_levels:
            errs.append(f"{base}: unknown collectives level(s) "
                        f"{sorted(bad_levels)}")
        if not _is_count_map(col.get("jaxpr", {}), 2):
            errs.append(f"{base}: collectives.jaxpr must be "
                        "{prim: {axis: count}}")
        if col.get("hlo") is not None \
                and not _is_count_map(col["hlo"], 1):
            errs.append(f"{base}: collectives.hlo must be {{op: count}}")
        if col.get("hlo_axes") is not None \
                and not _is_count_map(col["hlo_axes"], 2):
            errs.append(f"{base}: collectives.hlo_axes must be "
                        "{op: {axis: count}}")
        if col.get("hlo_schedule") is not None \
                and not _is_schedule_map(col["hlo_schedule"]):
            errs.append(f"{base}: collectives.hlo_schedule must be "
                        "{axis: [rle ops...]}")
    elif "collectives" in doc:
        errs.append(f"{base}: 'collectives' must be an object")

    if "outputs" in doc and not (
            isinstance(doc["outputs"], list)
            and all(isinstance(o, str) for o in doc["outputs"])):
        errs.append(f"{base}: 'outputs' must be a list of aval strings")

    don = doc.get("donation")
    if isinstance(don, dict):
        if not isinstance(don.get("declared_args"), int) \
                or isinstance(don.get("declared_args"), bool) \
                or don["declared_args"] < 0:
            errs.append(f"{base}: donation.declared_args must be a "
                        "non-negative int")
        if not (don.get("effective") is None
                or isinstance(don.get("effective"), bool)):
            errs.append(f"{base}: donation.effective must be "
                        "true/false/null")
    elif "donation" in doc:
        errs.append(f"{base}: 'donation' must be an object")

    con = doc.get("constants")
    if isinstance(con, dict):
        for field in ("count", "total_bytes"):
            v = con.get(field)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errs.append(f"{base}: constants.{field} must be a "
                            "non-negative int")
    elif "constants" in doc:
        errs.append(f"{base}: 'constants' must be an object")

    if "flops" in doc and not (
            doc["flops"] is None
            or isinstance(doc["flops"], (int, float))):
        errs.append(f"{base}: 'flops' must be a number or null")

    fc = doc.get("finding_counts")
    if isinstance(fc, dict):
        from .ir import FINDING_CLASSES

        if set(fc) != set(FINDING_CLASSES):
            errs.append(f"{base}: finding_counts keys must be exactly "
                        f"{sorted(FINDING_CLASSES)}, got {sorted(fc)}")
        if not _is_count_map(fc, 1):
            errs.append(f"{base}: finding_counts values must be "
                        "non-negative ints")
    elif "finding_counts" in doc:
        errs.append(f"{base}: 'finding_counts' must be an object")
    return errs


def load_contract(contracts_dir: str, program: str,
                  key: str) -> dict | None:
    path = contract_path(contracts_dir, program, key)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def check_report(report: dict, contracts_dir: str | None = None
                 ) -> list[str]:
    """Drift of one live audit report against its checked-in contract;
    a missing contract is itself a (single-line) failure."""
    contracts_dir = contracts_dir or default_contracts_dir()
    key = platform_key(report["platform"], report["n_devices"])
    contract = load_contract(contracts_dir, report["program"], key)
    if contract is None:
        return [f"no contract for {report['program']} on {key} "
                f"(run `jaxaudit update` and review the diff)"]
    return diff_contract(contract, report)


def check_report_status(report: dict, contracts_dir: str | None = None
                        ) -> str:
    """``'pass' | 'drift' | 'no_contract'`` — the one-word form bench.py
    stamps into its records."""
    contracts_dir = contracts_dir or default_contracts_dir()
    key = platform_key(report["platform"], report["n_devices"])
    contract = load_contract(contracts_dir, report["program"], key)
    if contract is None:
        return "no_contract"
    return "drift" if diff_contract(contract, report) else "pass"


# ------------------------------------------------------- canonical programs

def build_default_programs(names: tuple | list | None = None) -> dict:
    """``{name: (fn, example_args)}`` for the canonical audited set — the
    REAL mesh train/eval steps and serve bucket forwards at the tier-1
    config (DANet-ResNet18, 64², one lane per device).

    Train/eval state is shape-only (``jax.eval_shape`` of the real
    ``create_train_state``): tracing needs avals, not weights.  The serve
    forwards need concrete params (the jitted forward closes over them —
    the closure IS what the constants check audits), so one real init
    runs for those.
    """
    import jax
    import jax.numpy as jnp
    import optax

    from ..models import build_model
    from ..parallel import (
        create_train_state,
        make_eval_step,
        make_mesh,
        make_train_step,
    )
    from ..predict import Predictor

    names = tuple(names) if names else PROGRAM_NAMES
    unknown = [n for n in names
               if n not in ("train_step", "train_step_bf16", "eval_step",
                            "encode_step", "decode_step", "decode_int8")
               and n not in PLAN_PROGRAM_NAMES
               and not (n.startswith("serve_forward_b")
                        and n[len("serve_forward_b"):].isdigit())
               and not (n.startswith("serve_forward_int8_b")
                        and n[len("serve_forward_int8_b"):].isdigit())]
    if unknown:
        raise ValueError(f"unknown program(s): {unknown} "
                         f"(known: {list(PROGRAM_NAMES)} and "
                         "serve_forward_b<N>)")

    h, w = _AUDIT_HW
    ch = _AUDIT_CHANNELS
    sds = jax.ShapeDtypeStruct
    model = build_model("danet", nclass=1, backbone="resnet18",
                        output_stride=8, dtype="float32")
    tx = optax.sgd(1e-3, momentum=0.9)

    programs: dict = {}
    if {"train_step", "eval_step"} & set(names):
        mesh = make_mesh()
        b = mesh.devices.size  # one lane per device
        batch = {"concat": sds((b, h, w, ch), jnp.float32),
                 "crop_gt": sds((b, h, w), jnp.float32)}
        with mesh:
            state_struct = jax.eval_shape(
                lambda: create_train_state(
                    jax.random.PRNGKey(0), model, tx, (1, h, w, ch),
                    mesh=mesh))
            if "train_step" in names:
                step = make_train_step(model, tx, mesh=mesh,
                                       loss_type="multi_sigmoid")
                programs["train_step"] = (step, (state_struct, batch))
            if "eval_step" in names:
                ev = make_eval_step(model, mesh=mesh,
                                    loss_type="multi_sigmoid")
                programs["eval_step"] = (ev, (state_struct, batch))

    if "train_step_bf16" in names:
        # the fast-path twin: mixed-precision policy (bf16 compute, f32
        # master params — train/precision.py) + bucketed overlapped
        # gradient reduce (4 reverse-topo psum buckets) + cross-replica
        # BN (the bucketed step's shard_map region computes per-device,
        # so BN batch stats psum explicitly).  Audited against the
        # POLICY's JA002 allowlist — zero dtype_upcast findings pinned
        # means every f32 op on the bf16 path is a declared accumulation
        # point — and stamped overlap_expected, so a TPU-keyed contract
        # additionally requires async -start collectives (> 0).
        from ..train.precision import precision_policy

        policy = precision_policy("bfloat16")
        mesh_bf16 = make_mesh()
        b = mesh_bf16.devices.size
        batch = {"concat": sds((b, h, w, ch), jnp.float32),
                 "crop_gt": sds((b, h, w), jnp.float32)}
        model_bf16 = build_model(
            "danet", nclass=1, backbone="resnet18", output_stride=8,
            dtype=policy.compute_dtype,
            bn_cross_replica_axis="data")
        with mesh_bf16:
            state_struct = jax.eval_shape(
                lambda: create_train_state(
                    jax.random.PRNGKey(0), model_bf16, tx, (1, h, w, ch),
                    mesh=mesh_bf16))
            step = make_train_step(model_bf16, tx, mesh=mesh_bf16,
                                   loss_type="multi_sigmoid",
                                   precision=policy, reduce_buckets=4)
            programs["train_step_bf16"] = (
                step, (state_struct, batch),
                {"f32_allow": policy.ja002_allow(),
                 "overlap_expected": True})

    plan_names = [n for n in names if n in PLAN_PROGRAM_NAMES]
    if plan_names:
        # the per-strategy plan programs: each is THE train step the
        # planner builds for that rung of the ladder, at the canonical
        # audit config — state layout composed by plan.state_specs
        # (tp_param_specs x zero_opt_specs on one tree), shardings
        # threaded from a struct-only state (weights never initialize).
        # mesh_axes rides each entry so the audit attributes every HLO
        # collective to the mesh axis its replica groups span; the
        # checked-in contract pins that inventory exactly — deleting the
        # model-axis traffic (a step silently regressing to replicated)
        # fails `jaxaudit check`.
        from ..parallel import plan as plan_lib

        for n in plan_names:
            plan = plan_lib.resolve_plan(n[len("train_step_"):],
                                         n_devices=len(jax.devices()))
            mesh_p = plan.make_mesh()
            b = mesh_p.devices.size
            batch = {"concat": sds((b, h, w, ch), jnp.float32),
                     "crop_gt": sds((b, h, w), jnp.float32)}
            state_struct = plan.abstract_state(model, tx, (1, h, w, ch),
                                               mesh=mesh_p)
            with mesh_p:
                step = plan.make_train_step(
                    model, tx, mesh=mesh_p, state=state_struct,
                    loss_type="multi_sigmoid")
            programs[n] = (step, (state_struct, batch),
                           {"mesh_axes": plan.axis_sizes(b)})

    serve = [n for n in names if n.startswith("serve_forward_b")]
    quant_serve = [n for n in names if n.startswith("serve_forward_int8_b")]
    if serve or quant_serve:
        state = create_train_state(jax.random.PRNGKey(0), model, tx,
                                   (1, h, w, ch))
        pred = Predictor(model, state.params, state.batch_stats,
                         resolution=(h, w), relax=50)
        for n in serve:
            bucket = int(n[len("serve_forward_b"):])
            programs[n] = (pred.forward_jitted,
                           (sds((bucket, h, w, ch), jnp.float32),))
        if quant_serve:
            # the int8-quantized twin of the SAME weights: per-channel
            # symmetric int8 kernels dequantized inside the trace
            # (serve/quantize.py).  Audited against the QuantPolicy's
            # JA002 allowlist — dtype_upcast=0 pinned means every
            # int8→f32 convert in the program is a declared dequant
            # point (the same program audits DIRTY under the strict
            # default: the declaration is load-bearing) — and the
            # contract's const bytes pin the ~4x int8 shrink.
            from ..serve import quantize as quantize_lib

            qpolicy = quantize_lib.QuantPolicy()
            qpred = quantize_lib.quantize_predictor(pred, qpolicy)
            for n in quant_serve:
                bucket = int(n[len("serve_forward_int8_b"):])
                programs[n] = (qpred.forward_jitted,
                               (sds((bucket, h, w, ch), jnp.float32),),
                               {"f32_allow": qpolicy.ja002_allow()})

    if {"encode_step", "decode_step", "decode_int8"} & set(names):
        # the session-serving split at the same canonical config, with
        # the guidance channel re-entering at the head; b1 is the
        # interactive single-click shape.  The FLOPs fields of these two
        # contracts ARE the warm-vs-cold cost accounting: a warm click
        # costs decode_step.flops, a cold click the sum — the serving
        # acceptance pins decode <= 50% of the total.
        split_model = build_model(
            "danet", nclass=1, backbone="resnet18", output_stride=8,
            dtype="float32", guidance_inject="head")
        split_state = create_train_state(
            jax.random.PRNGKey(0), split_model, tx, (1, h, w, ch))
        split_pred = Predictor(split_model, split_state.params,
                               split_state.batch_stats,
                               resolution=(h, w), relax=50)
        feats = split_pred.feature_struct(1)
        if "encode_step" in names:
            programs["encode_step"] = (
                split_pred.encode_jitted,
                (sds((1, h, w, ch - 1), jnp.float32),))
        if "decode_step" in names:
            programs["decode_step"] = (
                split_pred.decode_jitted,
                (feats, sds((1, h, w, 1), jnp.float32)))
        if "decode_int8" in names:
            # the warm-click hot path, quantized: sessions and int8
            # compose (the split predictor's staged composition is the
            # SAME two programs, so warm/cold parity stays bitwise even
            # quantized — pinned in tests/test_quantize.py)
            from ..serve import quantize as quantize_lib

            qpolicy = quantize_lib.QuantPolicy()
            qsplit = quantize_lib.quantize_predictor(split_pred, qpolicy)
            programs["decode_int8"] = (
                qsplit.decode_jitted,
                (qsplit.feature_struct(1),
                 sds((1, h, w, 1), jnp.float32)),
                {"f32_allow": qpolicy.ja002_allow()})
    # preserve the caller's order
    return {n: programs[n] for n in names if n in programs}


# ------------------------------------------------------------------- the CLI

def _pin_cpu_topology() -> None:
    """Standalone ``jaxaudit`` pins the canonical 8-device CPU topology
    (exactly tests/conftest.py's) BEFORE jax initializes, so the checked
    gate sees the same programs everywhere.  A no-op when jax is already
    imported (in-process callers own their topology) or when the caller
    pinned another platform (``JAX_PLATFORMS=tpu jaxaudit update``)."""
    from ..backend_health import pin_cpu8_topology

    pin_cpu8_topology()


def run_cli(argv: list[str] | None = None, programs: dict | None = None
            ) -> int:
    """``jaxaudit {audit|check|update|list} [...]``.

    ``programs`` injects a prebuilt ``{name: (fn, args)}`` registry —
    tests audit throwaway jits through the same code path the gate runs.
    """
    import argparse

    parser = argparse.ArgumentParser(
        prog="jaxaudit",
        description="IR-level program auditor + compile contracts "
                    "(see docs/DESIGN.md 'IR auditing & compile "
                    "contracts').")
    parser.add_argument("command",
                        choices=("audit", "check", "update", "list"),
                        help="audit: print reports; check: diff against "
                             "contracts (exit 1 on drift); update: "
                             "regenerate contracts; list: program names")
    parser.add_argument("--programs",
                        help="comma-separated subset (default: all)")
    parser.add_argument("--contracts-dir", default=None,
                        help="contract directory (default: the repo's "
                             "tests/contracts)")
    args = parser.parse_args(argv)

    if args.command == "list":
        for name in PROGRAM_NAMES:
            print(f"{name:18s} {_PROGRAM_HELP.get(name, '')}")
        return 0

    names = tuple(s.strip() for s in args.programs.split(",")
                  if s.strip()) if args.programs else None
    contracts_dir = args.contracts_dir or default_contracts_dir()

    from . import ir  # jax import lives behind the CLI, not the package

    if programs is None:
        _pin_cpu_topology()
        try:
            from ..backend_health import enable_compile_cache

            enable_compile_cache()
        except Exception:
            pass
        try:
            programs = build_default_programs(names)
        except ValueError as e:
            print(f"jaxaudit: error: {e}", file=sys.stderr)
            return 2
    elif names:
        unknown = set(names) - set(programs)
        if unknown:
            print(f"jaxaudit: error: unknown program(s) "
                  f"{sorted(unknown)}", file=sys.stderr)
            return 2
        programs = {n: programs[n] for n in names}

    reports = ir.audit_many(programs)

    if args.command == "audit":
        print(json.dumps(reports, indent=1, sort_keys=True))
        findings = sum(len(r["findings"]) for r in reports.values())
        if findings:
            print(f"jaxaudit: {findings} finding(s) across "
                  f"{len(reports)} program(s)", file=sys.stderr)
        return 0

    if args.command == "update":
        for report in reports.values():
            path = save_contract(contract_from_report(report),
                                 contracts_dir)
            print(f"wrote {path}")
        return 0

    # check
    failed = 0
    for name, report in reports.items():
        drift = check_report(report, contracts_dir)
        tm = report.get("timing_ms") or {}
        fmt = lambda v: "-" if v is None else f"{v:.0f}ms"  # noqa: E731
        timing = (f" [lower {fmt(tm.get('lower'))} compile "
                  f"{fmt(tm.get('compile'))} walk {fmt(tm.get('walk'))}]"
                  if tm else "")
        if drift:
            failed += 1
            for line in drift:
                print(f"{name}: {line}")
        else:
            print(f"{name}: ok "
                  f"({platform_key(report['platform'], report['n_devices'])})"
                  f"{timing}")
    if failed:
        print(f"jaxaudit: {failed}/{len(reports)} program(s) drifted "
              "from their compile contracts", file=sys.stderr)
        return 1
    return 0


def main(argv: list[str] | None = None) -> int:
    """Console entry point (``jaxaudit`` in pyproject).  ``--guard``
    routes to the jaxguard CLI (:mod:`guard`) so one installed entry
    point fronts both gates: ``jaxaudit check`` for per-program IR
    contracts, ``jaxaudit --guard check`` for the cross-program
    SPMD/donation layer."""
    argv = sys.argv[1:] if argv is None else argv
    if "--guard" in argv:
        from .guard import run_guard_cli

        return run_guard_cli([a for a in argv if a != "--guard"])
    return run_cli(argv)


if __name__ == "__main__":
    sys.exit(main())
