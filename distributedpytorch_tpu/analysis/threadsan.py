"""Runtime lock-sanitizer witness for the jaxrace guard map.

jaxrace's JR001 verdicts are static claims: "every write to
``PredictorPool._active`` happens under ``_lock``".  This module makes
the existing under-load tests *witness* those claims at runtime — the
jaxaudit pattern of a runtime check vouching for a static one, applied
to host threads instead of compiled programs.

Opt-in via ``DPTPU_THREADSAN=1`` (the tests' conftest installs the
checked-in ``tests/contracts/threads.json`` for the whole session and
asserts zero violations at teardown), or programmatically::

    from distributedpytorch_tpu.analysis import threadsan
    threadsan.install(json.load(open("tests/contracts/threads.json")))
    ... run threaded workload ...
    assert threadsan.violations() == []
    threadsan.uninstall()

Mechanism, per pinned class:

* after ``__init__`` returns, every lock attribute named by the guard
  map is replaced with a :class:`_LockWitness` proxy that tracks a
  thread-local held set (``with``/``acquire``/``release``; everything
  else — ``wait``, ``notify``, ... — passes through);
* ``__setattr__`` is replaced with a checker: writing a guarded
  attribute while the pinned lock's witness is not held by the current
  thread records a violation.  Writes are the instrumented half by
  design — every data race needs a mutating side, and write-side-only
  keeps the hot-path read cost at zero.  During ``__init__`` the lock
  attribute is still a raw lock (the witness wraps it only afterwards),
  so single-threaded construction is exempt, mirroring JR001's
  ``__init__`` carve-out.

In-place container mutation (``self._gens[k] = ...``) never reaches
``__setattr__`` — the static layer covers those through the reads that
surround them; the witness covers rebinding.  Stdlib-only, no jax.
"""

from __future__ import annotations

import importlib
import threading
import traceback

_tls = threading.local()
_vlock = threading.Lock()
_violations: list[dict] = []
#: (cls, {"__init__": orig, "__setattr__": orig}) restore records
_installed: list[tuple] = []


def _held() -> dict:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = {}
    return held


class _LockWitness:
    """Wraps a Lock/RLock/Condition; tracks per-thread holds."""

    __slots__ = ("_tsan_lock",)

    def __init__(self, lock):
        object.__setattr__(self, "_tsan_lock", lock)

    # ---- the mutual-exclusion surface
    def acquire(self, *args, **kwargs):
        got = self._tsan_lock.acquire(*args, **kwargs)
        if got:
            held = _held()
            held[id(self)] = held.get(id(self), 0) + 1
        return got

    def release(self):
        held = _held()
        n = held.get(id(self), 0)
        if n <= 1:
            held.pop(id(self), None)
        else:
            held[id(self)] = n - 1
        self._tsan_lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def held_by_me(self) -> bool:
        return _held().get(id(self), 0) > 0

    # ---- everything else (Condition.wait/notify, locked(), ...)
    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_tsan_lock"), name)


def _record(cls_name: str, attr: str, lock_attr: str) -> None:
    with _vlock:
        _violations.append({
            "class": cls_name,
            "attr": attr,
            "lock": lock_attr,
            "thread": threading.current_thread().name,
            "stack": "".join(traceback.format_stack(limit=8)[:-2]),
        })


def _instrument(cls, guards: dict[str, str]) -> None:
    lock_attrs = sorted(set(guards.values()))
    orig_init = cls.__init__
    orig_setattr = cls.__setattr__

    def checked_init(self, *args, **kwargs):
        orig_init(self, *args, **kwargs)
        for la in lock_attrs:
            # getattr, not __dict__: guarded classes may use __slots__
            # (the registry primitives do)
            lk = getattr(self, la, None)
            if lk is not None and not isinstance(lk, _LockWitness):
                object.__setattr__(self, la, _LockWitness(lk))

    def checked_setattr(self, name, value):
        la = guards.get(name)
        if la is not None:
            w = getattr(self, la, None)
            # raw lock (mid-__init__) or absent: construction carve-out
            if isinstance(w, _LockWitness) and not w.held_by_me():
                _record(cls.__name__, name, la)
        orig_setattr(self, name, value)

    cls.__init__ = checked_init
    cls.__setattr__ = checked_setattr
    _installed.append((cls, {"__init__": orig_init,
                             "__setattr__": orig_setattr}))


def _resolve(class_key: str):
    """``distributedpytorch_tpu/serve/swap.py:PredictorPool`` -> class.
    Returns None for keys whose module lives outside the package
    (contract entries for test fixtures)."""
    path, _, cls_name = class_key.rpartition(":")
    if not path.endswith(".py") \
            or not path.startswith("distributedpytorch_tpu/"):
        return None
    mod_name = path[:-3].replace("/", ".")
    mod = importlib.import_module(mod_name)
    return getattr(mod, cls_name, None)


def install(contract: dict) -> list[str]:
    """Instrument every package class in the contract's guard map;
    returns the class keys actually instrumented.  Idempotent per
    session — call :func:`uninstall` before re-installing."""
    if _installed:
        raise RuntimeError("threadsan already installed — uninstall() "
                           "first")
    done: list[str] = []
    for class_key, guards in sorted((contract.get("guards")
                                     or {}).items()):
        cls = _resolve(class_key)
        if cls is None:
            continue
        _instrument(cls, dict(guards))
        done.append(class_key)
    return done


def uninstall() -> None:
    while _installed:
        cls, originals = _installed.pop()
        for name, fn in originals.items():
            setattr(cls, name, fn)


def violations() -> list[dict]:
    with _vlock:
        return list(_violations)


def reset() -> None:
    with _vlock:
        _violations.clear()


def is_installed() -> bool:
    return bool(_installed)
